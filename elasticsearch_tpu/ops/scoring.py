"""Batched query scoring on device — the replacement for Lucene's QueryPhase hot loop.

The reference's inner loop (search/query/QueryPhase.java:95-137: per-segment postings
advance + Similarity.score + priority-queue insert) becomes ONE fused device program per
(segment, query-batch):

  1. gather postings blocks for every (query, term) pair            [M, B]
  2. compute per-posting contributions (BM25 tfNorm / TF-IDF)       [M, B] FMA
  3. scatter-add into dense per-query score accumulators            [Q, Dpad+1]
  4. scatter-add packed match counters (should/must/must_not bits)  [Q, Dpad+1]
  5. apply bool-query semantics (must coverage, minimum_should_match,
     must_not exclusion), coord factor, live mask
  6. lax.top_k per query                                            [Q, k]

All shapes are static: M (triple count) is bucketed to powers of two, Dpad/NB come from
the packed segment's buckets, so executables cache across refreshes. No data-dependent
control flow — bool-query logic is mask arithmetic (XLA semantics, SURVEY header).

Match-count packing: one int32 scatter carries three counters —
  bit 0..9   : matched SHOULD clauses
  bit 10..19 : matched MUST clauses
  bit 20..29 : matched MUST_NOT clauses
(queries are capped at 1023 clauses per group, far beyond the reference's default
indices.query.bool.max_clause_count = 1024.)
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..common import profile as _profile
from ..common.breaker import reserve
from ..common.compilecache import REGISTRY as _WARM
from ..common.jaxenv import current_compile_family
from .device_index import (
    BLOCK,
    TFN_BM25,
    PackedSegment,
    _ladder_bucket,
    _pow2_bucket,
    ensure_blk_freqs,
)

GROUP_SHOULD, GROUP_MUST, GROUP_MUST_NOT = 0, 1, 2
_MUST_SHIFT, _NOT_SHIFT = 10, 20

MODE_BM25 = 0  # contribution = w * freq*(k1+1)/(freq + cache[normbyte])
MODE_TFIDF = 1  # contribution = w * sqrt(freq) * cache[normbyte]
MODE_CONST = 2  # contribution = w per matching term (constant-score / filters)


@dataclass
class TermBatch:
    """Flattened (query, term, block) triples + per-query bool-semantics arrays.
    Built host-side by the query planner (search/execute.py)."""

    n_queries: int
    # per triple (padded to bucket):
    qidx: np.ndarray  # int32 [M]
    blk: np.ndarray  # int32 [M] — block row in the packed segment (pad: NBpad-? safe row)
    weight: np.ndarray  # float32 [M]
    fidx: np.ndarray  # int32 [M] — index into the stacked norm/cache tables
    group: np.ndarray  # int32 [M] — GROUP_*
    tfmode: np.ndarray  # int32 [M] — MODE_* per clause (const-score clauses mix in)
    # per query:
    n_must: np.ndarray  # int32 [Q]
    msm: np.ndarray  # int32 [Q] — minimum should matches
    coord: np.ndarray  # float32 [Q, C+1] — coord factor by matched count (incl queryNorm)
    # stacked per-field tables:
    norm_fields: list = dc_field(default_factory=list)  # field names, order = fidx
    caches: np.ndarray | None = None  # float32 [F, 256]
    simple: bool | None = None  # cached fast-path eligibility (computed on first use)


@dataclass
class ScoreResult:
    scores: np.ndarray  # [Q, k] float32
    docs: np.ndarray  # [Q, k] int32 (local doc ids; doc_count → pad/no hit)
    total_hits: np.ndarray  # [Q] int64
    max_score: np.ndarray  # [Q] float32


def _score_batch_impl(blk_docs, blk_freqs, live_parent, norms_stack, caches,
                      qidx, blk, weight, fidx, group, tfmode,
                      n_must, msm, coord, *, n_queries: int, k: int, doc_pad: int,
                      simple: bool = False):
    """simple=True is a host-detected static fast path: every clause is a SHOULD with
    msm<=1, no coord — match reduces to score>0, so the int counters scatter and the
    per-doc match bookkeeping are skipped entirely (the bulk-query hot shape)."""
    import jax
    import jax.numpy as jnp

    Q = n_queries
    scores, flat_idx, valid = _dense_accumulate(
        blk_docs, blk_freqs, norms_stack, caches, qidx, blk, weight, fidx, group,
        tfmode, Q=Q, doc_pad=doc_pad)

    if simple:
        match = (scores > 0.0) & live_parent[None, :doc_pad]
        neg_inf = jnp.float32(-jnp.inf)
        masked = jnp.where(match, scores, neg_inf)
        top_scores, top_docs = jax.lax.top_k(masked, k)
        total = match.sum(axis=1, dtype=jnp.int32)
        # sentinel substitution + max_score are [Q, k]-tiny — done host-side in
        # score_term_batch (appending them here measurably slowed the whole program
        # on the axon backend)
        return top_scores, top_docs, total

    scores, match = _dense_semantics(scores, flat_idx, valid, group, live_parent,
                                     n_must, msm, coord, Q=Q, doc_pad=doc_pad)
    neg_inf = jnp.float32(-jnp.inf)
    masked = jnp.where(match, scores, neg_inf)
    top_scores, top_docs = jax.lax.top_k(masked, k)
    total = match.sum(axis=1, dtype=jnp.int32)
    return top_scores, top_docs, total


def _dense_accumulate(blk_docs, blk_freqs, norms_stack, caches,
                      qidx, blk, weight, fidx, group, tfmode, *, Q: int, doc_pad: int):
    """Steps 1-3 of the dense kernel: gather postings blocks, per-posting
    contributions, scatter-add into the [Q, doc_pad] accumulator. Returns
    (scores, flat_idx, valid) for the semantics pass."""
    import jax.numpy as jnp

    docs = blk_docs[blk]  # [M, B] int32; padded rows → doc_pad sentinel
    freqs = blk_freqs[blk]  # [M, B]
    valid = docs < doc_pad
    docs_safe = jnp.where(valid, docs, 0)

    nb = norms_stack[fidx[:, None], docs_safe]  # [M, B] uint8
    cache_vals = caches[fidx[:, None], nb.astype(jnp.int32)]  # [M, B]

    # float op ORDER matters for bit-parity with the host scorer and the sparse
    # kernel's in-scan tfn (sparse_candidates): the tf factor is computed FIRST,
    # then multiplied by the weight — Lucene's weight·tfNorm order
    # (BM25Similarity.BM25DocScorer / TFIDFSimilarity.ExactSimScorer)
    mode = tfmode[:, None]
    w = weight[:, None]
    bm25 = w * (freqs / (freqs + cache_vals))
    tfidf = w * (jnp.sqrt(freqs) * cache_vals)
    contrib = jnp.where(mode == MODE_BM25, bm25, jnp.where(mode == MODE_TFIDF, tfidf, w))
    scoring = (group[:, None] != GROUP_MUST_NOT) & valid
    contrib = jnp.where(scoring, contrib, 0.0)

    qd = (qidx[:, None] * (doc_pad + 1))
    flat_idx = jnp.where(valid, qd + docs_safe, Q * (doc_pad + 1))  # OOB → dropped

    scores = jnp.zeros(Q * (doc_pad + 1), jnp.float32).at[flat_idx.reshape(-1)].add(
        contrib.reshape(-1), mode="drop"
    ).reshape(Q, doc_pad + 1)[:, :doc_pad]
    return scores, flat_idx, valid


def _dense_semantics(scores, flat_idx, valid, group, live_parent, n_must, msm, coord,
                     *, Q: int, doc_pad: int):
    """Bool-query semantics + coord over the dense accumulator: returns the
    coord-scaled scores and the match mask (shared by the plain dense kernel and
    the function_score variants below)."""
    import jax.numpy as jnp

    counters = (
        jnp.where(group == GROUP_SHOULD, 1, 0)
        + jnp.where(group == GROUP_MUST, 1 << _MUST_SHIFT, 0)
        + jnp.where(group == GROUP_MUST_NOT, 1 << _NOT_SHIFT, 0)
    ).astype(jnp.int32)
    counter_vals = jnp.where(valid, counters[:, None], 0)
    counts = jnp.zeros(Q * (doc_pad + 1), jnp.int32).at[flat_idx.reshape(-1)].add(
        counter_vals.reshape(-1), mode="drop"
    ).reshape(Q, doc_pad + 1)[:, :doc_pad]

    m_should = counts & 0x3FF
    m_must = (counts >> _MUST_SHIFT) & 0x3FF
    m_not = counts >> _NOT_SHIFT

    match = (m_must == n_must[:, None]) & (m_should >= msm[:, None]) & (m_not == 0)
    match = match & ((m_should + m_must) > 0) & live_parent[None, :doc_pad]

    overlap = jnp.minimum(m_should + m_must, coord.shape[1] - 1)
    # per-row lookup into the small [Q, C+1] coord table as a static select-sum —
    # take_along_axis lowers to a serialized per-element gather on TPU (measured
    # ~1.3s for [1024, 128k] vs ~5ms for C+1 fused compare+FMA passes)
    coord_fac = jnp.zeros_like(scores)
    for j in range(coord.shape[1]):
        coord_fac = coord_fac + jnp.where(overlap == j, coord[:, j][:, None], 0.0)
    return scores * coord_fac, match


_compiled_cache: dict = {}


def _record(site: str, family: str, params: tuple, args) -> None:
    """Register this launch's executable with the compile-warm registry
    (common/compilecache): first sighting of a (site, params, arg shapes)
    signature stores a JSON-able WarmSpec the warmer replays at startup /
    post-restart, so the NEXT process never pays this compile on-path. The
    active compile_tag family wins attribution (a percolation's inner dense
    launch warms under its `compile:percolate` circuit)."""
    _WARM.record_launch(site, current_compile_family() or family, params, args)


def _get_compiled(n_queries: int, k: int, doc_pad: int, simple: bool = False):
    import jax

    key = (n_queries, k, doc_pad, simple)
    fn = _compiled_cache.get(key)
    if fn is None:
        def wrapper(*args):
            return _score_batch_impl(*args, n_queries=n_queries, k=k, doc_pad=doc_pad,
                                     simple=simple)

        fn = jax.jit(wrapper)
        _compiled_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# function_score variants of the dense kernel
# ---------------------------------------------------------------------------
#
# The reference rescores inside the Lucene query (FunctionScoreQuery wraps the sub
# scorer — common/lucene/search/function/FunctionScoreQuery.java); here the
# function value is fused into the same device program that scores the sub query:
#   "rows"   — every function is doc-only (decay/field_value_factor/boost_factor/
#              random/script-without-_score): the score_mode-combined value is one
#              host-computed f32 row per segment (functions.combined_doc_rows),
#              and the kernel applies max_boost/boost_mode/outer-boost/min_score.
#   "script" — a single script_score that READS _score: the sandboxed AST is
#              traced into the kernel (script.jax_vectorizer_cls) with _score
#              bound to the dense sub-score array and doc columns as device rows.
# Tail math is float32 in the same op order as functions.apply_functions, so host
# and device scores are bit-identical for the rows case.


def _bmode_combine(sub, comb, applied, bmode: str):
    """boost_mode combine, float32, op-order-identical to apply_functions.
    applied=None means every doc has a function applied (no filter)."""
    import jax.numpy as jnp

    if bmode == "multiply":
        return sub * comb
    if bmode == "replace":
        return comb if applied is None else jnp.where(applied, comb, sub)
    if bmode == "sum":
        return sub + comb
    if bmode == "avg":
        return (sub + comb) / jnp.float32(2.0)
    if bmode == "max":
        return jnp.maximum(sub, comb)
    if bmode == "min":
        return jnp.minimum(sub, comb)
    raise ValueError(f"unknown boost_mode [{bmode}]")


def _fs_rows_impl(blk_docs, blk_freqs, live_parent, norms_stack, caches,
                  qidx, blk, weight, fidx, group, tfmode, n_must, msm, coord,
                  g_row, applies_row, max_boost, fboost, min_score,
                  *, n_queries: int, k: int, doc_pad: int, bmode: str,
                  use_min_score: bool, no_functions: bool):
    import jax
    import jax.numpy as jnp

    Q = n_queries
    scores, flat_idx, valid = _dense_accumulate(
        blk_docs, blk_freqs, norms_stack, caches, qidx, blk, weight, fidx, group,
        tfmode, Q=Q, doc_pad=doc_pad)
    scores, match = _dense_semantics(scores, flat_idx, valid, group, live_parent,
                                     n_must, msm, coord, Q=Q, doc_pad=doc_pad)
    if no_functions:
        out = scores * fboost
    else:
        applied = applies_row[None, :]
        comb = jnp.where(applied, g_row[None, :], jnp.float32(1.0))
        comb = jnp.minimum(comb, max_boost)
        out = _bmode_combine(scores, comb, applied, bmode) * fboost
    if use_min_score:
        match = match & (out >= min_score)
    masked = jnp.where(match, out, jnp.float32(-jnp.inf))
    top_scores, top_docs = jax.lax.top_k(masked, k)
    return top_scores, top_docs, match.sum(axis=1, dtype=jnp.int32)


def _fs_script_impl(blk_docs, blk_freqs, live_parent, norms_stack, caches,
                    qidx, blk, weight, fidx, group, tfmode, n_must, msm, coord,
                    col_rows, fmask_row, bad_row, parent_row,
                    weight_s, max_boost, fboost, min_score,
                    *, n_queries: int, k: int, doc_pad: int, script,
                    used_fields: tuple, bmode: str, use_min_score: bool,
                    has_filter: bool, has_weight: bool):
    import jax
    import jax.numpy as jnp

    from ..script import jax_vectorizer_cls

    Q = n_queries
    scores, flat_idx, valid = _dense_accumulate(
        blk_docs, blk_freqs, norms_stack, caches, qidx, blk, weight, fidx, group,
        tfmode, Q=Q, doc_pad=doc_pad)
    scores, match = _dense_semantics(scores, flat_idx, valid, group, live_parent,
                                     n_must, msm, coord, Q=Q, doc_pad=doc_pad)

    cols = dict(zip(used_fields, col_rows))
    vec = jax_vectorizer_cls()(script, lambda f: cols[f], scores)
    val = jnp.broadcast_to(jnp.asarray(vec.vectorize(), jnp.float32), scores.shape)
    if has_weight:
        val = val * weight_s
    applied = fmask_row[None, :] if has_filter else None
    comb = val if applied is None else jnp.where(applied, val, jnp.float32(1.0))
    comb = jnp.minimum(comb, max_boost)
    out = _bmode_combine(scores, comb, applied, bmode) * fboost
    if use_min_score:
        match = match & (out >= min_score)
    # host error semantics (functions.vectorized_script_eval): any parent doc whose
    # used columns are missing or whose script value is non-finite would take the
    # per-doc path (which may raise ScriptError) — flag the query so the caller
    # reruns it on the host
    bad = (bad_row[None, :] | (parent_row[None, :] & ~jnp.isfinite(val))).any(axis=1)
    masked = jnp.where(match, out, jnp.float32(-jnp.inf))
    top_scores, top_docs = jax.lax.top_k(masked, k)
    return top_scores, top_docs, match.sum(axis=1, dtype=jnp.int32), bad


def _get_fs_compiled(kind: str, n_queries: int, k: int, doc_pad: int, **statics):
    import jax

    if kind == "rows":
        key = ("fs_rows", n_queries, k, doc_pad, tuple(sorted(statics.items())))
        impl = _fs_rows_impl
    else:
        script = statics.pop("script")
        key = ("fs_script", n_queries, k, doc_pad, script.source,
               repr(sorted(script.params.items())),
               tuple(sorted((k2, v) for k2, v in statics.items())))
        impl = functools.partial(_fs_script_impl, script=script)
    fn = _compiled_cache.get(key)
    if fn is None:
        def wrapper(*args):
            return impl(*args, n_queries=n_queries, k=k, doc_pad=doc_pad, **statics)

        fn = jax.jit(wrapper)
        _compiled_cache[key] = fn
    return fn


def _stack_args(packed: PackedSegment, batch: TermBatch):
    """Kernel ABI: the stacked norm-byte and cache tables every dense launch takes
    (single construction site — the fallback shapes are load-bearing)."""
    import jax.numpy as jnp

    norms_stack = (
        jnp.stack([packed.norm_bytes[f] for f in batch.norm_fields])
        if batch.norm_fields
        else jnp.zeros((1, packed.doc_pad), jnp.uint8)
    )
    caches = jnp.asarray(
        batch.caches if batch.caches is not None else np.ones((1, 256), np.float32)
    )
    return norms_stack, caches


def _scalar_f32(x):
    """Device f32 scalar via EXPLICIT placement: eager jnp.float32(x) routes a
    0-d convert_element_type through an implicit host→device transfer, which
    the transfer_guard("disallow") sanitizer rejects at dispatch sites."""
    import jax

    return jax.device_put(np.float32(x))


def score_fs_rows_batch(packed: PackedSegment, batch: TermBatch, k: int,
                        g_row, applies_row, max_boost: float, fboost: float,
                        min_score, bmode: str, no_functions: bool):
    """Dense launch with host-combined function rows; returns (scores, docs, total)
    numpy [Q, k]/[Q]."""
    import jax
    import jax.numpy as jnp

    norms_stack, caches = _stack_args(packed, batch)
    params = (batch.n_queries, min(k, packed.doc_pad), packed.doc_pad,
              bmode, min_score is not None, no_functions)
    fn = _get_fs_compiled(
        "rows", params[0], params[1], params[2],
        bmode=bmode, use_min_score=min_score is not None, no_functions=no_functions)
    args = (
        packed.blk_docs, ensure_blk_freqs(packed), packed.live_parent,
        norms_stack, caches,
        jnp.asarray(batch.qidx), jnp.asarray(batch.blk), jnp.asarray(batch.weight),
        jnp.asarray(batch.fidx), jnp.asarray(batch.group), jnp.asarray(batch.tfmode),
        jnp.asarray(batch.n_must), jnp.asarray(batch.msm), jnp.asarray(batch.coord),
        jnp.asarray(g_row, jnp.float32), jnp.asarray(applies_row, bool),
        _scalar_f32(max_boost), _scalar_f32(fboost),
        _scalar_f32(min_score if min_score is not None else 0.0),
    )
    out = fn(*args)
    # the script variant is NOT recorded: its executable closes over a live
    # sandboxed script object that has no JSON form to replay from a manifest
    _record("scoring.fs_rows", "function_score", params, args)
    return jax.device_get(out)


def score_fs_script_batch(packed: PackedSegment, batch: TermBatch, k: int,
                          script, used_fields: tuple, col_rows, fmask_row,
                          bad_row, parent_row, weight, max_boost: float,
                          fboost: float, min_score, bmode: str, has_filter: bool):
    """Dense launch with the script traced into the kernel; returns
    (scores, docs, total, bad) numpy."""
    import jax.numpy as jnp

    norms_stack, caches = _stack_args(packed, batch)
    fn = _get_fs_compiled(
        "script", batch.n_queries, min(k, packed.doc_pad), packed.doc_pad,
        script=script, used_fields=used_fields, bmode=bmode,
        use_min_score=min_score is not None, has_filter=has_filter,
        has_weight=weight is not None)
    top_scores, top_docs, total, bad = fn(
        packed.blk_docs, ensure_blk_freqs(packed), packed.live_parent,
        norms_stack, caches,
        jnp.asarray(batch.qidx), jnp.asarray(batch.blk), jnp.asarray(batch.weight),
        jnp.asarray(batch.fidx), jnp.asarray(batch.group), jnp.asarray(batch.tfmode),
        jnp.asarray(batch.n_must), jnp.asarray(batch.msm), jnp.asarray(batch.coord),
        tuple(jnp.asarray(c, jnp.float32) for c in col_rows),
        jnp.asarray(fmask_row, bool), jnp.asarray(bad_row, bool),
        jnp.asarray(parent_row, bool),
        _scalar_f32(weight if weight is not None else 1.0),
        _scalar_f32(max_boost), _scalar_f32(fboost),
        _scalar_f32(min_score if min_score is not None else 0.0),
    )
    return (np.asarray(top_scores), np.asarray(top_docs), np.asarray(total),
            np.asarray(bad))


# ---------------------------------------------------------------------------
# dense kernel + fused metric-aggregation stats
# ---------------------------------------------------------------------------
#
# The reference collects metric aggs in a second per-doc pass over the matched
# docs (search/aggregations/AggregationPhase + per-agg collectors); here the agg
# reduction fuses into the SAME device program that scored the query: the match
# mask multiplies per-doc (count, sum, sumsq) rows via a [Q, Dpad] @ [Dpad, 3F]
# matmul (MXU work), and min/max ride masked reductions. Rows come from
# device_index.agg_doc_rows — exact for multi-valued fields.


def score_filtered_batch(packed: PackedSegment, batch: TermBatch, k: int, fmask):
    """Dense launch with match-gating filter masks (the device form of the
    reference's FilteredQuery — the filter gates matching, never scoring,
    XFilteredQuery). Rides score_agg_batch with an empty agg stack (F=0): one
    kernel family to keep in sync. Returns numpy (scores, docs, total)."""
    empty = np.zeros((0, 5, packed.doc_pad), np.float32)
    scores, docs, total, _counts, _stats, _buckets = score_agg_batch(
        packed, batch, k, empty, (), fmask=fmask)
    return scores, docs, total


def _dense_sort_impl(blk_docs, blk_freqs, live_parent, norms_stack, caches,
                     qidx, blk, weight, fidx, group, tfmode, n_must, msm, coord,
                     fmask, key_row,  # f32 [Dpad] ascending-semantics sort keys
                     *, n_queries: int, k: int, doc_pad: int, descending: bool):
    """Dense kernel + field-sort top-k: the device form of the reference's
    sorted TopFieldCollector (QueryPhase sorted search). Keys come pre-folded
    per doc (sorting.device_sort_key_row — mode + missing policy baked in);
    ties break by doc id ascending via top_k's lower-index preference, matching
    the host lexsort."""
    import jax
    import jax.numpy as jnp

    Q = n_queries
    scores, flat_idx, valid = _dense_accumulate(
        blk_docs, blk_freqs, norms_stack, caches, qidx, blk, weight, fidx, group,
        tfmode, Q=Q, doc_pad=doc_pad)
    scores, match = _dense_semantics(scores, flat_idx, valid, group, live_parent,
                                     n_must, msm, coord, Q=Q, doc_pad=doc_pad)
    match = match & fmask
    key = jnp.broadcast_to(key_row[None, :], match.shape)
    pad = jnp.float32(-jnp.inf) if descending else jnp.float32(jnp.inf)
    sortable = jnp.where(match, key, pad)
    if descending:
        top_keys, top_docs = jax.lax.top_k(sortable, k)
    else:
        neg, top_docs = jax.lax.top_k(-sortable, k)
        top_keys = -neg
    top_scores = jnp.take_along_axis(scores, top_docs, axis=1)
    # max_score spans ALL matches (the host mask path computes it that way for
    # sorted searches), not just the k winners
    qmax = jnp.max(jnp.where(match, scores, jnp.float32(-jnp.inf)), axis=1)
    return (top_keys, top_docs, top_scores, qmax,
            match.sum(axis=1, dtype=jnp.int32))


def _get_sorted_compiled(n_queries: int, k: int, doc_pad: int,
                         descending: bool):
    import jax

    key = ("sorted", n_queries, k, doc_pad, descending)
    fn = _compiled_cache.get(key)
    if fn is None:
        def wrapper(*args):
            return _dense_sort_impl(*args, n_queries=n_queries, k=k,
                                    doc_pad=doc_pad, descending=descending)

        fn = jax.jit(wrapper)
        _compiled_cache[key] = fn
    return fn


def score_sorted_batch(packed: PackedSegment, batch: TermBatch, k: int,
                       key_row, descending: bool, fmask=None):
    """Field-sorted dense launch; returns numpy (keys, docs, scores, qmax,
    total). Matched docs occupy the first min(total, k) slots per query
    (padding ranks strictly after ±FLT_MAX missing keys)."""
    import jax
    import jax.numpy as jnp

    norms_stack, caches = _stack_args(packed, batch)
    params = (batch.n_queries, min(k, packed.doc_pad), packed.doc_pad,
              descending)
    fn = _get_sorted_compiled(*params)
    if fmask is None:
        fmask = np.ones((1, 1), dtype=bool)
    args = (
        packed.blk_docs, ensure_blk_freqs(packed), packed.live_parent,
        norms_stack, caches,
        jnp.asarray(batch.qidx), jnp.asarray(batch.blk), jnp.asarray(batch.weight),
        jnp.asarray(batch.fidx), jnp.asarray(batch.group), jnp.asarray(batch.tfmode),
        jnp.asarray(batch.n_must), jnp.asarray(batch.msm), jnp.asarray(batch.coord),
        jnp.asarray(fmask), jnp.asarray(key_row),
    )
    top_keys, top_docs, top_scores, qmax, total = fn(*args)
    _record("scoring.sorted", "sorted", params, args)
    return (np.asarray(top_keys), np.asarray(top_docs), np.asarray(top_scores),
            np.asarray(qmax), np.asarray(total))


def agg_stat_reduction(match, agg_rows):
    """Masked metric stats under a match mask — the ONE implementation both trace
    contexts call (single-shard _dense_aggstats_impl and the mesh SPMD program).

    match: bool [Q, Dpad]; agg_rows: f32 [F, 5, Dpad] per-doc folds
    (device_index.agg_doc_rows). Returns (counts int32 [Q, F], stats f32
    [Q, F, 4] = (sum, min, max, sumsq)). Counts ride an exact int32 reduction —
    an f32 accumulator would silently round past 2^24 matched values; sums and
    sumsq share one [Q, Dpad] @ [Dpad, 2F] matmul (MXU work)."""
    import jax.numpy as jnp

    F = agg_rows.shape[0]
    mf = match.astype(jnp.float32)
    lin = jnp.concatenate([agg_rows[:, 1], agg_rows[:, 4]], axis=0)  # [2F, Dpad]
    sums2 = mf @ lin.T  # [Q, 2F]
    cnt_rows = agg_rows[:, 0].astype(jnp.int32)  # [F, Dpad]
    counts = jnp.sum(jnp.where(match[:, None, :], cnt_rows[None], 0),
                     axis=2, dtype=jnp.int32)  # [Q, F]
    has = match[:, None, :] & (agg_rows[None, :, 0, :] > 0)  # [Q, F, Dpad]
    mins = jnp.where(has, agg_rows[None, :, 2, :], jnp.inf).min(axis=2)
    maxs = jnp.where(has, agg_rows[None, :, 3, :], -jnp.inf).max(axis=2)
    stats = jnp.stack([sums2[:, :F], mins, maxs, sums2[:, F:]], axis=2)
    return counts, stats


def _bucket_scatter(match, pdoc, pbucket, nb: int, sub_stack):
    """One bucket agg's reductions: exact int32 doc counts per bucket, plus —
    when the agg carries metric sub-aggs (sub_stack [Fs, 5, Dpad]) — per-bucket
    masked stats of the per-doc folds, scattered along the SAME (doc, bucket)
    pairs so a doc contributes once per bucket it belongs to (exactly the host's
    per-bucket mask collection)."""
    import jax.numpy as jnp

    Q = match.shape[0]
    hit = match[:, pdoc]  # [Q, NP] bool
    counts = jnp.zeros((Q, nb), jnp.int32).at[:, pbucket].add(
        hit.astype(jnp.int32))
    if sub_stack is None:
        return counts, None, None
    Fs = sub_stack.shape[0]
    m = hit[:, None, :]  # [Q, 1, NP]
    cnt_g = sub_stack[:, 0][:, pdoc].astype(jnp.int32)  # [Fs, NP]
    sub_cnt = jnp.zeros((Q, Fs, nb), jnp.int32).at[:, :, pbucket].add(
        jnp.where(m, cnt_g[None], 0))
    has_vals = m & (cnt_g[None] > 0)  # min/max must ignore value-less docs
    parts = []
    for row, fill, op in ((1, 0.0, "add"), (2, jnp.inf, "min"),
                          (3, -jnp.inf, "max"), (4, 0.0, "add")):
        g = sub_stack[:, row][:, pdoc]  # [Fs, NP]
        gate = m if op == "add" else has_vals
        contrib = jnp.where(gate, g[None], jnp.float32(fill))
        base = jnp.full((Q, Fs, nb), jnp.float32(fill))
        parts.append(getattr(base.at[:, :, pbucket], op)(contrib))
    sub_stats = jnp.stack([parts[0], parts[1], parts[2], parts[3]], axis=3)
    return counts, sub_cnt, sub_stats  # [Q,Fs,nb], [Q,Fs,nb,4]=(sum,min,max,sumsq)


def _dense_aggstats_impl(blk_docs, blk_freqs, live_parent, norms_stack, caches,
                         qidx, blk, weight, fidx, group, tfmode, n_must, msm, coord,
                         agg_rows,  # [F, 5, Dpad] f32 (F may be 0)
                         bucket_pairs,  # tuple of (pair_doc, pair_bucket, nb zeros, sub_stack|None)
                         fmask,  # bool [Q, Dpad] — FilteredQuery masks (all-true when none)
                         *, n_queries: int, k: int, doc_pad: int):
    import jax
    import jax.numpy as jnp

    Q = n_queries
    scores, flat_idx, valid = _dense_accumulate(
        blk_docs, blk_freqs, norms_stack, caches, qidx, blk, weight, fidx, group,
        tfmode, Q=Q, doc_pad=doc_pad)
    scores, match = _dense_semantics(scores, flat_idx, valid, group, live_parent,
                                     n_must, msm, coord, Q=Q, doc_pad=doc_pad)
    match = match & fmask
    masked = jnp.where(match, scores, jnp.float32(-jnp.inf))
    top_scores, top_docs = jax.lax.top_k(masked, k)
    total = match.sum(axis=1, dtype=jnp.int32)
    counts, stats = agg_stat_reduction(match, agg_rows)
    bucket_counts = tuple(
        _bucket_scatter(match, pdoc, pbucket, zeros_nb.shape[0], sub_stack)
        for (pdoc, pbucket, zeros_nb, sub_stack) in bucket_pairs
    )
    return top_scores, top_docs, total, counts, stats, bucket_counts


def _get_agg_compiled(n_queries: int, k: int, doc_pad: int, nb_bucket: int):
    import jax

    # bucket-agg count rides the pow-2 ladder: the wrapper is generic over the
    # pairs pytree (jit retraces per structure under ONE cache entry), so a
    # raw len() here would admit one executable per distinct agg count
    key = ("aggstats", n_queries, k, doc_pad, nb_bucket)
    fn = _compiled_cache.get(key)
    if fn is None:
        def wrapper(*args):
            return _dense_aggstats_impl(*args, n_queries=n_queries, k=k,
                                        doc_pad=doc_pad)

        fn = jax.jit(wrapper)
        _compiled_cache[key] = fn
    return fn


def score_agg_batch(packed: PackedSegment, batch: TermBatch, k: int,
                    agg_row_stack, bucket_pairs=(), fmask=None):
    """Dense launch returning (scores, docs, total, counts [Q, F] int,
    stats [Q, F, 4], bucket results) numpy. stats rows: (sum, min(+inf if none),
    max(-inf), sumsq) over matched docs per agg field; bucket_pairs: per bucket
    agg, (pair_doc, pair_bucket, zeros[NB], sub_stack [Fs,5,Dpad]|None) device
    arrays — each bucket result is (doc counts [Q,NB], sub value-counts
    [Q,Fs,NB]|None, sub stats [Q,Fs,NB,4]|None); fmask: optional bool [Q, Dpad]
    FilteredQuery match gates."""
    import jax
    import jax.numpy as jnp

    norms_stack, caches = _stack_args(packed, batch)
    params = (batch.n_queries, min(k, packed.doc_pad), packed.doc_pad,
              _pow2_bucket(len(bucket_pairs), 1) if bucket_pairs else 0)
    fn = _get_agg_compiled(*params)
    if fmask is None:
        # broadcastable no-op mask: [1, 1] & [Q, Dpad] — avoids allocating and
        # transferring a full all-true mask on the unfiltered aggs hot path
        fmask = np.ones((1, 1), dtype=bool)
    args = (
        packed.blk_docs, ensure_blk_freqs(packed), packed.live_parent,
        norms_stack, caches,
        jnp.asarray(batch.qidx), jnp.asarray(batch.blk), jnp.asarray(batch.weight),
        jnp.asarray(batch.fidx), jnp.asarray(batch.group), jnp.asarray(batch.tfmode),
        jnp.asarray(batch.n_must), jnp.asarray(batch.msm), jnp.asarray(batch.coord),
        # jnp.asarray commits a host stack explicitly (no-op for device
        # arrays); a raw numpy arg would be an implicit H2D at dispatch
        jnp.asarray(agg_row_stack), tuple(bucket_pairs), jnp.asarray(fmask),
    )
    out = fn(*args)
    _record("scoring.aggs", "aggs", params, args)
    # ONE explicit pull for the whole result pytree (None leaves pass through):
    # per-leaf np.asarray was a transfer per output — and an implicit one, which
    # the promoted transfer_guard("disallow") sanitizer now rejects
    return jax.device_get(out)


def _detect_simple(batch: TermBatch) -> bool:
    """Pure-should all-BM25 batches reduce match to score>0 — see
    _score_batch_impl(simple=). BM25 is the only mode whose contribution is provably
    positive for every posting hit ((w·freq)/(freq+cache) with w>0, cache>0): CONST
    clauses can carry weight 0, and TFIDF clauses score 0 on normless fields (norm
    byte 0 → cache 0 — the meta-field case: term _id/_uid/_type), yet both still
    MATCH — the simple path would drop those hits. Cached on the batch so
    device-resident arrays are not pulled back per call."""
    if batch.simple is None:
        batch.simple = bool(
            np.all(np.asarray(batch.group) == GROUP_SHOULD)
            and np.all(np.asarray(batch.msm) <= 1)
            and np.all(np.asarray(batch.n_must) == 0)
            and np.all(np.asarray(batch.tfmode) == MODE_BM25)
            and (batch.coord is None or np.all(np.asarray(batch.coord) == 1.0)))
    return batch.simple


def score_term_batch_async(packed: PackedSegment, batch: TermBatch, k: int):
    """Like score_term_batch but returns device arrays without syncing — callers that
    pipeline many batches block once at the end (the serving/bench throughput path)."""
    import jax.numpy as jnp

    Q = batch.n_queries
    norms_stack, caches = _stack_args(packed, batch)
    params = (Q, min(k, packed.doc_pad), packed.doc_pad, _detect_simple(batch))
    fn = _get_compiled(*params)
    args = (
        packed.blk_docs, ensure_blk_freqs(packed), packed.live_parent,
        norms_stack, caches,
        jnp.asarray(batch.qidx), jnp.asarray(batch.blk), jnp.asarray(batch.weight),
        jnp.asarray(batch.fidx), jnp.asarray(batch.group), jnp.asarray(batch.tfmode),
        jnp.asarray(batch.n_must), jnp.asarray(batch.msm), jnp.asarray(batch.coord),
    )
    out = fn(*args)
    _record("scoring.dense", "dense", params, args)
    return out


def score_term_batch(packed: PackedSegment, batch: TermBatch, k: int) -> ScoreResult:
    """Execute a term batch against one packed segment; returns per-query top-k with
    local doc ids (doc_count/doc_pad sentinel = no hit)."""
    import jax.numpy as jnp

    Q = batch.n_queries
    norms_stack, caches = _stack_args(packed, batch)
    params = (Q, min(k, packed.doc_pad), packed.doc_pad, _detect_simple(batch))
    fn = _get_compiled(*params)
    args = (
        packed.blk_docs, ensure_blk_freqs(packed), packed.live_parent,
        norms_stack, caches,
        jnp.asarray(batch.qidx), jnp.asarray(batch.blk), jnp.asarray(batch.weight),
        jnp.asarray(batch.fidx), jnp.asarray(batch.group), jnp.asarray(batch.tfmode),
        jnp.asarray(batch.n_must), jnp.asarray(batch.msm), jnp.asarray(batch.coord),
    )
    top_scores, top_docs, total = fn(*args)
    _record("scoring.dense", "dense", params, args)
    return finalize_score_result(np.asarray(top_scores), np.asarray(top_docs),
                                 np.asarray(total), packed.doc_pad)


def finalize_score_result(scores: np.ndarray, docs: np.ndarray, total: np.ndarray,
                          doc_pad: int) -> ScoreResult:
    """Host-side [Q, k] post-processing: -inf slots → doc_pad sentinel, max_score."""
    finite = np.isfinite(scores)
    docs = np.where(finite, docs, doc_pad).astype(np.int32)
    max_score = np.where(total > 0, scores[:, 0], np.nan).astype(np.float32)
    return ScoreResult(scores=scores, docs=docs, total_hits=total,
                       max_score=max_score)


# ---------------------------------------------------------------------------
# sparse candidate-centric path (the serving/bench hot path)
# ---------------------------------------------------------------------------
#
# The dense kernel above scatter-adds into a [Q, doc_pad] accumulator — measured on the
# v5e: ~112 ms/batch for the scatter alone plus ~49 ms for the full-width top_k, and the
# accumulator is O(Q·doc_count) HBM (24 GB at enwiki scale — impossible). The sparse
# path is candidate-centric, the device analogue of Lucene's doc-at-a-time merge
# (search/query/QueryPhase.java:95-137 walks a merged postings enum; we materialize the
# merged candidate list per query and reduce it in parallel):
#
#   1. row-gather each query's QUANTIZED postings blocks  [Qb, TB, B]   (~5 ms DMA;
#      6 B/posting resident — docs i32 + tf u8 + norm byte u8, see
#      device_index module docstring)
#   2. contribution = weight · tfn, decoded IN the scan: tf widened from the
#      int plane, norm byte through the per-field 256-entry similarity LUT
#      (SimTables — replaces the pack-time baked-tfn f32 plane; the per-doc
#      [M·B] random uint8 gather the bake used to avoid stays avoided because
#      the norm byte is stored per POSTING, a streaming row access)
#   3. sort candidates by doc id per query                [Qb, P] pairs (~6 ms)
#   4. doubling-pass segment-sum merges duplicate docs (run length ≤ clause count)
#   5. bool semantics on the summed match counters at run ends
#   6. top_k over [Qb, P]                                 (~5 ms; P ≪ doc_pad)
#
# Work scales with postings touched, not with corpus size: O(Q·P) HBM per batch,
# corpus-size-independent — the layout that holds 1M+ docs (see ARCHITECTURE.md
# "HBM budget"). Queries are bucketed by their block count (power-of-two TB buckets,
# chunked to a slot budget) so executables cache; pathological block counts
# (TB > tb_max: match-everything terms) fall back to the dense kernel.


class SparseScratchPool:
    """Reusable per-bucket padded staging arrays for plan_sparse_buckets.

    The sparse planner re-materialized four [Qb, TB] host arrays (qblk/qw/
    qconst/qcnt) for every bucket of every launch, even when the shapes repeat
    on every warmed batch — pure allocator churn on the serving hot path.
    The pool hands out (and takes back) array SETS keyed by (Qb, TB): a warmed
    repeat batch performs 0 new host allocations (`allocs` stays flat, pinned
    by tests/test_batcher.py). Arrays are borrowed from take() until the
    launch's results have been PULLED — device transfers are asynchronous (and
    on CPU possibly zero-copy aliases of the numpy buffer), so giving an array
    back while its launch is still in flight would let the next take() mutate
    data the device is reading. launch_flat_sparse returns a release callback
    its caller invokes after the batch's device_get. Check-out/check-in (not
    shared mutation) keeps concurrent launches on the same segment race-free;
    the free-list is bounded so a concurrency burst can't pin staging memory
    forever."""

    _MAX_FREE = 4  # sets kept per shape

    def __init__(self):
        self._free: dict[tuple, list] = {}
        self._lock = threading.Lock()
        self.allocs = 0  # fresh allocations (a warmed repeat adds none)
        self.reuses = 0

    @staticmethod
    def staging_bytes(Qb: int, tb: int) -> int:
        # qblk i32 + qw f32 + qconst bool + qcnt i32 + qfid i32
        return Qb * tb * (4 + 4 + 1 + 4 + 4)

    def take(self, Qb: int, tb: int, sentinel_row: int):
        with self._lock:
            lst = self._free.get((Qb, tb))
            arrs = lst.pop() if lst else None
        # profile attribution: whether this launch's staging came from the
        # pool or a fresh allocation (recorded OUTSIDE the pool lock — the
        # hook is record-only and must never run under another lock)
        prof = _profile.current()
        if arrs is None:
            with self._lock:
                self.allocs += 1
            if prof is not None:
                prof.event("scratch", cache="alloc", shape=[int(Qb), int(tb)])
            return (np.full((Qb, tb), sentinel_row, np.int32),
                    np.zeros((Qb, tb), np.float32),
                    np.zeros((Qb, tb), bool),
                    np.zeros((Qb, tb), np.int32),
                    np.zeros((Qb, tb), np.int32))
        with self._lock:
            self.reuses += 1
        if prof is not None:
            prof.event("scratch", cache="reuse", shape=[int(Qb), int(tb)])
        qblk, qw, qconst, qcnt, qfid = arrs
        qblk.fill(sentinel_row)
        qw.fill(0.0)
        qconst.fill(False)
        qcnt.fill(0)
        qfid.fill(0)
        return arrs

    def give(self, arrs):
        qblk = arrs[0]
        key = qblk.shape
        with self._lock:
            lst = self._free.setdefault(key, [])
            if len(lst) < self._MAX_FREE:
                lst.append(arrs)


@dataclass
class SparseBatch:
    """One bucket of queries sharing a [Qb, TB] block layout."""

    n_queries: int  # real queries (rows beyond are padding)
    qids: np.ndarray  # int32 [Qb] — caller's query index per row (-1 padding)
    qblk: np.ndarray  # int32 [Qb, TB] — block rows (pad: sentinel all-doc_pad row)
    qw: np.ndarray  # float32 [Qb, TB] — clause weight (0 for must_not/padding)
    qconst: np.ndarray  # bool [Qb, TB] — constant-score clause (contribution = w)
    qcnt: np.ndarray  # int32 [Qb, TB] — packed group counter (should/must/must_not bit)
    qfid: np.ndarray  # int32 [Qb, TB] — SimTables cache row of the clause's field
    n_must: np.ndarray  # int32 [Qb]
    msm: np.ndarray  # int32 [Qb]
    coord: np.ndarray  # float32 [Qb, C+1]
    passes: int  # segment-sum doubling passes = ceil(log2(max clauses per query))
    simple: bool  # pure-should all-BM25 msm<=1 no-coord (match ≡ score>0)


def sparse_candidates(blk_docs, blk_tf, blk_nb, caches, modes,
                      qblk, qw, qconst, qfid, *, doc_pad: int):
    """The decode half of the quantized sparse scan: row-gather each query's
    postings blocks and compute per-posting contributions IN the scan —
    quantized tf widened to f32, norm byte through the per-field 256-entry
    similarity LUT (device_index.SimTables; the byte315 quantization survives
    all the way into the kernel), tf→tfn in the same f32 op order as the host
    reference (device_index.tfn_values), weight last.

    Returns (docs [Qb, TB, B] i32, contrib [Qb, TB, B] f32 — zeroed on invalid
    slots, valid [Qb, TB, B] bool)."""
    import jax.numpy as jnp

    docs = blk_docs[qblk]  # [Qb, TB, B]
    tf = blk_tf[qblk].astype(jnp.float32)  # u8/i16 widen; f32 escape = no-op
    nb = blk_nb[qblk].astype(jnp.int32)
    # per-field LUT decode as ONE flat gather (row*256 + byte) — XLA lowers a
    # single-index gather better than the 2-axis advanced-indexing form
    cv = caches.reshape(-1)[qfid[:, :, None] * 256 + nb]  # [Qb, TB, B]
    mode = modes[qfid][:, :, None]
    # tf factor first, then weight — Lucene's weight·tfNorm rounding order
    # (shared with the dense kernel and HostScorer)
    tfn = jnp.where(mode == TFN_BM25, tf / (tf + cv), jnp.sqrt(tf) * cv)
    contrib = qw[:, :, None] * jnp.where(qconst[:, :, None], 1.0, tfn)
    valid = docs < doc_pad
    return docs, jnp.where(valid, contrib, 0.0), valid


def sparse_reduce(docs, contrib, cnt, n_must, msm, coord,
                  *, k: int, doc_pad: int, passes: int, simple: bool,
                  use_coord: bool):
    """The reduction half: sort candidates by doc id, segment-sum duplicate
    docs (log2 doubling), bool semantics on the folded counters, top-k.
    [Qb, P] in → ([Qb, k] scores, [Qb, k] docs, [Qb] totals).

    ONE definition executed by BOTH the composed-jnp path and the fused Pallas
    kernel's final grid step (pallas_kernels.sparse_score runs it on the VMEM
    accumulator with Qb=1) — bitwise parity between the two paths is by
    construction, not by test tolerance. `cnt` may be None when simple."""
    import jax
    import jax.numpy as jnp

    Qb = docs.shape[0]

    def segsum(docs_s, vals_list):
        # duplicate docs form runs of length <= clause count after the sort;
        # log2 doubling leaves the full run sum at the run's LAST element
        for i in range(passes):
            shift = 1 << i
            same = jnp.concatenate(
                [jnp.zeros((Qb, shift), bool),
                 docs_s[:, shift:] == docs_s[:, :-shift]], axis=1)
            out = []
            for v in vals_list:
                shifted = jnp.concatenate(
                    [jnp.zeros((Qb, shift), v.dtype), v[:, :-shift]], axis=1)
                out.append(v + jnp.where(same, shifted, jnp.zeros((), v.dtype)))
            vals_list = out
        return vals_list

    if simple:
        docs_s, c_s = jax.lax.sort((docs, contrib), num_keys=1)
        (c_s,) = segsum(docs_s, [c_s])
        is_last = jnp.concatenate(
            [docs_s[:, :-1] != docs_s[:, 1:], jnp.ones((Qb, 1), bool)], axis=1)
        match = is_last & (docs_s < doc_pad) & (c_s > 0.0)
        masked = jnp.where(match, c_s, -jnp.inf)
        top_scores, idx = jax.lax.top_k(masked, k)
        top_docs = jnp.take_along_axis(docs_s, idx, axis=1)
        return top_scores, top_docs, match.sum(axis=1, dtype=jnp.int32)

    docs_s, c_s, n_s = jax.lax.sort((docs, contrib, cnt), num_keys=1)
    c_s, n_s = segsum(docs_s, [c_s, n_s])
    is_last = jnp.concatenate(
        [docs_s[:, :-1] != docs_s[:, 1:], jnp.ones((Qb, 1), bool)], axis=1)
    m_should = n_s & 0x3FF
    m_must = (n_s >> _MUST_SHIFT) & 0x3FF
    m_not = n_s >> _NOT_SHIFT
    match = (
        is_last & (docs_s < doc_pad)
        & (m_must == n_must[:, None]) & (m_should >= msm[:, None]) & (m_not == 0)
        & ((m_should + m_must) > 0)
    )
    if use_coord:
        overlap = jnp.minimum(m_should + m_must, coord.shape[1] - 1)
        coord_fac = jnp.zeros_like(c_s)
        for j in range(coord.shape[1]):
            coord_fac = coord_fac + jnp.where(overlap == j, coord[:, j][:, None], 0.0)
        c_s = c_s * coord_fac
    masked = jnp.where(match, c_s, -jnp.inf)
    top_scores, idx = jax.lax.top_k(masked, k)
    top_docs = jnp.take_along_axis(docs_s, idx, axis=1)
    return top_scores, top_docs, match.sum(axis=1, dtype=jnp.int32)


def _sparse_impl(blk_docs, blk_tf, blk_nb, caches, modes,
                 qblk, qw, qconst, qcnt, qfid, n_must, msm, coord,
                 *, k: int, doc_pad: int, passes: int, simple: bool,
                 use_coord: bool, use_pallas: bool = False):
    import jax.numpy as jnp

    Qb, TB = qblk.shape
    P = TB * BLOCK
    if use_pallas:
        # fully-fused Pallas kernel: scalar-prefetch streaming of the quantized
        # block rows, in-scan decode, counter fold and per-query VMEM candidate
        # accumulator — the [Qb, P] matrix never round-trips HBM
        # (ops/pallas_kernels.py sparse_score; parity by shared sparse_reduce)
        from .pallas_kernels import sparse_score

        # jnp.take (not advanced indexing): this may run EAGERLY in tests, and
        # eager fancy indexing routes a scalar through an implicit transfer
        # the transfer_guard("disallow") sanitizer rejects
        return sparse_score(
            qblk, qw, qconst, qcnt, qfid, jnp.take(modes, qfid), n_must, msm,
            coord, blk_docs, blk_tf, blk_nb, caches,
            k=k, doc_pad=doc_pad, passes=passes, simple=simple,
            use_coord=use_coord)
    docs, contrib, valid = sparse_candidates(
        blk_docs, blk_tf, blk_nb, caches, modes, qblk, qw, qconst, qfid,
        doc_pad=doc_pad)
    docs = docs.reshape(Qb, P)
    contrib = contrib.reshape(Qb, P)
    cnt = (None if simple
           else jnp.where(valid, qcnt[:, :, None], 0).reshape(Qb, P))
    return sparse_reduce(docs, contrib, cnt, n_must, msm, coord,
                         k=k, doc_pad=doc_pad, passes=passes, simple=simple,
                         use_coord=use_coord)


def _get_sparse_compiled(Qb: int, TB: int, k: int, doc_pad: int, passes: int,
                         simple: bool, use_coord: bool, coord_w: int):
    import jax

    from .pallas_kernels import estpu_pallas_enabled

    use_pallas = estpu_pallas_enabled()
    key = ("sparse", Qb, TB, k, doc_pad, passes, simple, use_coord, coord_w,
           use_pallas)
    fn = _compiled_cache.get(key)
    if fn is None:
        def wrapper(*args):
            return _sparse_impl(*args, k=k, doc_pad=doc_pad, passes=passes,
                                simple=simple, use_coord=use_coord,
                                use_pallas=use_pallas)

        fn = jax.jit(wrapper)
        _compiled_cache[key] = fn
    return fn


def score_sparse_batch_async(packed: PackedSegment, sb: SparseBatch, k: int,
                             sim=None):
    """Launch one sparse bucket; returns device arrays (scores, docs, totals)
    without syncing. `sim` is the SimTables the planner resolved fids against
    (device_index.ensure_sim_tables); defaults to the segment's current one."""
    import jax.numpy as jnp

    sim = sim if sim is not None else packed.sim
    Qb, TB = sb.qblk.shape
    P = TB * BLOCK
    k_eff = min(k, P)
    use_coord = not sb.simple and not bool(np.all(sb.coord == 1.0))
    params = (Qb, TB, k_eff, packed.doc_pad, sb.passes, sb.simple, use_coord,
              sb.coord.shape[1])
    fn = _get_sparse_compiled(*params)
    args = (
        packed.blk_docs, packed.blk_tf, packed.blk_nb, sim.caches, sim.modes,
        jnp.asarray(sb.qblk), jnp.asarray(sb.qw), jnp.asarray(sb.qconst),
        jnp.asarray(sb.qcnt), jnp.asarray(sb.qfid), jnp.asarray(sb.n_must),
        jnp.asarray(sb.msm), jnp.asarray(sb.coord),
    )
    out = fn(*args)
    _record("scoring.sparse", "sparse", params, args)
    return out


def plan_sparse_buckets(clause_lists: list, n_must: np.ndarray, msm: np.ndarray,
                        coord: np.ndarray, sentinel_row: int, *, tb_max: int = 512,
                        slot_budget: int = 32768, simple: bool = False,
                        scratch: SparseScratchPool | None = None):
    """Bucket queries by block count and build SparseBatches.

    clause_lists: per query, list of (b0, b1, weight, group, is_const, fid)
    block ranges — `fid` is the clause field's SimTables cache row
    (device_index.ensure_sim_tables), the in-scan decode's LUT index.
    Returns (batches, overflow_qids): overflow queries (TB > tb_max) need the dense
    fallback; queries with zero blocks appear in no batch (zero hits).

    `scratch` (the packed segment's SparseScratchPool) supplies the [Qb, TB]
    staging arrays; callers that pass one MUST give the arrays back after the
    device launch (launch_flat_sparse does) — None allocates fresh arrays the
    caller owns outright (the bench keeps its batches alive across runs)."""
    Q = len(clause_lists)
    tb_q = np.array([sum(b1 - b0 for (b0, b1, _w, _g, _c, _fi) in cl)
                     for cl in clause_lists], dtype=np.int64)
    overflow = [qi for qi in range(Q) if tb_q[qi] > tb_max]
    tb_host = tb_q.tolist()  # one host conversion, not a per-qi scalar read
    buckets: dict[int, list[int]] = {}
    for qi in range(Q):
        if 0 < tb_host[qi] <= tb_max:
            # block-count rung rides the autotuned ladder (pow-2 until the
            # observed histogram commits a tighter fit — common/compilecache)
            tb = _ladder_bucket("sparse_tb", tb_host[qi], 8)
            buckets.setdefault(tb, []).append(qi)

    batches = []
    for tb, qis in sorted(buckets.items()):
        max_q = max(1, slot_budget // tb)
        for start in range(0, len(qis), max_q):
            chunk = qis[start: start + max_q]
            Qb = _ladder_bucket("sparse_qb", len(chunk), 8)
            if scratch is not None:
                qblk, qw, qconst, qcnt, qfid = scratch.take(Qb, tb, sentinel_row)
            else:
                qblk = np.full((Qb, tb), sentinel_row, np.int32)
                qw = np.zeros((Qb, tb), np.float32)
                qconst = np.zeros((Qb, tb), bool)
                qcnt = np.zeros((Qb, tb), np.int32)
                qfid = np.zeros((Qb, tb), np.int32)
            qids = np.full(Qb, -1, np.int32)
            bn_must = np.zeros(Qb, np.int32)
            bmsm = np.zeros(Qb, np.int32)
            bcoord = np.ones((Qb, coord.shape[1]), np.float32)
            maxc = 1
            for row, qi in enumerate(chunk):
                qids[row] = qi
                bn_must[row] = n_must[qi]
                bmsm[row] = msm[qi]
                bcoord[row] = coord[qi]
                maxc = max(maxc, len(clause_lists[qi]))
                off = 0
                for (b0, b1, w, g, is_const, fid) in clause_lists[qi]:
                    nb = b1 - b0
                    if nb <= 0:
                        continue
                    qblk[row, off: off + nb] = np.arange(b0, b1, dtype=np.int32)
                    qw[row, off: off + nb] = 0.0 if g == GROUP_MUST_NOT else w
                    qconst[row, off: off + nb] = is_const
                    qcnt[row, off: off + nb] = (
                        1 if g == GROUP_SHOULD
                        else (1 << _MUST_SHIFT) if g == GROUP_MUST
                        else (1 << _NOT_SHIFT))
                    qfid[row, off: off + nb] = fid
                    off += nb
            passes = max(0, (maxc - 1).bit_length())
            batches.append(SparseBatch(
                n_queries=len(chunk), qids=qids, qblk=qblk, qw=qw, qconst=qconst,
                qcnt=qcnt, qfid=qfid, n_must=bn_must, msm=bmsm, coord=bcoord,
                passes=passes, simple=simple))
    return batches, overflow


def launch_flat_sparse(packed: PackedSegment, clause_lists: list,
                       n_must: np.ndarray, msm: np.ndarray, coord: np.ndarray,
                       k: int, *, simple: bool = False, tb_max: int = 512,
                       breaker=None, sim=None):
    """Plan + launch every sparse bucket of a flat-query batch WITHOUT syncing.

    Returns (launches, overflow_qids, release) where launches =
    [(SparseBatch, device result triple)] and `release` is a zero-arg
    callback returning the borrowed staging arrays to the segment's scratch
    pool — the caller MUST invoke it only after the batch's device_get
    (transfers are async; see SparseScratchPool). collect_flat_sparse
    scatters the pulled results into [Q, k] host arrays. The dispatch half of
    the serving path's dispatch-then-merge split — it never calls
    jax.device_get.

    Staging accounting happens here, per BATCH: the padded [Qb, TB] staging
    arrays for the whole coalesced launch are reserved on the request breaker
    in one sum (the launch is the allocation, not the per-request share) and
    released once the buckets are launched."""
    sentinel_row = packed.blk_docs.shape[0] - 1
    scratch = packed.sparse_scratch
    if scratch is None:
        scratch = packed.sparse_scratch = SparseScratchPool()
    batches, overflow = plan_sparse_buckets(
        clause_lists, n_must, msm, coord, sentinel_row, tb_max=tb_max,
        simple=simple, scratch=scratch)
    est = sum(SparseScratchPool.staging_bytes(*sb.qblk.shape) for sb in batches)
    with reserve(breaker, est, "<sparse_staging>"):
        launches = [(sb, score_sparse_batch_async(packed, sb, k, sim=sim))
                    for sb in batches]

    def release():
        for sb in batches:
            scratch.give((sb.qblk, sb.qw, sb.qconst, sb.qcnt, sb.qfid))

    return launches, overflow, release


def collect_flat_sparse(launches: list, pulled: list, Q: int, k: int,
                        doc_pad: int):
    """Scatter pulled bucket results (host triples, same order as `launches`)
    into [Q, k] host arrays — the merge half's pure-host counterpart of
    launch_flat_sparse."""
    scores = np.full((Q, k), -np.inf, np.float32)
    docs = np.full((Q, k), doc_pad, np.int32)
    totals = np.zeros(Q, np.int64)
    for (sb, _r), (s, d, t) in zip(launches, pulled):
        rows = sb.qids >= 0
        qid = sb.qids[rows]
        kk = s.shape[1]
        scores[qid, :kk] = s[rows]
        docs[qid, :kk] = d[rows]
        totals[qid] = t[rows]
    return scores, docs, totals


def score_flat_sparse(packed: PackedSegment, clause_lists: list, n_must: np.ndarray,
                      msm: np.ndarray, coord: np.ndarray, k: int, *,
                      simple: bool = False, tb_max: int = 512, breaker=None,
                      sim=None):
    """Score a whole flat-query batch through the sparse path: plan buckets, launch all
    (pipelined), collect into [Q, k] host arrays.

    Returns (scores, docs, totals, overflow_qids); rows for zero-block and overflow
    queries are empty (caller handles overflow via the dense kernel)."""
    import jax

    Q = len(clause_lists)
    launches, overflow, release = launch_flat_sparse(
        packed, clause_lists, n_must, msm, coord, k, simple=simple,
        tb_max=tb_max, breaker=breaker, sim=sim)
    # all buckets launched async above; ONE explicit device_get drains them
    # (it blocks until ready) instead of a per-bucket-per-array np.asarray pull
    pulled = jax.device_get([r for (_sb, r) in launches]) if launches else []
    release()  # results are on the host — staging arrays are reusable now
    scores, docs, totals = collect_flat_sparse(launches, pulled, Q, k,
                                               packed.doc_pad)
    return scores, docs, totals, overflow


def build_term_batch(entries: list, n_queries: int, n_must: np.ndarray, msm: np.ndarray,
                     coord: np.ndarray, norm_fields: list[str], caches: np.ndarray,
                     nb_pad_row: int) -> TermBatch:
    """Assemble + bucket-pad the flat triple arrays.

    `entries` = list of (qidx, blk_row, weight, fidx, group, tfmode); padding rows point
    at `nb_pad_row` (a row of doc_pad sentinels — contributes nothing)."""
    M = _ladder_bucket("terms", max(len(entries), 1), 16)
    qidx = np.zeros(M, np.int32)
    blk = np.full(M, nb_pad_row, np.int32)
    weight = np.zeros(M, np.float32)
    fidx = np.zeros(M, np.int32)
    group = np.zeros(M, np.int32)
    tfmode = np.zeros(M, np.int32)
    for i, (q, b, w, f, g, m) in enumerate(entries):
        qidx[i], blk[i], weight[i], fidx[i], group[i], tfmode[i] = q, b, w, f, g, m
    return TermBatch(
        n_queries=n_queries, qidx=qidx, blk=blk, weight=weight, fidx=fidx, group=group,
        tfmode=tfmode, n_must=n_must.astype(np.int32), msm=msm.astype(np.int32),
        coord=coord.astype(np.float32), norm_fields=norm_fields, caches=caches,
    )


# ---------------------------------------------------------------------------
# compaction concat: re-block merged postings planes from resident sources
# ---------------------------------------------------------------------------


def _concat_impl(blk_term, blk_j0, cum, starts, bases, doc_pads,
                 src_docs, src_tf, src_nb, *, doc_pad_new: int,
                 tf_layout: str):
    """One fused gather/select program assembling a merged segment's
    quantized postings planes from its sources' RESIDENT planes — the
    device half of ops/device_index.pack_segment_concat (HBM → HBM, no host
    staging of the O(postings) data).

    Per output slot (block row nb, lane): the owning merged term is
    `blk_term[nb]` (blocks never span terms), the within-term flat offset is
    `blk_j0[nb] + lane`, and the per-term cumulative source counts `cum`
    pick WHICH source holds that posting; the gather index into that
    source's flat plane is its own block start plus the within-source
    offset. Source slots masked to the source's doc_pad sentinel (dead /
    non-parent docs) map to the NEW sentinel; everything else shifts by the
    source's doc base. tf widens along the choose_tf_layout ladder
    (u8 → i16 → f32) with a plain astype — exact for the integral rungs the
    eligibility gate admits. Pad rows carry a huge `blk_j0`, so every select
    misses and the sentinel/zero initializers survive — bitwise identical to
    what pack_segment writes there."""
    import jax.numpy as jnp

    from .device_index import _TF_DTYPE

    W = len(src_docs)
    NB = blk_term.shape[0]
    B = src_docs[0].shape[1]
    j = blk_j0[:, None] + jnp.arange(B, dtype=jnp.int32)[None, :]
    out_docs = jnp.full((NB, B), doc_pad_new, dtype=jnp.int32)
    out_tf = jnp.zeros((NB, B), dtype=_TF_DTYPE[tf_layout])
    out_nb = jnp.zeros((NB, B), dtype=jnp.uint8)
    for s in range(W):
        lo = cum[s][blk_term][:, None]
        hi = cum[s + 1][blk_term][:, None]
        sel = (j >= lo) & (j < hi)
        slot = starts[s][blk_term][:, None] * B + (j - lo)
        slot = jnp.clip(slot, 0, src_docs[s].size - 1)
        d = jnp.take(src_docs[s].reshape(-1), slot)
        d = jnp.where(d >= doc_pads[s], jnp.int32(doc_pad_new), d + bases[s])
        out_docs = jnp.where(sel, d, out_docs)
        out_tf = jnp.where(
            sel, jnp.take(src_tf[s].reshape(-1), slot).astype(out_tf.dtype),
            out_tf)
        out_nb = jnp.where(sel, jnp.take(src_nb[s].reshape(-1), slot),
                           out_nb)
    return out_docs, out_tf, out_nb


@functools.lru_cache(maxsize=None)
def _get_concat_compiled(doc_pad_new: int, tf_layout: str):
    import jax

    return jax.jit(
        functools.partial(_concat_impl, doc_pad_new=doc_pad_new,
                          tf_layout=tf_layout))


def concat_pack_planes(blk_term, blk_j0, cum, starts, bases, doc_pads,
                       src_docs, src_tf, src_nb, *, doc_pad_new: int,
                       tf_layout: str):
    """Launch the concat program (executables cached per sentinel/layout;
    jit re-specializes per source-shape set, which the pow-2 shape buckets
    keep bounded). Inputs stay on device; outputs are the merged segment's
    resident planes — no pull here."""
    fn = _get_concat_compiled(int(doc_pad_new), tf_layout)
    return fn(blk_term, blk_j0, cum, starts, bases, doc_pads,
              tuple(src_docs), tuple(src_tf), tuple(src_nb))


# ---------------------------------------------------------------------------
# compile-warm builders (common/compilecache)
# ---------------------------------------------------------------------------
# Each builder maps a WarmSpec's recorded params back to the SAME jitted
# callable the launch site uses (same _compiled_cache key), so the warmer's
# dummy invocation populates exactly the dispatch-cache entry a real query
# will hit. The script function_score variant has no builder on purpose: its
# executable closes over a live sandboxed script object.


@_WARM.builder("scoring.dense")
def _build_dense(params):
    return _get_compiled(*params)


@_WARM.builder("scoring.sorted")
def _build_sorted(params):
    return _get_sorted_compiled(*params)


@_WARM.builder("scoring.aggs")
def _build_aggs(params):
    return _get_agg_compiled(*params)


@_WARM.builder("scoring.fs_rows")
def _build_fs_rows(params):
    n_queries, k, doc_pad, bmode, use_min_score, no_functions = params
    return _get_fs_compiled("rows", n_queries, k, doc_pad, bmode=bmode,
                            use_min_score=use_min_score,
                            no_functions=no_functions)


@_WARM.builder("scoring.sparse")
def _build_sparse(params):
    return _get_sparse_compiled(*params)
