"""Pallas TPU kernels for the sparse scoring path.

`sparse_score` is the fully-fused form of the quantized sparse scan
(ops/scoring.py `_sparse_impl`): mask → BM25/TF-IDF → partial top-k in ONE pass
over the CSR block tiles. Per grid step (query q, block-slot t) the
scalar-prefetched `qblk` row indices select which [1, B] postings block rows
stream HBM→VMEM (Pallas double-buffers the DMAs across grid steps — the gather
the composed path lowers as a generic XLA gather becomes streaming DMA), the
prefetched `qfid` selects the clause field's 256-entry similarity LUT row, and
the same step then

  1. widens the quantized tf (uint8/int16 plane; f32 escape rides through),
  2. decodes the per-posting norm byte through the LUT (tf→tfn inside the
     scan — the byte315 encoding survives into the kernel, no baked f32 plane),
  3. applies the clause weight / const-clause select,
  4. folds the packed should/must/must_not counters,
  5. appends (doc, contrib, counter) into a per-query VMEM candidate
     accumulator that lives across the query's TB grid steps.

At the query's LAST block step the accumulator — still in VMEM — runs the
shared reduction (`scoring.sparse_reduce`: sort-by-doc, segment-sum duplicate
merge, bool semantics, `lax.top_k`) and writes only the [k] winners. The full
`[Qb, TB·128]` candidate matrix therefore never round-trips through HBM; HBM
traffic is one streaming read of the touched postings (6 B/posting quantized)
plus [Qb, k] results.

Opt-in, TPU-only: scoring.py uses it when ESTPU_PALLAS=1 AND the backend is a
TPU (pending on-silicon benchmarking before any default flips).
ESTPU_PALLAS=interpret forces the kernel in interpret mode on any backend —
bitwise-identical semantics BY CONSTRUCTION (the final phase executes the same
sparse_reduce the composed path runs), which is how the parity suite exercises
it on the CPU test mesh; interpret mode is orders of magnitude slower, so it
never engages implicitly.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from .device_index import BLOCK, TFN_BM25


def estpu_pallas_enabled() -> bool:
    """ESTPU_PALLAS=1 → only on a real TPU backend (interpret-mode Pallas on the
    serving path would be a silent orders-of-magnitude regression);
    ESTPU_PALLAS=interpret → force anywhere (tests/dev)."""
    flag = os.environ.get("ESTPU_PALLAS", "0")
    if flag == "interpret":
        return True
    return flag == "1" and _is_tpu()


def _is_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — backend probe failure → interpret mode
        return False


def _sparse_score_kernel(qblk_s, qfid_s, qmode_s, n_must_s, msm_s,  # SMEM prefetch
                         docs_ref, tf_ref, nb_ref, cache_ref,  # [1, B]/[1, 256] rows
                         qw_ref, qconst_ref, qcnt_ref, coord_ref,  # [Qb, TB]/[Qb, C+1]
                         scores_out, docs_out, totals_out,  # [1, k], [1, k], [1, 1]
                         acc_docs, acc_contrib, acc_cnt=None,  # VMEM scratch [1, P]
                         *, k: int, doc_pad: int, passes: int, simple: bool,
                         use_coord: bool, TB: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q = pl.program_id(0)
    t = pl.program_id(1)

    docs = docs_ref[0, :]  # [B] i32 — the qblk-selected block row
    tf = tf_ref[0, :].astype(jnp.float32)  # quantized plane widened in-scan
    nb = nb_ref[0, :].astype(jnp.int32)  # per-posting norm byte
    # LUT decode as a masked broadcast-sum (the one-hot form of cache[nb]):
    # exactly one lane matches per posting, every other addend is +0.0, so the
    # result is bit-identical to the composed path's gather — and it lowers to
    # VPU compare+select instead of a generic gather
    iota = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, 256), 1)
    cv = jnp.sum(jnp.where(nb[:, None] == iota, cache_ref[0, :][None, :], 0.0),
                 axis=1)
    # tf factor first, then weight — the scoring.sparse_candidates op order
    tfn = jnp.where(qmode_s[q, t] == TFN_BM25, tf / (tf + cv),
                    jnp.sqrt(tf) * cv)
    w = qw_ref[q, t]
    contrib = w * jnp.where(qconst_ref[q, t] != 0, 1.0, tfn)
    valid = docs < doc_pad
    contrib = jnp.where(valid, contrib, 0.0)

    acc_docs[0, pl.ds(t * BLOCK, BLOCK)] = docs
    acc_contrib[0, pl.ds(t * BLOCK, BLOCK)] = contrib
    if not simple:
        acc_cnt[0, pl.ds(t * BLOCK, BLOCK)] = jnp.where(
            valid, qcnt_ref[q, t], 0)

    @pl.when(t == TB - 1)
    def _finish():  # the query's candidates are complete — reduce in VMEM
        from .scoring import sparse_reduce

        d = acc_docs[0, :][None, :]  # [1, P]
        c = acc_contrib[0, :][None, :]
        n = None if simple else acc_cnt[0, :][None, :]
        top_scores, top_docs, total = sparse_reduce(
            d, c, n, n_must_s[q][None], msm_s[q][None],
            coord_ref[q, :][None, :], k=k, doc_pad=doc_pad, passes=passes,
            simple=simple, use_coord=use_coord)
        scores_out[0, :] = top_scores[0]
        docs_out[0, :] = top_docs[0]
        totals_out[0, 0] = total[0]


def _sparse_score_call(qblk, qw, qconst, qcnt, qfid, qmode, n_must, msm, coord,
                       blk_docs, blk_tf, blk_nb, caches, *, k: int,
                       doc_pad: int, passes: int, simple: bool,
                       use_coord: bool, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Qb, TB = qblk.shape
    P = TB * BLOCK
    C1 = coord.shape[1]
    kern = functools.partial(_sparse_score_kernel, k=k, doc_pad=doc_pad,
                             passes=passes, simple=simple, use_coord=use_coord,
                             TB=TB)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,  # qblk, qfid, qmode, n_must, msm
        grid=(Qb, TB),
        in_specs=[
            # the prefetched qblk drives WHICH postings block row each grid
            # cell streams in — this is the gather, as streaming DMA
            pl.BlockSpec((1, BLOCK), lambda q, t, qblk, qfid, *_: (qblk[q, t], 0)),
            pl.BlockSpec((1, BLOCK), lambda q, t, qblk, qfid, *_: (qblk[q, t], 0)),
            pl.BlockSpec((1, BLOCK), lambda q, t, qblk, qfid, *_: (qblk[q, t], 0)),
            # the prefetched qfid drives WHICH similarity LUT row rides along
            pl.BlockSpec((1, 256), lambda q, t, qblk, qfid, *_: (qfid[q, t], 0)),
            pl.BlockSpec((Qb, TB), lambda q, t, *_: (0, 0)),  # qw
            pl.BlockSpec((Qb, TB), lambda q, t, *_: (0, 0)),  # qconst (i32)
            pl.BlockSpec((Qb, TB), lambda q, t, *_: (0, 0)),  # qcnt
            pl.BlockSpec((Qb, C1), lambda q, t, *_: (0, 0)),  # coord
        ],
        out_specs=[
            pl.BlockSpec((1, k), lambda q, t, *_: (q, 0)),
            pl.BlockSpec((1, k), lambda q, t, *_: (q, 0)),
            pl.BlockSpec((1, 1), lambda q, t, *_: (q, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, P), jnp.int32),  # candidate docs
            pltpu.VMEM((1, P), jnp.float32),  # candidate contributions
        ] + ([] if simple else [
            pltpu.VMEM((1, P), jnp.int32),  # folded group counters
        ]),
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Qb, k), jnp.float32),
            jax.ShapeDtypeStruct((Qb, k), jnp.int32),
            jax.ShapeDtypeStruct((Qb, 1), jnp.int32),
        ],
        interpret=interpret,
    )(qblk, qfid, qmode, n_must, msm,
      blk_docs, blk_tf, blk_nb, caches, qw, qconst, qcnt, coord)


def sparse_score(qblk, qw, qconst, qcnt, qfid, qmode, n_must, msm, coord,
                 blk_docs, blk_tf, blk_nb, caches, *, k: int, doc_pad: int,
                 passes: int, simple: bool, use_coord: bool):
    """Fused quantized sparse scoring: one pass over the selected block rows →
    per-query ([Qb, k] scores, [Qb, k] docs, [Qb] totals).

    Drop-in equivalent of `scoring.sparse_candidates` + `scoring.sparse_reduce`
    (asserted bitwise by tests/test_pallas_kernels.py); the candidate matrix
    stays in a VMEM accumulator instead of round-tripping HBM."""
    import jax.numpy as jnp

    # ESTPU_PALLAS=interpret forces interpretation EVERYWHERE (incl. on TPU —
    # that's the escape hatch for comparing interpreted vs compiled output)
    interpret = (os.environ.get("ESTPU_PALLAS") == "interpret") or not _is_tpu()
    scores, docs, totals = _sparse_score_call(
        jnp.asarray(qblk, jnp.int32), jnp.asarray(qw, jnp.float32),
        jnp.asarray(qconst).astype(jnp.int32),
        jnp.asarray(qcnt, jnp.int32), jnp.asarray(qfid, jnp.int32),
        jnp.asarray(qmode, jnp.int32), jnp.asarray(n_must, jnp.int32),
        jnp.asarray(msm, jnp.int32), jnp.asarray(coord, jnp.float32),
        blk_docs, blk_tf, blk_nb, caches,
        k=k, doc_pad=doc_pad, passes=passes, simple=simple,
        use_coord=use_coord, interpret=interpret)
    return scores, docs, totals[:, 0]
