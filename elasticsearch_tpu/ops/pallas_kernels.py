"""Pallas TPU kernels for the sparse scoring path.

The sparse kernel's dominant remaining cost is the postings block gather
(`blk_docs[qblk]` / `blk_tfn[qblk]` — measured ~5.4 ms of the ~8 ms batch on v5e;
XLA lowers it as a generic gather far from DMA bandwidth). `gather_scale` replaces
it with a scalar-prefetch Pallas kernel: the per-(query, slot) block row indices are
prefetched to SMEM, the BlockSpec index maps select each [1, B] postings block row
directly (Pallas double-buffers the HBM→VMEM DMAs across grid steps), and the
weight multiply + const-clause select fuse into the same pass — the gather becomes
streaming DMA instead of generic gather.

Opt-in, TPU-only: scoring.py uses it when ESTPU_PALLAS=1 AND the backend is a TPU
(pending on-silicon benchmarking before any default flips). ESTPU_PALLAS=interpret
forces the kernel in interpret mode on any backend — bitwise-identical semantics,
which is how the parity suite exercises it on the CPU test mesh; interpret mode is
orders of magnitude slower, so it never engages implicitly.
"""

from __future__ import annotations

import os

import numpy as np

from .device_index import BLOCK


def estpu_pallas_enabled() -> bool:
    """ESTPU_PALLAS=1 → only on a real TPU backend (interpret-mode Pallas on the
    serving path would be a silent orders-of-magnitude regression);
    ESTPU_PALLAS=interpret → force anywhere (tests/dev)."""
    flag = os.environ.get("ESTPU_PALLAS", "0")
    if flag == "interpret":
        return True
    return flag == "1" and _is_tpu()


def _is_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — backend probe failure → interpret mode
        return False


def _gather_scale_kernel(qblk_ref, qw_ref, qconst_ref,  # scalar prefetch (SMEM)
                         docs_blk_ref, tfn_blk_ref,  # [1, B] selected block row
                         docs_out_ref, contrib_out_ref):  # [1, 1, B]
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q = pl.program_id(0)
    t = pl.program_id(1)
    w = qw_ref[q, t]
    is_const = qconst_ref[q, t]
    docs_out_ref[...] = docs_blk_ref[...].reshape(docs_out_ref.shape)
    tfn = tfn_blk_ref[...].reshape(contrib_out_ref.shape)
    # CONST clauses contribute w per match; scoring clauses w·tfn
    contrib_out_ref[...] = jnp.where(is_const != 0, w, w * tfn)


def _gather_scale_call(qblk, qw, qconst, blk_docs, blk_tfn, *, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Qb, TB = qblk.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # qblk, qw, qconst
        grid=(Qb, TB),
        in_specs=[
            # the prefetched qblk drives WHICH postings block row each grid cell
            # streams in — this is the gather
            pl.BlockSpec((1, BLOCK), lambda q, t, qblk, qw, qc: (qblk[q, t], 0)),
            pl.BlockSpec((1, BLOCK), lambda q, t, qblk, qw, qc: (qblk[q, t], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, BLOCK), lambda q, t, *_: (q, t, 0)),
            pl.BlockSpec((1, 1, BLOCK), lambda q, t, *_: (q, t, 0)),
        ],
    )
    return pl.pallas_call(
        _gather_scale_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Qb, TB, BLOCK), jnp.int32),
            jax.ShapeDtypeStruct((Qb, TB, BLOCK), jnp.float32),
        ],
        interpret=interpret,
    )(qblk, qw, qconst, blk_docs, blk_tfn)


def gather_scale(qblk, qw, qconst, blk_docs, blk_tfn):
    """[Qb, TB] block rows + weights → (docs [Qb, TB, B] int32,
    contrib [Qb, TB, B] f32 = w·tfn, or w for const clauses).

    Equivalent to `blk_docs[qblk]`, `qw[:, :, None] * where(qconst, 1, blk_tfn[qblk])`
    — asserted against that exact formulation by tests/test_pallas_kernels.py."""
    import jax.numpy as jnp

    # ESTPU_PALLAS=interpret forces interpretation EVERYWHERE (incl. on TPU —
    # that's the escape hatch for comparing interpreted vs compiled output)
    interpret = (os.environ.get("ESTPU_PALLAS") == "interpret") or not _is_tpu()
    return _gather_scale_call(
        jnp.asarray(qblk, jnp.int32), jnp.asarray(qw, jnp.float32),
        jnp.asarray(qconst).astype(jnp.int32),
        blk_docs, blk_tfn, interpret=interpret)
