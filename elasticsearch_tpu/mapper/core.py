"""Document mapping: JSON docs → indexable fields.

Analogue of index/mapper/ in the reference (MapperService, DocumentMapper, field mappers —
SURVEY.md §2.3): type registry, JSON parsing into per-field token streams + columnar
values, meta-fields, dynamic mapping of unseen fields, and mapping merges with conflict
detection (ref: index/mapper/MapperService.java, DocumentMapper.java, MergeContext).

TPU-native departure from Lucene: numeric/date/boolean fields are NOT trie-encoded into
postings terms (Lucene's NumericField approach, built for term-dictionary range scans).
They land in columnar doc-value arrays — device-resident f64/i64 columns — and range/term
queries on them compile to vectorized comparisons, which is the natural TPU layout
(SURVEY.md §2.3 fielddata note: "the natural device tensor").
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass, field as dc_field
from typing import Any

from ..analysis import AnalysisService, Analyzer
from ..common.errors import MapperParsingError
from ..common.settings import Settings

# ---------------------------------------------------------------------------
# date parsing (subset of Joda patterns the reference defaults to)
# ---------------------------------------------------------------------------

_ISO_RE = re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})(?:[T ](\d{2}):(\d{2})(?::(\d{2})(?:\.(\d{1,9}))?)?"
    r"(Z|[+-]\d{2}:?\d{2})?)?$"
)


def parse_date(value: Any, formats: list[str] | None = None) -> int:
    """Parse a date value → epoch millis (UTC). Supports epoch_millis ints,
    strict_date_optional_time (ISO-8601), yyyy/MM/dd style, and %-style custom formats."""
    if isinstance(value, bool):
        raise MapperParsingError(f"cannot parse boolean [{value}] as date")
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if s.isdigit() or (s.startswith("-") and s[1:].isdigit()):
        return int(s)
    m = _ISO_RE.match(s)
    if m:
        y, mo, d = int(m.group(1)), int(m.group(2)), int(m.group(3))
        hh = int(m.group(4) or 0)
        mm = int(m.group(5) or 0)
        ss = int(m.group(6) or 0)
        frac = m.group(7) or "0"
        micros = int(float("0." + frac) * 1e6)
        tz = m.group(8)
        tzinfo = _dt.timezone.utc
        if tz and tz != "Z":
            sign = 1 if tz[0] == "+" else -1
            tz = tz[1:].replace(":", "")
            tzinfo = _dt.timezone(sign * _dt.timedelta(hours=int(tz[:2]), minutes=int(tz[2:] or 0)))
        dt = _dt.datetime(y, mo, d, hh, mm, ss, micros, tzinfo=tzinfo)
        return int(dt.timestamp() * 1000)
    for fmt in formats or ("%Y/%m/%d %H:%M:%S", "%Y/%m/%d", "%d-%m-%Y", "%m/%d/%Y"):
        try:
            dt = _dt.datetime.strptime(s, fmt).replace(tzinfo=_dt.timezone.utc)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise MapperParsingError(f"failed to parse date field [{value}]")


# "now-1d/d" style date math used by range queries
_DATE_MATH_RE = re.compile(r"^now(?:([+-]\d+)([yMwdhHms]))?(?:/([yMwdhHms]))?$")
_UNIT_MILLIS = {
    "y": 365 * 86400_000, "M": 30 * 86400_000, "w": 7 * 86400_000,
    "d": 86400_000, "h": 3600_000, "H": 3600_000, "m": 60_000, "s": 1000,
}


def parse_date_math(value: str, now_ms: int | None = None) -> int:
    import time

    m = _DATE_MATH_RE.match(value)
    if not m:
        return parse_date(value)
    t = now_ms if now_ms is not None else int(time.time() * 1000)
    if m.group(1):
        t += int(m.group(1)) * _UNIT_MILLIS[m.group(2)]
    if m.group(3):
        unit = _UNIT_MILLIS[m.group(3)]
        t = (t // unit) * unit
    return t


# ---------------------------------------------------------------------------
# field types
# ---------------------------------------------------------------------------

TEXT_TYPES = {"string", "text"}
NUMERIC_TYPES = {"long", "integer", "short", "byte", "double", "float", "date", "boolean",
                 "ip", "token_count"}

_INT_BOUNDS = {
    "byte": (-(2**7), 2**7 - 1),
    "short": (-(2**15), 2**15 - 1),
    "integer": (-(2**31), 2**31 - 1),
    "long": (-(2**63), 2**63 - 1),
}


def parse_ip(value: str) -> int:
    parts = str(value).split(".")
    if len(parts) != 4:
        raise MapperParsingError(f"failed to parse ip [{value}]")
    n = 0
    for p in parts:
        b = int(p)
        if not 0 <= b <= 255:
            raise MapperParsingError(f"failed to parse ip [{value}]")
        n = (n << 8) | b
    return n


def format_ip(n: int) -> str:
    return ".".join(str((n >> s) & 0xFF) for s in (24, 16, 8, 0))


@dataclass
class FieldType:
    """Resolved, immutable view of one field's mapping."""

    name: str
    type: str = "string"
    index: str = "analyzed"  # analyzed | not_analyzed | no
    store: bool = False
    boost: float = 1.0
    analyzer: str | None = None
    search_analyzer: str | None = None
    formats: list[str] | None = None  # date formats
    null_value: Any = None
    include_in_all: bool = True
    precision_step: int | None = None  # accepted for parity; unused (columnar ranges)
    doc_values: bool = True
    copy_to: list[str] = dc_field(default_factory=list)
    nested: bool = False
    properties: dict | None = None  # for object/nested

    @property
    def is_text(self) -> bool:
        return self.type in TEXT_TYPES

    @property
    def is_numeric(self) -> bool:
        return self.type in NUMERIC_TYPES

    @property
    def searchable(self) -> bool:
        return self.index != "no"

    @property
    def analyzed(self) -> bool:
        return self.is_text and self.index == "analyzed"

    def coerce(self, value: Any):
        """Coerce a raw JSON value to this field's storage representation
        (numerics → int/float, dates → epoch millis, bools → 0/1, ip → int)."""
        t = self.type
        if value is None:
            value = self.null_value
            if value is None:
                return None
        if t in ("long", "integer", "short", "byte", "token_count"):
            try:
                v = int(float(value)) if not isinstance(value, bool) else int(value)
            except (TypeError, ValueError):
                raise MapperParsingError(f"failed to parse [{self.name}] value [{value}] as {t}")
            lo, hi = _INT_BOUNDS.get(t, _INT_BOUNDS["long"])
            if not lo <= v <= hi:
                raise MapperParsingError(f"value [{value}] out of range for {t} field [{self.name}]")
            return v
        if t in ("double", "float"):
            try:
                return float(value)
            except (TypeError, ValueError):
                raise MapperParsingError(f"failed to parse [{self.name}] value [{value}] as {t}")
        if t == "date":
            return parse_date(value, self.formats)
        if t == "boolean":
            if isinstance(value, bool):
                return 1 if value else 0
            return 1 if str(value).lower() in ("true", "1", "on", "yes") else 0
        if t == "ip":
            return parse_ip(value) if isinstance(value, str) else int(value)
        return value

    def to_mapping(self) -> dict:
        d: dict[str, Any] = {"type": "string" if self.type == "text" else self.type}
        if self.is_text and self.index != "analyzed":
            d["index"] = self.index
        elif not self.is_text and self.index == "no":
            d["index"] = "no"
        if self.store:
            d["store"] = True
        if self.boost != 1.0:
            d["boost"] = self.boost
        if self.analyzer:
            d["analyzer"] = self.analyzer
        if self.null_value is not None:
            d["null_value"] = self.null_value
        if self.copy_to:
            d["copy_to"] = self.copy_to
        return d


# meta-fields (ref: index/mapper/internal/ — _uid,_id,_type,_source,_all,_routing,...)
META_FIELDS = ("_uid", "_id", "_type", "_source", "_all", "_routing", "_parent",
               "_timestamp", "_ttl", "_version", "_size", "_index", "_boost")


@dataclass
class ParsedDocument:
    """Output of DocumentMapper.parse — what the segment builder consumes."""

    id: str
    type: str
    uid: str
    source: dict
    routing: str | None = None
    timestamp: int | None = None
    ttl: int | None = None
    parent: str | None = None
    # field → list[(term, position)] for analyzed/keyword postings
    postings: dict[str, list[tuple[str, int]]] = dc_field(default_factory=dict)
    # field → token count (for norms)
    field_lengths: dict[str, int] = dc_field(default_factory=dict)
    # field → numeric value(s) for columnar doc-values (list for multi-valued)
    doc_values_num: dict[str, list[float]] = dc_field(default_factory=dict)
    # field → raw keyword bytes values for columnar term store
    doc_values_str: dict[str, list[str]] = dc_field(default_factory=dict)
    # nested sub-documents (block-join style): list of (path, ParsedDocument-lite)
    nested_docs: list[tuple[str, "ParsedDocument"]] = dc_field(default_factory=list)


class FieldMapper:
    """One field's parse behavior. Kept minimal: FieldType + analyzer binding."""

    def __init__(self, ft: FieldType, analysis: AnalysisService):
        self.ft = ft
        self.analysis = analysis

    @property
    def index_analyzer(self) -> Analyzer:
        return self.analysis.analyzer(self.ft.analyzer)

    @property
    def search_analyzer(self) -> Analyzer:
        return self.analysis.analyzer(self.ft.search_analyzer or self.ft.analyzer)


def _infer_dynamic_type(value: Any, dynamic_date: bool = True) -> str | None:
    if isinstance(value, bool):
        return "boolean"
    if isinstance(value, int):
        return "long"
    if isinstance(value, float):
        return "double"
    if isinstance(value, str):
        if dynamic_date and _ISO_RE.match(value.strip()):
            return "date"
        return "string"
    if isinstance(value, dict):
        return "object"
    return None


class DocumentMapper:
    """Parses docs of one mapping type; holds the field-type registry for that type.
    (ref: index/mapper/DocumentMapper.java)"""

    def __init__(self, type_name: str, mapping: dict | None, analysis: AnalysisService,
                 index_settings: Settings | None = None):
        self.type = type_name
        self.analysis = analysis
        self.settings = index_settings or Settings.EMPTY
        mapping = mapping or {}
        self.meta = mapping.get("_meta", {})
        self.dynamic = mapping.get("dynamic", True)
        self.date_detection = mapping.get("date_detection", True)
        self.source_enabled = mapping.get("_source", {}).get("enabled", True)
        self.all_enabled = mapping.get("_all", {}).get("enabled", True)
        self.routing_required = mapping.get("_routing", {}).get("required", False)
        self.routing_path = mapping.get("_routing", {}).get("path")
        self.parent_type = mapping.get("_parent", {}).get("type")
        self.timestamp_enabled = mapping.get("_timestamp", {}).get("enabled", False)
        self.timestamp_path = mapping.get("_timestamp", {}).get("path")
        self.ttl_enabled = mapping.get("_ttl", {}).get("enabled", False)
        self.default_ttl = mapping.get("_ttl", {}).get("default")
        self.fields: dict[str, FieldType] = {}
        self._mapping_dirty = False
        self._parse_properties(mapping.get("properties", {}), prefix="", nested_path=None)

    # mapping registration ---------------------------------------------------
    def _parse_properties(self, props: dict, prefix: str, nested_path: str | None):
        for name, spec in props.items():
            full = f"{prefix}{name}"
            if not isinstance(spec, dict):
                raise MapperParsingError(f"invalid mapping for field [{full}]")
            ftype = spec.get("type")
            if ftype in (None, "object", "nested") and ("properties" in spec or ftype in ("object", "nested")):
                is_nested = ftype == "nested"
                self.fields[full] = FieldType(
                    name=full, type="object", nested=is_nested, properties=spec.get("properties", {})
                )
                self._parse_properties(
                    spec.get("properties", {}), prefix=f"{full}.",
                    nested_path=full if is_nested else nested_path,
                )
                continue
            if ftype == "multi_field":
                # legacy multi_field: subfields full.sub, default subfield aliased to full
                for sub, subspec in spec.get("fields", {}).items():
                    sub_full = full if sub == name else f"{full}.{sub}"
                    self.fields[sub_full] = self._field_type_from_spec(sub_full, subspec)
                continue
            ft = self._field_type_from_spec(full, spec)
            self.fields[full] = ft
            for sub, subspec in spec.get("fields", {}).items():
                self.fields[f"{full}.{sub}"] = self._field_type_from_spec(f"{full}.{sub}", subspec)

    def _field_type_from_spec(self, full: str, spec: dict) -> FieldType:
        ftype = spec.get("type", "string")
        if ftype == "text":
            ftype = "string"
        if ftype == "keyword":  # forward-compat alias: not_analyzed string
            ftype = "string"
            spec = {**spec, "index": "not_analyzed"}
        index = spec.get("index", "analyzed" if ftype in TEXT_TYPES else "yes")
        if index == "yes":
            index = "analyzed" if ftype in TEXT_TYPES else "not_analyzed"
        copy_to = spec.get("copy_to", [])
        if isinstance(copy_to, str):
            copy_to = [copy_to]
        return FieldType(
            name=full,
            type=ftype,
            index=index,
            store=bool(spec.get("store", False) in (True, "yes", "true")),
            boost=float(spec.get("boost", 1.0)),
            analyzer=spec.get("analyzer") or spec.get("index_analyzer"),
            search_analyzer=spec.get("search_analyzer"),
            formats=[spec["format"]] if "format" in spec else None,
            null_value=spec.get("null_value"),
            include_in_all=spec.get("include_in_all", True),
            precision_step=spec.get("precision_step"),
            doc_values=spec.get("doc_values", True),
            copy_to=copy_to,
        )

    def field_type(self, name: str) -> FieldType | None:
        return self.fields.get(name)

    # parsing ----------------------------------------------------------------
    def parse(self, source: dict, doc_id: str, routing: str | None = None,
              timestamp=None, ttl=None, parent: str | None = None) -> ParsedDocument:
        if not isinstance(source, dict):
            raise MapperParsingError("document source must be an object")
        doc = ParsedDocument(
            id=doc_id, type=self.type, uid=f"{self.type}#{doc_id}", source=source,
            routing=routing, parent=parent,
        )
        # an explicit timestamp always takes effect (it drives _ttl expiry); the
        # _timestamp docvalue is only stored when the meta-field is enabled
        if timestamp is not None:
            doc.timestamp = parse_date(timestamp)
        elif self.timestamp_enabled:
            if self.timestamp_path and self.timestamp_path in source:
                doc.timestamp = parse_date(source[self.timestamp_path])
            else:
                import time

                doc.timestamp = int(time.time() * 1000)
        if self.ttl_enabled:
            from ..common.units import parse_time

            raw_ttl = ttl if ttl is not None else self.default_ttl
            if raw_ttl is not None:
                doc.ttl = int(parse_time(raw_ttl) * 1000) if isinstance(raw_ttl, str) else int(raw_ttl)
        if self.routing_path and routing is None and self.routing_path in source:
            doc.routing = str(source[self.routing_path])
        if parent is not None:
            # child doc: store the parent pointer for join queries and route by it
            doc.parent = str(parent)
            doc.doc_values_str["_parent"] = [doc.parent]
            doc.postings["_parent"] = [(f"{self.parent_type or 'doc'}#{doc.parent}", 0)]
            if doc.routing is None:
                doc.routing = doc.parent
        if doc.ttl is not None:
            import time as _time

            base_ts = doc.timestamp if doc.timestamp is not None else int(
                _time.time() * 1000)
            expiry = base_ts + doc.ttl
            if expiry < int(_time.time() * 1000):
                from ..common.errors import AlreadyExpiredError

                raise AlreadyExpiredError(
                    f"already expired [{doc_id}]: expiry [{expiry}] < now")
            doc.doc_values_num["_expiry"] = [float(expiry)]
        if doc.timestamp is not None and self.timestamp_enabled:
            doc.doc_values_num["_timestamp"] = [float(doc.timestamp)]
        all_terms: list[tuple[str, int]] = []
        self._parse_object(source, "", doc, all_terms, nested_path=None)
        if self.all_enabled and all_terms:
            doc.postings["_all"] = all_terms
            doc.field_lengths["_all"] = len(all_terms)
        # _uid postings so ids queries/lookups work like any term query
        doc.postings["_uid"] = [(doc.uid, 0)]
        doc.postings["_id"] = [(doc.id, 0)]
        doc.postings["_type"] = [(self.type, 0)]
        return doc

    def _parse_object(self, obj: dict, prefix: str, doc: ParsedDocument,
                      all_terms: list, nested_path: str | None):
        for key, value in obj.items():
            if key in META_FIELDS:
                continue
            full = f"{prefix}{key}"
            ft = self.fields.get(full)
            if isinstance(value, dict) and (ft is None or ft.type == "object"):
                if ft is None:
                    if self.dynamic == "strict":
                        raise MapperParsingError(f"strict dynamic mapping: unknown field [{full}]")
                    if not self.dynamic:
                        continue
                    self.fields[full] = FieldType(name=full, type="object", properties={})
                    self._mapping_dirty = True
                    ft = self.fields[full]
                if ft.nested:
                    sub = ParsedDocument(id=doc.id, type=self.type, uid=doc.uid, source=value)
                    sub_all: list = []
                    self._parse_object(value, f"{full}.", sub, sub_all, nested_path=full)
                    doc.nested_docs.append((full, sub))
                else:
                    self._parse_object(value, f"{full}.", doc, all_terms, nested_path)
                continue
            values = value if isinstance(value, list) else [value]
            if values and all(isinstance(v, dict) for v in values) and ft is not None and ft.nested:
                for v in values:
                    sub = ParsedDocument(id=doc.id, type=self.type, uid=doc.uid, source=v)
                    sub_all: list = []
                    self._parse_object(v, f"{full}.", sub, sub_all, nested_path=full)
                    doc.nested_docs.append((full, sub))
                continue
            if values and all(isinstance(v, dict) for v in values) and (
                    ft is None or ft.type not in ("geo_point", "geo_shape")):
                # array of objects, non-nested: flatten each (geo types consume
                # their dict form as a leaf value: {lat,lon} / GeoJSON shape)
                for v in values:
                    self._parse_object(v, f"{full}.", doc, all_terms, nested_path)
                continue
            if ft is None:
                if self.dynamic == "strict":
                    raise MapperParsingError(f"strict dynamic mapping: unknown field [{full}]")
                if not self.dynamic:
                    continue
                sample = next((v for v in values if v is not None), None)
                inferred = _infer_dynamic_type(sample, self.date_detection)
                if inferred is None:
                    continue
                ft = self._field_type_from_spec(full, {"type": inferred})
                self.fields[full] = ft
                self._mapping_dirty = True
            self._index_values(ft, values, doc, all_terms)
            for target in ft.copy_to:
                tft = self.fields.get(target)
                if tft is None:
                    tft = self._field_type_from_spec(target, {"type": ft.type})
                    self.fields[target] = tft
                    self._mapping_dirty = True
                self._index_values(tft, values, doc, all_terms=[])

    def _index_values(self, ft: FieldType, values: list, doc: ParsedDocument, all_terms: list):
        if not ft.searchable and not ft.doc_values:
            return
        if ft.is_text:
            mapper = FieldMapper(ft, self.analysis)
            terms = doc.postings.setdefault(ft.name, [])
            pos_base = doc.field_lengths.get(ft.name, 0)
            for v in values:
                if v is None:
                    if ft.null_value is None:
                        continue
                    v = ft.null_value
                text = str(v)
                if ft.analyzed:
                    toks = mapper.index_analyzer.index_tokens(text)
                    for term, pos in toks:
                        terms.append((term, pos_base + pos))
                        if ft.include_in_all and self.all_enabled:
                            all_terms.append((term, len(all_terms)))
                    pos_base += len(toks) + 100  # position gap between values (Lucene default)
                else:
                    terms.append((text, pos_base))
                    pos_base += 1
                    if ft.include_in_all and self.all_enabled:
                        all_terms.append((text, len(all_terms)))
                doc.doc_values_str.setdefault(ft.name, []).extend(
                    t for t in ([text] if not ft.analyzed else [text])
                )
            doc.field_lengths[ft.name] = len(terms)
        elif ft.is_numeric:
            col = doc.doc_values_num.setdefault(ft.name, [])
            for v in values:
                cv = ft.coerce(v)
                if cv is not None:
                    col.append(float(cv))
            if not col:
                doc.doc_values_num.pop(ft.name, None)
        elif ft.type == "geo_point":
            for lat, lon in _parse_geo_points(values):
                doc.doc_values_num.setdefault(f"{ft.name}.lat", []).append(lat)
                doc.doc_values_num.setdefault(f"{ft.name}.lon", []).append(lon)
        elif ft.type == "geo_shape":
            # shape stored columnar as canonical JSON (the dv_str column persists
            # with the segment); relations evaluate host-side from the parsed form —
            # the TPU-framework replacement for the reference's spatial prefix-tree
            # terms (ref: index/mapper/geo/GeoShapeFieldMapper.java)
            import json as _json

            from ..common.geo import normalize_shape

            for v in values:
                if not isinstance(v, dict):
                    raise MapperParsingError(f"failed to parse geo_shape [{v}]")
                try:
                    kind, data = normalize_shape(v)
                except ValueError as e:
                    raise MapperParsingError(str(e))
                doc.doc_values_str.setdefault(ft.name, []).append(
                    _json.dumps([kind, data], separators=(",", ":")))
        elif ft.type == "binary":
            pass  # stored via _source only
        else:
            # unknown types degrade to keyword storage
            for v in values:
                if v is not None:
                    doc.doc_values_str.setdefault(ft.name, []).append(str(v))

    # mapping output / merge -------------------------------------------------
    def to_mapping(self) -> dict:
        props: dict[str, Any] = {}
        multi = []  # (parent_parts, leaf, ft) — rendered under the parent's "fields"
        for name, ft in sorted(self.fields.items()):
            if ft.type == "object":
                continue
            parts = name.split(".")
            parent = self.fields.get(".".join(parts[:-1])) if len(parts) > 1 else None
            if parent is not None and parent.type != "object":
                multi.append((parts[:-1], parts[-1], ft))
                continue
            node = props
            for p in parts[:-1]:
                obj_ft = self.fields.get(".".join(parts[: parts.index(p) + 1]))
                node = node.setdefault(p, {"type": "nested"} if obj_ft and obj_ft.nested else {})
                node = node.setdefault("properties", {})
            node[parts[-1]] = ft.to_mapping()
        for parent_parts, leaf, ft in multi:
            node = props
            for i, p in enumerate(parent_parts):
                if i:
                    node = node.setdefault("properties", {})
                node = node.setdefault(p, {})
            node.setdefault("fields", {})[leaf] = ft.to_mapping()
        out: dict[str, Any] = {"properties": props}
        if not self.source_enabled:
            out["_source"] = {"enabled": False}
        if not self.all_enabled:
            out["_all"] = {"enabled": False}
        if self.routing_required or self.routing_path:
            out["_routing"] = {k: v for k, v in
                               (("required", self.routing_required), ("path", self.routing_path)) if v}
        if self.parent_type:
            out["_parent"] = {"type": self.parent_type}
        if self.timestamp_enabled:
            out["_timestamp"] = {"enabled": True}
        if self.ttl_enabled:
            out["_ttl"] = {"enabled": True}
        return out

    def merge(self, new_mapping: dict, simulate: bool = False) -> list[str]:
        """Merge another mapping for this type; returns conflict messages.
        (ref: DocumentMapper merge + MergeContext conflict collection)"""
        other = DocumentMapper(self.type, new_mapping, self.analysis, self.settings)
        conflicts = []
        for name, ft in other.fields.items():
            mine = self.fields.get(name)
            if mine is None:
                if not simulate:
                    self.fields[name] = ft
            else:
                if mine.type != ft.type and not {mine.type, ft.type} <= {"object"}:
                    conflicts.append(
                        f"mapper [{name}] of different type, current [{mine.type}], merged [{ft.type}]"
                    )
                elif mine.index != ft.index:
                    conflicts.append(f"mapper [{name}] has different index values")
                elif mine.analyzer != ft.analyzer:
                    conflicts.append(f"mapper [{name}] has different analyzer")
        return conflicts


def _parse_geo_points(values: list) -> list[tuple[float, float]]:
    """One or many points: dict {lat,lon} / "lat,lon" / geohash / [lon,lat] —
    a bare numeric pair is ONE point (GeoJSON), anything else is per-element."""
    if len(values) == 2 and all(isinstance(x, (int, float)) for x in values):
        return [(float(values[1]), float(values[0]))]
    return [_parse_geo_point(v) for v in values]


def _parse_geo_point(v) -> tuple[float, float]:
    if isinstance(v, dict):
        return float(v["lat"]), float(v["lon"])
    if isinstance(v, str):
        if "," in v:
            lat, lon = v.split(",")
            return float(lat), float(lon)
        # bare string = geohash (ref: GeoPointFieldMapper geohash support)
        from ..common.geo import geohash_decode

        try:
            return geohash_decode(v.strip().lower())
        except (KeyError, ValueError):
            raise MapperParsingError(f"failed to parse geohash [{v}]")
    if isinstance(v, list):
        if len(v) == 2 and all(isinstance(x, (int, float)) for x in v):
            return float(v[1]), float(v[0])  # GeoJSON order [lon, lat]
    raise MapperParsingError(f"failed to parse geo_point [{v}]")


class MapperService:
    """type → DocumentMapper registry for one index (ref: index/mapper/MapperService.java).
    Looks up field types across all mapping types; `smart_field` resolves `type.field`."""

    DEFAULT_TYPE = "_default_"

    def __init__(self, index_settings: Settings | None = None,
                 analysis: AnalysisService | None = None):
        self.settings = index_settings or Settings.EMPTY
        self.analysis = analysis or AnalysisService(self.settings)
        self.mappers: dict[str, DocumentMapper] = {}
        self._default_mapping: dict = {}

    def put_mapping(self, type_name: str, mapping: dict, merge: bool = True) -> list[str]:
        body = mapping.get(type_name, mapping)
        if type_name == self.DEFAULT_TYPE:
            self._default_mapping = body
            return []
        existing = self.mappers.get(type_name)
        if existing is not None and merge:
            conflicts = existing.merge(body)
            if conflicts:
                from ..common.errors import MapperParsingError as MPE

                raise MPE(f"mapping merge conflicts: {conflicts}")
            return conflicts
        merged_body = dict(self._default_mapping)
        merged_body.update(body)
        self.mappers[type_name] = DocumentMapper(type_name, merged_body, self.analysis, self.settings)
        return []

    def mapper_for(self, type_name: str, create_if_missing: bool = True) -> DocumentMapper:
        m = self.mappers.get(type_name)
        if m is None:
            if not create_if_missing:
                from ..common.errors import TypeMissingError

                raise TypeMissingError(f"no mapping for type [{type_name}]")
            m = DocumentMapper(type_name, dict(self._default_mapping), self.analysis, self.settings)
            self.mappers[type_name] = m
        return m

    def types(self) -> list[str]:
        return list(self.mappers)

    def field_type(self, field: str, types: list[str] | None = None) -> FieldType | None:
        for tname, mapper in self.mappers.items():
            if types and tname not in types:
                continue
            ft = mapper.field_type(field)
            if ft is not None:
                return ft
        return None

    def search_analyzer_for(self, field: str) -> Analyzer:
        ft = self.field_type(field)
        if ft is None or not ft.is_text:
            return self.analysis.analyzer("default")
        return FieldMapper(ft, self.analysis).search_analyzer

    def mappings_dict(self) -> dict:
        return {t: m.to_mapping() for t, m in self.mappers.items()}
