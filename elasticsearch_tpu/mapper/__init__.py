from .core import (  # noqa: F401
    FieldType,
    FieldMapper,
    DocumentMapper,
    MapperService,
    ParsedDocument,
    parse_date,
)
