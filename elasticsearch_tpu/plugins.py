"""Plugin system.

Analogue of plugins/PluginsService.java (SURVEY.md §2.7): plugins are discovered in
`path.plugins` (default `<data>/plugins`) and from the `plugin.types` setting. The
reference's `es-plugin.properties` naming a Plugin class becomes: a plugin is a python
file/package whose module defines a `Plugin` subclass (or a `plugin` factory). Plugins
can contribute settings defaults, lifecycle hooks, and REST routes — the same extension
points the reference exposes through extra Guice modules/services/REST handlers.
"""

from __future__ import annotations

import importlib
import importlib.util
import os
import sys

from .common.logging import get_logger


class Plugin:
    """Base class. Override what you need; name/description appear in nodes_info."""

    name = "unnamed-plugin"
    description = ""

    def additional_settings(self) -> dict:
        """Defaults merged under the node's settings (lowest precedence)."""
        return {}

    def on_node_created(self, node) -> None:
        """Called after services are constructed, before discovery starts."""

    def on_node_started(self, node) -> None:
        """Called after the node joined the cluster."""

    def on_node_closed(self, node) -> None:
        """Called during node shutdown."""

    def rest_routes(self, controller, node) -> None:
        """Register extra REST handlers: controller.register(method, path, fn)."""


class PluginsService:
    """Discovers + holds plugin instances for one node."""

    def __init__(self, settings, data_path: str):
        self.logger = get_logger("plugins")
        self.plugins: list[Plugin] = []
        # 1) explicit classes: plugin.types = ["mypkg.mymod.MyPlugin", ...]
        for spec in settings.get_list("plugin.types", []):
            cls = self._load_class(spec)
            if cls is not None:
                self._instantiate(cls)
        # 2) directory scan (ref: PluginsService scans plugins/)
        plugin_dir = settings.get_str("path.plugins") or os.path.join(data_path, "plugins")
        if os.path.isdir(plugin_dir):
            for entry in sorted(os.listdir(plugin_dir)):
                path = os.path.join(plugin_dir, entry)
                if entry.endswith(".py"):
                    self._load_file(entry[:-3], path)
                elif os.path.isdir(path) and \
                        os.path.isfile(os.path.join(path, "__init__.py")):
                    self._load_file(entry, os.path.join(path, "__init__.py"))

    def _load_class(self, spec: str):
        mod_name, _, cls_name = spec.rpartition(".")
        try:
            return getattr(importlib.import_module(mod_name), cls_name)
        except (ImportError, AttributeError) as e:
            self.logger.warning("failed to load plugin [%s]: %s", spec, e)
            return None

    def _load_file(self, name: str, path: str):
        try:
            mod_key = f"estpu_plugin_{name}"
            spec = importlib.util.spec_from_file_location(mod_key, path)
            module = importlib.util.module_from_spec(spec)
            sys.modules[mod_key] = module
            spec.loader.exec_module(module)
        except Exception as e:  # noqa: BLE001 — a broken plugin must not kill the node
            self.logger.warning("failed to load plugin file [%s]: %s", path, e)
            return
        factory = getattr(module, "plugin", None)
        if callable(factory) and not isinstance(factory, type):
            try:
                self.plugins.append(factory())
                return
            except Exception as e:  # noqa: BLE001
                self.logger.warning("plugin factory [%s] failed: %s", name, e)
                return
        for attr in vars(module).values():
            if isinstance(attr, type) and issubclass(attr, Plugin) and attr is not Plugin:
                self._instantiate(attr)
                return
        self.logger.warning("no Plugin subclass in [%s]", path)

    def _instantiate(self, cls):
        try:
            self.plugins.append(cls())
        except Exception as e:  # noqa: BLE001
            self.logger.warning("plugin [%s] failed to construct: %s", cls, e)

    # ------------------------------------------------------------------ hooks
    def additional_settings(self) -> dict:
        out: dict = {}
        for p in self.plugins:
            out.update(p.additional_settings() or {})
        return out

    def on_node_created(self, node):
        for p in self.plugins:
            p.on_node_created(node)

    def on_node_started(self, node):
        for p in self.plugins:
            p.on_node_started(node)

    def on_node_closed(self, node):
        for p in self.plugins:
            try:
                p.on_node_closed(node)
            except Exception:  # noqa: BLE001
                pass

    def rest_routes(self, controller, node):
        for p in self.plugins:
            p.rest_routes(controller, node)

    def info(self) -> list[dict]:
        return [{"name": p.name, "description": p.description,
                 "jvm": False, "site": False} for p in self.plugins]
