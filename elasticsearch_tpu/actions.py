"""Action layer: the API kernel over transport.

Analogue of action/ (69k LoC — SURVEY.md §2.6). Each API is a transport action
implementing one of the reference's interaction patterns (action/support/):

- master-node  (TransportMasterNodeOperationAction): forwarded to the elected master,
  which mutates cluster state through the single-threaded executor → publish.
  [create/delete/open/close index, mappings, settings, aliases, templates, reroute]
- replication  (TransportShardReplicationOperationAction): route to primary by djb2,
  write-consistency precheck, primary op, fan to assigned replicas, ack.
  [index, delete, bulk per-shard groups, update (get-modify-reindex on primary)]
- single-shard (TransportSingleShardOperationAction): one active copy, realtime.
  [get, multi_get, explain, termvector-lite]
- scatter-gather (TransportSearchTypeAction): one copy per shard group, per-shard
  query phase (+ optional DFS pre-phase), controller reduce, fetch winners, per-shard
  failover to the next copy on failure.
  [search (query_then_fetch / dfs_query_then_fetch / count / scan), msearch, count,
   suggest, delete_by_query (broadcast), refresh/flush/optimize (broadcast)]
"""

from __future__ import annotations

import base64
import contextlib
import threading
import time
import uuid
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError

from .common import insights as _insights
from .common import profile as profiling
from .common import tracing
from .common.units import parse_time
from .common.deadline import NO_DEADLINE, Deadline
from .common.metrics import HistogramMetric
from .common.retry import RetryPolicy
from .common.errors import (
    ActionNotFoundError,
    CircuitBreakingError,
    DocumentMissingError,
    IllegalArgumentError,
    IndexAlreadyExistsError,
    IndexMissingError,
    MasterNotDiscoveredError,
    NoShardAvailableError,
    ReceiveTimeoutError,
    RejectedExecutionError,
    IndexWarmerMissingError,
    SearchEngineError,
    TransportError,
    TypeMissingError,
    UnavailableShardsError,
    VersionConflictError,
)
from .common.logging import get_logger
from .common.settings import Settings, validate_index_name
from .cluster.allocation import new_index_routing
from .cluster.service import HIGH, URGENT
from .cluster.state import (
    BLOCK_INDEX_CLOSED,
    ClusterState,
    IndexMetaData,
    IndexTemplateMetaData,
    ShardRouting,
)
from .index.translog import CREATE, DELETE, INDEX, TranslogOp
from .indices_service import ACTION_SHARD_FAILED, ACTION_SHARD_STARTED
from .search.queries import resolve_terms_lookups
from .search.request_cache import cache_policy, request_fingerprint
from .search.controller import (
    aggregate_dfs,
    collect_dfs,
    DfsResult,
    merge_responses,
    sort_docs,
)
from .search.execute import ShardContext
from .transport import fut_result
from .transport.service import complete_fut
from .search.queries import parse_query
from .search.service import (
    ParsedSearchRequest,
    ShardQueryResult,
    execute_fetch_phase,
    execute_query_phase,
    parse_search_body,
)

A_CREATE_INDEX = "indices:admin/create"
A_DELETE_INDEX = "indices:admin/delete"
A_OPEN_INDEX = "indices:admin/open"
A_CLOSE_INDEX = "indices:admin/close"
A_PUT_MAPPING = "indices:admin/mapping/put"
A_DELETE_MAPPING = "indices:admin/mapping/delete"
A_UPDATE_SETTINGS = "indices:admin/settings/update"
A_ALIASES = "indices:admin/aliases"
A_PUT_TEMPLATE = "indices:admin/template/put"
A_DELETE_TEMPLATE = "indices:admin/template/delete"
A_CLUSTER_SETTINGS = "cluster:admin/settings/update"
A_REROUTE = "cluster:admin/reroute"
A_SHUTDOWN_NODE = "cluster:admin/nodes/shutdown"
A_MAPPING_UPDATED = "internal:cluster/mapping_updated"

A_INDEX_PRIMARY = "indices:data/write/index[p]"
A_INDEX_REPLICA = "indices:data/write/index[r]"
A_DELETE_PRIMARY = "indices:data/write/delete[p]"
A_DELETE_REPLICA = "indices:data/write/delete[r]"
A_BULK_SHARD = "indices:data/write/bulk[s]"
A_GET = "indices:data/read/get[s]"
A_TERMVECTOR = "indices:data/read/termvector[s]"
A_QUERY_PHASE = "indices:data/read/search[phase/query]"
A_FETCH_PHASE = "indices:data/read/search[phase/fetch]"
A_FREE_CONTEXT = "indices:data/read/search[free-context]"
A_DFS_PHASE = "indices:data/read/search[phase/dfs]"
A_SHARD_BROADCAST = "indices:admin/broadcast[s]"
# stall-watchdog event gossip (common/events.py): a node's warn events are
# pushed best-effort to every peer's journal so any coordinator's /_events
# shows the cluster-wide causal record
A_EVENTS_PUBLISH = "internal:cluster/events/publish"


def _normalize_alias_specs(aliases: dict) -> dict:
    """Alias metadata stores index_routing/search_routing; a bare `routing` key sets
    both (ref: cluster/metadata/AliasMetaData + AliasAction semantics)."""
    out = {}
    for name, spec in aliases.items():
        spec = dict(spec or {})
        spec = {k: v for k, v in spec.items()
                if k in ("filter", "index_routing", "search_routing", "routing")}
        if "routing" in spec:
            r = spec.pop("routing")
            spec.setdefault("index_routing", r)
            spec.setdefault("search_routing", r)
        out[name] = spec
    return out


def _normalize_warmer(body) -> dict:
    """Warmer metadata is {types, source} (ref: search/warmer/IndexWarmersMetaData);
    a bare search body becomes the source."""
    body = dict(body or {})
    if "source" in body:
        return {"types": body.get("types") or [], "source": body["source"]}
    types = body.pop("types", []) or []
    return {"types": types, "source": body}


class ActionModule:
    """Registers every handler on one node + provides coordinator entry points."""

    def __init__(self, node):
        self.node = node
        self.transport = node.transport
        self.cluster_service = node.cluster_service
        self.indices = node.indices
        self.routing = node.operation_routing
        self.allocation = node.allocation
        self.logger = get_logger("action", node=node.name)
        # SPMD mesh serving for co-located shards (ICI data plane as the search path;
        # ref: the scatter-gather in TransportSearchTypeAction.java:117 this bypasses)
        from .parallel.mesh_serving import MeshServingService

        self.mesh_serving = MeshServingService(node.indices, node.settings,
                                               node_name=node.name)
        self.mesh_serving.pin_context = self._pin_context
        # plain mesh searches coalesce through the same cross-request queue as
        # the transport path's single-shard launches (search/batcher.py)
        self.mesh_serving.batcher = getattr(node, "search_batcher", None)
        # point-in-time contexts pinned between the query and fetch phases (the
        # reference's SearchService active-contexts map: a merge/refresh between
        # phases must not move local doc ids under the fetch — SearchContext
        # holds the query-time searcher; ref SearchService.java:177,315)
        self._pinned: dict[int, tuple] = {}  # cid -> (expiry, index, shard, ctx)
        self._pinned_lock = threading.Lock()
        self._pinned_next = [1]
        # write-path retry schedule (replica fan-out, shard-failed reports):
        # transient transport failures back off with decorrelated jitter, then
        # exhaustion is REPORTED to the master — never swallowed (tests swap in
        # a faster policy)
        self.retry_policy = RetryPolicy(max_attempts=3, base_s=0.05, cap_s=1.0)
        # deadline-aware admission control: searches whose remaining budget
        # cannot cover one observed shard phase are 429'd BEFORE the fan-out
        from .search.service import SearchAdmissionController

        self.admission = SearchAdmissionController()
        # parsed cluster-level slowlog thresholds, cached against the
        # metadata version that produced them: the unset-thresholds default
        # must not rebuild the flattened settings dict per query phase
        # (plain attr, single value — a benign race rebuilds once)
        self._slowlog_cluster: tuple | None = None
        # end-to-end coordinator search latency (accept -> response assembled):
        # the histogram behind /_nodes/stats search.latency percentiles and
        # the Prometheus estpu_search_latency_seconds series
        self.search_latency = HistogramMetric()
        t = self.transport
        # master-node actions
        for action, fn in [
            (A_CREATE_INDEX, self._m_create_index),
            (A_DELETE_INDEX, self._m_delete_index),
            (A_OPEN_INDEX, self._m_open_index),
            (A_CLOSE_INDEX, self._m_close_index),
            (A_PUT_MAPPING, self._m_put_mapping),
            (A_DELETE_MAPPING, self._m_delete_mapping),
            (A_UPDATE_SETTINGS, self._m_update_settings),
            (A_ALIASES, self._m_aliases),
            (A_PUT_TEMPLATE, self._m_put_template),
            (A_DELETE_TEMPLATE, self._m_delete_template),
            (A_CLUSTER_SETTINGS, self._m_cluster_settings),
            ("indices:admin/warmers/put", self._m_put_warmer),
            ("indices:admin/warmers/delete", self._m_delete_warmer),
            (A_REROUTE, self._m_reroute),
            (A_MAPPING_UPDATED, self._m_mapping_updated),
            (ACTION_SHARD_STARTED, self._m_shard_started),
            (ACTION_SHARD_FAILED, self._m_shard_failed),
        ]:
            t.register_handler(action, self._master_wrap(action, fn))
        # data-path actions, each on its named pool (ref: every TransportAction names
        # its ThreadPool executor — search ops on SEARCH, writes on INDEX/BULK, …).
        # The dispatch trampoline ("generic") then never blocks on handler work, so
        # concurrent fan-outs can't starve it into a deadlock.
        t.register_handler(A_INDEX_PRIMARY, self._p_index, executor="index")
        t.register_handler(A_INDEX_REPLICA, self._r_index, executor="replica")
        t.register_handler(A_DELETE_PRIMARY, self._p_delete, executor="index")
        t.register_handler(A_DELETE_REPLICA, self._r_delete, executor="replica")
        t.register_handler(A_BULK_SHARD, self._p_bulk_shard, executor="bulk")
        t.register_handler(A_GET, self._s_get, executor="get")
        t.register_handler(A_TERMVECTOR, self._s_termvector, executor="get")
        t.register_handler(A_QUERY_PHASE, self._s_query_phase, executor="search")
        t.register_handler(A_FETCH_PHASE, self._s_fetch_phase, executor="search")
        t.register_handler(A_FREE_CONTEXT, self._s_free_context, executor="search")
        t.register_handler(A_DFS_PHASE, self._s_dfs_phase, executor="search")
        t.register_handler(A_SHARD_BROADCAST, self._s_broadcast, executor="management")
        # sniffing TransportClient surface (ref: TransportClientNodesService — the
        # sampler asks for the node list; every API call arrives as a typed proxy)
        from .client import A_CLIENT_EXEC, A_CLIENT_NODES

        t.register_handler(A_CLIENT_NODES, self._s_client_nodes, executor="management")
        t.register_handler(A_CLIENT_EXEC, self._s_client_exec, executor="generic")
        t.register_handler(A_SHUTDOWN_NODE, self._s_shutdown_node,
                           executor="management")
        t.register_handler(A_EVENTS_PUBLISH, self._s_event_publish,
                           executor="management")

    def _s_event_publish(self, request, channel):
        """Gossip ingestion: a peer's watchdog event lands in this node's
        journal, dedup'd by origin seq (common/events.EventJournal.ingest)."""
        journal = getattr(self.node, "events", None)
        stored = journal.ingest(request.get("event") or {}) \
            if journal is not None else False
        return {"stored": stored}

    # ================= node shutdown =================
    def nodes_shutdown(self, node_ids=None, delay_s: float = 0.2) -> dict:
        """ref: TransportNodesShutdownAction — fan a shutdown order to the
        resolved nodes; each closes itself after `delay` (so the ack can make
        it back out first). node_ids: None/_all, _local, _master, or ids/names."""
        state = self.cluster_service.state
        targets = []
        spec = node_ids
        if spec in (None, "", "_all"):
            targets = list(state.nodes.nodes)
        else:
            wanted = [s.strip() for s in str(spec).split(",") if s.strip()]
            for w in wanted:
                if w == "_local":
                    targets.append(state.nodes.get(self.node.local_node.id))
                elif w == "_master":
                    targets.append(state.nodes.master)
                else:
                    targets.extend(n for n in state.nodes.nodes
                                   if n.id == w or n.name == w)
        targets = [t2 for t2 in targets if t2 is not None]
        acked = {}
        for n in targets:
            try:
                self.transport.submit_request(
                    n, A_SHUTDOWN_NODE, {"delay_s": delay_s}, timeout=10.0)
                acked[n.id] = {"name": n.name}
            except SearchEngineError:
                pass  # already gone — shutdown is best-effort, like the reference
        return {"cluster_name": state.cluster_name, "nodes": acked}

    def _s_shutdown_node(self, request, channel):
        delay = float(request.get("delay_s", 0.2))

        def _close():
            time.sleep(delay)
            try:
                self.node.close()
            except Exception:  # noqa: BLE001 — shutdown must not raise upward
                pass

        threading.Thread(target=_close, daemon=True,
                         name=f"estpu-shutdown[{self.node.name}]").start()
        return {"ok": True}

    # ================= transport-client proxy =================
    def _s_client_nodes(self, request, channel):
        state = self.cluster_service.state
        return {"nodes": [[n.id, n.name, n.transport_address]
                          for n in state.nodes.nodes]}

    def _s_client_exec(self, request, channel):
        from .client import CLIENT_PROXY_METHODS

        method = str(request.get("method"))
        if method not in CLIENT_PROXY_METHODS:
            raise ActionNotFoundError(f"client method [{method}] is not proxied")
        fn = getattr(self.node.client(), method)
        return {"r": fn(**(request.get("kwargs") or {}))}

    # ================= master-node pattern =================
    def _master_wrap(self, action, fn):
        def handler(request, channel):
            state = self.cluster_service.state
            if state.nodes.master_id is None:
                raise MasterNotDiscoveredError("no master")
            if state.nodes.master_id != self.node.node_id:
                # forward to master (ref: TransportMasterNodeOperationAction)
                master = state.nodes.master
                return self.transport.submit_request(master.transport_address, action,
                                                     request, timeout=30.0)
            return fn(request, channel)

        return handler

    def _submit(self, source, fn, priority=HIGH, timeout=30.0) -> ClusterState:
        return self.cluster_service.submit_state_update_task(source, fn, priority) \
            .result(timeout)

    def _m_create_index(self, request, channel):
        index = request["index"]
        validate_index_name(index)
        body = request.get("body") or {}

        def update(state: ClusterState) -> ClusterState:
            if state.metadata.has_index(index):
                raise IndexAlreadyExistsError(index)
            settings = {(k if k.startswith("index.") else f"index.{k}"): v
                        for k, v in Settings.from_flat(
                            body.get("settings") or {}).as_dict().items()}
            mappings = dict(body.get("mappings") or {})
            aliases = dict(body.get("aliases") or {})
            # apply matching templates lowest order first (ref: IndexTemplateMetaData)
            for tpl in state.metadata.templates_for(index):
                merged = dict(tpl.settings_map)
                merged.update(Settings.from_flat(settings).as_dict())
                settings = merged
                import json as _json

                for ttype, m in tpl.mappings:
                    mappings.setdefault(ttype, _json.loads(m) if isinstance(m, str) else m)
                for a, spec in tpl.aliases:
                    aliases.setdefault(a, spec)
            flat = {(k if k.startswith("index.") else f"index.{k}"): v
                    for k, v in Settings.from_flat(settings).as_dict().items()}
            flat.setdefault("index.number_of_shards", 5)
            flat.setdefault("index.number_of_replicas", 1)
            flat["index.number_of_shards"] = int(flat["index.number_of_shards"])
            flat["index.number_of_replicas"] = int(flat["index.number_of_replicas"])
            meta = IndexMetaData(
                name=index, settings_map=tuple(sorted(flat.items())),
            )
            for t, m in mappings.items():
                meta = meta.with_mapping(t, m)
            if aliases:
                meta = meta.with_aliases(_normalize_alias_specs(aliases))
            for wname, wbody in (body.get("warmers") or {}).items():
                meta = meta.with_warmer(wname, _normalize_warmer(wbody))
            new = state.next_version(
                metadata=state.metadata.with_index(meta),
                routing_table=state.routing_table.with_index(
                    new_index_routing(index, meta.number_of_shards,
                                      meta.number_of_replicas)),
            )
            return self.allocation.reroute(new)

        self._submit(f"create-index[{index}]", update, priority=URGENT)
        ok = self._wait_for_active_primaries(index, timeout=10.0)
        return {"acknowledged": True, "index": index, "primaries_active": ok}

    def _m_delete_index(self, request, channel):
        indices = self.cluster_service.state.metadata.resolve_indices(request["index"])

        def update(state: ClusterState) -> ClusterState:
            md, rt, blocks = state.metadata, state.routing_table, state.blocks
            for index in indices:
                md = md.without_index(index)
                rt = rt.without_index(index)
                blocks = blocks.without_index(index)
            return state.next_version(metadata=md, routing_table=rt, blocks=blocks)

        self._submit(f"delete-index{indices}", update, priority=URGENT)
        return {"acknowledged": True}

    def _m_open_index(self, request, channel):
        return self._set_index_state(request["index"], "open")

    def _m_close_index(self, request, channel):
        return self._set_index_state(request["index"], "close")

    def _set_index_state(self, index_expr, target):
        indices = self.cluster_service.state.metadata.resolve_indices(index_expr)

        def update(state: ClusterState) -> ClusterState:
            md, rt, blocks = state.metadata, state.routing_table, state.blocks
            from dataclasses import replace as _replace

            for index in indices:
                meta = md.require_index(index)
                md = md.with_index(_replace(meta, state=target, version=meta.version + 1))
                if target == "close":
                    rt = rt.without_index(index)
                    blocks = blocks.with_index_block(index, BLOCK_INDEX_CLOSED)
                else:
                    rt = rt.with_index(new_index_routing(
                        index, meta.number_of_shards, meta.number_of_replicas))
                    blocks = blocks.without_index(index)
            new = state.next_version(metadata=md, routing_table=rt, blocks=blocks)
            return self.allocation.reroute(new)

        self._submit(f"{target}-index{indices}", update, priority=URGENT)
        return {"acknowledged": True}

    def _m_put_mapping(self, request, channel):
        indices = self.cluster_service.state.metadata.resolve_indices(request["index"])
        type_name = request["type"]
        mapping = request["body"].get(type_name, request["body"])

        def update(state: ClusterState) -> ClusterState:
            md = state.metadata
            for index in indices:
                meta = md.require_index(index)
                existing = meta.mapping(type_name) or {}
                # validate merge via a throwaway mapper (conflicts raise)
                from .mapper import MapperService as MS

                svc = MS(meta.settings)
                if existing:
                    svc.put_mapping(type_name, existing)
                svc.put_mapping(type_name, mapping)
                merged_out = svc.mappings_dict()[type_name]
                md = md.with_index(meta.with_mapping(type_name, merged_out))
            return state.next_version(metadata=md)

        self._submit(f"put-mapping[{indices}/{type_name}]", update)
        return {"acknowledged": True}

    def _m_mapping_updated(self, request, channel):
        """Dynamic-mapping propagation from data nodes (ref: MappingUpdatedAction)."""
        return self._m_put_mapping(
            {"index": request["index"], "type": request["type"],
             "body": request["mapping"]}, channel)

    def _m_update_settings(self, request, channel):
        indices = self.cluster_service.state.metadata.resolve_indices(request["index"])
        flat = Settings.from_flat(request["body"].get("settings", request["body"])).as_dict()
        normalized = {}
        for k, v in flat.items():
            normalized[k if k.startswith("index.") else f"index.{k}"] = v

        # index.blocks.* settings install/remove the matching cluster blocks
        # (ref: IndexMetaData block settings → ClusterBlocks)
        block_keys = {"index.blocks.read_only": ("index_read_only", "write"),
                      "index.blocks.read": ("index_read", "read"),
                      "index.blocks.write": ("index_write", "write"),
                      "index.blocks.metadata": ("index_metadata", "metadata")}

        def update(state: ClusterState) -> ClusterState:
            md = state.metadata
            rt = state.routing_table
            blocks = state.blocks
            for index in indices:
                meta = md.require_index(index)
                old_replicas = meta.number_of_replicas
                meta = meta.with_settings(normalized)
                md = md.with_index(meta)
                if meta.number_of_replicas != old_replicas:
                    rt = self._resize_replicas(rt, index, meta.number_of_replicas)
                for key, block in block_keys.items():
                    if key in normalized:
                        on = str(normalized[key]).lower() in ("true", "1")
                        if on:
                            blocks = blocks.with_index_block(index, block)
                        else:
                            blocks = blocks.without_index_block(index, block)
            new = state.next_version(metadata=md, routing_table=rt, blocks=blocks)
            return self.allocation.reroute(new)

        self._submit(f"update-settings{indices}", update)
        return {"acknowledged": True}

    @staticmethod
    def _resize_replicas(rt, index, target):
        from dataclasses import replace as _replace

        from .cluster.state import IndexRoutingTable, IndexShardRoutingTable

        table = rt.index(index)
        groups = []
        for grp in table.shards:
            primary = [s for s in grp.shards if s.primary]
            replicas = [s for s in grp.shards if not s.primary]
            while len(replicas) > target:
                replicas.pop()
            while len(replicas) < target:
                replicas.append(ShardRouting(index, grp.shards[0].shard_id, None, False))
            groups.append(IndexShardRoutingTable(tuple(primary + replicas)))
        return rt.with_index(IndexRoutingTable(index, tuple(groups)))

    def _m_aliases(self, request, channel):
        actions = request["body"].get("actions", [])
        # resolve index expressions up-front so missing indices fail before mutation
        state0 = self.cluster_service.state
        resolved = []
        for entry in actions:
            (op, spec), = entry.items()
            indices = state0.metadata.resolve_indices(
                spec.get("index") or spec.get("indices") or "_all")
            aliases = spec.get("alias") or spec.get("aliases") or []
            if not isinstance(aliases, list):
                aliases = [a.strip() for a in str(aliases).split(",")]
            resolved.append((op, spec, indices, aliases))

        from .common.errors import AliasesMissingError
        from .common.names import name_matches

        # `remove` with wildcards must match something (ref: AliasesMissingException)
        for op, spec, indices, alias_exprs in resolved:
            if op != "remove":
                continue
            found = any(
                name_matches(a, expr)
                for index in indices
                for a, _ in state0.metadata.require_index(index).aliases
                for expr in alias_exprs)
            if not found:
                raise AliasesMissingError(alias_exprs)

        def update(state: ClusterState) -> ClusterState:
            md = state.metadata
            for op, spec, indices, alias_exprs in resolved:
                for index in indices:
                    meta = md.require_index(index)
                    aliases = dict(meta.aliases)
                    if op == "add":
                        for alias in alias_exprs:
                            aliases.update(_normalize_alias_specs({alias: spec}))
                    elif op == "remove":
                        for expr in alias_exprs:
                            for a in [a for a in aliases
                                      if name_matches(a, expr)]:
                                aliases.pop(a)
                    md = md.with_index(meta.with_aliases(aliases))
            return state.next_version(metadata=md)

        self._submit("aliases", update)
        return {"acknowledged": True}

    def _m_delete_mapping(self, request, channel):
        """ref: action/admin/indices/mapping/delete — drop the type's mapping and its
        documents from every resolved index."""
        state0 = self.cluster_service.state
        indices = state0.metadata.resolve_indices(request["index"])
        type_expr = request["type"]
        from .common.names import name_matches

        matched = {
            index: [t for t, _ in state0.metadata.require_index(index).mappings
                    if name_matches(t, type_expr)]
            for index in indices}
        if not any(matched.values()):
            raise TypeMissingError(f"type[[{type_expr}]] missing")

        def update(state: ClusterState) -> ClusterState:
            md = state.metadata
            for index, types in matched.items():
                meta = md.require_index(index)
                for t in types:
                    meta = meta.without_mapping(t)
                md = md.with_index(meta)
            return state.next_version(metadata=md)

        self._submit(f"delete-mapping[{indices}/{type_expr}]", update)
        # purge documents of the removed types locally (primary-owned shards)
        for index, types in matched.items():
            for t in types:
                try:
                    self.delete_by_query(index, {"query": {
                        "filtered": {"query": {"match_all": {}},
                                     "filter": {"type": {"value": t}}}}})
                except SearchEngineError as e:
                    self.logger.warning(
                        "delete-mapping [%s/%s]: mapping removed but doc purge "
                        "failed: %s", index, t, e)
        return {"acknowledged": True}

    def _m_put_template(self, request, channel):
        name = request["name"]
        body = request["body"]

        # template settings are stored flat with the index. prefix, like index settings
        flat_settings = {
            (k if k.startswith("index.") else f"index.{k}"): v
            for k, v in Settings.from_flat(body.get("settings", {})).as_dict().items()}

        def update(state: ClusterState) -> ClusterState:
            tpl = IndexTemplateMetaData(
                name=name, template=body.get("template", "*"),
                order=int(body.get("order", 0)),
                settings_map=tuple(sorted(flat_settings.items())),
                mappings=tuple((t, __import__("json").dumps(m))
                               for t, m in (body.get("mappings") or {}).items()),
                aliases=tuple(sorted((body.get("aliases") or {}).items())),
            )
            return state.next_version(metadata=state.metadata.with_template(tpl))

        self._submit(f"put-template[{name}]", update)
        return {"acknowledged": True}

    def _m_delete_template(self, request, channel):
        name = request["name"]

        def update(state: ClusterState) -> ClusterState:
            return state.next_version(metadata=state.metadata.without_template(name))

        self._submit(f"delete-template[{name}]", update)
        return {"acknowledged": True}

    def _m_put_warmer(self, request, channel):
        """ref: search/warmer/IndexWarmersMetaData + indices/warmer — registered
        searches run against new searchers on refresh before exposure."""
        indices = self.cluster_service.state.metadata.resolve_indices(request["index"])
        name, body = request["name"], _normalize_warmer(request.get("body"))

        def update(state: ClusterState) -> ClusterState:
            md = state.metadata
            for index in indices:
                md = md.with_index(md.require_index(index).with_warmer(name, body))
            return state.next_version(metadata=md)

        self._submit(f"put-warmer[{name}]", update)
        return {"acknowledged": True}

    def _m_delete_warmer(self, request, channel):
        state0 = self.cluster_service.state
        indices = state0.metadata.resolve_indices(request["index"])
        name_expr = request["name"] or "_all"
        from .common.names import name_matches

        matched = {
            index: [w for w, _ in state0.metadata.require_index(index).warmers
                    if name_matches(w, name_expr)]
            for index in indices}
        if not any(matched.values()):
            raise IndexWarmerMissingError(name_expr)

        def update(state: ClusterState) -> ClusterState:
            md = state.metadata
            for index, names in matched.items():
                meta = md.require_index(index)
                for w in names:
                    meta = meta.with_warmer(w, None)
                md = md.with_index(meta)
            return state.next_version(metadata=md)

        self._submit(f"delete-warmer[{name_expr}]", update)
        return {"acknowledged": True}

    def _run_warmers(self, index: str, shard_id: int):
        """After refresh, run registered warm-up searches against the new searcher
        (populates filter caches + device packing before user traffic)."""
        meta = self.cluster_service.state.metadata.index(index)
        if meta is None or not meta.warmers:
            return
        for name, body in meta.warmers_dict().items():
            try:
                ctx = self._shard_ctx(index, shard_id)
                execute_query_phase(ctx, parse_search_body(body.get("source", body)),
                                    shard_id=shard_id)
            except SearchEngineError as e:
                self.logger.debug("warmer [%s] failed on [%s][%d]: %s",
                                  name, index, shard_id, e)

    def _m_cluster_settings(self, request, channel):
        body = request["body"]

        def update(state: ClusterState) -> ClusterState:
            md = state.metadata
            from dataclasses import replace as _replace

            transient = dict(md.transient_settings)
            transient.update(Settings.from_flat(body.get("transient", {})).as_dict())
            persistent = dict(md.persistent_settings)
            persistent.update(Settings.from_flat(body.get("persistent", {})).as_dict())
            md = _replace(md, transient_settings=tuple(sorted(transient.items())),
                          persistent_settings=tuple(sorted(persistent.items())),
                          version=md.version + 1)
            return state.next_version(metadata=md)

        self._submit("cluster-settings", update)
        return {"acknowledged": True,
                "transient": body.get("transient", {}),
                "persistent": body.get("persistent", {})}

    def _m_reroute(self, request, channel):
        commands = (request.get("body") or {}).get("commands", [])

        def update(state: ClusterState) -> ClusterState:
            from dataclasses import replace as _replace

            for entry in commands:
                (cmd, spec), = entry.items()
                index, shard = spec["index"], int(spec["shard"])
                table = state.routing_table.index(index)
                group = table.shard(shard)
                shards = list(group.shards)
                if cmd in ("move",):
                    for i, s in enumerate(shards):
                        if s.node_id == spec["from_node"] and s.active:
                            shards[i] = _replace(s, node_id=spec["to_node"],
                                                 state="INITIALIZING")
                elif cmd in ("cancel",):
                    for i, s in enumerate(shards):
                        if s.node_id == spec.get("node") and not s.primary:
                            shards[i] = _replace(s, node_id=None, state="UNASSIGNED")
                elif cmd in ("allocate", "allocate_replica"):
                    for i, s in enumerate(shards):
                        if not s.assigned and not s.primary:
                            shards[i] = _replace(s, node_id=spec["node"],
                                                 state="INITIALIZING")
                            break
                from .cluster.state import IndexRoutingTable, IndexShardRoutingTable

                groups = list(table.shards)
                groups[shard] = IndexShardRoutingTable(tuple(shards))
                state = state.next_version(routing_table=state.routing_table.with_index(
                    IndexRoutingTable(index, tuple(groups))))
            return self.allocation.reroute(state)

        new_state = self._submit("reroute", update, priority=URGENT)
        return {"acknowledged": True, "state_version": new_state.version}

    def _m_shard_started(self, request, channel):
        shard = ShardRouting.from_dict(request["shard"])

        def update(state: ClusterState) -> ClusterState:
            return self.allocation.apply_started_shards(state, [shard])

        self._submit(f"shard-started[{shard.index}][{shard.shard_id}]", update,
                     priority=URGENT)
        return {"ok": True}

    def _m_shard_failed(self, request, channel):
        shard = ShardRouting.from_dict(request["shard"])

        def update(state: ClusterState) -> ClusterState:
            return self.allocation.apply_failed_shard(state, shard)

        self._submit(f"shard-failed[{shard.index}][{shard.shard_id}]", update,
                     priority=URGENT)
        return {"ok": True}

    def _wait_for_active_primaries(self, index: str, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            table = self.cluster_service.state.routing_table.index(index)
            if table is not None and table.primaries_active():
                return True
            time.sleep(0.02)
        return False

    # ================= replication pattern =================
    def _resolve_index_write(self, index: str) -> str:
        state = self.cluster_service.state
        if not state.metadata.has_index(index):
            # write to an alias targeting exactly one index
            resolved = state.metadata.resolve_indices(index)
            if len(resolved) == 1:
                return resolved[0]
            raise IndexMissingError(index)
        return index

    def _required_routing_check(self, index: str, type_name: str, doc_id: str,
                                routing) -> None:
        """ref: MetaData.resolveIndexRouting — `_routing.required` (and `_parent`
        mappings, whose parent value routes the doc) reject ops without routing."""
        if routing is not None:
            return
        meta = self.cluster_service.state.metadata.index(index)
        if meta is None:
            return
        mapping = meta.mapping(type_name) if type_name and type_name != "_all" else None
        if mapping and (mapping.get("_routing", {}).get("required")
                        or "_parent" in mapping):
            from .common.errors import RoutingMissingError

            raise RoutingMissingError(index, type_name, doc_id)

    def index_doc(self, index: str, type_name: str, doc_id: str | None, source: dict,
                  routing=None, version=None, version_type="internal",
                  op_type="index", refresh=False, consistency="quorum",
                  auto_create=True, parent=None, timestamp=None, ttl=None) -> dict:
        state = self.cluster_service.state
        if not state.metadata.has_index(index) and auto_create:
            try:
                resolved = state.metadata.resolve_indices(index)
                index = resolved[0] if len(resolved) == 1 else index
            except IndexMissingError:
                try:
                    self.transport.submit_request(
                        self.node.local_node, A_CREATE_INDEX,
                        {"index": index, "body": {}}, timeout=30.0)
                except IndexAlreadyExistsError:
                    pass
                state = self.cluster_service.state
        index = self._resolve_index_write(index)
        if doc_id is None:
            doc_id = uuid.uuid4().hex[:20]
        effective_routing = routing if routing is not None else parent
        self._required_routing_check(index, type_name, doc_id, effective_routing)
        req = {"index": index, "type": type_name, "id": doc_id, "source": source,
               "routing": routing, "parent": parent, "timestamp": timestamp,
               "ttl": ttl, "version": version, "version_type": version_type,
               "op_type": op_type, "refresh": refresh, "consistency": consistency}
        return self._route_to_primary(index, doc_id, effective_routing,
                                      A_INDEX_PRIMARY, req)

    def delete_doc(self, index: str, type_name: str, doc_id: str, routing=None,
                   version=None, version_type="internal", refresh=False,
                   parent=None) -> dict:
        index = self._resolve_index_write(index)
        effective_routing = routing if routing is not None else parent
        self._required_routing_check(index, type_name, doc_id, effective_routing)
        req = {"index": index, "type": type_name, "id": doc_id, "routing": routing,
               "version": version, "version_type": version_type, "refresh": refresh}
        return self._route_to_primary(index, doc_id, effective_routing,
                                      A_DELETE_PRIMARY, req)

    def update_doc(self, index: str, type_name: str, doc_id: str, body: dict,
                   routing=None, retry_on_conflict: int = 0, parent=None,
                   refresh=False, fields=None, ttl=None, timestamp=None,
                   version=None, version_type="internal") -> dict:
        """Get-modify-reindex on the coordinator with CAS retry
        (ref: TransportUpdateAction.java:212-270; auto-creates the index like the
        index action does)."""
        if not self.cluster_service.state.metadata.has_index(index):
            try:
                self.cluster_service.state.metadata.resolve_indices(index)
            except IndexMissingError:
                try:
                    self.transport.submit_request(
                        self.node.local_node, A_CREATE_INDEX,
                        {"index": index, "body": {}}, timeout=30.0)
                except IndexAlreadyExistsError:
                    pass
        index = self._resolve_index_write(index)
        effective_routing = routing if routing is not None else parent
        self._required_routing_check(index, type_name, doc_id, effective_routing)
        if isinstance(fields, str):
            fields = [f.strip() for f in fields.split(",")]
        attempts = retry_on_conflict + 1
        last_error = None
        for _ in range(attempts):
            try:
                current = self.get_doc(index, type_name, doc_id,
                                       routing=effective_routing)
                noop = False
                if not current["found"]:
                    # internal CAS against a missing doc is a conflict, not a 404
                    # (ref: update/30_internal_version.yaml)
                    if version is not None and version_type == "internal":
                        raise VersionConflictError(
                            f"{type_name}#{doc_id}", 0, version)
                    if "upsert" in body:
                        source = body["upsert"]
                    elif body.get("doc_as_upsert") and "doc" in body:
                        source = body["doc"]
                    else:
                        raise DocumentMissingError(
                            f"[{index}][{type_name}][{doc_id}] missing")
                    r = self.index_doc(index, type_name, doc_id, source,
                                       routing=routing, parent=parent,
                                       version=version, version_type=version_type,
                                       op_type="create" if version is None else "index",
                                       refresh=refresh, ttl=ttl, timestamp=timestamp)
                else:
                    source = dict(current["_source"])
                    op = "index"
                    if "script" in body:
                        from .script import compile_update_script

                        us = compile_update_script(body["script"],
                                                   body.get("params", {}),
                                                   lang=body.get("lang"))
                        ctx = {"_source": source, "op": "index",
                               "_index": index, "_type": type_name, "_id": doc_id,
                               "_version": current.get("_version"),
                               "_routing": current.get("_routing"),
                               "_parent": current.get("_parent"),
                               "_ttl": ttl, "_timestamp": timestamp}
                        us.run(ctx)
                        source = ctx.get("_source", source)
                        op = ctx.get("op", "index")
                        if ctx.get("_ttl") is not None:
                            ttl = ctx["_ttl"]
                        if ctx.get("_timestamp") is not None:
                            timestamp = ctx["_timestamp"]
                    elif "doc" in body:
                        _deep_merge(source, body["doc"])
                    if op == "delete":
                        r = self.delete_doc(index, type_name, doc_id, routing=routing,
                                            parent=parent, refresh=refresh)
                        r.pop("found", None)
                    elif op == "none":
                        noop = True
                        r = {"_index": index, "_type": type_name, "_id": doc_id,
                             "_version": current["_version"]}
                    else:
                        r = self.index_doc(index, type_name, doc_id, source,
                                           routing=routing, parent=parent,
                                           version=version if version is not None
                                           else current["_version"],
                                           version_type=version_type,
                                           refresh=refresh, ttl=ttl,
                                           timestamp=timestamp)
                out = {"_index": index, "_type": type_name, "_id": doc_id,
                       "_version": r.get("_version", current.get("_version", 1))}
                if fields:
                    # build the get section from the state in hand — no extra
                    # round-trip, and consistent with the _version we report
                    pseudo = {"found": True, "_source": source,
                              "_version": out["_version"]}
                    if effective_routing is not None:
                        pseudo["_routing"] = str(effective_routing)
                    if parent is not None:
                        pseudo["_parent"] = str(parent)
                    fdict, src = _extract_fields(pseudo, fields)
                    get_section = {"found": True}
                    if src is not None:
                        get_section["_source"] = src
                    if fdict:
                        get_section["fields"] = fdict
                    out["get"] = get_section
                if noop:
                    out["noop"] = True
                return out
            except VersionConflictError as e:
                last_error = e
        raise last_error

    def _route_to_primary(self, index: str, doc_id: str, routing, action, req) -> dict:
        state = self.cluster_service.state
        state.blocks.check("write", index)
        deadline = time.monotonic() + 10.0
        while True:
            group = self.routing.index_shard(state, index, doc_id, routing)
            primary = group.primary
            if primary is not None and primary.active:
                node = state.nodes.get(primary.node_id)
                req["shard"] = primary.shard_id
                try:
                    return self.transport.submit_request(node, action, req, timeout=30.0)
                except (NoShardAvailableError, SearchEngineError) as e:
                    if isinstance(e, VersionConflictError) or time.monotonic() > deadline:
                        raise
            if time.monotonic() > deadline:
                raise UnavailableShardsError(
                    f"primary not active for [{index}] doc [{doc_id}]")
            # wait for the next cluster state (ref: retry on cluster state change)
            time.sleep(0.05)
            state = self.cluster_service.state

    def _check_consistency(self, index: str, shard_id: int, consistency: str):
        """ref: write consistency precheck :393-408 — quorum/one/all of the group."""
        state = self.cluster_service.state
        group = state.routing_table.index(index).shard(shard_id)
        size = group.size()
        active = len(group.active_shards())
        if consistency == "one":
            required = 1
        elif consistency == "all":
            required = size
        else:
            required = size // 2 + 1 if size > 2 else 1
        if active < required:
            raise UnavailableShardsError(
                f"not enough active copies for [{index}][{shard_id}]: "
                f"{active} < required {required}")

    def _register_percolator(self, index: str, request: dict, delete: bool = False):
        if request.get("type") != ".percolator":
            return
        svc = getattr(self.node, "percolator", None)
        if svc is None:
            return
        if delete:
            svc.unregister_query(index, request["id"])
        else:
            svc.register_query(index, request["id"], request.get("source") or {})

    def _p_index(self, request, channel):
        index, shard_id = request["index"], request["shard"]
        self._check_consistency(index, shard_id, request.get("consistency", "quorum"))
        self._register_percolator(index, request)
        shard = self.indices.index_service(index).shard(shard_id)
        mapper = shard.engine.mapper_service.mapper_for(request["type"])
        known_before = set(mapper.fields)
        version, created = shard.engine.index(
            request["type"], request["id"], request["source"],
            routing=request.get("routing"), version=request.get("version"),
            version_type=request.get("version_type", "internal"),
            op_type=request.get("op_type", "index"),
            parent=request.get("parent"), timestamp=request.get("timestamp"),
            ttl=request.get("ttl"),
        )
        if set(mapper.fields) - known_before:
            # dynamic mapping grew: propagate to master → cluster state
            # (ref: MappingUpdatedAction via TransportIndexAction.java:278-290)
            try:
                self.transport.submit_request(
                    self.node.local_node, A_MAPPING_UPDATED,
                    {"index": index, "type": request["type"],
                     "mapping": mapper.to_mapping()}, timeout=10.0)
            except SearchEngineError as e:
                self.logger.warning("mapping update propagation failed: %s", e)
        self._replicate(index, shard_id, A_INDEX_REPLICA,
                        {**request, "version": version, "version_type": "external"})
        if request.get("refresh"):
            shard.engine.refresh()
        shard.engine.maybe_flush()
        return {"_index": index, "_type": request["type"], "_id": request["id"],
                "_version": version, "created": created}

    def _r_index(self, request, channel):
        self._register_percolator(request["index"], request)
        shard = self.indices.index_service(request["index"]).shard(request["shard"])
        try:
            shard.engine.index(
                request["type"], request["id"], request["source"],
                routing=request.get("routing"), version=request.get("version"),
                version_type="external",
            )
        except VersionConflictError:
            pass  # replica already has a newer copy
        if request.get("refresh"):
            shard.engine.refresh()
        return {"ok": True}

    def _p_delete(self, request, channel):
        index, shard_id = request["index"], request["shard"]
        self._register_percolator(index, request, delete=True)
        shard = self.indices.index_service(index).shard(shard_id)
        version, found = shard.engine.delete(
            request["type"], request["id"], version=request.get("version"),
            version_type=request.get("version_type", "internal"))
        self._replicate(index, shard_id, A_DELETE_REPLICA, dict(request))
        if request.get("refresh"):
            shard.engine.refresh()
        return {"_index": index, "_type": request["type"], "_id": request["id"],
                "_version": version, "found": found}

    def _r_delete(self, request, channel):
        shard = self.indices.index_service(request["index"]).shard(request["shard"])
        try:
            shard.engine.delete(request["type"], request["id"])
        except (VersionConflictError, SearchEngineError):
            pass
        return {"ok": True}

    REPLICA_OP_TIMEOUT = 30.0

    def _replicate(self, index: str, shard_id: int, action: str, request: dict):
        """Fan the op to every assigned replica concurrently, wait for all acks
        (sync replication default). Transient failures retry through the write
        retry policy (backoff + jitter); on exhaustion the copy is reported
        shard-failed to the master so it gets routed out and resynced — a
        swallowed replica failure is silent divergence until the next recovery
        (ref: :245 fan-out + ShardStateAction on replica error)."""
        state = self.cluster_service.state
        group = state.routing_table.index(index).shard(shard_id)
        futs = []
        for replica in group.replicas():
            if not replica.assigned:
                continue
            node = state.nodes.get(replica.node_id)
            if node is None:
                continue
            futs.append((replica, node,
                         self.transport.send_request(node, action, request)))
        for replica, node, fut in futs:
            try:
                self._await_replica_op(node, action, request, fut)
            except SearchEngineError as e:
                self._report_replica_failed(index, shard_id, replica, e)

    def _await_replica_op(self, node, action: str, request: dict, first_fut=None):
        """Wait for one replica's ack (first attempt may already be in flight
        for fan-out concurrency; retries re-send sequentially with backoff).
        The WHOLE retry sequence shares one REPLICA_OP_TIMEOUT deadline — a
        downed replica costs a synchronous write the same worst-case wait as
        the pre-retry single attempt did, not attempts x timeout."""
        deadline = Deadline.after(self.REPLICA_OP_TIMEOUT)
        pending = [first_fut] if first_fut is not None else []

        def one_attempt():
            # blocking wait — fut_result bounds it, no per-request timer
            budget = deadline.clamp(self.REPLICA_OP_TIMEOUT)
            fut = pending.pop() if pending else \
                self.transport.send_request(node, action, request)
            return fut_result(fut, budget)

        return self.retry_policy.call(one_attempt, deadline=deadline,
                                      describe=f"replica op [{action}]")

    def _report_replica_failed(self, index: str, shard_id: int, replica, error):
        """Mark a replica copy failed on the master (ref: ShardStateAction).
        The report itself retries; if even that exhausts, log at ERROR — the
        one thing this path must never do is stay silent."""
        self.logger.warning("replica [%s][%d] on %s failed: %s — reporting "
                            "shard-failed", index, shard_id, replica.node_id, error)
        try:
            self.retry_policy.call(
                lambda: self.transport.submit_request(
                    self.node.local_node, ACTION_SHARD_FAILED,
                    {"shard": replica.to_dict(), "reason": str(error)},
                    timeout=10.0),
                deadline=Deadline.after(20.0),
                describe="shard-failed report")
        except SearchEngineError as e:
            self.logger.error(
                "could not report shard-failed for [%s][%d] on %s (%s); the "
                "copy may serve stale reads until the next cluster-state "
                "change or recovery", index, shard_id, replica.node_id, e)

    def bulk(self, operations: list[dict], refresh=False) -> dict:
        """Coordinator: group ops per (index, shard) → one A_BULK_SHARD per group
        (ref: TransportShardBulkAction per-shard sub-batches)."""
        t0 = time.monotonic()
        # auto-create any missing target indices first so EVERY op takes the per-shard
        # path (a mixed path would miss the shard-level refresh for some docs)
        state = self.cluster_service.state
        for op in operations:
            (_op_name, meta) = next(iter(op["action"].items()))
            index = meta.get("_index")
            if index and not state.metadata.has_index(index):
                try:
                    self.transport.submit_request(self.node.local_node, A_CREATE_INDEX,
                                                  {"index": index, "body": {}},
                                                  timeout=30.0)
                except IndexAlreadyExistsError:
                    pass
                state = self.cluster_service.state
        prepared = []
        for i, op in enumerate(operations):
            (op_name, meta) = next(iter(op["action"].items()))
            index = meta.get("_index")
            type_name = meta.get("_type", "_default_")
            doc_id = meta.get("_id") or uuid.uuid4().hex[:20]
            routing = meta.get("_routing") or meta.get("routing")
            shard_id = self.routing.shard_id(state, index, doc_id, routing)
            prepared.append((i, (index, shard_id),
                             {"op": op_name, "index": index, "type": type_name,
                              "id": doc_id, "routing": routing,
                              "source": op.get("source"),
                              "version": meta.get("_version"),
                              "body": op.get("source")}))
        by_shard: dict = {}
        for i, key, item in prepared:
            by_shard.setdefault(key, []).append((i, item))
        results: dict[int, dict] = {}
        # all shard sub-batches in flight at once (ref: TransportBulkAction fans
        # TransportShardBulkAction per shard asynchronously)
        bulk_futs = []

        def primary_node(st, index, shard_id):
            group = st.routing_table.index(index).shard(shard_id)
            primary = group.primary
            return st.nodes.get(primary.node_id) \
                if primary and primary.assigned else None

        def dispatch_group(node, index, shard_id, items):
            bulk_futs.append((items, self.transport.send_request(
                node, A_BULK_SHARD,
                {"index": index, "shard": shard_id, "refresh": refresh,
                 "items": [item for _, item in items]})))

        unrouted = []
        for (index, shard_id), items in by_shard.items():
            node = primary_node(state, index, shard_id)
            if node is None:
                unrouted.append(((index, shard_id), items))
                continue
            dispatch_group(node, index, shard_id, items)
        if unrouted:
            # one retry against a FRESH cluster state: an unassigned primary is
            # usually mid-failover, and the next published state names its new
            # home (ref: TransportBulkAction retrying unavailable primaries on
            # cluster-state change)
            time.sleep(0.1)
            state = self.cluster_service.state
            for (index, shard_id), items in unrouted:
                node = primary_node(state, index, shard_id)
                if node is None:
                    for i, item in items:
                        results[i] = {"error": "primary unavailable",
                                      "status": 503, **item}
                else:
                    dispatch_group(node, index, shard_id, items)
        for items, fut in bulk_futs:
            try:
                resp = fut_result(fut, 60.0)
                for (i, _item), r in zip(items, resp["items"]):
                    results[i] = r
            except SearchEngineError as e:
                for i, item in items:
                    results[i] = {"error": str(e), "status": 503}
        items_out = [results[i] for i in range(len(operations))]
        errors = any("error" in r for r in items_out)
        return {"took": int((time.monotonic() - t0) * 1000), "errors": errors,
                "items": [{r.pop("op", "index"): r} for r in items_out]}

    def _p_bulk_shard(self, request, channel):
        index, shard_id = request["index"], request["shard"]
        shard = self.indices.index_service(index).shard(shard_id)
        out = []
        for item in request["items"]:
            op = item.get("op", "index")
            try:
                if op in ("index", "create"):
                    version, created = shard.engine.index(
                        item["type"], item["id"], item.get("source") or {},
                        routing=item.get("routing"), version=item.get("version"),
                        op_type="create" if op == "create" else "index")
                    out.append({"_index": index, "_type": item["type"], "_id": item["id"],
                                "_version": version,
                                "status": 201 if created else 200, "op": op})
                elif op == "delete":
                    version, found = shard.engine.delete(item["type"], item["id"])
                    out.append({"_index": index, "_type": item["type"], "_id": item["id"],
                                "_version": version, "found": found,
                                "status": 200 if found else 404, "op": op})
                elif op == "update":
                    body = item.get("source") or {}
                    r = self.update_doc(index, item["type"], item["id"], body,
                                        routing=item.get("routing"))
                    out.append({**r, "status": 200, "op": op})
                else:
                    out.append({"error": f"unknown bulk op [{op}]", "status": 400, "op": op})
            except SearchEngineError as e:
                out.append({"_index": index, "_type": item.get("type"),
                            "_id": item.get("id"), "error": e.to_dict(),
                            "status": e.status, "op": op})
        # replicas get individual replicated ops (simple + idempotent via
        # versions). Transient failures retry with backoff; when a replica
        # exhausts its retries it is reported shard-failed and the REST of the
        # stream to that copy stops — recovery resyncs the whole copy, and
        # silently skipping ops would leave it diverged from the primary
        state = self.cluster_service.state
        group = state.routing_table.index(index).shard(shard_id)
        for replica in group.replicas():
            if not replica.assigned:
                continue
            node = state.nodes.get(replica.node_id)
            if node is None:
                continue
            for item, r in zip(request["items"], out):
                if "error" in r:
                    continue
                if item.get("op") in ("index", "create", "update"):
                    rep_action, rep_req = A_INDEX_REPLICA, {
                        "index": index, "shard": shard_id, "type": item["type"],
                        "id": item["id"], "source": item.get("source") or {},
                        "routing": item.get("routing"),
                        "version": r.get("_version"), "version_type": "external",
                    }
                elif item.get("op") == "delete":
                    rep_action, rep_req = A_DELETE_REPLICA, {
                        "index": index, "shard": shard_id, "type": item["type"],
                        "id": item["id"],
                    }
                else:
                    continue
                try:
                    self._await_replica_op(node, rep_action, rep_req)
                except SearchEngineError as e:
                    self._report_replica_failed(index, shard_id, replica, e)
                    break
        if request.get("refresh"):
            shard.engine.refresh()
        shard.engine.maybe_flush()
        return {"items": out}

    # ================= single-shard reads =================
    def get_doc(self, index: str, type_name: str, doc_id: str, routing=None,
                realtime=True, refresh=False, preference=None, parent=None) -> dict:
        state = self.cluster_service.state
        state.blocks.check("read", index)
        index = state.metadata.resolve_indices(index)[0]
        effective_routing = routing if routing is not None else parent
        copy = self.routing.get_shard_copy(state, index, doc_id, effective_routing,
                                           preference)
        node = state.nodes.get(copy.node_id)
        return self.transport.submit_request(node, A_GET, {
            "index": index, "shard": copy.shard_id, "type": type_name, "id": doc_id,
            "realtime": realtime, "refresh": refresh}, timeout=10.0)

    def _s_get(self, request, channel):
        shard = self.indices.index_service(request["index"]).shard(request["shard"])
        if request.get("refresh"):
            shard.engine.refresh()
        type_name = request["type"] or "_all"
        if type_name == "_all":
            # resolve the uid across types (ref: _all type get)
            r = None
            for t in list(shard.engine.mapper_service.types()) or []:
                r = shard.engine.get(t, request["id"],
                                     realtime=request.get("realtime", True))
                if r.found:
                    type_name = t
                    break
            if r is None or not r.found:
                return {"_index": request["index"], "_type": request["type"],
                        "_id": request["id"], "found": False}
        else:
            r = shard.engine.get(type_name, request["id"],
                                 realtime=request.get("realtime", True))
        out = {"_index": request["index"], "_type": type_name,
               "_id": request["id"], "found": r.found}
        if r.found:
            out["_version"] = r.version
            out["_source"] = r.source
            if r.routing is not None:
                out["_routing"] = str(r.routing)
            if r.parent is not None:
                out["_parent"] = str(r.parent)
            if r.timestamp is not None:
                out["_timestamp"] = int(r.timestamp)
            if r.ttl is not None:
                out["_ttl"] = int(r.ttl)
        return out

    def term_vector(self, index: str, type_name: str, doc_id: str, routing=None,
                    fields=None, positions=True, offsets=True,
                    term_statistics=False, field_statistics=True,
                    preference=None) -> dict:
        """Term-vectors API (ref: action/termvector/TransportTermVectorAction —
        single-shard read pattern). Vectors are re-derived by re-analyzing the stored
        _source, which is exact for this framework's write-once segments."""
        state = self.cluster_service.state
        state.blocks.check("read", index)
        index = state.metadata.resolve_indices(index)[0]
        copy = self.routing.get_shard_copy(state, index, doc_id, routing, preference)
        node = state.nodes.get(copy.node_id)
        return self.transport.submit_request(node, A_TERMVECTOR, {
            "index": index, "shard": copy.shard_id, "type": type_name, "id": doc_id,
            "fields": list(fields) if fields else None,
            "positions": positions, "offsets": offsets,
            "term_statistics": term_statistics, "field_statistics": field_statistics,
        }, timeout=10.0)

    def multi_termvector(self, docs: list[dict]) -> dict:
        out = []
        for d in docs:
            try:
                out.append(self.term_vector(
                    d["_index"], d.get("_type", "_all"), d["_id"],
                    routing=d.get("routing"), fields=d.get("fields"),
                    positions=d.get("positions", True),
                    offsets=d.get("offsets", True),
                    term_statistics=d.get("term_statistics", False),
                    field_statistics=d.get("field_statistics", True)))
            except SearchEngineError as e:
                out.append({"_index": d.get("_index"), "_id": d.get("_id"),
                            "error": e.to_dict()})
        return {"docs": out}

    def _s_termvector(self, request, channel):
        index, shard_id = request["index"], request["shard"]
        shard = self.indices.index_service(index).shard(shard_id)
        r = shard.engine.get(request["type"], request["id"], realtime=True)
        out = {"_index": index, "_type": request["type"], "_id": request["id"],
               "found": r.found}
        if not r.found:
            return out
        out["_version"] = r.version
        ctx = self._shard_ctx(index, shard_id)
        flat = _flatten_text_fields(r.source)
        wanted = request.get("fields")
        tv = {}
        for field, texts in sorted(flat.items()):
            if wanted is not None and field not in wanted:
                continue
            ft = ctx.field_type(field)
            if ft is not None and getattr(ft, "index", "analyzed") == "no":
                continue
            terms: dict[str, dict] = {}
            for text in texts:
                for tok in ctx.analyze_tokens(field, str(text)):
                    e = terms.setdefault(tok.term, {"term_freq": 0, "tokens": []})
                    e["term_freq"] += 1
                    t = {}
                    if request.get("positions", True):
                        t["position"] = tok.position
                    if request.get("offsets", True):
                        t["start_offset"] = tok.start
                        t["end_offset"] = tok.end
                    if t:
                        e["tokens"].append(t)
            if not terms:
                continue
            if request.get("term_statistics"):
                for term, e in terms.items():
                    e["doc_freq"] = ctx.doc_freq(field, term)
                    e["ttf"] = sum(
                        int(seg.postings(field, term)[1].sum())
                        for seg in ctx.searcher.segments)
            entry = {"terms": terms}
            if request.get("field_statistics", True):
                fs = ctx.field_stats(field)
                entry["field_statistics"] = {
                    "doc_count": fs.doc_count, "sum_ttf": fs.sum_ttf,
                    "sum_doc_freq": fs.sum_dfs}
            tv[field] = entry
        out["term_vectors"] = tv
        return out

    def more_like_this(self, index: str, type_name: str, doc_id: str,
                       mlt_fields=None, search_body=None, routing=None,
                       **mlt_params) -> dict:
        """MLT API (ref: action/mlt/TransportMoreLikeThisAction): GET the doc, build a
        more_like_this query from its field text, exclude the doc itself, search."""
        doc = self.get_doc(index, type_name, doc_id, routing=routing)
        if not doc.get("found"):
            raise DocumentMissingError(f"[{index}][{type_name}][{doc_id}] missing")
        flat = _flatten_text_fields(doc.get("_source") or {})
        if mlt_fields:
            flat = {f: v for f, v in flat.items() if f in set(mlt_fields)}
        like_text = " ".join(str(t) for texts in flat.values() for t in texts)
        mlt = {"fields": sorted(flat) or ["_all"], "like_text": like_text}
        for k in ("min_term_freq", "min_doc_freq", "max_query_terms",
                  "minimum_should_match", "percent_terms_to_match", "boost_terms"):
            if mlt_params.get(k) is not None:
                mlt[k] = mlt_params[k]
        body = dict(search_body or {})
        body["query"] = {"bool": {
            "must": [{"more_like_this": mlt}],
            "must_not": [{"ids": {"type": type_name, "values": [doc_id]}}],
        }}
        return self.search(index, body)

    def multi_get(self, docs: list[dict]) -> dict:
        """ref: TransportMultiGetAction — request-level validation, then per-doc
        gets; a missing index yields found:false for that doc, not an error."""
        from .common.errors import ActionRequestValidationError

        if not docs:
            raise ActionRequestValidationError("Validation Failed: no documents to get")
        for i, d in enumerate(docs):
            if not d.get("_id"):
                raise ActionRequestValidationError(
                    f"Validation Failed: {i + 1}: id is missing")
            if not d.get("_index"):
                raise ActionRequestValidationError(
                    f"Validation Failed: {i + 1}: index is missing")
        out = []
        for d in docs:
            type_name = d.get("_type") or "_all"
            try:
                r = self.get_doc(d["_index"], type_name, str(d["_id"]),
                                 routing=d.get("routing") or d.get("_routing"),
                                 parent=d.get("parent") or d.get("_parent"),
                                 realtime=d.get("realtime", True),
                                 refresh=d.get("refresh", False))
                if d.get("_type") and r.get("_type") != d["_type"]:
                    # requested type doesn't hold this id
                    r = {"_index": d["_index"], "_type": d["_type"],
                         "_id": str(d["_id"]), "found": False}
                fields = d.get("fields") or d.get("_fields")
                src_spec = d.get("_source")
                if r.get("found") and (fields or src_spec is not None):
                    shaped = {k: v for k, v in r.items() if k != "_source"}
                    src = r.get("_source")
                    keep_source = True
                    if fields:
                        fdict, fsrc = _extract_fields(r, fields)
                        if fdict:
                            shaped["fields"] = fdict
                        keep_source = fsrc is not None
                    if src_spec is not None:
                        if src_spec is False or src_spec == "false":
                            keep_source = False
                        elif src_spec is True or src_spec == "true":
                            keep_source = True
                        elif isinstance(src_spec, (str, list)):
                            src = filter_source(src, src_spec, None)
                            keep_source = True
                        elif isinstance(src_spec, dict):
                            src = filter_source(
                                src, src_spec.get("include") or
                                src_spec.get("includes"),
                                src_spec.get("exclude") or src_spec.get("excludes"))
                            keep_source = True
                    if keep_source and src is not None:
                        shaped["_source"] = src
                    r = shaped
                out.append(r)
            except IndexMissingError:
                out.append({"_index": d["_index"], "_type": d.get("_type"),
                            "_id": str(d["_id"]), "found": False})
            except SearchEngineError as e:
                out.append({"_index": d.get("_index"), "_type": d.get("_type"),
                            "_id": str(d.get("_id")), "error": e.to_dict()})
        return {"docs": out}

    # ================= scatter-gather search =================
    def search(self, index_expr, body: dict | None = None, search_type="query_then_fetch",
               routing=None, preference=None, deadline: Deadline | None = None) -> dict:
        """Tracing + latency-histogram wrapper around the scatter-gather body.

        When the calling thread already carries a sampled span (REST ingress
        started the trace), the coordinator span nests under it; a direct
        client call roots a new trace here (subject to the sampling rate).
        Unsampled requests pay one thread-local read + one clock pair."""
        t0 = time.monotonic()
        parent = tracing.current_span()
        tracer = getattr(self.node, "tracer", None)
        if parent is not None:
            span = parent.child("coordinator")
        elif tracer is not None:
            span = tracer.start_trace("coordinator").root
        else:
            span = tracing.NOOP_SPAN
        try:
            with tracing.activate(span):
                return self._search_inner(index_expr, body, search_type,
                                          routing, preference, deadline)
        finally:
            span.end()
            self.search_latency.observe(time.monotonic() - t0)

    def _search_inner(self, index_expr, body: dict | None = None,
                      search_type="query_then_fetch", routing=None,
                      preference=None, deadline: Deadline | None = None) -> dict:
        t0 = time.monotonic()
        state = self.cluster_service.state
        indices = state.metadata.resolve_indices(index_expr)
        for i in indices:
            state.blocks.check("read", i)
        # filtered aliases compose into the query (ref: filtered alias handling)
        alias_filters = {i: state.metadata.alias_filter(i, index_expr) for i in indices}
        # terms LOOKUPS resolve here, once, against the get path — every shard
        # then sees identical literal values (ref: TermsFilterParser lookup)
        body = resolve_terms_lookups(body, self._lookup_get)
        req = parse_search_body(body)
        # ONE deadline for the whole request (REST `?timeout=` / body `timeout`):
        # every per-attempt transport timeout, failover-chain cap, and per-shard
        # segment clamp below derives from its REMAINING budget — k slow hops
        # run down one clock instead of stacking k fresh timeouts
        if deadline is None:
            deadline = Deadline.after(req.timeout_s) if req.timeout_s is not None \
                else NO_DEADLINE
        # admission control: a budget that cannot cover one expected shard
        # phase is rejected up front (429 + Retry-After) — running it would
        # only burn workers on an answer the client already abandoned
        self.admission.admit(deadline)
        # cache-affinity routing: cache-ELIGIBLE requests (the same policy
        # the shard consults — request_cache.cache_policy) carry their
        # fingerprint into copy selection as a soft affinity, so the same
        # hot query rendezvous-lands on the same healthy copy and N replica
        # caches become N× effective capacity instead of N× redundancy.
        # Health still dominates (affinity picks within the spread set);
        # ineligible requests route exactly as before (affinity=None).
        affinity = None
        _rc = getattr(self.node, "request_cache", None)
        if _rc is not None and _rc.enabled and cache_policy(body):
            affinity = request_fingerprint(body)
        shards = self.routing.search_shards(state, indices, routing,
                                            preference, affinity=affinity)

        # co-located shards + flat query → one SPMD program over the device mesh
        # (DFS psum + all_gather top-k on ICI) instead of per-shard RPC scatter-gather;
        # None = ineligible or failed, fall through to the transport path unchanged
        mesh_results = self.mesh_serving.try_search(
            state, self.node.local_node.id, indices, alias_filters, shards, req,
            use_global_stats=search_type in ("dfs_query_then_fetch",
                                             "dfs_query_and_fetch"),
            deadline=deadline)
        if mesh_results is not None:
            # mesh-served searches never reach _s_query_phase, so the
            # query-shape classification happens HERE instead (one record per
            # search, outcome mesh_spmd, latency from the t0 this method
            # already read) — "classify every search" includes the SPMD path
            insights_reg = getattr(self.node, "insights", None)
            if insights_reg is not None and insights_reg.enabled:
                sid, shape = insights_reg.fingerprint(body)
                obs = _insights.Observation()
                obs.outcome = "mesh_spmd"
                insights_reg.record(sid, shape, time.monotonic() - t0, obs)
            node_local = state.nodes.get(self.node.local_node.id)
            shard_meta = {o: (copy.index, copy.shard_id, node_local,
                              mesh_results[o].context_id)
                          for o, copy in enumerate(shards)}
            return self._finish_search(req, body, mesh_results, [], shards,
                                       shard_meta, t0)

        dfs_stats = None
        dfs_failed: set[int] = set()  # ordinals excluded from the query phase
        if search_type in ("dfs_query_then_fetch", "dfs_query_and_fetch"):
            # concurrent DFS fan-out — the distributed-IDF all-reduce's gather leg
            # (ref: TransportSearchDfsQueryThenFetchAction async per-shard phase).
            # Each shard fails over across its copies like the query phase; a
            # shard with no serving copy becomes a recorded shard FAILURE and is
            # excluded from the query phase — querying it against stats that
            # omit it would silently skew every shard's IDF
            dfs_futs = [(copy, self.transport.send_request(
                state.nodes.get(copy.node_id), A_DFS_PHASE, {
                    "index": copy.index, "shard": copy.shard_id, "body": body or {},
                })) for copy in shards]
            dfs_results = []
            for ordinal, (copy, fut) in enumerate(dfs_futs):
                r = self._dfs_shard_result(state, copy, body, fut, deadline)
                if r is None:
                    dfs_failed.add(ordinal)
                    continue
                dfs_results.append(DfsResult(
                    shard_id=copy.shard_id, max_doc=r["max_doc"],
                    term_df={(f, t): v for f, t, v in r["term_df"]},
                    field_stats={f: _fs_from(l) for f, l in r["field_stats"].items()},
                ))
            agg = aggregate_dfs(dfs_results)
            dfs_stats = {
                "max_doc": agg["max_doc"],
                "term_df": [[f, t, v] for (f, t), v in agg["df"].items()],
                "field_stats": {f: [s.doc_count, s.sum_ttf, s.sum_dfs]
                                for f, s in agg["field_stats"].items()},
            }
        results: list[ShardQueryResult] = []
        failures = []
        # terminal error of each FAILED chain (None = not overload-shaped,
        # e.g. a DFS phase dead on every copy) — decides 429 vs 200-partial
        chain_terminals: list = []
        # merge identity is a coordinator-assigned ordinal — (index, shard) pairs from
        # different indices may share a shard id (ref: the per-request shard index in
        # TransportSearchTypeAction), so results carry the ordinal as shard_id
        shard_meta: dict[int, tuple] = {}  # ordinal -> (index, real_shard_id, node, ctx_id)
        # concurrent query-phase fan-out: every shard's first phase is dispatched at
        # once and failover chains advance via future callbacks, so N-shard latency is
        # max(shard) not sum(shard) and no coordinator thread parks per shard
        # (ref: TransportSearchTypeAction.java:135-216 async performFirstPhase)
        t_fanout = time.monotonic()
        # hard copy pins disable HEDGING (a speculative answer from a node
        # the caller explicitly pinned away from violates the preference's
        # contract even on success); failover-on-failure keeps its
        # pre-existing cross-copy semantics. Soft preferences (_prefer_node,
        # _local, session keys) keep hedging — they name a starting point,
        # not an exclusivity constraint. The pin comes from the SAME parser
        # search_shards uses ("_shards:N;<pref>" carries the copy preference
        # after the ";" — testing the raw string would miss a compound
        # "_shards:0;_only_node:x" pin entirely).
        _, pin = self.routing.split_preference(preference)
        pin = pin or ""
        allow_hedge = not pin.startswith("_only_node:") and pin != "_primary"
        query_futs = [
            None if ordinal in dfs_failed else
            self._query_shard_async(state, copy, body, alias_filters, dfs_stats,
                                    deadline, allow_hedge=allow_hedge)
            for ordinal, copy in enumerate(shards)]
        # shared backstop: chains resolve themselves (every attempt is
        # timer-bounded), so this only catches a wedged chain — scaled to the
        # longest possible failover chain, and clamped by the request deadline
        # (plus grace for in-flight partials to land) when one is set.
        max_chain = max((getattr(f, "max_attempts", 1) for f in query_futs
                         if f is not None), default=1)
        backstop = deadline.clamp(
            self.QUERY_ATTEMPT_TIMEOUT * max(1, max_chain))
        collect_by = time.monotonic() + backstop + 5.0
        for ordinal, (copy, fut) in enumerate(zip(shards, query_futs)):
            if fut is None:
                failures.append({"index": copy.index, "shard": copy.shard_id,
                                 "reason": "dfs phase failed on every copy"})
                chain_terminals.append(None)  # a data failure, never overload
                continue
            try:
                r, used, err = fut.result(
                    timeout=max(0.0, collect_by - time.monotonic()))
            except (TimeoutError, FutureTimeoutError):
                r, used, err = None, None, TransportError("query phase timed out")
                cancel = getattr(fut, "cancel_chain", None)
                if cancel is not None:
                    cancel()  # abandoned chain must not keep scheduling attempts
            if r is not None:
                shard_meta[ordinal] = (copy.index, r.shard_id, used, r.context_id)
                r.shard_id = ordinal
                results.append(r)
                # feed admission control: coordinator-observed shard-phase
                # latency, fan-out → future RESOLUTION (stamped by the chain;
                # falls back to now inside the callback race window) — the
                # decaying signal the next request's budget is compared against
                self.admission.observe(
                    getattr(fut, "completed_at", time.monotonic()) - t_fanout)
            else:
                # one failure entry per attempted copy (ref: ShardSearchFailure
                # carries the shard target) — chains record each downed copy.
                # The terminal error is appended too unless it IS the last
                # recorded attempt error: a backstop/budget cutoff with an
                # attempt still in flight must not vanish from the response
                per_copy = list(getattr(fut, "attempt_errors", None) or [])
                if err is not None and \
                        (not per_copy or per_copy[-1][1] is not err):
                    per_copy.append((copy.node_id, err))
                for node_id, copy_err in per_copy:
                    failures.append({"index": copy.index, "shard": copy.shard_id,
                                     "node": node_id, "reason": str(copy_err)})
                terminal = err if err is not None \
                    else per_copy[-1][1] if per_copy else None
                chain_terminals.append(terminal)
                # failed chains feed admission too — a degrading node whose
                # phases all time out must RAISE the latency signal, not
                # starve it (successes-only would freeze it at the healthy
                # value). Overload rejections are excluded: they resolve
                # near-instantly and would drag the signal DOWN mid-overload
                if not isinstance(terminal, (CircuitBreakingError,
                                             RejectedExecutionError)):
                    self.admission.observe(
                        getattr(fut, "completed_at", time.monotonic())
                        - t_fanout)
        overload = [e for e in chain_terminals
                    if isinstance(e, (CircuitBreakingError,
                                      RejectedExecutionError))]
        if not results and chain_terminals \
                and len(overload) == len(chain_terminals):
            # EVERY shard's failover chain died on overload protection — this
            # is a load-shed, not a data failure: surface the 429 (with its
            # Retry-After hint) so clients back off instead of retrying hot.
            # Any chain that died on something ELSE keeps the normal partial
            # response with its _shards.failures entries — a permanent data
            # failure must not masquerade as "retry later"
            raise overload[-1]
        # shard-side partials mark timed_out in the reduce (sort_docs); chain
        # exhaustion by deadline must surface it too, even with no results back
        return self._finish_search(req, body, results, failures, shards,
                                   shard_meta, t0, timed_out=deadline.expired())

    def _finish_search(self, req, body, results, failures, shards, shard_meta, t0,
                       timed_out: bool = False):
        """Reduce + fetch + response assembly, shared by the transport scatter-gather
        and the mesh SPMD query phase (both deliver per-ordinal ShardQueryResults).
        The fetch phase deliberately ignores the request deadline: winners are
        already chosen, and hydrating them is what makes a timed-out response a
        PARTIAL answer instead of an empty one (ref: the reference's fetch runs
        after TimeLimitingCollector fires too). `timed_out` ORs in coordinator-
        level budget expiry; shard-level partials are folded in by sort_docs."""
        merged = sort_docs(req, results)
        merged.timed_out = merged.timed_out or timed_out
        page = merged.hits[req.from_: req.from_ + req.size]
        # fetch phase: winners only, grouped per shard, all shards in flight at once
        # (ref: TransportSearchQueryThenFetchAction.java:93-147)
        by_shard: dict = {}
        for rank, (score, ordinal, doc, sort_values) in enumerate(page):
            by_shard.setdefault(ordinal, []).append((rank, score, doc, sort_values))
        fetched: dict[int, dict] = {}
        fetch_failed = 0
        fetch_futs = []
        for ordinal, entries in by_shard.items():
            index_name, real_shard, node, ctx_id = shard_meta[ordinal]
            fetch_futs.append(((ordinal, entries), self.transport.send_request(
                node, A_FETCH_PHASE, {
                    "index": index_name, "shard": real_shard, "body": body or {},
                    "ctx": ctx_id,
                    "docs": [[score, doc, sort_values]
                             for (_rank, score, doc, sort_values) in entries],
                })))
        for (ordinal, entries), fut in fetch_futs:
            try:
                r = fut_result(fut, 30.0)
            except Exception as e:  # noqa: BLE001 — ANY per-shard fetch failure
                # (remote errors arrive typed over TCP but raw over the local
                # transport): a shard lost between phases drops ITS hits and
                # records a failure; the rest of the page still returns (ref:
                # fetch-phase onFailure collects ShardFetchFailures)
                index_name, real_shard, _node, _cid = shard_meta[ordinal]
                failures.append({"index": index_name, "shard": real_shard,
                                 "reason": f"fetch phase failed: {e}"})
                fetch_failed += 1
                continue
            for (rank, *_), hit in zip(entries, r["hits"]):
                fetched[rank] = hit
        # release pinned contexts of shards that contributed no fetched hits
        # (fire-and-forget, like the reference's free-context after the merge)
        for ordinal, meta in shard_meta.items():
            index_name, real_shard, node, ctx_id = meta
            if ctx_id is not None and ordinal not in by_shard:
                with contextlib.suppress(Exception):
                    self.transport.send_request(node, A_FREE_CONTEXT, {
                        "index": index_name, "shard": real_shard, "ctx": ctx_id})
        hits = [fetched[r] for r in sorted(fetched)]
        return merge_responses(req, merged, results, hits,
                               took_ms=int((time.monotonic() - t0) * 1000),
                               total_shards=len(shards),
                               successful=len(results) - fetch_failed,
                               failures=failures)

    @staticmethod
    def _shard_index(shards, shard_id):
        for s in shards:
            if s.shard_id == shard_id:
                return s.index
        return None

    QUERY_ATTEMPT_TIMEOUT = 60.0

    def _dfs_shard_result(self, state, copy: ShardRouting, body, first_fut,
                          deadline: Deadline = NO_DEADLINE):
        """DFS phase for one shard group with failover across its copies (the
        first attempt is already in flight for fan-out concurrency; failover
        attempts are sequential — rare). Returns the stats dict, or None when no
        copy on a live node serves it. Per-attempt waits and the failover chain
        are bounded by the request deadline's remaining budget."""
        group = state.routing_table.index(copy.index).shard(copy.shard_id)
        candidates = [copy] + [s for s in group.active_shards()
                               if s.node_id != copy.node_id]
        fut = first_fut
        for cand in candidates:
            if fut is None:
                if deadline.expired():
                    return None  # no budget left for another copy
                node = state.nodes.get(cand.node_id)
                if node is None:
                    continue
                fut = self.transport.send_request(node, A_DFS_PHASE, {
                    "index": cand.index, "shard": cand.shard_id,
                    "body": body or {}})
            try:
                return fut_result(fut, deadline.clamp(30.0))
            except SearchEngineError:  # TransportError subclasses it
                fut = None  # next copy
        return None

    def _query_shard_async(self, state, copy: ShardRouting, body, alias_filters,
                           dfs_stats, deadline: Deadline = NO_DEADLINE,
                           allow_hedge: bool = True) -> Future:
        """Per-shard query phase with rank-ordered failover and hedged
        attempts, driven entirely by future callbacks — the coordinator parks
        no thread per shard (ref: performFirstPhase + onFirstPhaseResult
        failover, TransportSearchTypeAction.java:135-216,292).

        Failover: candidates are `routing.ranked_copies` — the chosen copy
        first, then the remaining active copies best-first by the adaptive
        health rank (cluster/stats.py), so the first fallback is the best
        REMAINING copy. Each attempt's timeout is the flat attempt budget
        clamped to the request deadline's REMAINING budget, and the chain
        gives up once the deadline expires.

        Hedging (The Tail at Scale): when a primary attempt outlives its
        copy's own p99 (hedge_delay_s — warm copies only, clamped by the
        remaining budget) and the token-bucket HedgeBudget grants a token,
        the next-ranked unattempted copy is dispatched speculatively; the
        FIRST successful response resolves the chain (complete-once via
        complete_fut) and the loser's response is discarded by the existing
        late-response path. `allow_hedge=False` (hard copy pins:
        _only_node/_primary) suppresses hedging entirely — a speculative
        answer from an un-pinned copy would violate the preference even on
        success. Hedges ride the normal transport send — the
        in-flight breaker charges them and the remote search pool's bounded
        queue can 429 them, so overload protection governs hedges exactly
        like primaries. Every attempt feeds the health tracker: latency +
        piggybacked load on success (even when it lost the race), a decayed
        failure count on error/timeout.

        Resolves to (ShardQueryResult | None, node | None, error | None);
        every failed attempt is recorded on the returned future's
        `attempt_errors` as (node_id, error)."""
        done: Future = Future()
        # stamp resolution time for admission-control latency: the collection
        # loop drains futures in ordinal order, so "time until collected" of a
        # fast shard parked behind a slow chain would overstate its phase by
        # the whole wait (first callback → runs at resolution)
        done.add_done_callback(
            lambda f: setattr(f, "completed_at", time.monotonic()))
        # sampled trace of the calling coordinator (None when untraced): shard
        # responses carry their span lists back inline; stitching them here —
        # not in the collection loop — keeps the spans even for chains the
        # backstop later abandons
        cur_span = tracing.current_span()
        trace_ref = cur_span.trace if cur_span else None
        group = state.routing_table.index(copy.index).shard(copy.shard_id)
        # ONE wiring point: the same selector that ranks the failover chain
        # receives the observations and issues the hedge budget — reading it
        # from a second place (a node attribute) could leave an embedding
        # half-wired with no error
        selector = self.routing.selector
        candidates = self.routing.ranked_copies(group, copy)
        # the coordinator's backstop may abandon this chain; once it does, stop
        # scheduling further attempts (they'd leak requests + timers)
        cancelled = threading.Event()
        done.cancel_chain = cancelled.set  # type: ignore[attr-defined]
        done.max_attempts = len(candidates)  # type: ignore[attr-defined]
        attempt_errors: list = []
        done.attempt_errors = attempt_errors  # type: ignore[attr-defined]
        # chain state: which candidate indices have been attempted (hedges
        # included — a failover never double-sends to a copy a hedge already
        # covers) and how many attempts are in flight. The chain fails only
        # when every candidate is consumed AND nothing is in flight.
        chain_lock = threading.Lock()
        launched: set[int] = set()
        in_flight = [0]

        def resolve(result, node, err) -> bool:
            return complete_fut(done, (result, node, err))

        def attempt_failed(candidate, err, hedge: bool):
            with chain_lock:
                in_flight[0] -= 1
                alive = in_flight[0]
                attempt_errors.append((candidate.node_id, err))
                # attempts actually SENT (launched also counts dead-node
                # candidates the claim loop consumed without a send)
                attempts = len(attempt_errors)
            if cancelled.is_set() or done.done():
                return
            if deadline.expired():
                # budget exhausted mid-chain: trying another copy could only
                # answer after the caller stopped caring. But an attempt
                # STILL in flight keeps the chain open — its timer is
                # deadline-clamped, and a late success is exactly the partial
                # the coordinator's collection grace window exists to accept
                if alive == 0:
                    resolve(None, None, ReceiveTimeoutError(
                        f"search budget exhausted after {attempts} "
                        f"attempt(s) on [{copy.index}][{copy.shard_id}]: "
                        f"{err}"))
                return
            if hedge and alive > 0:
                return  # a dead hedge never advances the chain while the
                # primary attempt it shadowed is still in flight
            try_next(err)

        def try_next(last_err, hedge: bool = False) -> bool:
            """Claim + launch the best not-yet-attempted copy on a live node.
            False = no candidate left (the chain resolves its terminal error
            iff nothing is in flight either)."""
            with chain_lock:
                j = None
                for i in range(len(candidates)):
                    if i in launched:
                        continue
                    if state.nodes.get(candidates[i].node_id) is None:
                        launched.add(i)  # dead node: consumed, never retried
                        continue
                    j = i
                    launched.add(j)
                    in_flight[0] += 1
                    break
                alive = in_flight[0]
            if j is None:
                if alive == 0:
                    resolve(None, None, last_err or NoShardAvailableError(
                        f"no active copy of [{copy.index}][{copy.shard_id}] "
                        f"on a live node"))
                return False
            launch(j, hedge)
            return True

        def launch(j: int, hedge: bool):
            candidate = candidates[j]
            # liveness was checked by try_next's claim loop against the SAME
            # immutable ClusterState snapshot — node cannot be None here
            node = state.nodes.get(candidate.node_id)
            payload = {
                "index": candidate.index, "shard": candidate.shard_id,
                "body": body or {},
                "alias_filter": alias_filters.get(candidate.index),
                "dfs": dfs_stats,
                # remaining budget as a DURATION (monotonic clocks don't
                # cross processes); the shard restarts its own clock from it
                "deadline_s": deadline.remaining(),
            }
            if hedge:
                # the shard tags its span hedge:true from this (sibling shard
                # spans in ?trace=true); the winner annotation on the profile
                # happens coordinator-side below
                payload["hedge"] = True
            # re-activate the coordinator's span around the send: retry
            # attempts run on timer / transport-callback threads whose
            # thread-local is empty, and an un-activated send would strip the
            # trace context from exactly the failover attempts most worth
            # tracing (the transport injects context from current_span())
            with tracing.activate(cur_span):
                fut = self.transport.send_request(node, A_QUERY_PHASE, payload)
            t_sent = time.monotonic()
            if selector is not None:
                selector.begin_attempt(candidate)
                if hedge:
                    selector.hedges.record_issued()
                else:
                    selector.hedges.note_request()  # accrue hedge budget
            # exactly one of {response callback, attempt timer} consumes the
            # attempt for CHAIN purposes; `settled` separately guarantees the
            # selector's outstanding count drops exactly once
            consumed_lock = threading.Lock()
            consumed = [False]
            settled = [False]

            def consume() -> bool:
                with consumed_lock:
                    if consumed[0]:
                        return False
                    consumed[0] = True
                    return True

            def settle() -> bool:
                with consumed_lock:
                    if settled[0]:
                        return False
                    settled[0] = True
                    return True

            def on_timeout():
                if selector is not None and settle():
                    selector.end_attempt(candidate)
                    selector.failure(candidate)
                if consume():
                    err = ReceiveTimeoutError(
                        f"query phase attempt to [{candidate.node_id}] timed out")
                    attempt_failed(candidate, err, hedge)

            timer = self.node.threadpool.schedule(
                deadline.clamp(self.QUERY_ATTEMPT_TIMEOUT), "generic", on_timeout)

            if allow_hedge and not hedge and selector is not None:
                with chain_lock:
                    alts = [candidates[i] for i in range(len(candidates))
                            if i not in launched]
                hd = selector.hedge_delay_s(candidate, deadline.remaining(),
                                            others=alts)
                if hd is not None:
                    def on_hedge():
                        if cancelled.is_set() or done.done():
                            return
                        with consumed_lock:
                            if consumed[0]:
                                return  # attempt already failed over: the
                                # chain is advancing anyway, no hedge needed
                        with chain_lock:
                            has_next = any(
                                i not in launched and
                                state.nodes.get(candidates[i].node_id)
                                is not None
                                for i in range(len(candidates)))
                        if not has_next:
                            return  # nothing to hedge to
                        if not selector.hedges.try_acquire():
                            return  # budget exhausted (counted) — brown-out
                            # protection: never amplify load on a sick group
                        if not try_next(None, hedge=True):
                            # lost the claim race (concurrent failover took
                            # the last candidate / its node left): the token
                            # bought nothing — put it back
                            selector.hedges.refund()

                    hedge_timer = self.node.threadpool.schedule(
                        hd, "generic", on_hedge)
                    done.add_done_callback(lambda _f: hedge_timer.cancel())

            def on_done(f):
                err0 = f.exception()
                lat = time.monotonic() - t_sent
                if selector is not None and settle():
                    selector.end_attempt(candidate)
                if not consume():
                    # the timer already failed this attempt over; a late
                    # response still teaches the health tracker — the copy's
                    # TRUE latency is exactly what routing must learn
                    if selector is not None and err0 is None:
                        r0 = f.result()
                        selector.observe(candidate, lat,
                                         load=r0.get("load")
                                         if isinstance(r0, dict) else None)
                    return
                timer.cancel()
                if err0 is not None:
                    # ANY per-attempt failure fails over to the next copy —
                    # including transport errors to a node that died after
                    # this state was read (ref: onFirstPhaseResult treats
                    # every shard exception as failover, :292); terminal
                    # only when the chain runs out of candidates
                    if selector is not None:
                        selector.failure(candidate)
                    attempt_failed(candidate, err0, hedge)
                    return
                try:
                    r = f.result()
                    if selector is not None:
                        selector.observe(candidate, lat,
                                         load=r.get("load")
                                         if isinstance(r, dict) else None)
                    if trace_ref is not None and isinstance(r, dict):
                        trace_ref.add_remote(r.get("spans"))
                    prof = r.get("profile")
                    if isinstance(prof, dict):
                        # ?profile=true: record whether this shard's profile
                        # came from the winning primary attempt or a hedge
                        prof = {**prof,
                                "winner": "hedge" if hedge else "primary"}
                    result = ShardQueryResult(
                        total=r["total"],
                        docs=[tuple(d) for d in r["docs"]],
                        max_score=r["max_score"] if r["max_score"] is not None else float("nan"),
                        agg_partials=_decode_partials(r.get("agg_partials")),
                        facet_partials=_decode_partials(r.get("facet_partials")),
                        suggest=r.get("suggest"),
                        context_id=r.get("ctx_id"),
                        shard_id=candidate.shard_id,
                        timed_out=bool(r.get("timed_out")),
                        degraded=bool(r.get("degraded")),
                        profile=prof,
                    )
                    result.index_name = candidate.index  # type: ignore[attr-defined]
                except Exception as e:  # noqa: BLE001 — a malformed/corrupt
                    # response is an attempt failure like any other: fail
                    # over instead of terminally resolving (which would
                    # discard a concurrently in-flight sibling's good answer)
                    attempt_failed(candidate, e, hedge)
                    return
                # resolve BEFORE dropping the in-flight count: decrement-
                # first opens a window where the last OTHER attempt's
                # concurrent failure reads alive==0 and resolves the chain
                # with its terminal error, discarding this good response
                won = resolve(result, node, None)
                with chain_lock:
                    in_flight[0] -= 1
                if won and hedge and selector is not None:
                    selector.hedges.record_won()

            fut.add_done_callback(on_done)

        try_next(None)
        return done

    _PIN_KEEP_S = 60.0

    def _pin_context(self, index: str, shard_id: int, ctx: ShardContext) -> int:
        """Pin a query-phase ShardContext for the fetch phase; reaped lazily."""
        now = time.monotonic()
        with self._pinned_lock:
            for k in [k for k, v in self._pinned.items() if v[0] < now]:
                del self._pinned[k]
            cid = self._pinned_next[0]
            self._pinned_next[0] += 1
            self._pinned[cid] = (now + self._PIN_KEEP_S, index, shard_id, ctx)
        return cid

    def _take_pinned(self, cid, index: str, shard_id: int) -> ShardContext | None:
        now = time.monotonic()
        with self._pinned_lock:
            for k in [k for k, v in self._pinned.items() if v[0] < now]:
                del self._pinned[k]
            v = self._pinned.pop(cid, None) if cid is not None else None
        if v is not None and v[1] == index and v[2] == shard_id:
            return v[3]
        return None

    def _s_free_context(self, request, channel):
        """ES's free-context: the coordinator releases pinned searchers of shards
        that contributed no fetched hits (the fetch itself pops the winners)."""
        self._take_pinned(request.get("ctx"), request["index"], request["shard"])
        return {}

    def _shard_ctx(self, index: str, shard_id: int, dfs: dict | None = None) -> ShardContext:
        svc = self.indices.index_service(index)
        shard = svc.shard(shard_id)
        # opens the warmer's pack-scheduling gate (warmer.py): refreshes of a
        # shard that has never served a search stay device-free; after the
        # first search, every new view's packs/remasks move off the query
        # path onto the warmer/merge pools. Plain attr write, idempotent
        shard.engine.search_active = True
        global_stats = None
        if dfs:
            global_stats = {
                "max_doc": dfs["max_doc"],
                "df": {(f, t): v for f, t, v in dfs["term_df"]},
                "field_stats": {f: _fs_from(l) for f, l in dfs["field_stats"].items()},
            }
        return ShardContext(shard.engine.acquire_searcher(), svc.mapper_service,
                            svc.similarity_service, global_stats,
                            index_name=index, breakers=self.node.breakers,
                            batcher=getattr(self.node, "search_batcher", None),
                            filter_cache=getattr(self.node, "filter_cache",
                                                 None))

    def _s_query_phase(self, request, channel):
        index, shard_id = request["index"], request["shard"]
        body = dict(request.get("body") or {})
        alias_filter = request.get("alias_filter")
        if alias_filter:
            query = body.get("query") or {"match_all": {}}
            body["query"] = {"filtered": {"query": query, "filter": alias_filter}}
        # `"profile": true` (peeked BEFORE parsing, so the unprofiled path
        # pays no clock read — profile.py design rule): arm the white-box
        # execution profiler for THIS shard's query phase. The collector is
        # created ahead of parse_search_body so its t0 — and therefore
        # phases_ms.total — covers the parse phase it times; it is activated
        # thread-locally around the phase (profiled requests bypass the
        # batcher, so execution never leaves this thread), and its result
        # rides the response next to the span list.
        prof = None
        if isinstance(body, dict) and bool(body.get("profile")):
            prof = profiling.ProfileCollector(node=self.node.name,
                                              index=index, shard=shard_id)
        req = parse_search_body(body)
        if prof is not None:
            prof.phase_s("parse", time.monotonic() - prof.t0)
        ctx = self._shard_ctx(index, shard_id, request.get("dfs"))
        # shard-side budget: the tighter of the coordinator's remaining budget
        # (shipped as a duration in `deadline_s`) and the body's own `timeout`
        budget = request.get("deadline_s")
        if req.timeout_s is not None:
            budget = req.timeout_s if budget is None else min(budget, req.timeout_s)
        deadline = Deadline.after(budget) if budget is not None else NO_DEADLINE
        # continue the coordinator's trace from the wire context (the sender
        # only injects one for sampled traces); the shard span is the parent
        # every batcher span of this request attaches to
        tracer = getattr(self.node, "tracer", None)
        trace = tracer.continue_trace(request.get(tracing.TRACE_WIRE_KEY),
                                      "shard") if tracer is not None \
            else tracing.NOOP_TRACE
        shard_span = trace.root.tag(index=index, shard=shard_id)
        if request.get("hedge"):
            # speculative (hedged) attempt: its shard span shows as a sibling
            # of the primary attempt's in the stitched ?trace=true tree
            shard_span.tag(hedge=True)
        # ---- shard request cache (search/request_cache.py) ----------------
        # key = (index, shard, point-in-time view version, fingerprint of the
        # normalized body). A hit returns the stored partial BEFORE
        # execute_query_phase — zero device launches, zero device syncs. DFS
        # requests never cache (per-request global stats change clause
        # weights); profiled requests always execute (profiling is an
        # explicit opt-in to re-execution) but record hit/miss/store
        # attribution events. The uncached path pays one fingerprint
        # serialization and nothing else.
        rcache = getattr(self.node, "request_cache", None)
        cache_key = None
        peek_hit = False
        if (rcache is not None and rcache.enabled
                and request.get("dfs") is None and cache_policy(body)):
            cache_key = (index, shard_id, ctx.searcher.version,
                         request_fingerprint(body))
        # ---- always-on query-shape insights (common/insights.py) ----------
        # EVERY search classifies into a bounded registry of normalized plan
        # shapes — one canonicalization + hash per request (the same cost
        # class as the request-cache fingerprint above), zero added clocks
        # (latency reuses the slowlog's t_q pair below; the cache-hit path
        # records count + hit attribution only, reading no clock at all)
        insights_reg = getattr(self.node, "insights", None)
        shape_id = shape = None
        if insights_reg is not None and insights_reg.enabled:
            shape_id, shape = insights_reg.fingerprint(body)
        if cache_key is not None:
            if prof is None:
                data = rcache.get(cache_key)
                if data is not None:
                    try:
                        shard_span.tag(request_cache="hit")
                    finally:
                        shard_span.end()
                    if shape_id is not None:
                        insights_reg.record(shape_id, shape, cache="hit")
                    out = _decode_cached_partial(data)
                    out["ctx_id"] = self._pin_context(index, shard_id, ctx)
                    out["load"] = self._load_signal()
                    if trace:
                        out["spans"] = trace.span_dicts()
                    return out
            else:
                peek_hit = rcache.peek(cache_key)
                prof.event("request_cache",
                           cache="hit" if peek_hit else "miss")
        t_q = time.monotonic()
        obs = _insights.Observation() if shape_id is not None else None
        try:
            with tracing.activate(shard_span):
                if obs is not None:
                    with _insights.activate(obs):
                        result = self._execute_qp(ctx, req, shard_id,
                                                  deadline, prof)
                else:
                    result = self._execute_qp(ctx, req, shard_id, deadline,
                                              prof)
        except Exception:
            # a failing shape still classifies (outcome "error"): a query
            # shape storming a breaker/deadline must show in
            # /_insights/queries precisely when the operator needs it
            if shape_id is not None:
                obs.outcome = "error"
                insights_reg.record(
                    shape_id, shape, time.monotonic() - t_q, obs,
                    cache="miss" if cache_key is not None else None)
            raise
        finally:
            shard_span.end()
        took_s = time.monotonic() - t_q
        partial = _shard_partial_dict(result)
        if shape_id is not None:
            # profiled runs that found the entry present (peek) attribute a
            # hit even though profiling re-executed — same rule as the
            # profile event above
            insights_reg.record(
                shape_id, shape, took_s, obs,
                cache=("hit" if peek_hit else "miss")
                if cache_key is not None else None)
        self._maybe_slowlog(index, shard_id, body, took_s,
                            trace=trace, shape_id=shape_id)
        # store the partial for the next sighting of this (body, view) —
        # never a timed-out partial (an honest partial is not THE answer),
        # and never re-store what a profiled run already found present
        if cache_key is not None and not result.timed_out and not peek_hit:
            # the stored bytes drop the degraded flag: it describes HOW this
            # execution was served (host path while a device domain was open),
            # not the data — the partial itself is bitwise-identical, and a
            # later cache hit is served from memory, degraded by nothing
            data = _encode_cached_partial({**partial, "degraded": False})
            # `body` registers the fingerprint in the shard's hot-key memory
            # (hit counts drive the warmer's post-refresh top-N replay)
            if data is not None and rcache.put(cache_key, data, body=body) \
                    and prof is not None:
                prof.event("request_cache", cache="store")
        out = {
            **partial,
            # fetch must read the SAME point-in-time searcher these doc ids
            # come from (a merge between phases moves local ids)
            "ctx_id": self._pin_context(index, shard_id, ctx),
            # response-piggybacked load signals for the coordinator's adaptive
            # replica selection (cluster/stats.py): this node's search-pool
            # queue depth + request-breaker headroom. Plain attribute reads —
            # the serving path gains no locks, clocks, or device traffic
            "load": self._load_signal(),
        }
        if trace:
            # the shard's span list rides the response so the coordinator can
            # stitch the cross-node tree inline (the `?trace=true` contract);
            # the shard node ALSO keeps its own copy in its /_traces ring
            out["spans"] = trace.span_dicts()
        if prof is not None:
            # the shard profile crosses the wire the same way the span list
            # does — plain scalars through the binary codec, stitched by the
            # coordinator into the top-level `profile` section
            out["profile"] = prof.to_dict()
        return out

    @staticmethod
    def _execute_qp(ctx, req, shard_id: int, deadline, prof):
        """One shard query phase, with the profiler activated only when the
        request opted in (profile.py rule: activate(None) is never entered)."""
        if prof is None:
            return execute_query_phase(ctx, req, shard_id=shard_id,
                                       deadline=deadline)
        with profiling.activate(prof):
            return execute_query_phase(ctx, req, shard_id=shard_id,
                                       deadline=deadline)

    def warm_shard_queries(self, index: str, shard_id: int,
                           bodies: list[dict],
                           budget_s: float = 5.0) -> tuple[int, int]:
        """Warmer re-prime (warmer.py, on the `warmer` pool): execute the
        shard's hottest cached bodies against its CURRENT view and store the
        partials, so the first post-refresh sighting of a hot query is a
        request-cache hit. Mirrors _s_query_phase's execute→encode→store
        path minus the spans/insights/slowlog (a warm execution is not a
        request); already-warmed keys are skipped via peek (no hit/miss
        accounting perturbed). Returns (warmed, failed)."""
        rcache = getattr(self.node, "request_cache", None)
        if rcache is None or not rcache.enabled:
            return 0, 0
        warmed = failed = 0
        for body in bodies:
            try:
                ctx = self._shard_ctx(index, shard_id)
                key = (index, shard_id, ctx.searcher.version,
                       request_fingerprint(body))
                if rcache.peek(key):
                    continue  # a live request (or earlier warm) beat us
                req = parse_search_body(dict(body))
                result = execute_query_phase(
                    ctx, req, shard_id=shard_id,
                    deadline=Deadline.after(budget_s))
                if result.timed_out:
                    continue  # honest partials are never cached
                data = _encode_cached_partial(_shard_partial_dict(result))
                # body=None: the warm store must not touch the hot-key
                # ranking the live traffic builds
                if data is not None and rcache.put(key, data):
                    warmed += 1
            except SearchEngineError:
                failed += 1  # shard gone / parse drift: skip this body
            except Exception:  # noqa: BLE001 — warming must never throw into
                # the warmer pool; a single bad body just doesn't warm
                failed += 1
        return warmed, failed

    def _load_signal(self) -> dict:
        """The query-phase response's piggybacked load sample: search-pool
        queue depth + request-breaker headroom fraction, read as plain
        attributes (unlocked int/float reads are exact enough for a decayed
        routing signal and keep the hot path free of new locks and clocks)."""
        queue = self.node.threadpool.queue_depth("search")
        br = self.node.breakers.breaker("request")
        headroom = 1.0 if br.limit <= 0 else \
            max(0.0, 1.0 - br.used / br.limit)
        out = {"queue": queue, "headroom": round(headroom, 4)}
        # per-copy request-cache hit rate piggybacks alongside (also plain
        # int reads): the adaptive selector records it per copy so operators
        # can see WHERE the affinity routing is landing hits (reported in
        # /_nodes/stats adaptive_routing; never a rank input — health ranks)
        rc = getattr(self.node, "request_cache", None)
        if rc is not None:
            lookups = rc.hits + rc.misses
            out["rc_hit_rate"] = round(rc.hits / lookups, 4) if lookups \
                else 0.0
        return out

    def _cluster_slowlog_levels(self, md) -> dict:
        """Parsed cluster-level slowlog thresholds {level: seconds|None},
        rebuilt only when the metadata version moves — the shipped default
        (no thresholds anywhere) costs one attr read + version compare per
        query phase, never a settings-dict flatten."""
        cached = self._slowlog_cluster
        if cached is not None and cached[0] == md.version:
            return cached[1]
        flat = dict(md.persistent_settings)
        flat.update(dict(md.transient_settings))
        levels: dict = {}
        for level in ("warn", "info", "debug"):
            raw = flat.get(f"index.search.slowlog.threshold.query.{level}")
            value = None
            if raw is not None:
                try:
                    value = parse_time(raw)
                except IllegalArgumentError:
                    value = None
            levels[level] = value
        self._slowlog_cluster = (md.version, levels)
        return levels

    def _maybe_slowlog(self, index: str, shard_id: int, body: dict, took_s: float,
                       trace=None, shape_id: str | None = None):
        """Per-shard query slowlog (ref: index/search/slowlog/
        ShardSlowLogSearchService.java:41,60-63 — warn/info/debug/trace thresholds from
        dynamic index settings). Each line carries the trace id, the
        query-shape fingerprint (joinable to `GET /_insights/queries` exactly
        the way the trace id joins `/_traces`), and the queue/device/merge
        phase breakdown (zeros + trace[-] when the request was unsampled).

        Thresholds resolve index settings first, then the CLUSTER transient/
        persistent settings — so `PUT /_cluster/settings` arms the slowlog
        fleet-wide at runtime, no node restart (transient wins over
        persistent, per-index settings win over both)."""
        md = self.cluster_service.state.metadata
        meta = md.index(index)
        if meta is None:
            return
        settings = meta.settings
        cluster_levels = self._cluster_slowlog_levels(md)
        for level, log in (("warn", self.logger.warning), ("info", self.logger.info),
                           ("debug", self.logger.debug)):
            key = f"index.search.slowlog.threshold.query.{level}"
            threshold = settings.get_time(key, None)
            if threshold is None:
                threshold = cluster_levels.get(level)
            if threshold is not None and threshold >= 0 and took_s >= threshold:
                # breakdown only on a threshold hit: phase_breakdown copies
                # the span list under the trace lock — with thresholds unset
                # (the default) a sampled query must not pay that per call
                phases = tracing.phase_breakdown(trace)
                trace_id = trace.trace_id if trace else "-"
                log("slowlog [%s][%d] took[%.1fms] trace[%s] shape[%s] "
                    "queue[%.1fms] device[%.1fms] merge[%.1fms] source[%s]",
                    index, shard_id, took_s * 1000, trace_id, shape_id or "-",
                    phases["queue_ms"], phases["device_ms"],
                    phases["merge_ms"], str(body)[:500])
                return

    def _s_fetch_phase(self, request, channel):
        # the pinned query-time context when available (expired/restarted nodes
        # fall back to a fresh searcher — best effort, like a lost scroll)
        ctx = self._take_pinned(request.get("ctx"), request["index"],
                                request["shard"]) \
            or self._shard_ctx(request["index"], request["shard"])
        req = parse_search_body(request.get("body") or {})
        docs = [(s, d, sv) for s, d, sv in request["docs"]]
        hits = execute_fetch_phase(ctx, req, docs, index_name=request["index"],
                                   shard_id=request["shard"])
        return {"hits": hits}

    def _s_dfs_phase(self, request, channel):
        ctx = self._shard_ctx(request["index"], request["shard"])
        body = request.get("body") or {}
        query = parse_query(body.get("query")) if body.get("query") else None
        from .search.queries import MatchAllQuery

        dfs = collect_dfs(ctx, query or MatchAllQuery(), shard_id=request["shard"])
        return {
            "max_doc": dfs.max_doc,
            "term_df": [[f, t, v] for (f, t), v in dfs.term_df.items()],
            "field_stats": {f: [s.doc_count, s.sum_ttf, s.sum_dfs]
                            for f, s in dfs.field_stats.items()},
        }

    def count(self, index_expr, body=None) -> dict:
        r = self.search(index_expr, {**(body or {}), "size": 0})
        return {"count": r["hits"]["total"], "_shards": r["_shards"]}

    def _lookup_get(self, index, type_name, doc_id, routing=None):
        # a missing lookup DOCUMENT resolves to no terms (reference behavior);
        # a missing lookup INDEX (typo) must fail the request, not silently
        # return zero hits — get_doc's IndexMissingError propagates
        return self.get_doc(index, type_name or "_all", doc_id, routing=routing)

    def delete_by_query(self, index_expr, body) -> dict:
        """Broadcast: resolve matching uids per shard, tombstone (ref: delete_by_query
        replication action — here resolved per shard then replicated)."""
        body = resolve_terms_lookups(body, self._lookup_get)
        state = self.cluster_service.state
        indices = state.metadata.resolve_indices(index_expr)
        futs = []
        for index in indices:
            table = state.routing_table.index(index)
            for group in table.shards:
                for copy in group.active_shards():
                    node = state.nodes.get(copy.node_id)
                    futs.append((index, copy, self.transport.send_request(
                        node, A_SHARD_BROADCAST, {
                            "index": index, "shard": copy.shard_id,
                            "op": "delete_by_query", "body": body})))
        deleted = {i: 0 for i in indices}
        for index, copy, fut in futs:
            r = fut_result(fut, 30.0)
            if copy.primary:
                deleted[index] += r.get("deleted", 0)
        return {"_indices": {i: {"deleted": n} for i, n in deleted.items()}}

    def broadcast(self, index_expr, op: str, extra: dict | None = None) -> dict:
        """refresh / flush / optimize / clear_cache across all shard copies.
        `extra` rides the per-shard payload (e.g. the _cache/clear
        request/filter tier selectors)."""
        state = self.cluster_service.state
        indices = state.metadata.resolve_indices(index_expr) if index_expr else \
            state.metadata.index_names()
        futs = []
        for index in indices:
            table = state.routing_table.index(index)
            if table is None:
                continue
            for group in table.shards:
                for copy in group.active_shards():
                    node = state.nodes.get(copy.node_id)
                    futs.append(self.transport.send_request(node, A_SHARD_BROADCAST, {
                        "index": index, "shard": copy.shard_id, "op": op,
                        **(extra or {}),
                    }))
        ok = 0
        for fut in futs:
            try:
                fut_result(fut, 30.0)
                ok += 1
            except SearchEngineError:
                pass
        total = len(futs)
        return {"_shards": {"total": total, "successful": ok, "failed": total - ok}}

    def _s_broadcast(self, request, channel):
        shard = self.indices.index_service(request["index"]).shard(request["shard"])
        op = request["op"]
        if op == "refresh":
            if shard.engine.refresh():
                self._run_warmers(request["index"], request["shard"])
            return {"ok": True}
        if op == "flush":
            shard.engine.flush()
            return {"ok": True}
        if op == "optimize":
            shard.engine.optimize()
            return {"ok": True}
        if op == "clear_cache":
            # tier selectors (the `?request=&filter=` params of
            # POST /_cache/clear): both default true, reference parity
            clear_request = request.get("request", True) is not False
            clear_filter = request.get("filter", True) is not False
            cleared = {"request": 0, "filter": 0}
            if clear_filter:
                fcache = getattr(self.node, "filter_cache", None)
                for seg in shard.engine.acquire_searcher().segments:
                    seg._device_cache.pop("filters", None)  # host mask cache
                    if fcache is not None:  # device-resident masks + breaker
                        cleared["filter"] += fcache.clear_segment(seg)
            if clear_request:
                rcache = getattr(self.node, "request_cache", None)
                if rcache is not None:
                    cleared["request"] = rcache.invalidate_shard(
                        request["index"], request["shard"], None)
            return {"ok": True, "cleared": cleared}
        if op == "delete_by_query":
            ctx = self._shard_ctx(request["index"], request["shard"])
            from .search.execute import host_match_mask
            from .search.queries import parse_query as pq

            query = pq((request.get("body") or {}).get("query"))
            uids = []
            for seg in ctx.searcher.segments:
                mask = host_match_mask(query, seg, ctx) & seg.live & seg.parent_mask
                import numpy as np

                for local in np.nonzero(mask)[0]:
                    uids.append(f"{seg.types[local]}#{seg.ids[local]}")
            shard.engine.delete_by_uids(uids, query=(request.get("body") or {}).get("query"))
            shard.engine.refresh()
            return {"ok": True, "deleted": len(uids)}
        raise SearchEngineError(f"unknown broadcast op [{op}]")


class _SourceDoc:
    """doc[...] access over a plain source dict (for update scripts)."""

    def __init__(self, source: dict):
        self._source = source

    def __getitem__(self, field):
        from .search.filters import FieldVal

        v = self._source.get(field)
        if v is None:
            return FieldVal([])
        return FieldVal(v if isinstance(v, list) else [v])


def _flatten_text_fields(source: dict, prefix: str = "") -> dict[str, list]:
    """Flatten a _source dict to dotted-path -> list of string values (termvector/mlt
    operate on text fields only)."""
    out: dict[str, list] = {}
    for key, value in (source or {}).items():
        path = f"{prefix}{key}"
        if isinstance(value, dict):
            for k, v in _flatten_text_fields(value, path + ".").items():
                out.setdefault(k, []).extend(v)
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, dict):
                    for k, v in _flatten_text_fields(item, path + ".").items():
                        out.setdefault(k, []).extend(v)
                elif isinstance(item, str):
                    out.setdefault(path, []).append(item)
        elif isinstance(value, str):
            out.setdefault(path, []).append(value)
    return out


def _extract_fields(get_response: dict, fields) -> tuple[dict, dict | None]:
    """Build the `fields` section of a get/update response: meta fields as scalars,
    source leaves as single-element lists (ref: GetResult field rendering)."""
    if isinstance(fields, str):
        fields = [f.strip() for f in fields.split(",")]
    out: dict = {}
    source_out = None
    src = get_response.get("_source") or {}
    for f in fields or []:
        if f == "_source":
            source_out = src
        elif f in ("_routing", "_parent"):
            v = get_response.get(f)
            if v is not None:
                out[f] = str(v)
        elif f in ("_timestamp", "_ttl"):
            v = get_response.get(f)
            if v is not None:
                out[f] = int(v)
        else:
            vals = _source_leaf(src, f)
            if vals:
                out[f] = vals
    return out, source_out


def _source_leaf(src: dict, path: str) -> list:
    cur = src
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return []
    return cur if isinstance(cur, list) else [cur]


def filter_source(src: dict, includes, excludes) -> dict:
    """_source filtering with wildcard paths (ref: common/xcontent XContentMapValues
    .filter — include/exclude globs over the source tree). An include naming an
    object node keeps its whole subtree; an include naming a deeper path descends."""
    import fnmatch

    def norm(spec):
        if spec is None:
            return []
        if isinstance(spec, str):
            return [s.strip() for s in spec.split(",") if s.strip()]
        return [str(s) for s in spec]

    includes, excludes = norm(includes), norm(excludes)

    def matches(path, pattern):
        return fnmatch.fnmatch(path, pattern)

    def is_ancestor(path, pattern):
        """`path` is a strict ancestor of a path the pattern could match."""
        pa, pp = path.split("."), pattern.split(".")
        if len(pa) >= len(pp):
            return False
        return all(fnmatch.fnmatch(a, b) for a, b in zip(pa, pp))

    def walk(obj, prefix, included):
        out = {}
        for k, v in obj.items():
            path = f"{prefix}{k}"
            if excludes and any(matches(path, p) for p in excludes):
                continue
            hit = included or not includes or any(matches(path, p)
                                                 for p in includes)
            if isinstance(v, dict):
                if hit:
                    sub = walk(v, path + ".", included=True)
                    out[k] = sub
                elif any(is_ancestor(path, p) for p in includes):
                    sub = walk(v, path + ".", included=False)
                    if sub:
                        out[k] = sub
            elif hit:
                out[k] = v
        return out

    return walk(src, "", included=False)


def _deep_merge(dst: dict, src: dict):
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v


def _fs_from(lst):
    from .index.segment import FieldStats

    return FieldStats(*lst)


def _shard_partial_dict(result) -> dict:
    """The wire/cache shape of one shard's query-phase partial — the ONE
    construction site shared by the live query phase (_s_query_phase) and
    the warmer's re-prime (warm_shard_queries): warm-stored and live-stored
    request-cache entries must decode identically or a post-refresh hit on
    a warmed entry fails where the live entry worked."""
    return {
        "total": result.total,
        "docs": [[s, d, sv] for (s, d, sv) in result.docs],
        "max_score": None if result.max_score != result.max_score
        else result.max_score,
        "agg_partials": _encode_partials(result.agg_partials),
        "facet_partials": _encode_partials(result.facet_partials),
        "suggest": result.suggest,
        "timed_out": result.timed_out,
        "degraded": result.degraded,
    }


def _encode_cached_partial(partial: dict) -> bytes | None:
    """Serialize a cacheable shard partial through the binary wire codec
    (common/stream.py) — the SAME bytes that cross the transport, so breaker
    accounting is honest and a cache hit hands back an isolated copy. A
    value the codec refuses (an exotic plugin payload) skips caching rather
    than failing the search."""
    from .common.stream import StreamOutput

    try:
        out = StreamOutput()
        out.write_map(partial)
        return out.bytes()
    except SearchEngineError:
        return None


def _decode_cached_partial(data: bytes) -> dict:
    from .common.stream import StreamInput

    return StreamInput(data).read_map()


def _encode_partials(partials):
    """Agg partials cross the wire pickled+b64 (they contain numpy arrays/sets;
    a typed codec replaces this when the TCP transport hardens)."""
    import pickle

    return base64.b64encode(pickle.dumps(partials)).decode("ascii") if partials else None


def _decode_partials(blob):
    import pickle

    if not blob:
        return []
    return pickle.loads(base64.b64decode(blob))
