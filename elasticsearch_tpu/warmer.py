"""Index warmer: off-query-path device packing + post-refresh cache re-prime.

The reference dedicates a named executor to warming new searchers before they
serve (PAPER.md's threadpool model — `warmer`; IndicesWarmer runs registered
warmers on every refresh). Here the warmer is what makes the WRITE path a
first-class perf surface: before this service, a refresh/merge produced a
fresh FrozenSegment whose device pack was built lazily ON the first search
that touched it — the query path paid host staging + HBM upload. Now every
searcher install (refresh, merge publish, optimize, recovery) schedules the
cold device work off the query path:

  * **delta packs / full packs** — unpacked segments of the new view get an
    in-flight pack Future (ops/device_index.begin_warm) installed UNDER the
    engine lock (dict work only), and the pack itself runs on the `warmer`
    pool; a search racing the pack waits on the future instead of
    duplicating the work, so the steady state is `packed_for` = cache hit
    with ZERO query-path packs (PACK_LEDGER pool attribution pins it).
  * **compaction packs** — a merged segment published by `maybe_merge`
    carries a `pack_hint` naming its sources; its pack runs on the `merge`
    pool and concatenates the sources' already-resident device planes
    (pack_segment_concat) instead of re-staging O(postings) from host.
  * **remasks** — a copy-on-write tombstone view re-masks on the warmer
    pool too, so the first post-delete search doesn't pay it.
  * **cache re-prime** (`indices.warmer.enabled` kill switch) — the shard's
    hottest request-cache bodies (top-N by hit count, tracked by
    search/request_cache) replay against the NEW view so the first
    post-refresh sighting is a hit, not a miss; hot filter keys from the
    previous view's holders are pre-seeded on the new segments
    (DeviceFilterCache.seed) so the warm replay promotes their masks to
    residency immediately.

Lock discipline (PR 6): the view listener runs under the engine lock and is
a LEAF — begin_warm is dict work, threadpool.submit never blocks; all pack
compute, device transfers, and query execution happen on pool threads with
no engine lock held. Pack warming only arms once a shard has actually served
a search (`engine.search_active`, set by the action layer): an index that is
written but never read keeps its refreshes device-free.
"""

from __future__ import annotations

import threading
import time

from .common.devicehealth import DEVICE_HEALTH, classify_device_error
from .common.errors import SearchEngineError
from .common.logging import get_logger
from .common.retry import RetryPolicy


class IndexWarmerService:
    """Node-level scheduler hanging pack/re-prime work off engine view
    listeners (wired per shard by indices_service alongside the cache
    invalidation listeners)."""

    def __init__(self, node):
        self.node = node
        settings = node.settings
        self.enabled = bool(
            settings.get_bool("indices.warmer.enabled", True))
        self.top_n = max(0, settings.get_int("indices.warmer.top_n", 8))
        # per-warm-query time budget: a wedged warm execution must not pin a
        # warmer pool thread indefinitely
        self.query_budget_s = settings.get_float(
            "indices.warmer.query_timeout", 5.0)
        # capped retry budget for DEVICE-classified warm-pack failures
        # (common/devicehealth taxonomy): a transient OOM on the warmer pool
        # retries with decorrelated-jitter backoff instead of leaving the
        # segment unpacked for the query path to cold-pack inline
        self.pack_retry_budget = max(0, settings.get_int(
            "indices.warmer.pack_retries", 2))
        self._retry_policy = RetryPolicy(base_s=settings.get_float(
            "indices.warmer.pack_retry_base", 0.05), cap_s=1.0)
        self.logger = get_logger("indices.warmer", node=node.name)
        self._lock = threading.Lock()  # leaf: counters only
        self.packs_scheduled = 0
        self.packs_done = 0
        self.packs_stolen = 0  # claimed by a racing search before we ran
        self.pack_failures = 0
        self.pack_retries = 0  # device-classified failures retried on-pool
        self.reprimes = 0
        self.queries_warmed = 0
        self.query_failures = 0
        self.filters_seeded = 0
        self.rejected = 0  # pool rejections (shutdown/saturation)
        self.compile_warms_scheduled = 0
        self.compile_warm_cycles = 0
        self._compile_warm_queued = False  # one in-flight cycle at a time

    # -- wiring ---------------------------------------------------------------
    def wire(self, index: str, shard_id: int, engine) -> None:
        """Append this shard's warm listener to the engine's view listeners
        (runs under the engine lock on every searcher install — leaf work
        only; see module docstring)."""

        def on_view_change(searcher, dropped):
            if searcher is not None:
                self.on_view_installed(index, shard_id, engine, searcher,
                                       dropped)

        engine.view_listeners.append(on_view_change)

    # -- listener (under the engine lock: leaves only) ------------------------
    def on_view_installed(self, index: str, shard_id: int, engine, searcher,
                          dropped) -> None:
        from .ops.device_index import begin_warm, cancel_warm

        node = self.node
        tp = getattr(node, "threadpool", None)
        if tp is None:
            return
        # pack warming arms only once the shard has served a search: a
        # write-only index's refreshes stay device-free, and the first
        # search's inline pack (query path, by design) opens the gate
        if getattr(engine, "search_active", False):
            breakers = getattr(node, "breakers", None)
            breaker = (breakers.breaker("fielddata")
                       if breakers is not None else None)
            for seg in searcher.segments:
                fut = begin_warm(seg)
                if fut is None:
                    continue  # already live, or a pack is in flight
                hint = seg._device_cache.get("pack_hint") or {}
                pool = "merge" if hint.get("kind") == "compact" else "warmer"
                try:
                    tp.submit(pool, self._run_pack, seg, fut, breaker, index)
                    with self._lock:
                        self.packs_scheduled += 1
                except Exception:  # noqa: BLE001 — rejected/shut-down pool:
                    # clear the marker so the query path packs inline instead
                    # of waiting on work nobody will do
                    cancel_warm(seg, fut)
                    with self._lock:
                        self.rejected += 1
        # cache re-prime (the warmer satellite): replay the hottest cached
        # bodies against the new view. Gated on the kill switch AND on hit-
        # bearing hot keys actually existing for this shard
        if not self.enabled:
            return
        # compile warming rides the same install event (and the same kill
        # switch): a refresh that changed mappers/similarity invalidates
        # executables exactly when it installs the new searcher, so any spec
        # the registry holds un-warm gets replayed off-path NOW, before a
        # query sights the new shapes
        self.schedule_compile_warm(f"searcher-install:{index}")
        rcache = getattr(node, "request_cache", None)
        if (rcache is None or not rcache.enabled
                or not rcache.has_hot(index, shard_id)):
            return
        try:
            tp.submit("warmer", self._re_prime, index, shard_id, engine,
                      list(dropped or ()))
        except Exception:  # noqa: BLE001
            with self._lock:
                self.rejected += 1

    # -- pool workers ---------------------------------------------------------
    def _run_pack(self, seg, fut, breaker, index: str) -> None:
        from .ops.device_index import begin_warm, run_warm

        attempts = 0
        prev_sleep = None
        while True:
            try:
                res = run_warm(seg, fut, breaker=breaker, owner=index)
            except Exception as e:  # noqa: BLE001 — a warm pack failure
                # (breaker trip, device trouble) is survivable: waiters saw
                # the exception through the future and degraded; later
                # searches retry inline
                attempts += 1
                if (classify_device_error(e) is not None
                        and attempts <= self.pack_retry_budget):
                    # DEVICE-classified failure with retry budget left: back
                    # off (decorrelated jitter, still on this warmer/merge
                    # pool thread — never the query path) and re-arm. The
                    # failed attempt cleared the pack marker and resolved the
                    # old future (device_index._perform_pack), so no waiter
                    # ever observes half-packed state; a search racing in
                    # meanwhile claims the fresh future and we stand down.
                    prev_sleep = self._retry_policy.next_backoff(prev_sleep)
                    time.sleep(prev_sleep)
                    fut = begin_warm(seg)
                    if fut is None:
                        with self._lock:
                            self.packs_done += 1
                        return  # packed (or claimed) while we backed off
                    with self._lock:
                        self.pack_retries += 1
                    continue
                with self._lock:
                    self.packs_done += 1
                    self.pack_failures += 1
                # advance the pack fault domain: with no query waiting on the
                # future, nobody else ever classifies this failure
                DEVICE_HEALTH.record_failure(
                    getattr(e, "_estpu_device_domain", None)
                    or f"pack:{index}", e)
                self.logger.debug("warm pack failed [%s][gen %s] after %d "
                                  "attempt(s): %s", index,
                                  getattr(seg, "gen", "?"), attempts, e)
                return
            else:
                with self._lock:
                    # res None = a racing search CLAIMED the work first and
                    # packs it inline (device_index's claimable-future
                    # protocol) — the scheduled work is complete either way,
                    # just not by us
                    self.packs_done += 1
                    if res is None:
                        self.packs_stolen += 1
                if res is not None:
                    # clean pack: reset the domain's strike count (and close
                    # it if this was the recovery probe after a trip)
                    DEVICE_HEALTH.note_success((f"pack:{index}",))
                return

    def schedule_compile_warm(self, reason: str) -> bool:
        """Enqueue one compile-warm cycle on the warmer pool (leaf: dict work
        + submit only — callable under the engine lock). Coalesces: at most
        one queued cycle at a time, and nothing queues when the registry has
        no pending (un-warm) specs — the steady-state searcher install costs
        one counter read."""
        from .common.compilecache import REGISTRY

        tp = getattr(self.node, "threadpool", None)
        if (tp is None or not self.enabled or not REGISTRY.enabled
                or REGISTRY.pending_count() == 0):
            return False
        with self._lock:
            if self._compile_warm_queued:
                return False
            self._compile_warm_queued = True
        try:
            tp.submit("warmer", self.run_compile_warm, reason)
            with self._lock:
                self.compile_warms_scheduled += 1
            return True
        except Exception:  # noqa: BLE001 — rejected/shut-down pool
            with self._lock:
                self._compile_warm_queued = False
                self.rejected += 1
            return False

    def run_compile_warm(self, reason: str) -> dict:
        """Warmer-pool worker: one registry warm cycle (ladder autotune +
        pending-spec replay + manifest save under this node's path.data)."""
        from .common.compilecache import REGISTRY

        with self._lock:
            self._compile_warm_queued = False
        res = REGISTRY.warm_cycle(
            reason, save_path=getattr(self.node, "data_path", None))
        with self._lock:
            self.compile_warm_cycles += 1
        if res.get("warmed") or res.get("failed"):
            self.logger.debug(
                "compile warm cycle (%s): %s", reason, res)
        return res

    def _re_prime(self, index: str, shard_id: int, engine, dropped) -> None:
        node = self.node
        try:
            searcher = engine.acquire_searcher()
        except SearchEngineError:
            return  # engine closed under us
        # seed the previous view's hot filter keys onto the new segments so
        # the warm replay (or the first live sighting) promotes their masks
        # to device residency without the min_sightings ramp
        fcache = getattr(node, "filter_cache", None)
        if fcache is not None and fcache.enabled:
            keys = fcache.hot_keys(list(dropped) + list(searcher.segments))
            if keys:
                seeded = 0
                for seg in searcher.segments:
                    seeded += fcache.seed(seg, keys)
                if seeded:
                    with self._lock:
                        self.filters_seeded += seeded
        rcache = getattr(node, "request_cache", None)
        actions = getattr(node, "actions", None)
        if rcache is None or actions is None or self.top_n <= 0:
            return
        bodies = rcache.hot_bodies(index, shard_id, self.top_n)
        if not bodies:
            return
        warmed, failed = actions.warm_shard_queries(
            index, shard_id, bodies, budget_s=self.query_budget_s)
        with self._lock:
            self.reprimes += 1
            self.queries_warmed += warmed
            self.query_failures += failed

    # -- observability --------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "packs_scheduled": self.packs_scheduled,
                "packs_done": self.packs_done,
                "packs_stolen": self.packs_stolen,
                "pack_failures": self.pack_failures,
                "pack_retries": self.pack_retries,
                "reprimes": self.reprimes,
                "queries_warmed": self.queries_warmed,
                "query_failures": self.query_failures,
                "filters_seeded": self.filters_seeded,
                "rejected": self.rejected,
                "compile_warms_scheduled": self.compile_warms_scheduled,
                "compile_warm_cycles": self.compile_warm_cycles,
            }
