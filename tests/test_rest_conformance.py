"""Executes the reference's YAML REST conformance suite against this framework.

The suite (`/root/reference/rest-api-spec/test/**/*.yaml`) is the reference's behavioral
contract (SURVEY.md §4.4, runner `test/rest/RestTestSuiteRunner.java:85`); we read it as
data at test time and drive our in-process REST controller through the same
do/match/catch assertions. One pytest test per YAML file; the cluster is wiped between
sections exactly as the reference runner wipes indices/templates between tests.
"""

import json
import os

import pytest

from tests import restspec

pytestmark = pytest.mark.skipif(
    not os.path.isdir(restspec.SPEC_ROOT), reason="reference spec not available")

# Sections exercising features this framework intentionally does not implement, with the
# reason (the reference runner has the same concept: a blacklist in RestTestSuiteRunner).
BLACKLIST = {
}

NDJSON_APIS = {"bulk", "msearch", "mpercolate", "mtermvectors"}


@pytest.fixture(scope="module")
def conformance_node(tmp_path_factory):
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.transport.local import LocalTransportRegistry

    registry = LocalTransportRegistry()
    node = Node(name="conformance", registry=registry,
                data_path=str(tmp_path_factory.mktemp("conformance")),
                settings={"index.number_of_shards": 2,
                          "index.number_of_replicas": 0})
    node.start([node.local_node.transport_address])
    node.wait_for_master()
    from elasticsearch_tpu.rest.controller import build_rest_controller
    controller = build_rest_controller(node)
    yield node, controller
    node.close()


def make_dispatch(controller):
    from elasticsearch_tpu.rest.controller import RestRequest

    def dispatch(method, path, query, body):
        if isinstance(body, list):
            body = "".join(
                (line if isinstance(line, str) else json.dumps(line)) + "\n"
                for line in body)
        if not path.startswith("/"):
            path = "/" + path
        resp = controller.dispatch(RestRequest(
            method=method, path=path, params=query, body=body))
        parsed, text = None, ""
        if isinstance(resp.body, (dict, list)):
            parsed = resp.body
        elif isinstance(resp.body, str):
            text = resp.body
            try:
                parsed = json.loads(resp.body)
            except ValueError:
                parsed = None
        return resp.status, parsed, text

    return dispatch


def wipe(dispatch):
    dispatch("DELETE", "/_all", {}, None)
    _, templates, _ = dispatch("GET", "/_template", {}, None)
    for name in (templates or {}):
        dispatch("DELETE", f"/_template/{name}", {}, None)
    _, repos, _ = dispatch("GET", "/_snapshot", {}, None)
    for name in (repos or {}):
        dispatch("DELETE", f"/_snapshot/{name}", {}, None)


SUITES = restspec.discover_suites() if os.path.isdir(restspec.SPEC_ROOT) else []


@pytest.mark.parametrize("rel_path", SUITES)
def test_conformance(rel_path, conformance_node):
    node, controller = conformance_node
    specs = restspec.load_specs()
    dispatch = make_dispatch(controller)
    setup, sections = restspec.load_suite(rel_path)
    ran, skipped = 0, []
    failures = []
    for name, steps in sections:
        key = f"{rel_path}::{name}"
        if key in BLACKLIST or rel_path in BLACKLIST:
            skipped.append((name, BLACKLIST.get(key) or BLACKLIST.get(rel_path)))
            continue
        wipe(dispatch)
        runner = restspec.YamlRunner(dispatch=dispatch, specs=specs)
        try:
            if setup:
                runner.run_steps(setup)
            runner.run_steps(steps)
            ran += 1
        except restspec.SkippedSection as e:
            skipped.append((name, str(e)))
        except Exception as e:  # collect all section failures for one report
            failures.append(f"[{name}] {type(e).__name__}: {e}")
    if failures:
        raise AssertionError(
            f"{len(failures)}/{len(sections)} sections failed:\n" + "\n".join(failures))
    if ran == 0 and skipped:
        pytest.skip("; ".join(f"{n}: {r}" for n, r in skipped))
