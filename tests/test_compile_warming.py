"""Compile warming (ROADMAP item 5): shape-driven AOT executable pre-warming,
autotuned bucket ladders, and the persistent shape manifest.

Unit half: BucketLadder pow-2 cold fallback / DP fit + commit gates
(min_samples, improvement, monotone rungs) / JSON roundtrip; the
encode_args → materialize argspec roundtrip WarmSpec persistence rides on;
registry capture semantics (a serving launch records its spec already-warm, so
steady state never re-executes); request-cache zlib compression (floor,
keep-raw-when-zlib-loses, breaker charged the RESIDENT size, drop-adjusted
gauges).

E2E half (the acceptance pin): boot → serve a query mix → close persists
`<path.data>/compile_manifest.json` → simulated process restart
(jax.clear_caches + registry/ladder reset) → a second node on the SAME
path.data loads the manifest, its startup warm cycle replays every spec on the
warmer pool, and the observed mix then serves under
`sanitize(max_compiles=0)` — zero on-path compiles on a warmed node.
"""

from __future__ import annotations

import os
import time
import zlib

import pytest

from elasticsearch_tpu.common.breaker import CircuitBreakerService
from elasticsearch_tpu.common.compilecache import (LADDERS, MANIFEST_NAME,
                                                   REGISTRY, BucketLadder,
                                                   WarmSpec, encode_args,
                                                   materialize)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.search.request_cache import ShardRequestCache
from elasticsearch_tpu.transport.local import LocalTransportRegistry

pytestmark = pytest.mark.compile


def wait_until(fn, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def registry_guard():
    """REGISTRY/LADDERS are process singletons shared with every other test
    in the session — restore the default knobs and a clean slate afterwards
    (an empty registry is the steady-state no-op: pending 0, no warm work)."""
    yield
    REGISTRY.reset()
    LADDERS.reset()
    REGISTRY.enabled = True
    REGISTRY.persist = True
    REGISTRY.autotune_min_samples = 512
    REGISTRY.autotune_improvement = 0.10


# ---------------------------------------------------------------------------
# bucket ladders
# ---------------------------------------------------------------------------


class TestBucketLadder:
    def test_cold_fallback_is_exact_pow2(self):
        lad = BucketLadder("t")
        # bit-identical to the fixed _pow2_bucket ladder until a fit commits
        assert lad.bucket(5, 4) == 8
        assert lad.bucket(3, 4) == 4
        assert lad.bucket(1, 16) == 16
        assert lad.bucket(17, 16) == 32
        assert lad.bucket(100, 1) == 128

    def test_autotune_commits_fitted_rung(self):
        lad = BucketLadder("t")
        for _ in range(600):
            lad.bucket(17, 1)  # pow-2 pads 17 -> 32 every time
        assert lad.autotune(min_samples=512, improvement=0.10)
        assert lad.stats()["rungs"] == [17]
        assert lad.bucket(17, 1) == 17  # fitted rung adopted
        assert lad.bucket(18, 1) == 32  # past the top rung: pow-2 fallback
        assert lad.bucket(3, 1) == 17  # smallest covering rung

    def test_rungs_monotone_and_bounded(self):
        lad = BucketLadder("t", max_rungs=4)
        for v in (9, 17, 33, 65, 129, 250, 400, 500):
            for _ in range(100):
                lad.bucket(v, 1)
        assert lad.autotune(min_samples=512, improvement=0.10)
        rungs = lad.stats()["rungs"]
        assert rungs == sorted(rungs)
        assert len(rungs) <= 4
        # every observed value has a covering rung at/below its pow-2 pad
        for v in (9, 17, 33, 65, 129, 250, 400, 500):
            assert v <= lad.bucket(v, 1) <= max(rungs)

    def test_no_commit_when_pow2_already_tight(self):
        lad = BucketLadder("t")
        for _ in range(600):
            lad.bucket(64, 1)  # already a pow-2 lane: zero waste to win
        assert not lad.autotune(min_samples=512, improvement=0.10)
        assert lad.stats()["rungs"] is None

    def test_no_commit_below_sample_floor(self):
        lad = BucketLadder("t")
        for _ in range(50):
            lad.bucket(17, 1)
        assert not lad.autotune(min_samples=512, improvement=0.10)
        assert lad.bucket(17, 1) == 32  # still the cold pow-2 ladder

    def test_json_roundtrip_restores_rungs_and_histogram(self):
        lad = BucketLadder("t")
        for _ in range(600):
            lad.bucket(17, 1)
        assert lad.autotune(min_samples=512, improvement=0.10)
        clone = BucketLadder("t")
        clone.load_json(lad.to_json())
        assert clone.bucket(17, 1) == 17  # rungs survive the manifest
        st = clone.stats()
        assert st["observations"] >= 600 and st["rungs"] == [17]


# ---------------------------------------------------------------------------
# argspec encoding
# ---------------------------------------------------------------------------


class TestArgspecRoundtrip:
    def test_encode_materialize_roundtrip(self):
        import numpy as np

        args = [np.zeros((4, 8), np.float32), np.arange(3, dtype=np.int32),
                (np.ones((2,), np.int64), 7), True, "bm25", None, [1.5, 2.5]]
        spec = encode_args(args)
        out = materialize(spec)
        assert out[0].shape == (4, 8) and str(out[0].dtype) == "float32"
        assert out[1].shape == (3,) and str(out[1].dtype) == "int32"
        assert isinstance(out[2], tuple)
        assert out[2][0].shape == (2,) and out[2][1] == 7
        assert out[3] is True and out[4] == "bm25" and out[5] is None
        assert out[6] == [1.5, 2.5]

    def test_warmspec_json_roundtrip_keys_equal(self):
        import json as _json

        import numpy as np

        spec = WarmSpec(site="scoring.dense", family="dense",
                        params=(4, 16, 4096, True),
                        argspec=encode_args([np.zeros((4, 4096), np.float32),
                                             (np.zeros((4,), np.int32), 10)]))
        back = WarmSpec.from_json(_json.loads(_json.dumps(spec.to_json())))
        assert back.key() == spec.key()
        assert back.family == "dense" and back.params == (4, 16, 4096, True)


# ---------------------------------------------------------------------------
# registry capture + warm cycle
# ---------------------------------------------------------------------------


class TestRegistryWarm:
    def test_serving_launch_records_already_warm(self, registry_guard):
        import numpy as np

        REGISTRY.reset()
        REGISTRY.record_launch("test.site", "dense", (2, 16),
                               [np.zeros((2, 64), np.float32)])
        st = REGISTRY.stats()
        # the launch itself populated the dispatch cache: nothing pending, so
        # steady-state warm cycles (and the autotunes they gate) never run
        assert st["specs"] == 1 and st["pending"] == 0

    def test_manifest_restart_warm_cycle_zero_compile_loop(
            self, registry_guard, tmp_path):
        """The invariant in miniature: record a real jitted launch, persist,
        reset (simulated restart), reload, warm — then the SAME-shaped real
        call holds under sanitize(max_compiles=0)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from elasticsearch_tpu.common.jaxenv import compile_tag, sanitize

        REGISTRY.reset()
        LADDERS.reset()

        cache = {}

        def get_fn(scale):
            fn = cache.get(scale)
            if fn is None:
                fn = cache[scale] = jax.jit(lambda x: x * scale + 1.0)
            return fn

        @REGISTRY.builder("test.warm")
        def _build(params):
            return get_fn(params[0])

        x = jax.device_put(np.ones((8, 32), np.float32))
        with compile_tag("dense"):
            get_fn(3.0)(x).block_until_ready()
        REGISTRY.record_launch("test.warm", "dense", (3.0,), [x])
        assert REGISTRY.pending_count() == 0
        REGISTRY._dirty = True
        REGISTRY.save_manifest(str(tmp_path / MANIFEST_NAME))

        # simulated restart: executables and warm state both gone
        cache.clear()
        jax.clear_caches()
        REGISTRY.reset()
        assert REGISTRY.load_manifest(str(tmp_path / MANIFEST_NAME)) == 1
        assert REGISTRY.pending_count() == 1
        REGISTRY._builders["test.warm"] = _build  # reset survivor (module im-
        # port would normally re-register; this test's builder lives here)
        res = REGISTRY.warm_cycle("test")
        assert res["warmed"] == 1 and res["failed"] == 0
        assert REGISTRY.pending_count() == 0
        # the warmed executable serves the real shape with zero compiles
        with sanitize(max_compiles=0) as rep:
            y = get_fn(3.0)(jax.device_put(np.full((8, 32), 2.0, np.float32)))
            jax.block_until_ready(y)
        assert rep.compiles == 0
        assert float(jnp.max(y)) == 7.0  # real math, not a stub

    def test_warm_failure_trips_compile_circuit_off_path(self, registry_guard):
        import numpy as np

        from elasticsearch_tpu.common.devicehealth import DEVICE_HEALTH

        REGISTRY.reset()

        class XlaRuntimeError(RuntimeError):
            """Duck-typed like jaxlib's — a plain Python bug in a builder
            must NOT trip a device circuit (classify returns None for it)."""

        @REGISTRY.builder("test.broken")
        def _build(params):
            raise XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory")

        REGISTRY.record_launch("test.broken", "dense", (1,),
                               [np.zeros((2,), np.float32)])
        with REGISTRY._lock:
            REGISTRY._warmed.clear()  # force it pending
        before = DEVICE_HEALTH.stats().get("domains", {}).get(
            "compile:dense", {}).get("failures", 0)
        res = REGISTRY.warm_cycle("test")
        assert res["failed"] == 1 and res["warmed"] == 0
        after = DEVICE_HEALTH.stats().get("domains", {}).get(
            "compile:dense", {}).get("failures", 0)
        assert after == before + 1  # contained in the compile:<family> domain

    def test_disabled_registry_records_nothing(self, registry_guard):
        import numpy as np

        REGISTRY.reset()
        REGISTRY.enabled = False
        REGISTRY.record_launch("test.site", "dense", (1,),
                               [np.zeros((2,), np.float32)])
        assert REGISTRY.stats()["specs"] == 0
        assert REGISTRY.warm_cycle("test") == {
            "warmed": 0, "failed": 0, "skipped": 0}


# ---------------------------------------------------------------------------
# node e2e: restart persistence (the satellite's acceptance test)
# ---------------------------------------------------------------------------


QUERIES = [
    {"query": {"match": {"body": "alpha"}}, "size": 10},
    {"query": {"match": {"body": "alpha beta"}}, "size": 10},
    {"query": {"match": {"body": "gamma"}}, "size": 20},
    {"query": {"match": {"body": "beta"}}, "size": 0},
]


def _boot(data_path, extra=None):
    node = Node(name="warm_node", registry=LocalTransportRegistry(),
                data_path=data_path,
                settings=Settings.from_flat(extra or {}))
    node.start([node.local_node.transport_address])
    assert node.wait_for_master(5.0)
    return node


def _seed_and_serve(node):
    c = node.client()
    c.create_index("warm", {"settings": {"number_of_shards": 1,
                                         "number_of_replicas": 0}})
    for i in range(80):
        c.index("warm", "doc",
                {"body": f"alpha beta{'' if i % 3 else ' beta'}"
                         f"{' gamma' if i % 5 == 0 else ''}", "n": i},
                id=str(i))
    c.refresh("warm")
    return c, [c.search("warm", q)["hits"]["total"] for q in QUERIES]


def _warmer_drained(node):
    w = node.threadpool.stats().get("warmer", {})
    return not w.get("active") and not w.get("queue")


class TestRestartPersistence:
    def test_warmed_restart_serves_observed_mix_with_zero_compiles(
            self, registry_guard, tmp_path):
        import jax

        from elasticsearch_tpu.common.jaxenv import sanitize

        REGISTRY.reset()
        LADDERS.reset()
        data = str(tmp_path / "n0")

        node = _boot(data)
        try:
            _, totals_a = _seed_and_serve(node)
            assert REGISTRY.stats()["specs_recorded"] > 0
        finally:
            node.close()  # persists the manifest under path.data
        manifest = os.path.join(data, MANIFEST_NAME)
        assert os.path.exists(manifest)

        # simulated process restart: every in-process executable and all
        # warm/ladder state is gone; only path.data survives
        jax.clear_caches()
        REGISTRY.reset()
        LADDERS.reset()

        node = _boot(data)
        try:
            assert REGISTRY.stats()["specs_loaded"] > 0
            # the startup warm cycle drains the manifest on the warmer pool
            assert wait_until(lambda: REGISTRY.pending_count() == 0)
            assert wait_until(lambda: _warmer_drained(node))
            st = node.compile_warming.stats()
            assert st["warmed_total"] > 0 and st["warm_failures"] == 0
            ws = node.warmer.stats()
            assert ws["compile_warms_scheduled"] >= 1
            assert ws["compile_warm_cycles"] >= 1
            c = node.client()
            c.refresh("warm")
            assert wait_until(lambda: _warmer_drained(node))
            # the acceptance pin: the observed mix serves on the warmed node
            # with ZERO package compiles — the warm replay, not the serving
            # path, paid every XLA bill
            with sanitize(max_compiles=0) as rep:
                totals_b = [c.search("warm", q)["hits"]["total"]
                            for q in QUERIES]
            assert rep.compiles == 0, rep.compile_events
            assert totals_b == totals_a  # warmed ≠ wrong
        finally:
            node.close()

    def test_compile_warming_kill_switch(self, registry_guard, tmp_path):
        REGISTRY.reset()
        node = _boot(str(tmp_path / "n1"),
                     {"node.compile_warming.enabled": "false"})
        try:
            _seed_and_serve(node)
            st = node.compile_warming.stats()
            assert not st["enabled"]
            assert st["specs_recorded"] == 0  # capture is off node-wide
            assert not node.warmer.schedule_compile_warm("manual")
        finally:
            node.close()
        # disabled: no manifest written either
        assert not os.path.exists(os.path.join(str(tmp_path / "n1"),
                                               MANIFEST_NAME))

    def test_warmer_kill_switch_blocks_scheduling(self, registry_guard,
                                                  tmp_path):
        import numpy as np

        REGISTRY.reset()
        node = _boot(str(tmp_path / "n2"),
                     {"indices.warmer.enabled": "false"})
        try:
            REGISTRY.record_launch("test.site", "dense", (1,),
                                   [np.zeros((2,), np.float32)])
            with REGISTRY._lock:
                REGISTRY._warmed.clear()
            assert REGISTRY.pending_count() == 1
            # warm work rides the warmer subsystem; its kill switch rules
            assert not node.warmer.schedule_compile_warm("manual")
        finally:
            node.close()


# ---------------------------------------------------------------------------
# request-cache compression (satellite)
# ---------------------------------------------------------------------------


def _breaker():
    svc = CircuitBreakerService(Settings.from_flat(
        {"indices.breaker.total_budget": "1mb"}))
    return svc.breaker("request")


class TestRequestCacheCompression:
    def test_compressed_roundtrip_and_breaker_charges_resident(self):
        br = _breaker()
        rc = ShardRequestCache(Settings.EMPTY, breaker=br,
                               total_budget=1 << 20)
        data = b'{"hits":{"total":12345}}' * 200  # 4.8k, highly compressible
        key = ("i", 0, 1, "fp")
        assert rc.put(key, data)
        st = rc.stats()
        assert st["compressions"] == 1
        assert 0 < st["compressed_bytes"] < len(data)
        assert st["compressed_raw_bytes"] == len(data)
        assert st["compression_ratio"] < 1.0
        # the breaker holds the RESIDENT (compressed) size, not the raw size
        assert br.used == st["compressed_bytes"] + rc.ENTRY_OVERHEAD
        assert rc.get(key) == data  # hit path inflates back to the original

    def test_floor_keeps_small_values_raw(self):
        rc = ShardRequestCache(Settings.EMPTY, total_budget=1 << 20)
        assert rc.put(("i", 0, 1, "fp"), b"x" * 100)  # under the 1k floor
        st = rc.stats()
        assert st["compressions"] == 0 and st["compressed_bytes"] == 0
        assert st["compression_ratio"] == 1.0
        assert rc.get(("i", 0, 1, "fp")) == b"x" * 100

    def test_incompressible_value_stays_raw(self):
        rc = ShardRequestCache(Settings.EMPTY, total_budget=1 << 20)
        data = os.urandom(4096)  # zlib would grow it: keep-raw wins
        assert rc.put(("i", 0, 1, "fp"), data)
        assert rc.stats()["compressions"] == 0
        assert rc.get(("i", 0, 1, "fp")) == data

    def test_negative_floor_disables_compression(self):
        rc = ShardRequestCache(
            Settings.from_flat(
                {"indices.requests.cache.compress_min_bytes": "-1"}),
            total_budget=1 << 20)
        data = b"compress me please " * 400
        assert rc.put(("i", 0, 1, "fp"), data)
        assert rc.stats()["compressions"] == 0
        assert rc.get(("i", 0, 1, "fp")) == data

    def test_gauges_drop_with_entries(self):
        br = _breaker()
        rc = ShardRequestCache(Settings.EMPTY, breaker=br,
                               total_budget=1 << 20)
        data = b'{"aggs":{"m":{"value":59.0}}}' * 100
        rc.put(("i", 0, 1, "a"), data)
        rc.put(("i", 0, 2, "b"), data)
        assert rc.stats()["compressions"] == 2
        # view-advance invalidation drops view-1 entries and their gauges
        rc.invalidate_shard("i", 0, current_view=2)
        st = rc.stats()
        assert st["compressed_raw_bytes"] == len(data)
        rc.clear()
        st = rc.stats()
        assert st["compressed_bytes"] == 0
        assert st["compressed_raw_bytes"] == 0
        assert st["compression_ratio"] == 1.0
        assert br.used == 0  # every resident byte released

    def test_replace_releases_old_compressed_entry(self):
        br = _breaker()
        rc = ShardRequestCache(Settings.EMPTY, breaker=br,
                               total_budget=1 << 20)
        key = ("i", 0, 1, "fp")
        rc.put(key, b"old old old " * 300)
        first = rc.stats()["compressed_bytes"]
        rc.put(key, b"new new new new " * 300)
        st = rc.stats()
        assert st["entries"] == 1 and st["compressions"] == 2
        assert st["compressed_bytes"] != first or first == 0
        assert br.used == st["compressed_bytes"] + rc.ENTRY_OVERHEAD
