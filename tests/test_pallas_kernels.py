"""Pallas gather_scale kernel vs the XLA formulation it replaces — bitwise parity
(interpret mode on CPU; the identical kernel compiles for TPU)."""

import numpy as np
import pytest

from elasticsearch_tpu.ops.device_index import BLOCK
from elasticsearch_tpu.ops.pallas_kernels import gather_scale


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    NB, Qb, TB = 64, 8, 16
    blk_docs = rng.integers(0, 10_000, (NB, BLOCK)).astype(np.int32)
    blk_tfn = rng.random((NB, BLOCK)).astype(np.float32)
    qblk = rng.integers(0, NB, (Qb, TB)).astype(np.int32)
    qw = (rng.random((Qb, TB)) * 3).astype(np.float32)
    qconst = (rng.random((Qb, TB)) < 0.2)
    return blk_docs, blk_tfn, qblk, qw, qconst


class TestGatherScale:
    def test_matches_xla_gather(self, data):
        import jax.numpy as jnp

        blk_docs, blk_tfn, qblk, qw, qconst = data
        docs, contrib = gather_scale(qblk, qw, qconst,
                                     jnp.asarray(blk_docs), jnp.asarray(blk_tfn))
        ref_docs = blk_docs[qblk]
        ref_contrib = qw[:, :, None] * np.where(qconst[:, :, None], 1.0,
                                                blk_tfn[qblk])
        assert np.array_equal(np.asarray(docs), ref_docs)
        assert np.array_equal(np.asarray(contrib),
                              ref_contrib.astype(np.float32))

    def test_full_sparse_path_parity_with_flag(self, tmp_path, monkeypatch):
        """ESTPU_PALLAS=1 must produce bit-identical serving results."""
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.mapper.core import MapperService
        from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
        from elasticsearch_tpu.search.similarity import SimilarityService

        settings = Settings.from_flat({})
        svc = MapperService(settings)
        eng = Engine(str(tmp_path / "pp"), svc)
        rng = np.random.default_rng(4)
        words = [f"w{i}" for i in range(50)]
        for i in range(200):
            eng.index("doc", str(i),
                      {"b": " ".join(rng.choice(words, size=12))})
        eng.refresh()
        ctx = ShardContext(eng.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))
        queries = [{"match": {"b": "w1 w2 w3"}},
                   {"bool": {"must": [{"term": {"b": "w4"}}],
                             "must_not": [{"term": {"b": "w5"}}]}}]
        base = [search_shard(ctx, parse_query(q), 20, use_device=True)
                for q in queries]
        monkeypatch.setenv("ESTPU_PALLAS", "interpret")
        flagged = [search_shard(ctx, parse_query(q), 20, use_device=True)
                   for q in queries]
        for b, f in zip(base, flagged):
            assert b.total == f.total
            assert b.hits == f.hits
        eng.close()

    def test_inside_jit(self, data):
        import jax
        import jax.numpy as jnp

        blk_docs, blk_tfn, qblk, qw, qconst = data
        bd, bt = jnp.asarray(blk_docs), jnp.asarray(blk_tfn)

        @jax.jit
        def fused(qblk, qw, qconst):
            docs, contrib = gather_scale(qblk, qw, qconst, bd, bt)
            return contrib.sum(), docs.max()

        s, m = fused(jnp.asarray(qblk), jnp.asarray(qw),
                     jnp.asarray(qconst.astype(np.int32)))
        ref = (qw[:, :, None] * np.where(qconst[:, :, None], 1.0, blk_tfn[qblk]))
        # f32 sum order differs between backends — tolerance is for the reduction
        # only; element-wise parity is exact (test_matches_xla_gather)
        assert np.allclose(float(s), ref.astype(np.float32).sum(), rtol=1e-4)
        assert int(m) == blk_docs[qblk].max()
