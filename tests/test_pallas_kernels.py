"""Fused Pallas `sparse_score` kernel vs the composed-jnp quantized scan it
fuses — bitwise parity (interpret mode on CPU; the identical kernel compiles
for TPU). The composed path (`scoring.sparse_candidates` + `sparse_reduce`)
stays the behavioral reference; the kernel's final grid step executes the SAME
`sparse_reduce`, so any divergence here is a decode/accumulator bug."""

import numpy as np
import pytest

from elasticsearch_tpu.ops.device_index import BLOCK, TFN_BM25, TFN_TFIDF
from elasticsearch_tpu.ops.scoring import _sparse_impl

pytestmark = pytest.mark.pallas


@pytest.fixture(scope="module")
def data():
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    NB, Qb, TB, F = 64, 8, 16, 3
    doc_pad = 10_240
    return {
        "doc_pad": doc_pad,
        "blk_docs": jnp.asarray(
            rng.integers(0, doc_pad + 1, (NB, BLOCK)).astype(np.int32)),
        "blk_tf": jnp.asarray(rng.integers(0, 200, (NB, BLOCK)).astype(np.uint8)),
        "blk_nb": jnp.asarray(rng.integers(0, 256, (NB, BLOCK)).astype(np.uint8)),
        "caches": jnp.asarray((rng.random((F, 256)) * 2 + 0.1).astype(np.float32)),
        "modes": jnp.asarray(np.array([TFN_BM25, TFN_TFIDF, TFN_BM25], np.int32)),
        "qblk": rng.integers(0, NB, (Qb, TB)).astype(np.int32),
        "qw": (rng.random((Qb, TB)) * 3).astype(np.float32),
        "qconst": rng.random((Qb, TB)) < 0.2,
        "qcnt": np.where(rng.random((Qb, TB)) < 0.7, 1, 1 << 10).astype(np.int32),
        "qfid": rng.integers(0, F, (Qb, TB)).astype(np.int32),
        "n_must": rng.integers(0, 2, Qb).astype(np.int32),
        "msm": np.ones(Qb, np.int32),
        "coord": (rng.random((Qb, 5)) + 0.5).astype(np.float32),
    }


def _run(data, *, use_pallas, simple, use_coord, k=10, passes=3):
    """Launch through jax.jit — exactly how serving launches it
    (_get_sparse_compiled wraps _sparse_impl in one jit; the eager path is not
    a production path and trips the transfer-guard sanitizer on fancy
    indexing)."""
    import jax
    import jax.numpy as jnp

    coord = data["coord"] if use_coord else np.ones_like(data["coord"])
    args = (data["blk_docs"], data["blk_tf"], data["blk_nb"], data["caches"],
            data["modes"], jnp.asarray(data["qblk"]), jnp.asarray(data["qw"]),
            jnp.asarray(data["qconst"]), jnp.asarray(data["qcnt"]),
            jnp.asarray(data["qfid"]), jnp.asarray(data["n_must"]),
            jnp.asarray(data["msm"]), jnp.asarray(coord))

    @jax.jit
    def fn(*a):
        return _sparse_impl(*a, k=k, doc_pad=data["doc_pad"], passes=passes,
                            simple=simple, use_coord=use_coord,
                            use_pallas=use_pallas)

    return fn(*args)


class TestSparseScore:
    @pytest.mark.parametrize("simple,use_coord", [
        (True, False), (False, False), (False, True)])
    def test_bitwise_parity_with_composed(self, data, simple, use_coord):
        """Every variant of the fused kernel must be BIT-identical to the
        composed scan: same scores, same docs, same totals."""
        ref = _run(data, use_pallas=False, simple=simple, use_coord=use_coord)
        out = _run(data, use_pallas=True, simple=simple, use_coord=use_coord)
        for r, o, name in zip(ref, out, ("scores", "docs", "totals")):
            assert np.array_equal(np.asarray(r), np.asarray(o),
                                  equal_nan=True), name

    def test_inside_jit(self, data):
        """The kernel composes under jax.jit (how serving actually launches
        it — _get_sparse_compiled wraps _sparse_impl in one jit)."""
        import jax

        ref = _run(data, use_pallas=False, simple=True, use_coord=False)

        fn = jax.jit(lambda: _run(data, use_pallas=True, simple=True,
                                  use_coord=False))
        out = fn()
        for r, o in zip(ref, out):
            assert np.array_equal(np.asarray(r), np.asarray(o), equal_nan=True)

    def test_i16_and_f32_tf_planes(self, data):
        """The overflow rungs of the tf ladder ride the same kernel: widening
        int16/float32 planes must stay bit-identical to the composed path."""
        import jax.numpy as jnp

        for dt in (np.int16, np.float32):
            d = dict(data)
            d["blk_tf"] = jnp.asarray(np.asarray(data["blk_tf"]).astype(dt))
            ref = _run(d, use_pallas=False, simple=False, use_coord=False)
            out = _run(d, use_pallas=True, simple=False, use_coord=False)
            for r, o in zip(ref, out):
                assert np.array_equal(np.asarray(r), np.asarray(o),
                                      equal_nan=True), dt

    def test_full_sparse_path_parity_with_flag(self, tmp_path, monkeypatch):
        """ESTPU_PALLAS=interpret must produce bit-identical serving results."""
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.mapper.core import MapperService
        from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
        from elasticsearch_tpu.search.similarity import SimilarityService

        settings = Settings.from_flat({})
        svc = MapperService(settings)
        eng = Engine(str(tmp_path / "pp"), svc)
        rng = np.random.default_rng(4)
        words = [f"w{i}" for i in range(50)]
        for i in range(200):
            eng.index("doc", str(i),
                      {"b": " ".join(rng.choice(words, size=12))})
        eng.refresh()
        ctx = ShardContext(eng.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))
        queries = [{"match": {"b": "w1 w2 w3"}},
                   {"bool": {"must": [{"term": {"b": "w4"}}],
                             "must_not": [{"term": {"b": "w5"}}]}}]
        # the CI pallas-interpret leg exports ESTPU_PALLAS for the whole job —
        # the baseline must be the COMPOSED path, not fused-vs-fused
        monkeypatch.delenv("ESTPU_PALLAS", raising=False)
        base = [search_shard(ctx, parse_query(q), 20, use_device=True)
                for q in queries]
        monkeypatch.setenv("ESTPU_PALLAS", "interpret")
        flagged = [search_shard(ctx, parse_query(q), 20, use_device=True)
                   for q in queries]
        for b, f in zip(base, flagged):
            assert b.total == f.total
            assert b.hits == f.hits
        eng.close()
