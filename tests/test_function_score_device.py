"""Device-path function_score: differential tests vs the host scorer.

Every case runs the SAME query through search_shard(use_device=True) — which lowers
function_score onto the dense device kernel (ops/scoring._fs_rows_impl /
_fs_script_impl) — and through the host path, asserting identical totals, hit
ordering and scores. The rows case is bit-identical by construction (float32
lockstep, functions.combined_doc_rows shared); the script case is compared at 5
decimals (f32 device vs f64-then-cast host evaluation).

ref: index/query/functionscore/FunctionScoreQueryParser.java,
common/lucene/search/function/FunctionScoreQuery.java; SURVEY §7 hard-parts
("compiled expression subset that lowers to XLA").
"""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import ScriptError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query
from elasticsearch_tpu.search.execute import lower_flat, search_shard
from elasticsearch_tpu.search.similarity import SimilarityService

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta", "theta"]


def _build(similarity: str):
    tmp = tempfile.mkdtemp()
    settings = Settings.from_flat({"index.similarity.default.type": similarity})
    svc = MapperService(settings)
    eng = Engine(tmp, svc)
    rng = np.random.default_rng(42)
    for i in range(400):
        doc = {
            "body": " ".join(rng.choice(WORDS, size=6)),
            "pop": int(rng.integers(1, 200)),
            "price": float(np.round(rng.uniform(1, 60), 2)),
            "ts": f"2014-01-{int(rng.integers(1, 28)):02d}",
        }
        if i % 7 == 0:
            del doc["pop"]  # missing column
        if i % 11 == 0:
            doc["zero"] = 0
        eng.index("doc", str(i), doc)
        if i == 199:
            eng.refresh()  # force a second segment
    # tombstones interact with live/parent masks in both kernels
    for i in (3, 77, 140, 301):
        eng.delete("doc", str(i))
    eng.refresh()
    ctx = ShardContext(eng.acquire_searcher(), svc,
                       SimilarityService(settings, mapper_service=svc))
    return eng, ctx


@pytest.fixture(scope="module")
def bm25():
    eng, ctx = _build("BM25")
    yield ctx
    eng.close()


@pytest.fixture(scope="module")
def tfidf():
    eng, ctx = _build("default")
    yield ctx
    eng.close()


def _parity(ctx, qd, k=10, expect_device=True, places=None):
    q = parse_query(qd)
    plan = lower_flat(q, ctx)
    if expect_device:
        assert plan is not None and plan.fs is not None, f"not device-lowered: {qd}"
    dev = search_shard(ctx, q, k, use_device=True)
    host = search_shard(ctx, q, k, use_device=False)
    assert dev.total == host.total
    if places is None:  # rows case: float32 lockstep → exact
        assert dev.hits == host.hits
    else:
        assert [d for _s, d in dev.hits] == [d for _s, d in host.hits]
        for (ds, _), (hs, _) in zip(dev.hits, host.hits):
            assert ds == pytest.approx(hs, rel=10 ** -places)
    return dev


# ---------------------------------------------------------------------------
# rows case: doc-only functions (bit-identical to host)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["gauss", "exp", "linear"])
def test_decay_numeric(bm25, kind):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "alpha beta"}},
        "functions": [{kind: {"price": {"origin": 25, "scale": 10,
                                        "offset": 2, "decay": 0.4}}}]}})


def test_decay_date(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "gamma"}},
        "functions": [{"gauss": {"ts": {"origin": "2014-01-15", "scale": "7d"}}}]}})


@pytest.mark.parametrize("mod", ["none", "log1p", "log2p", "ln1p", "ln2p",
                                 "square", "sqrt", "reciprocal"])
def test_field_value_factor_modifiers(bm25, mod):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "delta"}},
        "field_value_factor": {"field": "pop", "factor": 1.3, "modifier": mod,
                               "missing": 2}}})


def test_boost_factor_with_filter(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "alpha beta gamma"}},
        "functions": [
            {"filter": {"range": {"pop": {"gte": 100}}}, "boost_factor": 3},
            {"filter": {"term": {"body": "zeta"}}, "boost_factor": 0.5},
        ]}})


def test_random_score_seeded(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "epsilon"}},
        "functions": [{"random_score": {"seed": 1234}}]}})


@pytest.mark.parametrize("sm", ["multiply", "sum", "avg", "max", "min", "first"])
def test_score_modes(bm25, sm):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "alpha"}},
        "functions": [
            {"filter": {"range": {"price": {"lte": 30}}},
             "gauss": {"price": {"origin": 10, "scale": 15}}},
            {"filter": {"range": {"pop": {"gte": 50}}}, "boost_factor": 2,
             "weight": 1.5},
        ],
        "score_mode": sm}})


@pytest.mark.parametrize("bm", ["multiply", "replace", "sum", "avg", "max", "min"])
def test_boost_modes(bm25, bm):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "beta gamma"}},
        "functions": [{"filter": {"range": {"pop": {"gte": 80}}},
                       "field_value_factor": {"field": "pop", "modifier": "ln2p"}}],
        "boost_mode": bm}})


def test_max_boost_and_outer_boost(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "alpha delta"}},
        "functions": [{"field_value_factor": {"field": "pop", "missing": 1}}],
        "max_boost": 5.0, "boost": 2.5}})


def test_min_score_gates_total(bm25):
    q = {"function_score": {
        "query": {"match": {"body": "alpha"}},
        "functions": [{"gauss": {"price": {"origin": 25, "scale": 8}}}],
        "min_score": 0.8}}
    dev = _parity(bm25, q)
    loose = search_shard(bm25, parse_query(
        {"function_score": q["function_score"]["query"] and {
            "query": q["function_score"]["query"],
            "functions": q["function_score"]["functions"]}}), 10)
    assert dev.total < loose.total  # min_score really trims matches


def test_empty_functions_min_score_only(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "alpha beta"}},
        "min_score": 0.3, "boost_mode": "sum"}})


def test_weight_only_function(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "zeta"}},
        "functions": [{"weight": 4.0, "filter": {"range": {"pop": {"gte": 20}}}}]}})


def test_doc_only_script_rides_rows(bm25):
    # script_score that never reads _score folds into the host-combined row
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "eta"}},
        "script_score": {"script": "log(2 + doc['price'].value)"}}})


def test_tfidf_coord_querynorm_interplay(tfidf):
    # outer boost participates in queryNorm (prepass) but not sub scores
    _parity(tfidf, {"function_score": {
        "query": {"bool": {"should": [{"term": {"body": "alpha"}},
                                      {"term": {"body": "beta"}},
                                      {"term": {"body": "gamma"}}]}},
        "functions": [{"gauss": {"price": {"origin": 20, "scale": 12}}}],
        "boost": 1.7}})


# ---------------------------------------------------------------------------
# script case: _score-reading scripts traced into the kernel
# ---------------------------------------------------------------------------


def test_script_score_basic(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "gamma delta"}},
        "script_score": {"script": "_score * log(2 + doc['price'].value)"}}},
        places=5)


def test_script_score_params_and_weight(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "alpha"}},
        "functions": [{"script_score": {
            "script": "_score * factor + doc['price'].value / divisor",
            "params": {"factor": 2.5, "divisor": 10}}, "weight": 1.25}],
        "boost_mode": "replace"}}, places=5)


def test_script_score_with_filter(bm25):
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "beta epsilon"}},
        "functions": [{"filter": {"range": {"price": {"lte": 40}}},
                       "script_score": {"script": "_score + sqrt(doc['price'].value)"}}],
        "boost_mode": "sum", "max_boost": 20.0, "min_score": 0.2}}, places=5)


def test_script_missing_column_falls_back_to_host(bm25):
    # `pop` is missing on some docs: host evaluates those per-doc (None →
    # ScriptError). The device kernel must flag the query bad and rerun on the
    # host so both paths raise identically.
    qd = {"function_score": {
        "query": {"match": {"body": "alpha"}},
        "script_score": {"script": "_score * doc['pop'].value"}}}
    q = parse_query(qd)
    assert lower_flat(q, bm25) is not None  # device-eligible until data says no
    with pytest.raises(ScriptError):
        search_shard(bm25, q, 10, use_device=False)
    with pytest.raises(ScriptError):
        search_shard(bm25, q, 10, use_device=True)


def test_script_empty_guard_falls_back_and_agrees(bm25):
    # guards missing values via .empty: host serves it (per-doc for the missing
    # rows), device flags bad → host rerun → identical results, no error
    _parity(bm25, {"function_score": {
        "query": {"match": {"body": "alpha"}},
        "script_score": {
            "script": "_score if doc['pop'].empty else _score * log(1 + doc['pop'].value)"}}},
        places=5)


def test_script_nonfinite_raises_on_both_paths(bm25):
    qd = {"function_score": {
        "query": {"match_all": {}},
        "script_score": {"script": "log(doc['zero'].value)"}}}
    # match_all sub query doesn't lower flat — host path both ways, still raises
    with pytest.raises(ScriptError):
        search_shard(bm25, parse_query(qd), 10, use_device=False)
    qd2 = {"function_score": {
        "query": {"match": {"body": "alpha beta gamma delta"}},
        "script_score": {"script": "_score / doc['zero'].value"}}}
    with pytest.raises(ScriptError):
        search_shard(bm25, parse_query(qd2), 10, use_device=True)


def test_multi_function_with_score_script_stays_host(bm25):
    # two functions where one reads _score → not device-expressible → plan None
    q = parse_query({"function_score": {
        "query": {"match": {"body": "alpha"}},
        "functions": [
            {"script_score": {"script": "_score * 2"}},
            {"boost_factor": 3},
        ]}})
    assert lower_flat(q, bm25) is None
    dev = search_shard(bm25, q, 10, use_device=True)
    host = search_shard(bm25, q, 10, use_device=False)
    assert dev.hits == host.hits and dev.total == host.total


def test_service_level_device_serving(bm25):
    # the serving path (execute_query_phase) routes fs plans through the kernels
    from elasticsearch_tpu.search.service import execute_query_phase, parse_search_body

    req = parse_search_body({
        "query": {"function_score": {
            "query": {"match": {"body": "alpha beta"}},
            "functions": [{"gauss": {"price": {"origin": 25, "scale": 10}}}]}},
        "size": 10})
    dev = execute_query_phase(bm25, req, use_device=True)
    host = execute_query_phase(bm25, req, use_device=False)
    assert dev.total == host.total
    assert [(s, d) for s, d, _ in dev.docs] == [(s, d) for s, d, _ in host.docs]
