"""Column-lowered script evaluation: the vectorized fast path must be
indistinguishable from per-doc eval (SURVEY §7 hard-parts: expression subset that
lowers to column math)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.script import ColumnVectorizer, compile_script
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
from elasticsearch_tpu.search.similarity import SimilarityService


@pytest.fixture(scope="module")
def ctx(tmp_path_factory):
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    eng = Engine(str(tmp_path_factory.mktemp("vec")), svc)
    rng = np.random.default_rng(11)
    for i in range(300):
        eng.index("doc", str(i), {"t": "scored doc",
                                  "n": float(rng.integers(1, 100))})
    # missing-field docs: only empty-guarded scripts can score them (the per-doc
    # path raises on unguarded None — identical either way); they exercise the
    # vectorizer's per-doc fallback domain
    for i in range(300, 310):
        eng.index("doc", str(i), {"t": "scored doc"})
    eng.refresh()
    c = ShardContext(eng.acquire_searcher(), svc,
                     SimilarityService(settings, mapper_service=svc))
    yield c
    eng.close()


SCRIPTS = [
    "_score * 2",
    "0 if doc['n'].empty else doc['n'].value * 3 + 1",
    "_score if doc['n'].empty else _score + log(doc['n'].value)",
    "1 if doc['n'].empty else min(doc['n'].value, 10) + max(_score, 0.5)",
    "0 if doc['n'].empty else doc['n'].value",
    "0 if doc['n'].empty else (doc['n'].value * f if doc['n'].value > 50 "
    "else doc['n'].value / f)",
    "0 if doc['n'].empty else sqrt(abs(doc['n'].value - 50))",
]


class TestVectorizedScripts:
    @pytest.mark.parametrize("script", SCRIPTS)
    def test_vectorized_equals_per_doc(self, ctx, script, monkeypatch):
        q = {"function_score": {"query": {"match": {"t": "scored"}},
                                "script_score": {"script": script,
                                                 "params": {"f": 2.0}}}}
        fast = search_shard(ctx, parse_query(q), 300, use_device=False)
        # force the per-doc path and compare bit-for-bit hit lists
        monkeypatch.setattr(ColumnVectorizer, "vectorize", lambda self: None)
        slow = search_shard(ctx, parse_query(q), 300, use_device=False)
        assert fast.total == slow.total
        assert [(round(s, 5), d) for s, d in fast.hits] == \
            [(round(s, 5), d) for s, d in slow.hits]

    def test_subset_boundary_falls_back(self, ctx):
        # doc['n'].values (the list form) is outside the vectorizable subset
        cs = compile_script("doc['n'].values[0] if not doc['n'].empty else 0")
        v = ColumnVectorizer(cs, lambda f: np.zeros(4), np.zeros(4))
        assert v.vectorize() is None

    def test_boolop_returns_values_not_booleans(self, ctx, monkeypatch):
        # Python and/or return operand VALUES; logical_and-style lowering would
        # score every doc 1.0
        script = "(not doc['n'].empty) and log(doc['n'].value + 1)"
        q = {"function_score": {"query": {"match": {"t": "scored"}},
                                "script_score": {"script": script},
                                "boost_mode": "replace"}}
        fast = search_shard(ctx, parse_query(q), 300, use_device=False)
        monkeypatch.setattr(ColumnVectorizer, "vectorize", lambda self: None)
        slow = search_shard(ctx, parse_query(q), 300, use_device=False)
        assert [(round(s, 5), d) for s, d in fast.hits] == \
            [(round(s, 5), d) for s, d in slow.hits]
        assert fast.hits[0][0] > 1.01  # real log values, not collapsed booleans

    def test_params_shadow_score_and_functions(self):
        # per-doc env order is {doc, _score, **funcs, **params} — params win
        cs = compile_script("_score * 2", {"_score": 5.0})
        v = ColumnVectorizer(cs, lambda f: None, np.array([1.0, 2.0]))
        out = v.vectorize()
        assert np.allclose(out, [10.0, 10.0])  # param, not the real scores
        cs2 = compile_script("log(3)", {"log": 2.0})
        v2 = ColumnVectorizer(cs2, lambda f: None, np.zeros(2))
        assert v2.vectorize() is None  # per-doc raises (calling a float) — fall back

    def test_domain_errors_keep_per_doc_semantics(self, tmp_path):
        # log(0): per-doc raises ScriptError; the fast path must not silently
        # return -inf — it routes the doc to per-doc eval, which raises identically
        from elasticsearch_tpu.common.errors import ScriptError

        settings = Settings.from_flat({})
        svc = MapperService(settings)
        eng = Engine(str(tmp_path / "dom"), svc)
        eng.index("doc", "1", {"t": "x", "n": 0.0})
        eng.refresh()
        c = ShardContext(eng.acquire_searcher(), svc,
                         SimilarityService(settings, mapper_service=svc))
        q = {"function_score": {"query": {"match": {"t": "x"}},
                                "script_score": {"script": "log(doc['n'].value)"}}}
        with pytest.raises(ScriptError):
            search_shard(c, parse_query(q), 10, use_device=False)
        eng.close()

    def test_numpy_arity_mismatch_falls_back_not_crashes(self):
        # pow(2,3,5) is legal per-doc (builtin 3-arg pow); np.power(2,3,5) would
        # TypeError — vectorize() must return None, not raise
        cs = compile_script("pow(2, 3, 5)")
        v = ColumnVectorizer(cs, lambda f: None, np.zeros(2))
        assert v.vectorize() is None

    def test_script_sort_vectorized_equals_per_doc(self, ctx, monkeypatch):
        q = {"query": {"match": {"t": "scored"}},
             "sort": [{"_script": {"script":
                                   "0 if doc['n'].empty else doc['n'].value % 17",
                                   "type": "number", "order": "asc"}}],
             "size": 300}
        from elasticsearch_tpu.search.service import (
            execute_query_phase,
            parse_search_body,
        )

        fast = execute_query_phase(ctx, parse_search_body(q))
        monkeypatch.setattr(ColumnVectorizer, "vectorize", lambda self: None)
        slow = execute_query_phase(ctx, parse_search_body(q))
        assert [(d, sv) for (_s, d, sv) in fast.docs] == \
            [(d, sv) for (_s, d, sv) in slow.docs]

    def test_script_sort_sees_real_score(self, ctx):
        # reference semantics: _script sorts expose the doc's _score
        from elasticsearch_tpu.search.service import (
            execute_query_phase,
            parse_search_body,
        )

        q = {"query": {"match": {"t": "scored"}}, "track_scores": True,
             "sort": [{"_script": {"script": "_score * -1.0", "type": "number",
                                   "order": "asc"}}], "size": 300}
        r = execute_query_phase(ctx, parse_search_body(q))
        keys = [sv[0] for (_s, _d, sv) in r.docs]
        assert keys == sorted(keys)
        assert any(k != 0.0 for k in keys)  # real scores, not the old zero default

    def test_vectorizer_direct(self):
        cs = compile_script("_score * w + doc['p'].value", {"w": 3.0})
        cols = {"p": np.array([1.0, 2.0, np.nan, 4.0])}
        v = ColumnVectorizer(cs, cols.get, np.array([10.0, 20.0, 30.0, 40.0]))
        out = v.vectorize()
        assert np.allclose(out[:2], [31.0, 62.0])
        assert np.isnan(out[2])
        assert v.used_fields == {"p"}
