"""Runner for the reference's declarative REST conformance suite.

The reference ships a machine-readable API contract (`rest-api-spec/api/*.json`) and a
YAML test suite (`rest-api-spec/test/**/*.yaml`) executed by
`test/rest/RestTestSuiteRunner.java:85` (SURVEY.md §4.4: "the behavioral contract").
This module re-implements that runner natively: it reads the reference's spec + YAML
files *as data* at test time (nothing is vendored) and drives our in-process REST
controller with the same do/match/length/set/is_true/is_false/lt/gt/skip semantics.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

import yaml

SPEC_ROOT = "/root/reference/rest-api-spec"

# The reference master this framework tracks (pom.xml:9); version-range skips in the
# YAML suite are evaluated against it, exactly as the reference runner does.
EMULATED_VERSION = (2, 0, 0)

# Runner features we implement (the reference runner gates tests on these).
SUPPORTED_FEATURES = {"regex"}


def _parse_version(s) -> tuple:
    s = str(s).strip()
    if not s:
        return (0, 0, 0)
    parts = []
    for piece in s.split("."):
        m = re.match(r"\d+", piece)
        parts.append(int(m.group()) if m else 999)
    while len(parts) < 3:
        parts.append(999 if parts and parts[-1] == 999 else 0)
    return tuple(parts[:3])


def version_skipped(version_range: str) -> bool:
    lo, _, hi = str(version_range).partition("-")
    return _parse_version(lo) <= EMULATED_VERSION <= _parse_version(hi or "999")


class ApiSpec:
    """One endpoint from rest-api-spec/api/<name>.json: methods, path templates, params."""

    def __init__(self, name: str, raw: dict):
        self.name = name
        self.methods = raw.get("methods", ["GET"])
        url = raw.get("url", {})
        self.paths = url.get("paths", [url.get("path", "/")])
        self.parts = set((url.get("parts") or {}).keys())
        self.params = set((url.get("params") or {}).keys())
        self.has_body = raw.get("body") is not None

    def build(self, args: dict) -> tuple[str, str, dict]:
        """Pick the most specific path template satisfiable from args → (method, path, query)."""
        args = {k: ",".join(str(x) for x in v) if isinstance(v, list) else v
                for k, v in args.items()}
        best = None
        for template in self.paths:
            placeholders = set(re.findall(r"\{(\w+)\}", template))
            if placeholders <= set(k for k, v in args.items() if v is not None):
                if best is None or len(placeholders) > len(best[1]):
                    best = (template, placeholders)
        if best is None:
            raise ApiCallError(400, {"error": f"no path of {self.name} satisfiable "
                                              f"from {sorted(args)}"})
        template, placeholders = best
        path = template
        for part in placeholders:
            v = args.pop(part)
            path = path.replace("{%s}" % part, str(v))
        query = {k: ("true" if v is True else "false" if v is False else str(v))
                 for k, v in args.items()}
        return self.methods[0] if len(self.methods) == 1 else self._pick_method(), path, query

    def _pick_method(self):
        # Prefer the mutating verb when a body may be sent (matches the reference
        # runner's behavior of respecting the spec's canonical method list).
        for m in ("POST", "PUT"):
            if m in self.methods:
                return m
        return self.methods[0]


class ApiCallError(Exception):
    def __init__(self, status: int, body):
        super().__init__(f"status={status} body={body}")
        self.status = status
        self.body = body


def load_specs() -> dict[str, ApiSpec]:
    specs = {}
    api_dir = os.path.join(SPEC_ROOT, "api")
    for fname in os.listdir(api_dir):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(api_dir, fname)) as f:
            raw = json.load(f)
        for name, spec in raw.items():
            specs[name] = ApiSpec(name, spec)
    # `create` has no spec file — the reference runner maps it through the client's
    # create() (index with op_type=create); synthesize the equivalent endpoint.
    if "create" not in specs and "index" in specs:
        index_params = raw_params = {}
        try:
            with open(os.path.join(api_dir, "index.json")) as f:
                raw_params = json.load(f)["index"]["url"].get("params", {})
        except (OSError, KeyError):
            pass
        index_params = dict(raw_params)
        specs["create"] = ApiSpec("create", {
            "methods": ["PUT", "POST"],
            # id-less create maps to POST /{index}/{type}?op_type=create, like the
            # reference client's create()
            "url": {"paths": ["/{index}/{type}/{id}/_create", "/{index}/{type}"],
                    "parts": {"index": {}, "type": {}, "id": {}},
                    "params": index_params},
            "body": {"required": True}})
    return specs


def discover_suites() -> list[str]:
    """All YAML test files, as paths relative to the test root."""
    root = os.path.join(SPEC_ROOT, "test")
    out = []
    for dirpath, _, files in os.walk(root):
        for f in sorted(files):
            if f.endswith(".yaml"):
                out.append(os.path.relpath(os.path.join(dirpath, f), root))
    return sorted(out)


def load_suite(rel_path: str) -> tuple[list | None, list[tuple[str, list]]]:
    """Parse one YAML file → (setup_steps, [(section_name, steps), ...])."""
    with open(os.path.join(SPEC_ROOT, "test", rel_path)) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    setup = None
    sections = []
    for doc in docs:
        for name, steps in doc.items():
            if name == "setup":
                setup = steps
            else:
                sections.append((name, steps))
    return setup, sections


class SkippedSection(Exception):
    pass


@dataclass
class YamlRunner:
    """Executes one test section's steps against a dispatch callable.

    dispatch(method, path, query, body) -> (status, parsed_body, text_body)
    """

    dispatch: callable
    specs: dict[str, ApiSpec]
    stash: dict = field(default_factory=dict)
    last_status: int = 0
    last_body: object = None
    last_text: str = ""

    def run_steps(self, steps: list):
        for step in steps:
            assert isinstance(step, dict) and len(step) == 1, f"malformed step {step}"
            (kind, payload), = step.items()
            getattr(self, "step_" + kind)(payload)

    # ---- steps -------------------------------------------------------------

    def step_skip(self, payload):
        if "features" in payload:
            feats = payload["features"]
            feats = feats if isinstance(feats, list) else [feats]
            if not set(feats) <= SUPPORTED_FEATURES:
                raise SkippedSection(f"unsupported runner features {feats}")
        if "version" in payload and version_skipped(payload["version"]):
            raise SkippedSection(payload.get("reason", payload["version"]))

    def step_do(self, payload):
        payload = dict(payload)
        catch = payload.pop("catch", None)
        assert len(payload) == 1, f"do with multiple apis: {payload}"
        (api, args), = payload.items()
        args = self._substitute(args or {})
        body = args.pop("body", None) if isinstance(args, dict) else None
        ignore = args.pop("ignore", None) if isinstance(args, dict) else None
        ignored = ([ignore] if not isinstance(ignore, list) else ignore) \
            if ignore is not None else []
        ignored = [int(s) for s in ignored]
        spec = self.specs[api]
        try:
            method, path, query = spec.build(args)
        except ApiCallError as e:
            self._handle_catch(catch, e.status, e.body, "")
            return
        if api == "create" and not path.endswith("/_create"):
            query = {**query, "op_type": "create"}
        status, parsed, text = self.dispatch(method, path, query, body)
        self.last_status, self.last_body, self.last_text = status, parsed, text
        if method == "HEAD":
            self.last_body = status == 200
        if catch is None:
            if status >= 400 and method != "HEAD" and status not in ignored:
                raise ApiCallError(status, parsed if parsed is not None else text)
        else:
            self._handle_catch(catch, status, parsed, text)

    def _handle_catch(self, catch, status, body, text):
        if catch is None:
            raise ApiCallError(status, body)
        expected = {"missing": (404,), "conflict": (409,), "forbidden": (403,),
                    "request": tuple(range(400, 600)), "param": (400,)}
        if catch in expected:
            assert status in expected[catch], \
                f"expected catch '{catch}' {expected[catch]}, got {status}: {body or text}"
        elif catch.startswith("/") and catch.endswith("/"):
            blob = json.dumps(body) if body is not None else text
            assert status >= 400, f"expected an error matching {catch}, got {status}"
            assert re.search(catch[1:-1], blob), \
                f"error {blob!r} does not match {catch}"
        else:
            raise AssertionError(f"unknown catch clause {catch!r}")

    def step_set(self, payload):
        for path, var in payload.items():
            self.stash[var] = self._lookup(path)

    def step_match(self, payload):
        for path, expected in payload.items():
            actual = self._lookup(path)
            expected = self._substitute(expected)
            if isinstance(expected, str) and len(expected) > 2 and \
                    expected.strip().startswith("/") and expected.strip().endswith("/"):
                pattern = expected.strip()[1:-1]
                blob = actual if isinstance(actual, str) else json.dumps(actual)
                assert re.search(pattern, blob, re.VERBOSE | re.MULTILINE), \
                    f"{path}: {blob!r} !~ /{pattern}/"
            else:
                if isinstance(expected, int) and isinstance(actual, str) and \
                        actual.isdigit():
                    actual = int(actual)
                assert self._eq(actual, expected), \
                    f"{path}: expected {expected!r}, got {actual!r}"

    def _eq(self, actual, expected):
        # YAML 1 == "1" fuzziness, matching the reference runner's lenient comparisons
        if isinstance(expected, dict) and isinstance(actual, dict):
            return (set(expected) == set(actual)
                    and all(self._eq(actual[k], v) for k, v in expected.items()))
        if isinstance(expected, list) and isinstance(actual, list):
            return (len(expected) == len(actual)
                    and all(self._eq(a, e) for a, e in zip(actual, expected)))
        if isinstance(expected, bool) or isinstance(actual, bool):
            return actual is expected or str(actual).lower() == str(expected).lower()
        if isinstance(expected, (int, float)) and isinstance(actual, (int, float)):
            return float(actual) == float(expected)
        if isinstance(expected, (int, float)) and isinstance(actual, str):
            try:
                return float(actual) == float(expected)
            except ValueError:
                return False
        return actual == expected

    def step_length(self, payload):
        for path, expected in payload.items():
            actual = self._lookup(path)
            assert len(actual) == expected, \
                f"length({path}) = {len(actual)}, expected {expected}"

    def step_is_true(self, path):
        v = self._lookup(path)
        assert v not in (None, False, "", 0, "false"), f"is_true({path}) failed: {v!r}"

    def step_is_false(self, path):
        v = self._lookup(path)
        assert v in (None, False, "", 0, {}, [], "false", "0"), \
            f"is_false({path}) failed: {v!r}"

    def step_lt(self, payload):
        for path, bound in payload.items():
            v = self._lookup(path)
            assert float(v) < float(self._substitute(bound)), f"{path}: {v} !< {bound}"

    def step_gt(self, payload):
        for path, bound in payload.items():
            v = self._lookup(path)
            assert float(v) > float(self._substitute(bound)), f"{path}: {v} !> {bound}"

    # ---- helpers -----------------------------------------------------------

    def _substitute(self, value):
        if isinstance(value, str):
            if value.startswith("$"):
                key = value[1:]
                if key == "body":
                    return self.last_body
                return self.stash.get(key, value)
            return value
        if isinstance(value, dict):
            return {k: self._substitute(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self._substitute(v) for v in value]
        return value

    def _lookup(self, path: str):
        if path in ("", "$body"):
            return self.last_text if path == "$body" and isinstance(
                self.last_body, str) else (
                self.last_body if self.last_body is not None else self.last_text)
        obj = self.last_body
        # split on unescaped dots; `\.` is a literal dot inside a key
        keys = [k.replace("\\.", ".") for k in re.split(r"(?<!\\)\.", path)]
        i = 0
        while i < len(keys):
            key = keys[i]
            key = self._substitute(key) if key.startswith("$") else key
            if isinstance(obj, list):
                idx = int(key)
                assert idx < len(obj), \
                    f"path [{path}]: index {idx} out of range (len {len(obj)})"
                obj = obj[idx]
                i += 1
            elif isinstance(obj, dict):
                if key in obj:
                    obj = obj[key]
                    i += 1
                    continue
                # flat↔nested tolerance: try greedily joining following segments
                # ("index" + "number_of_shards" → "index.number_of_shards") or
                # splitting an escaped key into nested descent
                joined = None
                for j in range(len(keys), i, -1):
                    cand = ".".join(keys[i:j])
                    if cand in obj:
                        joined = (obj[cand], j)
                        break
                if joined is not None:
                    obj, i = joined
                    continue
                if "." in key:
                    sub = obj
                    for p in key.split("."):
                        if isinstance(sub, dict) and p in sub:
                            sub = sub[p]
                        else:
                            return None
                    obj = sub
                    i += 1
                    continue
                return None
            else:
                return None
        return obj
