"""tpulint fixture — the HELPER half of the cross-MODULE TPU003 pair.

`leaky_accumulate` appends to a module-level list. Linted ALONE this file is
silent — nothing in it is jitted, and the PR-1 engine (module-local traced
closure) could never flag it. Linted TOGETHER with tp_xmod_tpu003_root.py
(which jits a function that imports and calls this one), the project-wide
traced closure marks it traced and the `TP` line must fire.

Never imported: parsed by tests/test_tpulint.py.
"""

_TRACE_LOG = []


def leaky_accumulate(x):
    y = x * 2
    _TRACE_LOG.append(y)  # TP (only with the root file): closure-append leak
    return y
