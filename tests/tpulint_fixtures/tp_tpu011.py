"""tpulint fixture — TRUE positives for TPU011 (blocking call under a lock)."""

import queue
import threading


class Coordinator:
    def __init__(self, transport):
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._worker = threading.Thread(target=lambda: None)
        self._queue = queue.Queue()
        self.transport = transport

    def wait_for_future(self, fut):
        with self._lock:
            return fut.result(10)  # TP: future wait while holding the lock

    def wait_for_event(self):
        with self._lock:
            self._done.wait()  # TP: untimed Event.wait under the lock

    def join_worker(self):
        with self._lock:
            self._worker.join()  # TP: thread join under the lock

    def drain_one(self):
        with self._lock:
            return self._queue.get()  # TP: queue get under the lock

    def ping(self, node):
        with self._lock:
            return self.transport.send_request(node, "ping", {})  # TP: rpc under the lock

    # -- interprocedural: the wait is buried one call away -------------------
    def _await_reply(self, fut):
        return fut.result(30)  # TP: bottoms out here (only ever called locked)

    def locked_rpc(self, fut):
        with self._lock:
            return self._await_reply(fut)  # TP: blocking wait reached via helper
