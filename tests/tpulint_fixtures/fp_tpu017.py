"""tpulint fixture — FALSE positives for TPU017: everything here must stay
silent. The sanctioned geometry idioms: device sets sized from config,
capability checks as inequalities, `jax.devices()[0]` for "any one device",
grid factors derived from len(devices), and the `axis_index == 0` leader
election.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

# geometry from config, not a literal baked into code paths
N_SHARDS = int(os.environ.get("ESTPU_FIXTURE_SHARDS", "4"))

devices = jax.devices()
if len(devices) < N_SHARDS:  # capability check (inequality) — silent
    devices = devices * N_SHARDS
pool = devices[:N_SHARDS]  # dynamic slice from config — silent
first = jax.devices()[0]  # sanctioned "any one device" idiom — silent

R = max(1, len(pool) // 2)  # grid factors derived from the device count
mesh = Mesh(np.array(pool[:R * 2]).reshape(R, 2), ("replicas", "shards"))


def capability_check():
    return len(jax.devices()) >= N_SHARDS  # inequality — silent


def leader_only(x):
    i = jax.lax.axis_index("shards")
    is_leader = i == 0  # leader-election idiom — silent
    return jnp.where(is_leader, x, 0.0)


def run(x):
    f = shard_map(leader_only, mesh=mesh, in_specs=(P("shards"),),
                  out_specs=P("shards"))
    return f(x), capability_check()
