"""tpulint fixture — TRUE positives for TPU020 (leaky executable caches).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU020. Executables constructed per loop iteration, and
cache stores keyed by raw request shapes (`len(...)` of live data) — the
cache admits one compiled program per distinct request size and never
converges.
"""

import jax

_cache = {}


def _impl(x):
    return x * 2


def store_raw_key(batch):
    n = len(batch)
    key = (n, 128)
    fn = jax.jit(_impl)
    _cache[key] = fn  # TP: cache keyed by the raw request length
    return fn


def setdefault_raw_key(batch):
    fn = jax.jit(_impl)
    return _cache.setdefault(len(batch), fn)  # TP: raw-shape setdefault key


def build_per_iteration(batches):
    outs = []
    for b in batches:
        fn = jax.jit(_impl)  # TP: fresh executable every iteration
        outs.append(fn(b))
    return outs


def build_in_while(batches):
    i = 0
    while i < len(batches):
        step = jax.jit(_impl)  # TP: ctor inside the retry loop
        batches[i] = step(batches[i])
        i += 1
    return batches
