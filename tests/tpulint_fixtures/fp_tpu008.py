"""tpulint fixture — FALSE positives for TPU008: everything here must stay
silent. The standard donation idioms: rebind the name to the result, read
BEFORE donating, donate different buffers per call, loop-carried rebinds.
"""

import functools

import jax
import jax.numpy as jnp


def _step(state, xs):
    return state + xs.sum()


@functools.partial(jax.jit, donate_argnums=(0,))
def decorated_step(state, xs):
    return state * 2 + xs


def rebind_idiom(state, xs):
    step = jax.jit(_step, donate_argnums=(0,))
    state = step(state, xs)  # rebinding revives the name
    return state + 1


def read_before_donate(state, xs):
    checksum = jnp.sum(state)  # reads strictly before the donating call
    step = jax.jit(_step, donate_argnums=(0,))
    return step(state, xs), checksum


def loop_carried(state, batches):
    for xs in batches:
        state = decorated_step(state, xs)  # rebound every iteration
    return state


def non_donating_wrapper(state, xs):
    plain = jax.jit(_step)  # no donate_* — reads after the call are fine
    out = plain(state, xs)
    return out, state + 1
