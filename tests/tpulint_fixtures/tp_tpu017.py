"""tpulint fixture — TRUE positives for TPU017 (hard-coded mesh geometry).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU017. Literal device counts, pinned grid shapes, and
equality checks against topology constants all detonate the moment the fleet
moves off the 8-device dev mesh — geometry must come from mesh.shape[axis] or
config.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

DEVS = jax.devices()[:8]  # TP: literal device-count slice
mesh = Mesh(np.array(DEVS).reshape(2, 4), ("replicas", "shards"))  # TP: grid


def assumes_eight():
    if len(jax.devices()) == 8:  # TP: equality pins the topology
        return True
    return jax.device_count() != 4  # TP: inequality against a literal count


def picks_third_device(arr):
    return jax.device_put(arr, jax.devices()[2])  # TP: literal index > 0


def assumes_axis_size(x):
    i = jax.lax.axis_index("shards")
    mask = i == 3  # TP: axis_index vs literal > 0 assumes the axis size
    return jnp.where(mask, x, 0.0)


def run(x):
    f = shard_map(assumes_axis_size, mesh=mesh, in_specs=(P("shards"),),
                  out_specs=P("shards"))
    return f(x), assumes_eight(), picks_third_device(x)
