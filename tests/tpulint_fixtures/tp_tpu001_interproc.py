"""tpulint fixture — TRUE positives for TPU001's INTERPROCEDURAL extension.

The PR-1 file-local engine analyzed each function in isolation: a branch on a
value produced by a helper call was invisible because only direct `jnp.*`
assignments marked a name as device-resident. The two `TP` lines here were
verified to be MISSED by the file-local engine (device_names empty for
`decide`) and are caught by the pass-1 device-returning fixpoint: `_device_total`
returns a jnp call, `_two_hops` returns `_device_total(...)` one hop further.

Never imported: parsed by tests/test_tpulint.py; exact `TP` line agreement.
"""

import jax.numpy as jnp


def _device_total(xs):
    return jnp.sum(xs)


def _two_hops(xs):
    return _device_total(xs * 2)


def decide(xs):
    total = _device_total(xs)
    if total > 0:  # TP: branch on a device value produced ONE CALL AWAY
        return 1
    hopped = _two_hops(xs)
    while hopped:  # TP: device value through TWO call hops (fixpoint)
        break
    return 0


def host_path(xs):
    # a helper that returns a HOST value (tolist) must not poison the branch
    vals = _host_list(xs)
    if vals:  # silent: _host_list returns .tolist(), not a device value
        return len(vals)
    return 0


def _host_list(xs):
    return jnp.asarray(xs).tolist()
