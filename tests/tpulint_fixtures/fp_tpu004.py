"""tpulint fixture — FALSE positives for TPU004: none of these may fire."""

import threading

import numpy as np
import jax.numpy as jnp


class Ordered:
    """One global acquisition order, host-only critical sections."""

    def __init__(self):
        self._first = threading.Lock()
        self._second = threading.Lock()

    def pair_one(self):
        with self._first:
            with self._second:  # consistent order everywhere: no cycle
                x = np.zeros(3)  # host work under lock is fine
        return x

    def pair_two(self):
        with self._first:
            with self._second:
                return 1

    def dispatch_outside(self, x):
        with self._first:
            n = len(x)
        return jnp.zeros(n)  # device dispatch after the lock is released

    def callback_defined_under_lock(self):
        with self._first:
            def later(x):
                return jnp.sum(x)  # runs later, NOT while the lock is held
        return later
