"""tpulint fixture — FALSE positives for TPU004: none of these may fire."""

import threading

import numpy as np
import jax.numpy as jnp


class Ordered:
    """One global acquisition order, host-only critical sections."""

    def __init__(self):
        self._first = threading.Lock()
        self._second = threading.Lock()

    def pair_one(self):
        with self._first:
            with self._second:  # consistent order everywhere: no cycle
                x = np.zeros(3)  # host work under lock is fine
        return x

    def pair_two(self):
        with self._first:
            with self._second:
                return 1

    def dispatch_outside(self, x):
        with self._first:
            n = len(x)
        return jnp.zeros(n)  # device dispatch after the lock is released

    def callback_defined_under_lock(self):
        with self._first:
            def later(x):
                return jnp.sum(x)  # runs later, NOT while the lock is held
        return later

    def lambda_defined_under_lock(self):
        with self._first:
            later = lambda x: jnp.dot(x, x)  # noqa: E731 — same: defined, not run
        return later

    # helper that dispatches, called ONLY with no lock held: silent
    def _pack(self, x):
        return jnp.asarray(x)

    def pack_unlocked(self, x):
        with self._first:
            n = len(x)
        return self._pack(x[:n])


class Hierarchy:
    """The breaker shape: child -> parent on the SAME class attribute is
    reentrancy on one lock class, not an order edge — instances are strictly
    layered by construction."""

    def __init__(self, parent: "Hierarchy | None" = None):
        self._lock = threading.Lock()
        self.parent = parent
        self.used = 0

    def add(self, n):
        with self._lock:
            if self.parent is not None:
                self.parent._add_from_child(n)  # same lock class: no self-edge
            self.used += n

    def _add_from_child(self, n):
        with self._lock:
            self.used += n


class FilterMaskCacheRight:
    """The build-outside/publish-under idiom (ISSUE 11 filter-mask cache):
    the mask build and its device_put happen with NO lock held; only the
    dict publish takes the leaf lock. Nothing here may fire."""

    def __init__(self):
        self._lock = threading.Lock()
        self._masks = {}

    def store_mask(self, key, host_mask):
        import jax

        row = jax.device_put(host_mask)  # transfer outside any lock
        with self._lock:
            winner = self._masks.get(key)
            if winner is None:
                self._masks[key] = row
                winner = row
        return winner

    def lookup(self, key):
        with self._lock:
            return self._masks.get(key)
