"""tpulint fixture — TRUE positives for TPU019 (unbounded static args).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU019. Static jit arguments key the executable cache by
VALUE: binding one to raw request data (`len(...)` of live input) compiles a
fresh executable per distinct value — positionally, by keyword, and through
the decorated-def parameter mapping.
"""

from functools import partial

import jax


def _impl(x, n):
    return x[:n]


_fn = jax.jit(_impl, static_argnums=(1,))


@partial(jax.jit, static_argnames=("k",))
def _topk(x, k):
    return x[:k]


def call_static_pos(xs, data):
    n = len(xs)
    return _fn(data, n)  # TP: unbounded value bound to static_argnums slot


def call_static_kw(xs, data):
    return _topk(data, k=len(xs))  # TP: unbounded keyword static


def call_static_named_pos(xs, data):
    return _topk(data, len(xs))  # TP: positional binding of a named static
