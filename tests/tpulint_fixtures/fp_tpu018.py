"""tpulint fixture — FALSE positives for TPU018: must stay silent.

The sanctioned shapes: bucket-ladder dims (`_pow2_bucket`/`_k_bucket`),
`min()`-capped dims, config constants — and raw lengths in host-side
bookkeeping functions nowhere near a jit boundary (out of the compile-surface
scope by construction).
"""

import jax
import numpy as np

PAD = 128


def _pow2_bucket(n, minimum=16):
    b = minimum
    while b < n:
        b *= 2
    return b


def _impl(x):
    return x * 2


def launch_bucketed(hits):
    n = _pow2_bucket(len(hits), 16)
    fn = jax.jit(_impl)
    return fn(np.zeros((n, 128), np.float32))  # bucket ladder: bounded


def launch_capped(hits):
    fn = jax.jit(_impl)
    k = min(len(hits), 64)
    return fn(np.zeros((k, 4), np.float32))  # min() bounds the dim


def launch_const(x):
    fn = jax.jit(_impl)
    return fn(x + np.ones((PAD, 4), np.float32))  # config constant


def launch_param(x, n):
    fn = jax.jit(_impl)
    return fn(x * np.zeros(n, np.float32))  # bare parameter: unknown, silent


def host_bookkeeping(hits):
    # raw length is FINE here: no executable is constructed in this function
    # and it calls no factory — host-side numpy never compiles anything
    return np.zeros(len(hits), np.int64)
