"""tpulint fixture — FALSE positives for TPU005: none of these may fire."""

import os


def respectful():
    plat = os.environ.get("JAX_PLATFORMS", "")  # reading is always fine
    child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}  # subprocess env dict
    os.environ["ESTPU_PALLAS"] = "1"  # unrelated key
    os.environ.pop("ESTPU_PALLAS", None)  # unrelated key
    return plat, child_env
