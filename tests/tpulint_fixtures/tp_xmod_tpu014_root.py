"""tpulint fixture — cross-module TRUE positive for TPU014: the host-dependent
branch lives HERE, the collective lives in tp_xmod_tpu014_helper.py. The
spmd.py reach fixpoint follows the call graph across the module boundary and
flags the call site below, naming the helper's psum line as the origin.
"""

import os

import jax
import numpy as np
from jax.sharding import Mesh

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from tp_xmod_tpu014_helper import reduce_all

mesh = Mesh(np.array(jax.devices()[:4]), ("xshards",))


def program(x):
    if os.environ.get("ESTPU_WIDE") == "1":
        x = reduce_all(x)  # TP: reaches lax.psum in the helper module
    return x


def run(x):
    f = shard_map(program, mesh=mesh, in_specs=None, out_specs=None)
    return f(x)
