"""tpulint fixture — FALSE positives for TPU013: none of these may fire."""

import threading

_mod_lock = threading.Lock()


class Channel:
    def __init__(self):
        self._wlock = threading.Lock()
        self.frames = 0

    def send_with(self, frame):
        with self._wlock:  # the sanctioned shape
            self.with_frames = frame

    def send_try_finally(self, frame):
        self._wlock.acquire()
        try:
            self.frames += 1
        finally:
            self._wlock.release()

    def send_conditional(self, frame):
        if self._wlock.acquire(timeout=1.0):
            try:
                self.frames += 1
            finally:
                self._wlock.release()
        return self.frames

    def send_acquire_inside_try(self, frame):
        try:
            self._wlock.acquire()
            self.frames += 1
        finally:
            self._wlock.release()


def module_level_balanced():
    _mod_lock.acquire()
    try:
        return 1
    finally:
        _mod_lock.release()
