"""tpulint fixture — FALSE positives for TPU021: must stay silent.

Consistent operand families never fire: every site of one callable committed
(the `_scalar_f32` idiom), every site of another weak-scalar-only, unknown
operands (bare parameters, arbitrary calls) contributing nothing. The two
factories below are DISTINCT origins — their families never merge.
"""

import jax
import numpy as np


def _impl(x, alpha):
    return x * alpha


def _scalar_f32(v):
    return jax.device_put(np.float32(v))


def _get_committed_fn():
    fn = jax.jit(_impl)
    return fn


def _get_scalar_fn():
    fn = jax.jit(_impl)
    return fn


def score_a(x):
    fn = _get_committed_fn()
    return fn(x, _scalar_f32(0.5))  # committed via the sanctioned idiom


def score_b(x, t):
    fn = _get_committed_fn()
    return fn(x, jax.device_put(np.float32(t)))  # also committed: consistent


def rank_a(x):
    fn = _get_scalar_fn()
    return fn(x, 0.5)  # scalar-only family: one weak executable, consistent


def rank_b(x, fast):
    fn = _get_scalar_fn()
    return fn(x, 0.5 if fast else 2.0)  # both branches scalar: still one kind


def unknown_operand(x, alpha):
    fn = _get_scalar_fn()
    return fn(x, alpha)  # bare parameter: unknown kind, never contributes
