"""tpulint fixture — TRUE positives for TPU009 (dtype drift into jit regions).

Never imported: parsed by tests/test_tpulint.py; exact `TP` line agreement.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_kernel(x):
    table = np.arange(256)  # TP: numpy default int64 inside a jit region
    bias = np.zeros(x.shape[0])  # TP: numpy default float64
    return x + jnp.asarray(table)[0] + jnp.asarray(bias)


def _helper_reached_from_jit(x):
    # traced transitively: wrapper (jitted below) calls this
    scale = np.full(4, 0.5)  # TP: default float64 one call away from the jit
    return x * jnp.asarray(scale)


def wrapper(x):
    y = jnp.asarray(x, dtype=jnp.float64)  # TP: explicit f64 dtype in trace
    w = np.float64(2.0) * 1.0  # TP: f64 scalar cast in trace
    return _helper_reached_from_jit(y) * w


fn = jax.jit(wrapper)
