"""tpulint fixture — TRUE positives for TPU013 (unbalanced acquire)."""

import threading

_mod_lock = threading.Lock()


class Channel:
    def __init__(self):
        self._wlock = threading.Lock()
        self.frames = 0

    def send_leaky(self, frame):
        self._wlock.acquire()  # TP: no release anywhere on this path
        self.frames += 1

    def send_exception_leaks(self, frame):
        self._wlock.acquire()  # TP: release exists but no try/finally guards it
        self.frames += 1
        self._wlock.release()

    def conditional_no_guard(self):
        if self._wlock.acquire(timeout=1.0):  # TP: body has no try/finally release
            self.frames += 1
            self._wlock.release()


def module_level_leak():
    _mod_lock.acquire()  # TP: bare module-lock acquire
    return 1
