"""tpulint fixture — TRUE positives for TPU003 (tracer leaks)."""

import jax

_trace_log = []
_last_value = None


class Holder:
    def compute(self, x):
        def traced(v):
            self.cache = v * 2  # TP: self assignment during trace
            _trace_log.append(v)  # TP: closure append during trace
            return v * 2

        fn = jax.jit(traced)
        return fn


def make_global_leak():
    def traced(v):
        global _last_value
        _last_value = v  # TP: global assignment during trace
        return v

    fn = jax.jit(traced)
    return fn


_acc = []


def _transitive_helper(v):
    _acc.append(v)  # TP: reached through the traced call graph
    return v


def traced_root(v):
    return _transitive_helper(v) * 2


root_fn = jax.jit(traced_root)
