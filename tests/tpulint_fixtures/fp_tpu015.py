"""tpulint fixture — FALSE positives for TPU015: everything here must stay
silent. Placements that MATCH the shard_map signature, the sanctioned
explicit-reshard idiom (re-device_put to the expected spec before dispatch),
dynamically built in_specs (unknowable — mesh_search builds its specs
imperatively), and arrays from unknown producers.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("replicas", "shards"))


def program(x):
    return jax.lax.psum(x, "shards")


def matching_spec(arr):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"),), out_specs=P())
    x = jax.device_put(arr, NamedSharding(mesh, P("shards")))
    return f(x)  # placement agrees with in_specs — silent


def explicit_reshard(arr):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"),), out_specs=P())
    x = jax.device_put(arr, NamedSharding(mesh, P("replicas")))
    x = jax.device_put(x, NamedSharding(mesh, P("shards")))  # sanctioned fix
    return f(x)  # rebind updated the tracked placement — silent


def dynamic_specs(arr, extra):
    specs = [P("shards")]
    if extra:
        specs.append(P())
    f = shard_map(program, mesh=mesh, in_specs=tuple(specs), out_specs=P())
    x = jax.device_put(arr, NamedSharding(mesh, P("replicas")))
    return f(x)  # in_specs built dynamically: unknowable — silent


def unknown_producer(arr, make_input):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"),), out_specs=P())
    x = make_input(arr)
    return f(x)  # producer's placement unknown — silent


def run(arr):
    return (matching_spec(arr), explicit_reshard(arr),
            dynamic_specs(arr, None), unknown_producer(arr, jnp.asarray))
