"""tpulint fixture — TRUE positives for TPU007 (shard_map spec drift).

Never imported: parsed by tests/test_tpulint.py; exact `TP` line agreement.
"""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:2]), ("shards",))


def two_arg_program(docs, freqs):
    return docs + freqs


def build():
    f = shard_map(two_arg_program, mesh=mesh,  # TP: 3 in_specs, 2 params
                  in_specs=(P("shards"), P("shards"), P("shards")),
                  out_specs=P())
    g = shard_map(two_arg_program, mesh=mesh,  # TP: 1 in_spec, 2 params
                  in_specs=(P("shards"),),
                  out_specs=P())
    bad_spec = P("replicaz")  # TP: no Mesh declares axis "replicaz"
    return f, g, bad_spec
