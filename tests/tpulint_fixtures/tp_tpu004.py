"""tpulint fixture — TRUE positives for TPU004 (lock hazards)."""

import threading

import jax.numpy as jnp


class Service:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:  # TP: a→b edge of the cycle
                pass

    def backward(self):
        with self._b:
            with self._a:  # TP: b→a edge of the cycle
                pass

    def dispatch_under_lock(self, x):
        with self._a:
            y = jnp.sum(x)  # TP: device dispatch while holding a lock
            y.block_until_ready()  # TP: device sync while holding a lock
        return y
