"""tpulint fixture — TRUE positives for TPU004 (lock hazards).

Since PR 6 the rule is interprocedural: cycles formed by edges that only exist
through a call (holding one lock, calling a helper that takes another) and
device dispatch buried one call away are flagged too.
"""

import threading

import jax.numpy as jnp


class Service:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()
        self._d = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:  # TP: a→b edge of the cycle
                pass

    def backward(self):
        with self._b:
            with self._a:  # TP: b→a edge of the cycle
                pass

    def dispatch_under_lock(self, x):
        with self._a:
            y = jnp.sum(x)  # TP: device dispatch while holding a lock
            y.block_until_ready()  # TP: device sync while holding a lock
        return y

    # -- interprocedural cycle: the c→d edge only exists through a call ------
    def _takes_d(self):
        with self._d:  # TP: acquired while every caller holds c (c→d edge)
            return 1

    def via_helper(self):
        with self._c:
            return self._takes_d()  # TP: call-propagated edge on the cycle

    def reverse_pair(self):
        with self._d:
            with self._c:  # TP: d→c edge closing the cycle
                pass

    # -- interprocedural dispatch: the jnp call is one hop away --------------
    def _score(self, x):
        return jnp.dot(x, x)  # TP: bottoms out here (only ever called locked)

    def score_under_lock(self, x):
        with self._b:
            return self._score(x)  # TP: dispatch reached via helper


class FilterMaskCacheWrong:
    """The cache-publish anti-idiom (ISSUE 11): device_put of a freshly built
    filter mask UNDER the publish lock — the transfer (and any dispatch it
    implies) serializes every concurrent lookup behind HBM traffic. The
    correct shape (build + device_put outside, publish under) is pinned
    clean in fp_tpu004.py."""

    def __init__(self):
        self._lock = threading.Lock()
        self._masks = {}

    def store_mask(self, key, host_mask):
        with self._lock:
            import jax

            row = jax.device_put(host_mask)  # TP: device transfer under the publish lock
            self._masks[key] = row
        return row
