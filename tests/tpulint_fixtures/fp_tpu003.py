"""tpulint fixture — FALSE positives for TPU003: none of these may fire."""

import jax


def clean_traced(v):
    parts = []
    for i in range(3):
        parts.append(v * i)  # append to a LOCAL list: legal inside a trace
    return sum(parts)


clean_fn = jax.jit(clean_traced)


class HostSide:
    """Untraced object code may do all of this freely."""

    def update(self, x):
        self.state = x  # self assignment outside any trace
        out = []
        out.append(x)
        return out


_host_log = []


def untraced_logger(v):
    _host_log.append(v)  # closure append outside any trace
    return v
