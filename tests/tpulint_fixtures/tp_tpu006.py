"""tpulint fixture — TRUE positives for TPU006 (SPMD collective hazards).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU006; exact line agreement is asserted, so this file is the
rule's behavioral spec.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]), ("shards",))


def mapped_wrong_axis(x):
    # inside shard_map (passed by name below) but the axis name doesn't exist
    local = jnp.sum(x)
    return jax.lax.psum(local, "shardz")  # TP: no Mesh declares axis "shardz"


def mapped_wrong_axis_gather(x):
    return jax.lax.all_gather(x, axis_name="replicaz")  # TP: unknown mesh axis


def not_mapped_at_all(x):
    # this function is called directly (below) and never shard_map'd: there is
    # no named axis here at runtime
    return jax.lax.psum(jnp.sum(x), "shards")  # TP: collective outside shard_map


def helper_reached_from_mapped(x):
    # covered transitively: mapped_entry (shard_map'd) calls this — the axis
    # check still applies through the call graph
    return jax.lax.pmax(x, "bad_axis")  # TP: unknown axis via transitive cover


def mapped_entry(x):
    return helper_reached_from_mapped(jnp.abs(x))


def run(x):
    f = shard_map(mapped_wrong_axis, mesh=mesh, in_specs=None, out_specs=None)
    g = shard_map(mapped_wrong_axis_gather, mesh=mesh, in_specs=None,
                  out_specs=None)
    h = shard_map(mapped_entry, mesh=mesh, in_specs=None, out_specs=None)
    return f(x), g(x), h(x), not_mapped_at_all(x)
