"""tpulint fixture — FALSE positives for TPU007: everything here must stay
silent. Mirrors mesh_search's real spec construction: matching arity,
declared axes, dynamically-built spec lists, *args programs.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("replicas", "shards"))


def two_arg_program(docs, freqs):
    return docs + freqs


def vararg_program(docs, *extra):
    return docs


def build(has_extra: bool):
    # matching arity, declared axes — silent
    f = shard_map(two_arg_program, mesh=mesh,
                  in_specs=(P("shards"), P("shards")), out_specs=P())
    # *args target: arity open — silent
    g = shard_map(vararg_program, mesh=mesh,
                  in_specs=(P("shards"), P("shards"), P()), out_specs=P())
    # dynamically-assembled specs (the mesh_search idiom) — silent
    specs = [P("shards"), P("shards")]
    if has_extra:
        specs.append(P())
    h = shard_map(two_arg_program, mesh=mesh, in_specs=tuple(specs),
                  out_specs=P())
    # PartitionSpec with declared axes, incl. NamedSharding placement — silent
    sharding = NamedSharding(mesh, P("replicas", "shards"))
    empty = P()
    return f, g, h, sharding, empty
