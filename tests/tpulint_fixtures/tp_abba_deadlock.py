"""tpulint fixture AND runtime lock-trace fixture — the ABBA deadlock.

Static: TPU004 flags both inner acquisitions (the a→b and b→a edges of the
cycle). Runtime: run as a script under ESTPU_LOCKTRACE=1 and the lock-trace
sanitizer (elasticsearch_tpu/common/locktrace.py) records the same cycle from
the actual thread interleaving and FAILS with a report naming both
acquisition sites — without ever hitting the deadlock (the threads run one
after the other; the order graph, not the wall clock, proves the hazard —
lockdep's trick).

    python tests/tpulint_fixtures/tp_abba_deadlock.py abba    -> exit 1, cycle
    python tests/tpulint_fixtures/tp_abba_deadlock.py fixed   -> exit 0
"""

import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, os.pardir))

from elasticsearch_tpu.common.locktrace import TRACER, maybe_install  # noqa: E402

maybe_install()

# constructed AFTER install so the tracer wraps them
lock_a = threading.Lock()
lock_b = threading.Lock()


def take_ab():
    with lock_a:
        with lock_b:  # TP: a→b edge of the cycle
            pass


def take_ba():
    with lock_b:
        with lock_a:  # TP: b→a edge of the cycle
            pass


def main(order: str) -> int:
    first = threading.Thread(target=take_ab)
    first.start()
    first.join()
    second = threading.Thread(target=take_ab if order == "fixed" else take_ba)
    second.start()
    second.join()
    TRACER.check()  # raises LockOrderViolation on the abba interleaving
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "abba"))
