"""tpulint fixture — cross-module TPU004, helper side.

Alone this file is SILENT: pack_rows dispatches to the device but holds no
lock here. The hazard only exists when a caller in another module invokes it
while holding a lock (tp_xmod_tpu004_root.py) — the shape of a lock taken in
search/batcher.py with the device work buried in ops/scoring.py.
"""

import jax.numpy as jnp


def pack_rows(rows):
    return jnp.asarray(rows)
