"""tpulint fixture — FALSE positives for TPU014: everything here must stay
silent. Mirrors the real mesh-serving idioms: MESH-UNIFORM control flow
(branches on mesh.shape, static config, plain parameters — every process
computes the same answer, so the collective sequence cannot diverge) and
host-side wall-clock reads AROUND the mesh call, never inside it
(mesh_serving's took_ms latency measurement).
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

# read once at import: static config, identical on every process of a fleet
N_LANES = int(os.environ.get("ESTPU_FIXTURE_LANES", "2"))

mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("replicas", "shards"))


def mesh_uniform_shape(x):
    # branching on mesh geometry: every process computes the same answer
    if mesh.shape["shards"] > 1:
        x = jax.lax.psum(x, "shards")
    return jax.lax.all_gather(x, "replicas")


def mesh_uniform_config(x, use_global_stats):
    # a plain parameter is not provably host-divergent — the factory pattern
    # (mesh_search._mesh_score_program closes over static config) stays legal
    if use_global_stats:
        x = jax.lax.psum(x, "shards")
    for _ in range(N_LANES):
        x = jax.lax.pmax(x, "shards")
    return x


def unconditional_collectives(x):
    total = jax.lax.psum(jnp.sum(x), "shards")
    return jax.lax.all_gather(total, "replicas")


def host_side_timing(x):
    # wall clock AROUND the mesh call, never inside the program — the serving
    # loop's latency measurement; this function is never shard_map'd
    f = shard_map(unconditional_collectives, mesh=mesh, in_specs=None,
                  out_specs=None)
    t0 = time.monotonic()
    out = f(x)
    if time.monotonic() - t0 > 1.0:
        return None
    return out


def run(x):
    g = shard_map(mesh_uniform_shape, mesh=mesh, in_specs=None,
                  out_specs=None)
    h = shard_map(mesh_uniform_config, mesh=mesh, in_specs=None,
                  out_specs=None)
    return g(x), h(x, True), host_side_timing(x)
