"""tpulint fixture — FALSE positives for TPU020: must stay silent.

The sanctioned patterns: module-level executables (the decorator idiom),
caches keyed on bucket-ladder dims or config flags, and get-or-build caches
whose ctor sits under an `if` (not a loop). Unknown key elements (parameters,
`.shape` reads of already-bucketed arrays) never fire.
"""

import jax

_cache = {}


def _pow2_bucket(n, minimum=16):
    b = minimum
    while b < n:
        b *= 2
    return b


def _impl(x):
    return x * 2


_module_fn = jax.jit(_impl)  # module-level construction: compiles once


def bucket_keyed(batch, simple):
    key = (_pow2_bucket(len(batch), 16), bool(simple))
    fn = _cache.get(key)
    if fn is None:
        fn = jax.jit(_impl)  # under an if, not a loop — get-or-build
        _cache[key] = fn  # bucketed key: bounded executable family
    return fn


def config_keyed(doc_pad, k):
    key = (doc_pad, k)  # bare parameters: unknown provenance, silent
    fn = _cache.get(key)
    if fn is None:
        fn = jax.jit(_impl)
        _cache[key] = fn
    return fn


def shape_keyed(x):
    key = x.shape[0]  # .shape of an already-padded operand: unknown, silent
    return _cache.setdefault(key, _module_fn)
