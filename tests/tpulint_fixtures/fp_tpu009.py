"""tpulint fixture — FALSE positives for TPU009: everything here must stay
silent. Explicit-dtype trace-time constants (the DL_TABLE idiom), jnp
constructors (x64-governed, not numpy-default), and numpy-default
constructions OUTSIDE any traced region (host-side assembly is allowed to be
int64 — it gets cast at the device_put boundary on purpose).
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_kernel(x):
    table = np.arange(256, dtype=np.uint8)  # explicit dtype — silent
    bias = np.zeros(x.shape[0], dtype=np.float32)  # explicit dtype — silent
    acc = jnp.zeros(x.shape[0])  # jnp: governed by jax_enable_x64 — silent
    return x + jnp.asarray(table)[0] + jnp.asarray(bias) + acc


def host_side_assembly(entries):
    # NOT traced (never jitted, not called from a jit root): host numpy with
    # default dtypes is the normal packing idiom — silent
    offsets = np.zeros(len(entries) + 1)
    counts = np.arange(len(entries))
    return offsets, counts


def _helper(x):
    return x * jnp.float32(2.0)  # f32 scalar — silent


def wrapper(x):
    return _helper(x)


fn = jax.jit(wrapper)
