"""tpulint fixture — TRUE positives for TPU015 (sharding drift).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU015. Each function places an array under one
NamedSharding/PartitionSpec and then hands it to a shard_map whose literal
in_specs expect a different spec — jit will silently insert an all-gather /
device-to-device reshard on the hot path instead of failing.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("replicas", "shards"))


def program(x):
    return jax.lax.psum(x, "shards")


def replicated_helper(arr):
    # spec-returning helper: callers inherit the P("replicas") placement
    return jax.device_put(arr, NamedSharding(mesh, P("replicas")))


def drift_direct(arr):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"),), out_specs=P())
    x = jax.device_put(arr, NamedSharding(mesh, P("replicas")))
    return f(x)  # TP: placed P("replicas"), in_specs[0] expects P("shards")


def drift_via_sharding_name(arr):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"),), out_specs=P())
    s = NamedSharding(mesh, P())
    x = jax.device_put(arr, s)
    return f(x)  # TP: replicated placement vs sharded in_specs[0]


def drift_via_helper(arr):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"),), out_specs=P())
    x = replicated_helper(arr)
    return f(x)  # TP: helper-returned placement disagrees with in_specs[0]


def run(arr):
    return (drift_direct(arr), drift_via_sharding_name(arr),
            drift_via_helper(arr))
