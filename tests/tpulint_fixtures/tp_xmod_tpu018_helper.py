"""tpulint fixture — cross-module half of the TPU018 pair: the raw length.

Linted ALONE this file has no TPU018 findings (no executable is constructed
here — host-side bookkeeping is out of the compile surface). Linted together
with tp_xmod_tpu018_root.py, the return-calls fixpoint marks `staged_len` as
unbounded-returning and the root's allocation is flagged AT ITS OWN LINE.
"""


def staged_len(entries):
    return len(entries)
