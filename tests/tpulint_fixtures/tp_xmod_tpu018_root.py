"""tpulint fixture — cross-module TRUE positive for TPU018: the unbucketed
length is computed in tp_xmod_tpu018_helper.py, the jit boundary lives HERE.
The compile-surface return-calls fixpoint classifies `staged_len` as
unbounded-returning across the module boundary, so the allocation below is a
request-derived shape with no bucket ladder.
"""

import jax
import numpy as np

from tp_xmod_tpu018_helper import staged_len


def _impl(x):
    return x * 2


def launch(entries):
    fn = jax.jit(_impl)
    m = staged_len(entries)
    return fn(np.zeros((m, 128), np.float32))  # TP: helper-computed raw length
