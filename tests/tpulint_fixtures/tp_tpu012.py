"""tpulint fixture — TRUE positives for TPU012 (unsynchronized shared state)."""

import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.active = 0
        self.completed = 0

    def start_task(self):
        with self._lock:
            self.active += 1

    def finish_task(self):
        self.active -= 1  # TP: races the locked increment (lost update)
        with self._lock:
            self.completed += 1

    def reset(self):
        self.completed = 0  # TP: bare write to a lock-guarded counter


class Registry:
    def __init__(self):
        self._mu = threading.RLock()
        self.entries = {}

    def put(self, k, v):
        with self._mu:
            self.entries = {**self.entries, k: v}

    def clear(self):
        self.entries = {}  # TP: replaces the map without the lock


_unrelated = threading.Lock()


class WrongLock:
    """Holding SOME lock is not synchronization — only the class's own."""

    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def good(self):
        with self._lock:
            self.n += 1

    def bad(self):
        with _unrelated:
            self.n -= 1  # TP: an unrelated lock still races the guarded write
