"""tpulint fixture — FALSE positives for TPU002: none of these may fire.

The repo's sanctioned caching idioms (scoring._compiled_cache,
mesh_search self._compiled) in miniature.
"""

import functools

import jax
import jax.numpy as jnp

_cache: dict = {}


def cached_wrapper(key, x):
    fn = _cache.get(key)
    if fn is None:
        fn = jax.jit(jnp.sum)  # escapes into the module cache below
        _cache[key] = fn
    return fn(x)


class Holder:
    def build(self, x):
        fn = jax.jit(jnp.cumsum)  # escapes onto the instance
        self._fn = fn
        return fn(x)


def returned_wrapper():
    fn = jax.jit(jnp.sort)  # escapes via return — caller owns caching
    return fn


@functools.partial(jax.jit, static_argnums=(1,))
def static_shape(x, n):
    return x + jnp.zeros(n)  # n is static: shape use is fine


module_level = jax.jit(jnp.sum)  # module-level wrapper lives forever


def plain_args(x):
    return module_level(x)  # array arg, hashable signature
