"""tpulint fixture — TRUE positives for TPU021 (weak-type family splits).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU021. One compiled callable reached with both a raw Python
scalar (weak-typed trace) and a committed `device_put` operand traces two
executables for one program — across call sites sharing a jit factory, on a
single local executable, and inside one mixed-branch expression.
"""

import jax
import numpy as np


def _impl(x, alpha):
    return x * alpha


def _get_fn():
    fn = jax.jit(_impl)
    return fn


def score_committed(x):
    fn = _get_fn()
    return fn(x, jax.device_put(np.float32(0.5)))  # committed family anchor


def score_scalar(x):
    fn = _get_fn()
    return fn(x, 0.5)  # TP: raw scalar splits the factory's executable family


def local_split(x):
    fn = jax.jit(_impl)
    a = fn(x, jax.device_put(np.float32(2.0)))
    b = fn(x, 2.0)  # TP: scalar vs committed on one local executable
    return a + b


def mixed_branch(x, fast):
    fn = _get_fn()
    return fn(x, jax.device_put(np.float32(0.5)) if fast else 0.5)  # TP: mixed
