"""tpulint fixture — TRUE positives for TPU014 (collective-order divergence).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU014; exact line agreement is asserted, so this file is the
rule's behavioral spec. Each function is shard_map'd by name in run(), and
each branches on a provably host-divergent value around a collective — the
multi-host launch-order divergence that deadlocks the mesh.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]), ("shards",))


def read_flag():
    # divergent-RETURNING helper: as host-dependent as the env read itself
    return os.environ.get("ESTPU_FAST_PATH")


def _reduce(x):
    return jax.lax.psum(x, "shards")


def branch_on_clock(x):
    if time.time() % 2.0 > 1.0:
        x = jax.lax.psum(x, "shards")  # TP: collective under wall-clock branch
    return jax.lax.all_gather(x, "shards")


def branch_on_env_name(x):
    fast = os.environ.get("ESTPU_FAST") == "1"
    if fast:
        g = jax.lax.all_gather(x, "shards")  # TP: env decides launch order
    else:
        g = jax.lax.psum(x, "shards")  # TP: env decides launch order
    return g


def branch_on_helper(x):
    mode = read_flag()
    if mode:
        x = jax.lax.pmax(x, "shards")  # TP: divergent helper decides branch
    return x


def loop_on_deadline(x):
    t0 = time.monotonic()
    while time.monotonic() - t0 < 0.1:
        x = jax.lax.psum(x, "shards")  # TP: collective count rides the clock
    return x


def helper_reached_under_branch(x):
    if os.environ["ESTPU_MODE"] == "wide":
        x = _reduce(x)  # TP: reaches lax.psum under a host-dependent branch
    return x


def run(x):
    a = shard_map(branch_on_clock, mesh=mesh, in_specs=None, out_specs=None)
    b = shard_map(branch_on_env_name, mesh=mesh, in_specs=None, out_specs=None)
    c = shard_map(branch_on_helper, mesh=mesh, in_specs=None, out_specs=None)
    d = shard_map(loop_on_deadline, mesh=mesh, in_specs=None, out_specs=None)
    e = shard_map(helper_reached_under_branch, mesh=mesh, in_specs=None,
                  out_specs=None)
    return a(x), b(x), c(x), d(x), e(x)
