"""tpulint fixture — TRUE positives for TPU018 (unbucketed request dims).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU018. Raw request-derived lengths (`len(...)` of live data,
directly or through a helper) shaping arrays inside the compile surface —
the function constructing the executable, or its direct launch-wrapper
caller — give every distinct request size its own XLA executable.
"""

import jax
import numpy as np


def _impl(x):
    return x * 2


def launch_raw_len(hits):
    fn = jax.jit(_impl)
    x = np.zeros((len(hits), 128), np.float32)  # TP: raw length shapes operand
    return fn(x)


def launch_raw_arange(qs):
    fn = jax.jit(_impl)
    idx = np.arange(len(qs))  # TP: request-sized iota into the launch
    return fn(idx)


def launch_via_name(rows):
    n = len(rows)
    fn = jax.jit(_impl)
    buf = np.ones((4, n), np.float32)  # TP: the raw length flowed through n
    return fn(buf)


def _get_compiled(x):
    fn = jax.jit(_impl)
    return fn(x)


def wrapper_feeds_factory(entries):
    pad = np.zeros(len(entries), np.float32)  # TP: direct caller of a factory
    return _get_compiled(pad)
