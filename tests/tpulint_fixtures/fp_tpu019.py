"""tpulint fixture — FALSE positives for TPU019: must stay silent.

The sanctioned statics: bools, enum strings, config constants, bucketed
values, and plain parameters (unknown provenance never fires). Static args
with a handful of distinct values are exactly what static_argnums is FOR.
"""

from functools import partial

import jax


def _pow2_bucket(n, minimum=16):
    b = minimum
    while b < n:
        b *= 2
    return b


def _impl(x, n):
    return x[:n]


_fn = jax.jit(_impl, static_argnums=(1,))


@partial(jax.jit, static_argnames=("desc", "mode"))
def _sorter(x, desc, mode):
    return x if desc else -x


def call_config_const(data):
    return _fn(data, 128)  # literal config constant


def call_bucketed(data, xs):
    return _fn(data, _pow2_bucket(len(xs), 16))  # bucket ladder bounds it


def call_bool_enum(data):
    return _sorter(data, desc=True, mode="bm25")  # bool/enum statics


def call_param(data, k):
    return _fn(data, k)  # bare parameter: unknown, silent


def traced_operand(data, xs):
    # the len flows into a TRACED (non-static) slot: jit shares executables
    # per shape there, so only TPU018's bucket discipline applies, not TPU019
    return _impl(data, len(xs))
