"""tpulint fixture — FALSE positives for TPU016: everything here must stay
silent. Seeded RNG (deterministic per seed, identical on every process),
jax.random (key-seeded by construction), static config values, and wall-clock
reads that only feed host-side telemetry AROUND the mesh call — none of these
diverge across processes.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]), ("shards",))

DEFAULT_SCALE = 1.5  # static config: identical on every process


def program(x, scale):
    return jax.lax.psum(x * scale, "shards")


def feed_config(x):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"), P()),
                  out_specs=P())
    return f(x, DEFAULT_SCALE)  # static config — silent


def feed_seeded_numpy(x):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"), P()),
                  out_specs=P())
    rng = np.random.default_rng(42)  # seeded: same stream on every process
    return f(x, rng.normal())  # silent


def feed_jax_random(x):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"), P()),
                  out_specs=P())
    key = jax.random.PRNGKey(0)
    noise = jax.random.uniform(key)  # key-seeded by construction — silent
    return f(x, noise)


def timed_dispatch(x):
    # wall clock feeds only host-side telemetry, never the program — silent
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"), P()),
                  out_specs=P())
    t0 = time.monotonic()
    out = f(x, DEFAULT_SCALE)
    took_ms = (time.monotonic() - t0) * 1e3
    return out, took_ms


def run(x):
    return (feed_config(x), feed_seeded_numpy(x), feed_jax_random(x),
            timed_dispatch(x))
