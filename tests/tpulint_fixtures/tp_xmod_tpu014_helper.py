"""tpulint fixture — cross-module half of the TPU014 pair: the collective.

Linted ALONE this file has no TPU014 findings (no host-dependent branch
here). Linted together with tp_xmod_tpu014_root.py, the root's env-dependent
call into reduce_all is flagged AT THE CALL SITE in the root, naming the
psum below as the collective it bottoms out on.
"""

import jax


def reduce_all(x):
    return jax.lax.psum(x, "xshards")
