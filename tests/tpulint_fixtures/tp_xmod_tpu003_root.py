"""tpulint fixture — the ROOT half of the cross-MODULE TPU003 pair.

`kernel` is jitted here and calls `leaky_accumulate` imported from
tp_xmod_tpu003_helper.py. The PR-1 engine resolved the traced closure within
one module only, so the helper's closure-append leak was invisible; the
project-wide call graph follows the import and flags it IN THE HELPER FILE.

Never imported: parsed by tests/test_tpulint.py.
"""

import jax

from tp_xmod_tpu003_helper import leaky_accumulate


def kernel(x):
    return leaky_accumulate(x) + 1


fn = jax.jit(kernel)
