"""tpulint fixture — TRUE positives for TPU010 (breaker accounting in traced code)."""

import jax
import jax.numpy as jnp


def traced_kernel(x, breaker):
    breaker.add_estimate_and_maybe_break(1024, "kernel")  # TP: estimate during trace
    y = jnp.sum(x * 2.0)
    breaker.release(1024)  # TP: release during trace
    return y


kernel = jax.jit(traced_kernel)


def _charge_helper(x, request_breaker):
    request_breaker.add_without_breaking(16)  # TP: reached through the traced call graph
    return x * 2


def traced_root(x, request_breaker):
    return _charge_helper(x, request_breaker)


root = jax.jit(traced_root)
