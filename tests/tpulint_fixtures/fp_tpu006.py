"""tpulint fixture — FALSE positives for TPU006: everything here must stay
silent. Mirrors the real SPMD idioms in parallel/mesh_search.py: collectives
over declared mesh axes inside shard_map'd functions, the escaping-closure
factory pattern, and dynamic axis names the analyzer can't prove wrong.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("replicas", "shards"))


def mapped_ok(x):
    # direct shard_map target, declared axes — silent
    total = jax.lax.psum(jnp.sum(x), "shards")
    gathered = jax.lax.all_gather(x, "replicas")
    idx = jax.lax.axis_index("shards")
    return total, gathered, idx


def reduce_helper(x):
    # covered transitively from mapped_ok2 — silent
    return jax.lax.psum(x, "shards")


def mapped_ok2(x):
    return reduce_helper(x * 2)


def make_program(k: int):
    # the factory pattern: the closure escapes via return, some caller
    # shard_maps it later (mesh_search._mesh_score_program) — benefit of
    # the doubt, silent
    def program(x):
        return jax.lax.psum(x * k, "shards")

    return program


def dynamic_axis(x, axis_name):
    # covered (shard_map'd below) and the axis is dynamic — not provably
    # wrong, silent
    return jax.lax.psum(x, axis_name)


def run(x):
    f = shard_map(mapped_ok, mesh=mesh, in_specs=(P("shards"),),
                  out_specs=(P(), P(), P()))
    g = shard_map(mapped_ok2, mesh=mesh, in_specs=(P("shards"),), out_specs=P())
    h = shard_map(make_program(3), mesh=mesh, in_specs=(P("shards"),),
                  out_specs=P())
    d = shard_map(dynamic_axis, mesh=mesh, in_specs=(P("shards"), None),
                  out_specs=P())
    return f(x), g(x), h(x), d(x, "shards")
