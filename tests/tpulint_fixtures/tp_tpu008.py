"""tpulint fixture — TRUE positives for TPU008 (use-after-donate).

Never imported: parsed by tests/test_tpulint.py; exact `TP` line agreement.
"""

import functools

import jax
import jax.numpy as jnp


def _step(state, xs):
    return state + xs.sum()


@functools.partial(jax.jit, donate_argnums=(0,))
def decorated_step(state, xs):
    return state * 2 + xs


def wrapper_donation(state, xs):
    step = jax.jit(_step, donate_argnums=(0,))
    new_state = step(state, xs)
    stale = state + 1  # TP: `state` was donated to `step` above
    return new_state, stale


def kwarg_donation(state, xs):
    step = jax.jit(_step, donate_argnames=("state",))
    new_state = step(state=state, xs=xs)
    return jnp.sum(state), new_state  # TP: donated-by-name buffer re-read


def decorated_donation(state, xs):
    out = decorated_step(state, xs)
    return out, state.shape  # TP: read after donation to decorated_step
