"""tpulint fixture — cross-module TPU004, root side.

Holds a lock and calls tp_xmod_tpu004_helper.pack_rows, whose body dispatches
to the device. Linted TOGETHER with the helper, the project-wide call graph
flags the call site here; the helper alone stays silent.
"""

import threading

from tp_xmod_tpu004_helper import pack_rows


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self.packed = None

    def fill(self, rows):
        with self._lock:
            self.packed = pack_rows(rows)  # TP: device dispatch via helper module
        return self.packed
