"""tpulint fixture — TRUE positives for TPU002 (retrace hazards)."""

import functools

import jax
import jax.numpy as jnp


def per_call_wrapper(x):
    return jax.jit(lambda v: v * 2)(x)  # TP: jit built+called per invocation


def uncached_wrapper(x):
    fn = jax.jit(jnp.sum)  # TP: wrapper local to the frame, never cached
    return fn(x)


@jax.jit
def shape_from_param(x, n):
    return x + jnp.zeros(n)  # TP: param used as Python shape in bare @jit


@functools.partial(jax.jit)
def loop_over_param(x, steps):
    for i in range(steps):  # TP: range(param) in bare @jit
        x = x + i
    return x


jitted_sum = jax.jit(jnp.sum)


def unhashable_args(x):
    return jitted_sum([x, x])  # TP: list literal into a jitted callable
