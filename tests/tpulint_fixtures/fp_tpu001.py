"""tpulint fixture — FALSE positives for TPU001: none of these may fire.

The batched idioms the rule is steering people toward, plus host-only code
that shares surface syntax with the flagged patterns.
"""

import numpy as np
import jax.numpy as jnp


def clean_merge(dev_scores, dev_docs, rows):
    host_scores = np.asarray(dev_scores)  # ONE batched pull outside any loop
    scores = host_scores.tolist()  # batched conversion
    first = float(scores[0]) if scores else 0.0  # scalar cast outside a loop
    acc = 0.0
    for s in scores:
        acc += float(s)  # float() on a bare name: host list iteration
    return first, acc


def clean_host_math(rows):
    host = np.arange(8)
    if host.size:  # attribute test on a numpy value
        rows = rows + 1
    counts = [int(n) for n in range(4)]  # int() on a bare loop var
    return rows, counts


def clean_device_compose(x):
    mask = jnp.isfinite(x)
    masked = jnp.where(mask, x, 0.0)  # device values stay composed on device
    return jnp.sum(masked)
