"""tpulint fixture — FALSE positives for TPU012: none of these may fire."""

import threading


class Disciplined:
    """Every write locked; __init__ is pre-publication; reads stay free."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.rate = 0.0  # single-writer-thread attr, never locked anywhere

    def bump(self):
        with self._lock:
            self.count += 1

    def reset(self):
        with self._lock:
            self.count = 0

    def observe(self, dt):
        self.rate = 0.2 * dt + 0.8 * self.rate  # one discipline: always bare

    def snapshot(self):
        return self.count  # lock-free READ is legal (stats snapshots)

    # a helper only ever invoked under the lock: its bare write IS locked
    # (meet-over-call-sites), like the engine's _merge_window
    def _advance_locked(self):
        self.count += 1

    def bump_twice(self):
        with self._lock:
            self._advance_locked()
            self._advance_locked()


class NotConcurrent:
    """No lock owned: TPU012 does not apply, whatever the write mix."""

    def __init__(self):
        self.x = 0

    def a(self):
        self.x += 1

    def b(self):
        self.x = 5
