"""tpulint fixture — TRUE positives for TPU005 (platform drift)."""

import os

import jax


def hijack_platform():
    os.environ["JAX_PLATFORMS"] = "cpu"  # TP: env write outside jaxenv
    os.environ.setdefault("JAX_PLATFORMS", "tpu")  # TP
    os.environ.pop("JAX_PLATFORMS", None)  # TP
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"  # TP
    jax.config.update("jax_platforms", "cpu")  # TP: live config flip
    os.environ.update({"JAX_PLATFORMS": "cpu"})  # TP
    del os.environ["JAX_PLATFORMS"]  # TP
