"""tpulint fixture — FALSE positives for TPU010: host-side breaker accounting
around a launch, and non-breaker .release() calls inside traced code, must all
stay silent."""

import threading

import jax
import jax.numpy as jnp

_lock = threading.Lock()


def host_charge_then_launch(x, breaker):
    # the sanctioned pattern: estimate BEFORE the launch, release in finally —
    # all outside the traced region
    breaker.add_estimate_and_maybe_break(4096, "launch")
    try:
        return _compiled(x)
    finally:
        breaker.release(4096)


def _traced_body(x):
    # a lock's release inside traced code is not breaker accounting
    _lock.release()
    return jnp.sum(x)


_compiled = jax.jit(_traced_body)
