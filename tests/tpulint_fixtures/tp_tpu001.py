"""tpulint fixture — TRUE positives for TPU001 (implicit host sync).

Never imported: parsed by tests/test_tpulint.py. Every line carrying a
TP marker comment must be flagged with TPU001; the test asserts exact line
agreement, so this file doubles as the rule's behavioral spec.
"""

import jax
import numpy as np
import jax.numpy as jnp


def leaky_merge(dev_scores, dev_docs, rows):
    total = dev_scores.sum().item()  # TP: .item() is the canonical sync
    out = []
    for j in range(10):
        out.append(float(dev_scores[0, j]))  # TP: per-element float() in loop
        d = int(dev_docs[j])  # TP: per-element int() in loop
        out.append(d)
    hits = [bool(rows[i]) for i in range(4)]  # TP: bool(subscript) in comp
    return total, out, hits


def leaky_transfers(dev_scores, dev_docs, rows):
    pulled = []
    for _r in rows:
        arr = np.asarray(dev_scores)  # TP: conversion inside a loop
        got = jax.device_get(dev_docs)  # TP: device_get inside a loop
        pulled.append((arr, got))
    return pulled


def leaky_branch(x):
    flags = jnp.isfinite(x)
    if flags:  # TP: if on a jnp-produced value
        return 1
    while flags:  # TP: while on a jnp-produced value
        break
    assert flags  # TP: assert on a jnp-produced value
    return 0
