"""tpulint fixture — FALSE positives for TPU011: none of these may fire."""

import os
import threading


class Service:
    def __init__(self, transport):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._stopped = threading.Event()
        self.transport = transport
        self.types = {}

    def timed_waits_are_fine(self):
        with self._cv:
            self._cv.wait(0.1)  # timed condition wait: the drainer idiom
        with self._lock:
            ok = self._stopped.wait(timeout=0.5)  # timed event wait
        return ok

    def wait_outside_the_lock(self, fut):
        with self._lock:
            armed = True
        return fut.result(10) if armed else None  # wait AFTER release

    def dict_get_is_not_queue_get(self, key):
        with self._lock:
            return self.types.get(key)  # dict lookup, not a blocking pop

    def string_and_path_joins(self, parts, d):
        with self._lock:
            line = " ".join(parts)  # str.join is not Thread.join
            p = os.path.join(d, line)  # neither is os.path.join
        return p

    # helper that blocks, but is ALSO called with no lock held — the
    # meet-over-call-sites context is empty, so its body stays silent
    def _await(self, fut):
        return fut.result(5)

    def unlocked_path(self, fut):
        return self._await(fut)

    def send_outside(self, node):
        with self._lock:
            action = "ping"
        return self.transport.send_request(node, action, {})

    def lambda_defined_under_lock(self, fut):
        with self._lock:
            waiter = lambda: fut.result(5)  # noqa: E731 — defined, not run
        return waiter
