"""tpulint fixture — TRUE positives for TPU016 (host-divergent inputs).

Never imported: parsed by tests/test_tpulint.py. Every `TP`-marked line must
be flagged with TPU016. Wall-clock reads, per-process env reads, and
process-local identities either read INSIDE a mesh program or fed INTO one as
arguments: each process traces a different constant into the same SPMD
program, so device results diverge across hosts.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:4]), ("shards",))


def program(x, scale):
    return jax.lax.psum(x * scale, "shards")


def program_reads_clock(x):
    t = time.time()  # TP: wall-clock read inside the mesh program
    return jax.lax.psum(x + t, "shards")


def feed_wall_clock(x):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"), P()),
                  out_specs=P())
    now = time.time()
    return f(x, now)  # TP: wall clock flows into the mesh program


def feed_env(x):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"), P()),
                  out_specs=P())
    boost = float(os.environ.get("ESTPU_BOOST", "1"))
    return f(x, boost)  # TP: per-process env read flows into the program


def feed_identity(x, obj):
    f = shard_map(program, mesh=mesh, in_specs=(P("shards"), P()),
                  out_specs=P())
    return f(x, id(obj) % 7)  # TP: id() is process-local

def run(x):
    g = shard_map(program_reads_clock, mesh=mesh, in_specs=(P("shards"),),
                  out_specs=P())
    return g(x), feed_wall_clock(x), feed_env(x), feed_identity(x, mesh)
