"""Adaptive replica selection + hedged shard requests: tail-tolerant routing.

Unit half: CopyHealth EWMA/failure decay, cold-start min_samples, rotation +
quarantine + probe re-entry, the hedge token bucket and delay derivation, and
the `_local`/`_prefer_node` fall-through regression (hashing the preference
literal pinned every coordinator to the SAME copy index).

Chaos half (deterministic, seeded FaultPolicy — never wall-clock handler
sleeps): a delay-faulted replica loses its traffic share while hedged requests
keep latency far below the injected delay; clearing the fault lets probe
traffic restore it into the rotation; an error-faulted copy quarantines and
probes back in; an ALL-copies-slow brown-out exhausts the hedge budget without
load amplification. Trace/profile integration: hedged attempts show as sibling
`shard` spans tagged hedge:true, and the winning profile entry records
primary-vs-hedge.
"""

from __future__ import annotations

import time

import pytest

from elasticsearch_tpu.cluster.routing import OperationRouting
from elasticsearch_tpu.cluster.state import (
    STARTED,
    ClusterState,
    DiscoveryNode,
    DiscoveryNodes,
    IndexShardRoutingTable,
    ShardRouting,
)
from elasticsearch_tpu.cluster.stats import (
    AdaptiveReplicaSelector,
    CopyHealth,
    HedgeBudget,
)
from elasticsearch_tpu.common.errors import NoShardAvailableError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.rest.controller import RestRequest, build_rest_controller

from .harness import TestCluster

pytestmark = pytest.mark.adaptive


def _copies(n=3, index="i", shard=0, first_node=1):
    return [ShardRouting(index, shard, f"n{i + first_node}", i == 0, STARTED)
            for i in range(n)]


def _selector(**over):
    flat = {"search.adaptive.min_samples": 3, **over}
    return AdaptiveReplicaSelector(Settings.from_flat(flat))


def _warm_all(sel, copies, seconds=0.01, n=None):
    for _ in range(n if n is not None else sel.min_samples):
        for c in copies:
            sel.observe(c, seconds)


# ---------------------------------------------------------------------------
# CopyHealth units
# ---------------------------------------------------------------------------


class TestCopyHealth:
    def test_ewma_tracks_recent_latency(self):
        sel = _selector()
        (c,) = _copies(1)
        for _ in range(10):
            sel.observe(c, 0.01)
        e = sel._copy(sel.key(c))
        assert e.ewma_s == pytest.approx(0.01, rel=0.01)
        for _ in range(10):
            sel.observe(c, 0.5)
        # alpha=0.3: ten slow samples pull the EWMA almost all the way over
        assert e.ewma_s > 0.4
        assert e.samples == 20

    def test_failure_penalty_raises_score_and_quarantines(self):
        sel = _selector()
        a, b = _copies(2)
        _warm_all(sel, [a, b])
        now = time.monotonic()
        hl, qt = sel.failure_halflife_s, sel.quarantine_failures
        ea, eb = sel._copy(sel.key(a)), sel._copy(sel.key(b))
        assert ea.score(now, hl) == pytest.approx(eb.score(now, hl), rel=0.01)
        for _ in range(4):
            sel.failure(a)
        assert ea.score(now, hl) > 5 * eb.score(now, hl)
        assert ea.quarantined(now, hl, qt)
        assert not eb.quarantined(now, hl, qt)

    def test_success_halves_failures_deterministically(self):
        sel = _selector()
        (c,) = _copies(1)
        for _ in range(4):
            sel.failure(c)
        e = sel._copy(sel.key(c))
        now = time.monotonic()
        assert e.quarantined(now, sel.failure_halflife_s,
                             sel.quarantine_failures)
        sel.observe(c, 0.01)  # 4 -> 2
        assert not e.quarantined(now, sel.failure_halflife_s,
                                 sel.quarantine_failures)

    def test_failure_time_decay(self):
        e = CopyHealth(("n1", "i", 0))
        e.failure(now=100.0, halflife_s=1.0)
        e.failure(now=100.0, halflife_s=1.0)
        e.failure(now=100.0, halflife_s=1.0)
        assert e.quarantined(100.0, 1.0, 3.0)
        # three half-lives later the count decayed below the threshold
        assert not e.quarantined(103.0, 1.0, 3.0)


# ---------------------------------------------------------------------------
# selection: cold start, rotation, quarantine + probe re-entry
# ---------------------------------------------------------------------------


class TestSelection:
    def test_cold_start_returns_none_until_min_samples(self):
        sel = _selector()
        copies = _copies(2)
        assert sel.select(copies) is None  # cold: caller round-robins
        _warm_all(sel, copies[:1])  # one copy warm, the other cold
        assert sel.select(copies) is None
        _warm_all(sel, copies[1:])
        assert sel.select(copies) is not None
        assert sel.stats()["selections"]["round_robin"] >= 2

    def test_rotation_balanced_when_healthy(self):
        sel = _selector()
        copies = _copies(3)
        _warm_all(sel, copies)
        picks = {c.node_id: 0 for c in copies}
        for _ in range(30):
            picks[sel.select(copies).node_id] += 1
        # equal scores keep every copy in the rotation — no starvation
        assert all(v >= 6 for v in picks.values()), picks

    def test_slow_copy_leaves_rotation_but_gets_probes(self):
        sel = _selector()
        copies = _copies(3)
        _warm_all(sel, copies[:2], seconds=0.01)
        _warm_all(sel, copies[2:], seconds=1.0)  # 100x slower than the rest
        picks = {c.node_id: 0 for c in copies}
        for _ in range(32):
            picks[sel.select(copies).node_id] += 1
        # the slow copy only sees probe traffic (every probe_every-th pick)
        assert picks["n3"] <= 32 // sel.probe_every + 1, picks
        assert picks["n3"] >= 1, "no probe traffic — permanent blacklist"
        assert sel.stats()["probes"] >= 1
        assert picks["n1"] + picks["n2"] >= 32 - 32 // sel.probe_every - 1

    def test_quarantine_probe_reentry(self):
        sel = _selector()
        copies = _copies(2)
        _warm_all(sel, copies)
        for _ in range(4):
            sel.failure(copies[1])
        assert sel.stats()["copies"]["n2/i/0"]["quarantined"]
        # quarantined: only probe turns pick n2
        picks = [sel.select(copies).node_id for _ in range(16)]
        assert picks.count("n2") <= 16 // sel.probe_every + 1
        # two probe successes halve 4 -> 1 (< threshold): back in rotation
        sel.observe(copies[1], 0.01)
        sel.observe(copies[1], 0.01)
        assert not sel.stats()["copies"]["n2/i/0"]["quarantined"]
        picks = [sel.select(copies).node_id for _ in range(16)]
        assert picks.count("n2") >= 4, picks  # well above the probe rate

    def test_failing_from_birth_copy_does_not_keep_group_cold(self):
        """A copy that only ever FAILS has no latency samples — failures must
        count as warmth, or the whole group stays round-robin forever and
        keeps routing 1/N of traffic into the dead copy."""
        sel = _selector()
        copies = _copies(3)
        _warm_all(sel, copies[:2])
        for _ in range(4):
            sel.failure(copies[2])  # zero successes, only failures
        picks = [sel.select(copies) for _ in range(16)]
        assert all(p is not None for p in picks)  # adaptive, not round-robin
        n3 = sum(1 for p in picks if p.node_id == "n3")
        assert n3 <= 16 // sel.probe_every + 1, n3  # probe traffic only
        assert sel.stats()["copies"]["n3/i/0"]["quarantined"]

    def test_registry_prunes_idle_copies(self):
        """Records of deleted indices / departed nodes age out of the
        registry (and therefore out of /_nodes/stats + the per-copy
        Prometheus gauges) once creation pressure crosses the bound."""
        sel = _selector()
        sel.PRUNE_AT = 8
        sel.PRUNE_IDLE_S = 0.0  # anything not re-touched is stale
        for i in range(8):
            sel._copy((f"n{i}", "old", 0))
        live = sel._copy(("n0", "live", 0))
        live.last_touch = time.monotonic() + 60.0  # still fresh at prune time
        sel._copy(("n1", "live", 0))  # creation past the bound triggers prune
        with sel._dict_lock:
            keys = set(sel._copies)
        assert ("n0", "live", 0) in keys and ("n1", "live", 0) in keys
        assert not any(k[1] == "old" for k in keys), keys

    def test_all_quarantined_group_still_serves(self):
        sel = _selector()
        copies = _copies(2)
        _warm_all(sel, copies)
        for c in copies:
            for _ in range(4):
                sel.failure(c)
        assert sel.select(copies) is not None  # no blacklist: someone serves

    def test_ranked_orders_by_health(self):
        sel = _selector()
        copies = _copies(3)
        _warm_all(sel, copies[:1], seconds=0.2)
        _warm_all(sel, copies[1:2], seconds=0.01)
        _warm_all(sel, copies[2:], seconds=0.05)
        assert [c.node_id for c in sel.ranked(copies)] == ["n2", "n3", "n1"]
        for _ in range(4):
            sel.failure(copies[1])  # quarantined sorts last despite speed
        assert [c.node_id for c in sel.ranked(copies)] == ["n3", "n1", "n2"]


# ---------------------------------------------------------------------------
# hedge budget + delay derivation
# ---------------------------------------------------------------------------


class TestHedging:
    def test_budget_token_bucket(self):
        b = HedgeBudget(ratio=0.05, burst=2.0)
        assert b.try_acquire() and b.try_acquire()
        assert not b.try_acquire()  # burst drained
        assert b.budget_exhausted == 1
        for _ in range(20):  # 20 primaries accrue exactly one hedge token
            b.note_request()
        assert b.try_acquire()
        assert not b.try_acquire()
        for _ in range(1000):
            b.note_request()
        assert b.stats()["tokens"] == pytest.approx(2.0)  # capped at burst
        # an acquired-but-unlaunched hedge refunds its token (capped)
        assert b.try_acquire()
        b.refund()
        assert b.stats()["tokens"] == pytest.approx(2.0)
        b.refund()
        assert b.stats()["tokens"] == pytest.approx(2.0)  # never past burst

    def test_hedge_delay_cold_copy_is_none(self):
        sel = _selector()
        (c,) = _copies(1)
        assert sel.hedge_delay_s(c, None) is None

    def test_hedge_delay_tracks_copy_p99(self):
        sel = _selector()
        (c,) = _copies(1)
        _warm_all(sel, [c], seconds=0.05, n=20)
        d = sel.hedge_delay_s(c, None)
        assert d is not None and 0.03 <= d <= 0.15

    def test_hedge_delay_clamped_by_best_alternative(self):
        sel = _selector()
        slow, fast = _copies(2)
        _warm_all(sel, [slow], seconds=0.8, n=20)
        _warm_all(sel, [fast], seconds=0.01, n=20)
        # probing the slow copy hedges as soon as a healthy copy would have
        # answered — not after the slow copy's own (useless) 0.8s p99
        d = sel.hedge_delay_s(slow, None, others=[fast])
        assert d is not None and d <= 0.05
        # ...but an all-slow group derives an all-slow delay (no useless
        # speculative traffic during a brown-out)
        slow2 = _copies(3)[2]
        _warm_all(sel, [slow2], seconds=0.8, n=20)
        d2 = sel.hedge_delay_s(slow, None, others=[slow2])
        assert d2 is not None and d2 >= 0.5

    def test_hedge_delay_clamped_by_deadline(self):
        sel = _selector()
        (c,) = _copies(1)
        _warm_all(sel, [c], seconds=0.2, n=20)
        d = sel.hedge_delay_s(c, 0.1)
        assert d is not None and d <= 0.05  # half the remaining budget
        assert sel.hedge_delay_s(c, 0.001) is None  # no budget left


# ---------------------------------------------------------------------------
# _select preference fall-through regression
# ---------------------------------------------------------------------------


class TestPreferenceFallthrough:
    def _state(self, local_id="n0", n_nodes=4):
        nodes = DiscoveryNodes(local_id=local_id)
        for i in range(n_nodes):
            nodes = nodes.with_node(
                DiscoveryNode(f"n{i}", f"n{i}", f"local://n{i}"))
        return ClusterState(nodes=nodes)

    def test_local_without_local_copy_distributes(self):
        """REGRESSION: a 3-copy group with no copy on the coordinator used to
        hash the literal "_local" — a constant — so EVERY coordinator
        deterministically hotspotted the same copy index."""
        state = self._state()  # local is n0; copies live on n1..n3
        group = IndexShardRoutingTable(shards=tuple(_copies(3)))
        r = OperationRouting()
        picks = {r._select(group, state, "_local").node_id for _ in range(6)}
        assert picks == {"n1", "n2", "n3"}

    def test_local_with_local_copy_sticks(self):
        state = self._state(local_id="n2")
        group = IndexShardRoutingTable(shards=tuple(_copies(3)))
        assert all(OperationRouting()._select(group, state, "_local").node_id
                   == "n2" for _ in range(4))

    def test_prefer_node_fallthrough_distributes(self):
        state = self._state()
        group = IndexShardRoutingTable(shards=tuple(_copies(3)))
        r = OperationRouting()
        picks = {r._select(group, state, "_prefer_node:missing").node_id
                 for _ in range(6)}
        assert picks == {"n1", "n2", "n3"}
        assert r._select(group, state, "_prefer_node:n2").node_id == "n2"

    def test_only_node_still_raises(self):
        state = self._state()
        group = IndexShardRoutingTable(shards=tuple(_copies(3)))
        with pytest.raises(NoShardAvailableError):
            OperationRouting()._select(group, state, "_only_node:missing")

    def test_session_key_still_stable(self):
        state = self._state()
        group = IndexShardRoutingTable(shards=tuple(_copies(3)))
        r = OperationRouting()
        first = r._select(group, state, "session-abc").node_id
        assert all(r._select(group, state, "session-abc").node_id == first
                   for _ in range(5))

    def test_adaptive_pick_avoids_slow_copy(self):
        sel = _selector()
        copies = _copies(3)
        _warm_all(sel, copies[:2], seconds=0.01)
        _warm_all(sel, copies[2:], seconds=1.0)
        state = self._state()
        group = IndexShardRoutingTable(shards=tuple(copies))
        r = OperationRouting(selector=sel)
        picks = [r._select(group, state, None).node_id for _ in range(16)]
        assert picks.count("n3") <= 16 // sel.probe_every + 1


# ---------------------------------------------------------------------------
# live chaos: the full feedback loop under seeded faults
# ---------------------------------------------------------------------------


A_QUERY_GLOB = "*[phase/query]*"
BODY = {"query": {"match": {"body": "alpha1 alpha2"}}, "size": 3}


def _boot(tmp_path, seed):
    """2-node cluster, one index with 1 shard + 1 replica (a copy on each
    node), generous hedge burst so budget never masks routing assertions."""
    cluster = TestCluster(n_nodes=2, data_root=tmp_path, seed=seed,
                          settings={"search.hedge.burst": 24})
    cluster.start()
    names = sorted(cluster.nodes)
    coord = cluster.nodes[names[0]]
    client = coord.client()
    client.create_index("hx", {"settings": {
        "number_of_shards": 1, "number_of_replicas": 1}})
    cluster.ensure_green("hx")
    for i in range(30):
        client.index("hx", "doc",
                     {"body": f"alpha{i % 4} alpha{(i + 1) % 4}", "n": i},
                     id=str(i))
    client.refresh("hx")
    return cluster, coord, names


def _copy_key(node, index="hx", shard=0):
    return f"{node.node_id}/{index}/{shard}"


def _warm(coord, keys, max_iters=200):
    """Warm until both copies carry min_samples observations AND their EWMAs
    converge. Convergence matters: the process's ONE first-search XLA compile
    lands in exactly one copy's stats as a multi-second outlier (which copy
    depends on round-robin phase), and the chaos assertions below need a
    symmetric healthy baseline — the outlier decays as warm traffic (rotation
    or probes) reaches that copy."""
    sel = coord.adaptive_routing
    for _ in range(max_iters):
        coord.actions.search("hx", BODY)
        copies = sel.stats()["copies"]
        if all(k in copies and copies[k]["samples"] >= sel.min_samples
               for k in keys):
            ew = [copies[k]["ewma_ms"] for k in keys]
            if max(ew) <= max(3.0 * min(ew), 60.0):
                return
    raise AssertionError(f"warmup never converged: {sel.stats()['copies']}")


def _drive(coord, n):
    durs = []
    for _ in range(n):
        t0 = time.monotonic()
        r = coord.actions.search("hx", BODY)
        durs.append(time.monotonic() - t0)
        assert r["hits"]["total"] > 0
    return sorted(durs)


class TestChaosAdaptiveRouting:
    def test_slow_replica_shifts_traffic_hedges_bound_tail_then_recovers(
            self, tmp_path):
        """The full loop: FaultPolicy-slowed replica -> its traffic share
        collapses and hedged requests keep latency far under the injected
        delay -> fault cleared -> probe traffic restores the rotation."""
        cluster, coord, names = _boot(tmp_path, seed=3)
        try:
            other = cluster.nodes[names[1]]
            sel = coord.adaptive_routing
            slow_key = _copy_key(other)
            fast_key = _copy_key(coord)
            _warm(coord, [slow_key, fast_key])
            healthy = _drive(coord, 10)
            healthy_p99 = healthy[-1]

            # seeded, deterministic slowness: the replica's query phase
            # handler runs 0.75s late (recv-side delay — a slow NODE, not a
            # slow wire, so only its copy is affected)
            pol = cluster.fault_policy(names[1], seed=11)
            pol.delay(0.75, action=A_QUERY_GLOB, direction="recv")
            before = sel.stats()
            b_slow = before["copies"][slow_key]["selected"]
            b_fast = before["copies"][fast_key]["selected"]
            durs = _drive(coord, 40)
            after = sel.stats()
            slow_delta = after["copies"][slow_key]["selected"] - b_slow
            fast_delta = after["copies"][fast_key]["selected"] - b_fast
            # traffic share shifted away within the window (probes + the
            # pre-detection picks are all the slow copy gets)
            assert slow_delta <= 15, (slow_delta, fast_delta)
            assert fast_delta >= 25, (slow_delta, fast_delta)
            # hedges fired and won — that is what bounded the tail
            assert after["hedges"]["issued"] > before["hedges"]["issued"]
            assert after["hedges"]["won"] > before["hedges"]["won"]
            # p95 stays strictly under the injected 0.75s (an unhedged pick
            # of the slow copy would cost >= 0.75s) and within ~2x the
            # healthy baseline — measured on the same box, so the relative
            # bound self-calibrates under CI load; the absolute floor covers
            # fast-baseline runs
            p95 = durs[int(0.95 * len(durs)) - 1]
            assert p95 < 0.7, (p95, durs[-3:])
            assert p95 < max(2.0 * healthy_p99, 0.45), (p95, healthy_p99)

            # clear the fault: probe traffic must decay the stale slow EWMA
            # and restore the copy into the rotation — no permanent blacklist
            cluster.clear_faults()
            restored = False
            for _chunk in range(15):
                base = sel.stats()["copies"][slow_key]["selected"]
                _drive(coord, 16)
                got = sel.stats()["copies"][slow_key]["selected"] - base
                if got >= 5:  # clearly above the probe rate (16/8 = 2)
                    restored = True
                    break
            assert restored, sel.stats()["copies"]
            assert sel.stats()["probes"] > 0
        finally:
            cluster.close()

    def test_failing_copy_quarantines_and_probes_back(self, tmp_path):
        """Error-faulted copy: failures decay-count it into quarantine (probe
        traffic only), searches keep answering via the ranked failover chain,
        and clearing the fault re-admits it after a couple of probe
        successes."""
        cluster, coord, names = _boot(tmp_path, seed=5)
        try:
            other = cluster.nodes[names[1]]
            sel = coord.adaptive_routing
            slow_key = _copy_key(other)
            _warm(coord, [slow_key, _copy_key(coord)])

            pol = cluster.fault_policy(names[0], seed=7)
            pol.error(action=A_QUERY_GLOB, node=cluster.address(names[1]))
            # every search still answers (failover chain); failures accumulate
            # until quarantine. Chunked: when warmup ended asymmetric the
            # copy is score-excluded from the start and only probe turns
            # (every 8th) reach it, so the failure count grows probe-slow
            quarantined = False
            for _chunk in range(8):
                _drive(coord, 8)
                if sel.stats()["copies"][slow_key]["quarantined"]:
                    quarantined = True
                    break
            st = sel.stats()
            assert quarantined, st["copies"]
            assert st["quarantined"] == 1

            cluster.clear_faults()
            readmitted = False
            for _chunk in range(12):
                _drive(coord, 8)
                if not sel.stats()["copies"][slow_key]["quarantined"]:
                    readmitted = True
                    break
            assert readmitted, sel.stats()["copies"]
            assert sel.stats()["probes"] > 0
            # and it actually receives rotation traffic again once the
            # residual failure penalty decays (each probe success halves it)
            restored = False
            for _chunk in range(10):
                base = sel.stats()["copies"][slow_key]["selected"]
                _drive(coord, 16)
                if sel.stats()["copies"][slow_key]["selected"] - base >= 5:
                    restored = True
                    break
            assert restored, sel.stats()["copies"]
        finally:
            cluster.close()

    def test_all_copies_slow_budget_bounds_hedges(self, tmp_path):
        """Brown-out: EVERY copy is slow. The token bucket caps speculative
        traffic (no retry-storm amplification) and exhaustion is counted —
        hedging cannot help when there is no fast copy to hedge to."""
        cluster, coord, names = _boot(tmp_path, seed=9)
        try:
            sel = coord.adaptive_routing
            _warm(coord, [_copy_key(coord), _copy_key(cluster.nodes[names[1]])])
            for name in names:
                cluster.fault_policy(name, seed=13).delay(
                    0.25, action=A_QUERY_GLOB, direction="recv")
            # drain the bucket: in the early window the copies' p99s still
            # read healthy, so hedge timers fire well before the 0.25s
            # attempts complete — every fire must hit the empty bucket
            with sel.hedges._lock:
                sel.hedges.tokens = 0.0
            b = sel.hedges.stats()
            _drive(coord, 8)
            mid = sel.hedges.stats()
            assert mid["issued"] == b["issued"], mid  # cap held at zero
            assert mid["budget_exhausted"] > b["budget_exhausted"], mid
            # grant a small budget: issuance stays bounded by it (and by the
            # caught-up p99s — an all-slow group derives an all-slow hedge
            # delay, so speculative traffic never amplifies the brown-out)
            with sel.hedges._lock:
                sel.hedges.tokens = 3.0
            durs = _drive(coord, 16)
            a = sel.hedges.stats()
            # <= the 3 granted tokens + the trickle 16 primaries accrue (<1)
            assert a["issued"] - mid["issued"] <= 4, a
            # no amplification pile-up: every search ~one injected delay, and
            # the window's wall clock is bounded by sequential primaries
            assert durs[-1] < 1.5, durs[-3:]
        finally:
            cluster.close()

    def test_hedge_trace_and_profile_integration(self, tmp_path):
        """?trace=true shows the hedged attempt as a sibling `shard` span
        tagged hedge:true (the slow primary's span stitches into the ring
        late); ?profile=true records whether the winning shard entry came
        from the primary attempt or a hedge."""
        cluster, coord, names = _boot(tmp_path, seed=21)
        try:
            other = cluster.nodes[names[1]]
            sel = coord.adaptive_routing
            _warm(coord, [_copy_key(coord), _copy_key(other)])
            pol = cluster.fault_policy(names[1], seed=17)
            pol.delay(1.5, action=A_QUERY_GLOB, direction="recv")
            with sel.hedges._lock:  # a token per request below, determinism
                sel.hedges.tokens = sel.hedges.burst
            rc = build_rest_controller(coord)
            # steer the PRIMARY attempt to the slow copy with the SOFT pin
            # (_prefer_node keeps hedging; the hard _only_node pin disables
            # it — covered below); the hedge, clamped to the healthy copy's
            # EWMA, answers long before the 1.5s delay
            pref = f"_prefer_node:{other.node_id}"

            r = rc.dispatch(RestRequest(
                method="POST", path="/hx/_search",
                params={"trace": "true", "preference": pref}, body=BODY))
            assert r.status == 200
            tid = r.body["trace"]["trace_id"]

            def flatten(node_, out):
                out.append(node_)
                for ch in node_.get("children", []):
                    flatten(ch, out)
                return out

            inline = flatten(r.body["trace"]["tree"], [])
            hedged = [s for s in inline if s["name"] == "shard"
                      and s.get("tags", {}).get("hedge")]
            assert hedged, [s["name"] for s in inline]

            # the losing primary's spans arrive with its (discarded) response
            # ~1.5s later and late-stitch into the ring snapshot: both shard
            # spans — hedge:true and the primary — end up siblings there
            shard_spans = []
            deadline = time.monotonic() + 8.0
            while time.monotonic() < deadline:
                snaps = [t for t in coord.tracer.traces()
                         if t["trace_id"] == tid]
                if snaps:
                    shard_spans = [s for s in snaps[0]["spans"]
                                   if s["name"] == "shard"]
                    if len(shard_spans) >= 2:
                        break
                time.sleep(0.05)
            assert len(shard_spans) >= 2, shard_spans
            assert any(s["tags"].get("hedge") for s in shard_spans)
            assert any(not s["tags"].get("hedge") for s in shard_spans)

            # retried: the hedge wins whenever its (EWMA-clamped) delay plus
            # the fast copy's service time beats the 1.5s injected delay —
            # a transient CI load spike can lose one race, not three
            shards = None
            for _attempt in range(3):
                r = rc.dispatch(RestRequest(
                    method="POST", path="/hx/_search",
                    params={"profile": "true", "preference": pref},
                    body=BODY))
                assert r.status == 200
                shards = r.body["profile"]["shards"]
                assert shards and shards[0]["winner"] in ("primary", "hedge")
                if shards[0]["winner"] == "hedge":
                    break
            assert shards[0]["winner"] == "hedge", shards

            # the HARD pin must not hedge: an answer from a node the caller
            # explicitly pinned away from violates _only_node even on
            # success — the search waits out the full injected delay
            b = sel.hedges.stats()
            r = rc.dispatch(RestRequest(
                method="POST", path="/hx/_search",
                params={"profile": "true",
                        "preference": f"_only_node:{other.node_id}"},
                body=BODY))
            assert r.status == 200
            shards = r.body["profile"]["shards"]
            assert shards[0]["winner"] == "primary", shards
            assert shards[0]["node"] == other.node_id, shards
            assert sel.hedges.stats()["issued"] == b["issued"]

            # the compound "_shards:N;<pref>" form carries the pin after the
            # ";" — it must be parsed out, not string-prefix-missed
            r = rc.dispatch(RestRequest(
                method="POST", path="/hx/_search",
                params={"profile": "true",
                        "preference": f"_shards:0;_only_node:{other.node_id}"},
                body=BODY))
            assert r.status == 200
            assert r.body["profile"]["shards"][0]["winner"] == "primary"
            assert sel.hedges.stats()["issued"] == b["issued"]
        finally:
            cluster.close()

    def test_nodes_stats_surface(self, tmp_path):
        """/_nodes/stats adaptive_routing: per-copy rank inputs + hedge
        counters + quarantine/probe counts, via the REST path."""
        cluster, coord, names = _boot(tmp_path, seed=31)
        try:
            _warm(coord, [_copy_key(coord), _copy_key(cluster.nodes[names[1]])])
            rc = build_rest_controller(coord)
            r = rc.dispatch(RestRequest(
                method="GET", path="/_nodes/stats/adaptive_routing",
                params={}))
            assert r.status == 200
            (sections,) = r.body["nodes"].values()
            ar = sections["adaptive_routing"]
            assert set(ar["hedges"]) >= {"issued", "won", "budget_exhausted",
                                         "tokens"}
            copy = ar["copies"][_copy_key(coord)]
            for field in ("ewma_ms", "p99_ms", "queue", "headroom",
                          "outstanding", "failures", "samples", "selected",
                          "quarantined"):
                assert field in copy, copy
            assert copy["samples"] > 0
            assert "probes" in ar and "quarantined" in ar
        finally:
            cluster.close()
