"""Multi-node cluster integration: election, state publish, routing, replication,
peer recovery, failover — the TestCluster-style in-process suite (SURVEY.md §4.2)."""

import time

import pytest

from elasticsearch_tpu.cluster.allocation import AllocationService, new_index_routing
from elasticsearch_tpu.cluster.routing import djb2_hash
from elasticsearch_tpu.cluster.state import (
    ClusterState,
    DiscoveryNode,
    DiscoveryNodes,
    IndexMetaData,
    STARTED,
    UNASSIGNED,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry


def make_cluster(tmp_path, n_nodes=3, settings=None):
    registry = LocalTransportRegistry()
    nodes = []
    for i in range(n_nodes):
        node = Node(name=f"node_{i}", registry=registry,
                    data_path=str(tmp_path / f"node_{i}"),
                    settings=settings)
        nodes.append(node)
    for node in nodes:
        node.start([n.local_node.transport_address for n in nodes])
    for node in nodes:
        assert node.wait_for_master(5.0)
    return registry, nodes


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


class TestAllocationPure:
    """Pure-function allocator tests on synthetic states (no nodes) —
    the ElasticsearchAllocationTestCase trick."""

    def _state(self, n_nodes=3, shards=2, replicas=1):
        nodes = DiscoveryNodes(local_id="n0")
        for i in range(n_nodes):
            nodes = nodes.with_node(DiscoveryNode(f"n{i}", f"n{i}", f"local://n{i}"))
        nodes = nodes.with_master("n0")
        meta = IndexMetaData("idx", settings_map=(
            ("index.number_of_shards", shards), ("index.number_of_replicas", replicas)))
        state = ClusterState(nodes=nodes)
        state = state.next_version(
            metadata=state.metadata.with_index(meta),
            routing_table=state.routing_table.with_index(
                new_index_routing("idx", shards, replicas)))
        return state

    def test_reroute_assigns_primaries_first(self):
        svc = AllocationService()
        state = svc.reroute(self._state())
        shards = state.routing_table.index("idx").all_shards()
        # primaries initialize immediately; replicas WAIT for an active primary
        # (ReplicaAfterPrimaryActiveDecider)
        assert all(s.state == "INITIALIZING" for s in shards if s.primary)
        assert all(s.state == UNASSIGNED for s in shards if not s.primary)
        # primaries started → replicas allocate, never sharing a node with their primary
        state = svc.apply_started_shards(state, [s for s in shards if s.primary])
        shards = state.routing_table.index("idx").all_shards()
        assert all(s.state == "INITIALIZING" for s in shards if not s.primary)
        by_key = {}
        for s in shards:
            by_key.setdefault((s.index, s.shard_id), []).append(s.node_id)
        for nodes_used in by_key.values():
            assert len(set(nodes_used)) == len(nodes_used)

    def test_replica_not_allocated_without_nodes(self):
        svc = AllocationService()
        state = svc.reroute(self._state(n_nodes=1, shards=1, replicas=1))
        shards = state.routing_table.index("idx").all_shards()
        primary = [s for s in shards if s.primary][0]
        replica = [s for s in shards if not s.primary][0]
        assert primary.state == "INITIALIZING"
        assert replica.state == UNASSIGNED  # same-shard decider blocks single node

    def test_failed_primary_promotes_replica(self):
        svc = AllocationService()
        state = svc.reroute(self._state(shards=1, replicas=1))
        # start primaries → replicas allocate → start replicas
        state = svc.apply_started_shards(
            state, [s for s in state.routing_table.all_shards() if s.primary])
        state = svc.apply_started_shards(
            state, [s for s in state.routing_table.all_shards() if not s.primary])
        group = state.routing_table.index("idx").shard(0)
        assert all(s.state == STARTED for s in group.shards)
        primary = group.primary
        state = svc.apply_failed_shard(state, primary)
        group = state.routing_table.index("idx").shard(0)
        assert group.primary is not None
        assert group.primary.node_id != primary.node_id
        assert group.primary.state == STARTED  # promoted replica keeps STARTED

    def test_filter_decider_excludes_node(self):
        svc = AllocationService(Settings.from_flat(
            {"cluster.routing.allocation.exclude._name": "n1"}))
        state = svc.reroute(self._state())
        for s in state.routing_table.all_shards():
            assert s.node_id != "n1"

    def test_djb2_matches_java_semantics(self):
        # spot values computed from the DJB2 definition with 32-bit overflow
        assert djb2_hash("") == 5381
        assert abs(djb2_hash("1")) % 5 == abs(((5381 << 5) + 5381 + 49) % 2**32 - 0) % 5


class TestClusterFormation:
    def test_election_and_state_publish(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 3)
        try:
            masters = {n.cluster_service.state.nodes.master_id for n in nodes}
            assert len(masters) == 1
            # lowest node id wins
            assert masters == {"node_0"}
            assert all(n.cluster_service.state.nodes.size == 3 for n in nodes)
            # create an index on a NON-master node → forwarded to master → published
            client = nodes[2].client()
            client.create_index("events", {"settings": {"number_of_shards": 3,
                                                        "number_of_replicas": 1}})
            assert wait_until(lambda: all(
                n.cluster_service.state.metadata.has_index("events") for n in nodes))
            h = client.cluster_health(wait_for_status="green")
            assert h["status"] == "green"
            assert h["active_shards"] == 6
        finally:
            for n in nodes:
                n.close()

    def test_replication_and_routed_reads(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 3)
        try:
            client = nodes[0].client()
            client.create_index("docs", {"settings": {"number_of_shards": 2,
                                                      "number_of_replicas": 1}})
            client.cluster_health(wait_for_status="green")
            for i in range(20):
                client.index("docs", "doc", {"n": i, "body": f"text number {i}"},
                             id=str(i))
            client.refresh("docs")
            # reads from any node see all docs
            for node in nodes:
                c = node.client()
                assert c.count("docs")["count"] == 20
                g = c.get("docs", "doc", "7")
                assert g["found"] and g["_source"]["n"] == 7
            # search fans out and merges
            r = client.search("docs", {"query": {"match": {"body": "text"}}, "size": 30})
            assert r["hits"]["total"] == 20
            assert r["_shards"]["successful"] == 2
        finally:
            for n in nodes:
                n.close()

    def test_update_and_bulk(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 2)
        try:
            client = nodes[0].client()
            client.create_index("b", {"settings": {"number_of_shards": 1,
                                                   "number_of_replicas": 0}})
            client.cluster_health(wait_for_status="green")
            r = client.bulk([
                {"action": {"index": {"_index": "b", "_type": "d", "_id": "1"}},
                 "source": {"v": 1}},
                {"action": {"index": {"_index": "b", "_type": "d", "_id": "2"}},
                 "source": {"v": 2}},
                {"action": {"delete": {"_index": "b", "_type": "d", "_id": "2"}}},
            ], refresh=True)
            assert not r["errors"]
            assert client.count("b")["count"] == 1
            client.update("b", "d", "1", {"doc": {"extra": "x"}})
            g = client.get("b", "d", "1")
            assert g["_source"] == {"v": 1, "extra": "x"}
        finally:
            for n in nodes:
                n.close()

    def test_dynamic_mapping_propagates(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 2)
        try:
            client = nodes[0].client()
            client.create_index("dyn", {"settings": {"number_of_shards": 1,
                                                     "number_of_replicas": 0}})
            client.cluster_health(wait_for_status="green")
            client.index("dyn", "doc", {"brand_new_field": 42}, id="1")
            assert wait_until(lambda: "brand_new_field" in
                              (nodes[1].cluster_service.state.metadata.index("dyn")
                               .mapping("doc") or {}).get("properties", {}))
        finally:
            for n in nodes:
                n.close()


class TestReplicaRecoveryAndFailover:
    def test_peer_recovery_copies_data(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 2)
        try:
            client = nodes[0].client()
            # replicas=0 first: write data, then add a replica → peer recovery
            client.create_index("r", {"settings": {"number_of_shards": 1,
                                                   "number_of_replicas": 0}})
            client.cluster_health(wait_for_status="green")
            for i in range(10):
                client.index("r", "doc", {"i": i}, id=str(i))
            client.flush("r")
            client.update_settings("r", {"settings": {"number_of_replicas": 1}})
            h = client.cluster_health(wait_for_status="green", timeout=10)
            assert h["status"] == "green", h
            # find the replica's node and read from it directly with preference
            state = nodes[0].cluster_service.state
            group = state.routing_table.index("r").shard(0)
            replica = group.replicas()[0]
            rnode = next(n for n in nodes if n.node_id == replica.node_id)
            shard = rnode.indices.shard_or_none("r", 0)
            assert shard is not None
            assert shard.engine.doc_stats()["count"] == 10
        finally:
            for n in nodes:
                n.close()

    def test_node_loss_promotes_replica_and_recovers(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 3)
        try:
            client = nodes[0].client()
            client.create_index("ha", {"settings": {"number_of_shards": 1,
                                                    "number_of_replicas": 1}})
            client.cluster_health(wait_for_status="green")
            for i in range(12):
                client.index("ha", "doc", {"i": i}, id=str(i), refresh=True)
            state = nodes[0].cluster_service.state
            group = state.routing_table.index("ha").shard(0)
            primary_node_id = group.primary.node_id
            # kill the node hosting the primary (not the master: node_0 is master;
            # if primary IS on master, kill it anyway unless it's node_0)
            victim = next(n for n in nodes if n.node_id == primary_node_id)
            if victim.node_id == "node_0":
                # choose replica's node as victim instead (keep master alive)
                victim_id = group.replicas()[0].node_id
                victim = next(n for n in nodes if n.node_id == victim_id)
            registry.isolate(victim.local_node.transport_address)
            survivor = next(n for n in nodes if n is not victim and n.node_id != victim.node_id)
            ok = wait_until(lambda: (
                survivor.cluster_service.state.nodes.get(victim.node_id) is None
            ), timeout=15.0)
            assert ok, "victim was not removed from the cluster"
            # shard group recovers to green on the remaining nodes
            c = survivor.client()
            h = c.cluster_health(wait_for_status="green", timeout=15)
            assert h["status"] in ("green", "yellow")
            r = c.search("ha", {"query": {"match_all": {}}, "size": 20})
            assert r["hits"]["total"] == 12
        finally:
            registry.heal()
            for n in nodes:
                n.close()


class TestAliasesTemplatesGateway:
    def test_filtered_alias_and_template(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 1)
        try:
            client = nodes[0].client()
            client.put_template("logs_tpl", {
                "template": "logs-*",
                "settings": {"number_of_shards": 1, "number_of_replicas": 0},
                "mappings": {"event": {"properties": {"level": {
                    "type": "string", "index": "not_analyzed"}}}},
            })
            client.create_index("logs-2014")
            client.cluster_health(wait_for_status="green")
            meta = nodes[0].cluster_service.state.metadata.index("logs-2014")
            assert meta.number_of_shards == 1
            assert "level" in meta.mapping("event")["properties"]
            client.index("logs-2014", "event", {"level": "error", "msg": "boom"}, id="1")
            client.index("logs-2014", "event", {"level": "info", "msg": "fine"}, id="2")
            client.update_aliases({"actions": [
                {"add": {"index": "logs-2014", "alias": "errors",
                         "filter": {"term": {"level": "error"}}}}]})
            client.refresh()
            r = client.search("errors", {"query": {"match_all": {}}})
            assert r["hits"]["total"] == 1
            assert r["hits"]["hits"][0]["_source"]["level"] == "error"
        finally:
            for n in nodes:
                n.close()

    def test_gateway_restores_metadata_after_full_restart(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 1)
        client = nodes[0].client()
        client.create_index("persist", {
            "settings": {"number_of_shards": 1, "number_of_replicas": 0},
            "mappings": {"doc": {"properties": {"x": {"type": "long"}}}}})
        client.cluster_health(wait_for_status="green")
        client.index("persist", "doc", {"x": 1}, id="1")
        client.flush("persist")
        nodes[0].close()
        # full restart with the same data path
        registry2 = LocalTransportRegistry()
        node2 = Node(name="node_0", registry=registry2,
                     data_path=str(tmp_path / "node_0"))
        node2.start([node2.local_node.transport_address])
        try:
            assert node2.wait_for_master()
            c2 = node2.client()
            assert wait_until(lambda: node2.cluster_service.state.metadata.has_index("persist"))
            h = c2.cluster_health(wait_for_status="green", timeout=10)
            assert h["status"] == "green"
            g = c2.get("persist", "doc", "1")
            assert g["found"] and g["_source"]["x"] == 1
        finally:
            node2.close()


class TestSidecars:
    def test_percolator(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 1)
        try:
            client = nodes[0].client()
            client.create_index("pq", {"settings": {"number_of_shards": 1,
                                                    "number_of_replicas": 0}})
            client.cluster_health(wait_for_status="green")
            client.index("pq", ".percolator",
                         {"query": {"match": {"body": "alert"}}}, id="q1")
            client.index("pq", ".percolator",
                         {"query": {"range": {"level": {"gte": 3}}}}, id="q2")
            r = client.percolate("pq", {"doc": {"body": "an alert fired", "level": 1}})
            assert [m["_id"] for m in r["matches"]] == ["q1"]
            r = client.percolate("pq", {"doc": {"body": "quiet", "level": 5}})
            assert [m["_id"] for m in r["matches"]] == ["q2"]
            r = client.percolate("pq", {"doc": {"body": "alert", "level": 9}})
            assert [m["_id"] for m in r["matches"]] == ["q1", "q2"]
            client.delete("pq", ".percolator", "q1")
            r = client.percolate("pq", {"doc": {"body": "alert", "level": 9}})
            assert [m["_id"] for m in r["matches"]] == ["q2"]
        finally:
            for n in nodes:
                n.close()

    def test_warmers_registered_and_run(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 1)
        try:
            client = nodes[0].client()
            client.create_index("w", {"settings": {"number_of_shards": 1,
                                                   "number_of_replicas": 0}})
            client.cluster_health(wait_for_status="green")
            client.put_warmer("w", "warm1", {"query": {"match_all": {}}})
            assert "warm1" in client.get_warmer("w")["w"]["warmers"]
            client.index("w", "d", {"a": "x"}, id="1")
            client.refresh("w")  # runs the warmer (smoke: no exception)
            client.delete_warmer("w", "warm1")
            assert client.get_warmer("w")["w"]["warmers"] == {}
        finally:
            for n in nodes:
                n.close()

    def test_ttl_purge(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 1)
        try:
            client = nodes[0].client()
            client.create_index("ttl", {
                "settings": {"number_of_shards": 1, "number_of_replicas": 0},
                "mappings": {"doc": {"_ttl": {"enabled": True},
                                     "_timestamp": {"enabled": True}}}})
            client.cluster_health(wait_for_status="green")
            svc = nodes[0].indices.index_service("ttl")
            shard = svc.shard(0)
            # one about-to-expire doc, one far-future doc (indexing an already-
            # expired doc raises AlreadyExpiredError, as the reference does)
            shard.engine.index("doc", "old", {"x": 1}, ttl=30)
            shard.engine.index("doc", "new", {"x": 2}, ttl="10d")
            shard.engine.refresh()
            time.sleep(0.05)
            assert shard.engine.doc_stats()["count"] == 2
            nodes[0]._purge_expired()
            assert shard.engine.doc_stats()["count"] == 1
            assert not shard.engine.get("doc", "old").found
        finally:
            for n in nodes:
                n.close()

    def test_monitor_stats(self, tmp_path):
        registry, nodes = make_cluster(tmp_path, 1)
        try:
            stats = nodes[0].client().nodes_stats()["nodes"]["node_0"]
            assert stats["process"]["mem"]["resident_in_bytes"] > 0
            assert stats["os"]["mem"]["total_in_bytes"] > 0
            assert stats["fs"]["data"][0]["total_in_bytes"] > 0
            assert stats["runtime"]["runtime"] == "python"
        finally:
            for n in nodes:
                n.close()


class TestGatewayLockDiscipline:
    def test_recovery_waits_on_state_thread_outside_its_lock(self, tmp_path):
        """PR-6 TPU011 fix: maybe_recover must submit the recovery task under
        LocalGateway._lock but WAIT for it with the lock released — blocking
        on the cluster-state thread while holding the lock couples the two
        executors (any state task re-entering the gateway deadlocks), and
        every other gateway caller convoys behind a 10 s result() wait."""
        import json as _json
        import threading
        import time
        from concurrent.futures import Future

        from elasticsearch_tpu.cluster.state import (
            ClusterState, DiscoveryNode, DiscoveryNodes, IndexMetaData,
            MetaData)
        from elasticsearch_tpu.gateway import LocalGateway

        node = DiscoveryNode("n1", "n1", "local[gw]")
        state = ClusterState(nodes=DiscoveryNodes(
            nodes=(node,), master_id="n1", local_id="n1"))

        class StubClusterService:
            def __init__(self):
                self.state = state
                self.submissions = []

            def add_listener(self, listener):
                pass

            def submit_state_update_task(self, source, fn, priority=2):
                fut = Future()
                self.submissions.append((source, fn, fut))
                return fut

        cs = StubClusterService()
        gw = LocalGateway(str(tmp_path), cs, node_name="n1")
        meta = MetaData(indices=(("idx", IndexMetaData("idx")),))
        with open(gw.meta_path, "w") as fh:
            _json.dump(meta.to_dict(), fh)

        t = threading.Thread(target=gw.maybe_recover)
        t.start()
        try:
            deadline = time.monotonic() + 5.0
            while not cs.submissions and time.monotonic() < deadline:
                time.sleep(0.01)
            assert cs.submissions, "recovery task never submitted"
            # the result() wait is in flight NOW — the lock must be free
            acquired = gw._lock.acquire(timeout=2.0)
            assert acquired, "maybe_recover blocks on the future holding _lock"
            gw._lock.release()
        finally:
            cs.submissions[0][2].set_result(state)
            t.join(5.0)
        assert not t.is_alive()
        assert [s for s, _fn, _fut in cs.submissions] == [
            "gateway-recovery", "gateway-post-recovery-reroute"]
