"""Foundations tests: settings, units, smallfloat codec, wire codec, metrics, breaker."""

import numpy as np
import pytest

from elasticsearch_tpu.common.breaker import CircuitBreakerService
from elasticsearch_tpu.common.errors import CircuitBreakingError, IllegalArgumentError
from elasticsearch_tpu.common.settings import DynamicSettings, Settings
from elasticsearch_tpu.common.smallfloat import (
    byte315_to_float,
    decode_norm_doclen,
    encode_norm,
    float_to_byte315,
)
from elasticsearch_tpu.common.stream import StreamInput, StreamOutput
from elasticsearch_tpu.common.units import format_bytes, parse_bytes, parse_time


class TestSettings:
    def test_nested_flattening_and_typed_getters(self):
        s = Settings({"index": {"number_of_shards": 5, "refresh_interval": "1s"},
                      "node": {"name": "n1", "master": "true"}})
        assert s.get_int("index.number_of_shards") == 5
        assert s.get_time("index.refresh_interval") == 1.0
        assert s.get_bool("node.master") is True
        assert s.get_str("node.name") == "n1"
        assert s.get_int("missing", 7) == 7

    def test_prefix_and_groups(self):
        s = Settings.from_flat({
            "index.analysis.analyzer.my.type": "custom",
            "index.analysis.analyzer.my.tokenizer": "standard",
            "index.analysis.analyzer.other.type": "keyword",
        })
        groups = s.groups("index.analysis.analyzer.")
        assert set(groups) == {"my", "other"}
        assert groups["my"].get_str("type") == "custom"

    def test_structured_roundtrip(self):
        s = Settings.from_flat({"a.b.c": 1, "a.b.d": 2, "e": "x"})
        assert s.as_structured() == {"a": {"b": {"c": 1, "d": 2}}, "e": "x"}

    def test_merged_override(self):
        s = Settings.from_flat({"a": 1, "b": 2}).merged({"b": 3})
        assert s.get_int("b") == 3

    def test_list_settings(self):
        s = Settings.from_flat({"x": "a, b ,c", "y": ["p", "q"]})
        assert s.get_list("x") == ["a", "b", "c"]
        assert s.get_list("y") == ["p", "q"]

    def test_dynamic_settings_whitelist(self):
        d = DynamicSettings().add("cluster.routing.allocation.*").add("index.number_of_replicas")
        assert d.is_dynamic("cluster.routing.allocation.enable")
        assert d.is_dynamic("index.number_of_replicas")
        assert not d.is_dynamic("index.number_of_shards")


class TestUnits:
    def test_bytes(self):
        assert parse_bytes("1kb") == 1024
        assert parse_bytes("512mb") == 512 * 1024 * 1024
        assert parse_bytes("2g") == 2 * 1024**3
        assert parse_bytes(100) == 100
        assert format_bytes(1536) == "1.5kb"

    def test_time(self):
        assert parse_time("30s") == 30.0
        assert parse_time("5m") == 300.0
        assert parse_time("200ms") == 0.2
        assert parse_time(1500) == 1.5  # bare numbers are millis
        with pytest.raises(IllegalArgumentError):
            parse_time("5parsecs")


class TestSmallFloat:
    """The 1-byte norm codec must match Lucene's byte315 semantics exactly —
    hit-ordering parity depends on it (SURVEY.md §7 hard parts)."""

    def test_known_values(self):
        # 1/sqrt(1)=1.0 encodes to 124 and decodes back to 1.0 in Lucene's table
        assert byte315_to_float(float_to_byte315(1.0))[0] == 1.0
        # zero and negatives encode to 0
        assert float_to_byte315(0.0)[0] == 0
        assert float_to_byte315(-1.0)[0] == 0
        assert byte315_to_float(np.uint8(0))[0] == 0.0

    def test_roundtrip_is_idempotent_quantization(self):
        vals = np.float32(1.0) / np.sqrt(np.arange(1, 10000, dtype=np.float32))
        enc = float_to_byte315(vals)
        dec = byte315_to_float(enc)
        # re-encoding a decoded value must be a fixed point
        assert np.array_equal(float_to_byte315(dec), enc)
        # truncation error bounded by the stored mantissa bits (<25% relative)
        assert np.all(np.abs(dec - vals) / vals < 0.25)

    def test_doc_length_decode(self):
        # a 100-term doc: norm = 1/10 → decode doclen ≈ 100 (quantized)
        b = encode_norm(100)
        dl = decode_norm_doclen(b)[0]
        assert 70 <= dl <= 135

    def test_monotonic(self):
        # longer docs must never get a LARGER decoded norm
        lengths = np.arange(1, 5000)
        dec = byte315_to_float(encode_norm(lengths))
        assert np.all(np.diff(dec) <= 0)


class TestStream:
    def test_primitives_roundtrip(self):
        out = StreamOutput()
        out.write_vint(0)
        out.write_vint(127)
        out.write_vint(128)
        out.write_vint(300000)
        out.write_zlong(-12345)
        out.write_string("héllo wörld")
        out.write_optional_string(None)
        out.write_bool(True)
        out.write_long(-(2**40))
        out.write_double(3.14159)
        inp = StreamInput(out.bytes())
        assert inp.read_vint() == 0
        assert inp.read_vint() == 127
        assert inp.read_vint() == 128
        assert inp.read_vint() == 300000
        assert inp.read_zlong() == -12345
        assert inp.read_string() == "héllo wörld"
        assert inp.read_optional_string() is None
        assert inp.read_bool() is True
        assert inp.read_long() == -(2**40)
        assert inp.read_double() == pytest.approx(3.14159)
        assert inp.remaining() == 0

    def test_generic_value_roundtrip(self):
        doc = {"user": "kimchy", "age": 42, "tags": ["a", "b"], "nested": {"x": 1.5},
               "flag": True, "none": None}
        out = StreamOutput()
        out.write_value(doc)
        assert StreamInput(out.bytes()).read_value() == doc

    def test_checksum_detects_corruption(self):
        out = StreamOutput()
        out.write_string("payload")
        data = bytearray(out.bytes_with_checksum())
        StreamInput.with_checksum(bytes(data))  # ok
        data[2] ^= 0xFF
        with pytest.raises(Exception):
            StreamInput.with_checksum(bytes(data))


class TestBreaker:
    def test_trips_over_limit(self):
        svc = CircuitBreakerService(total_budget_bytes=1000)
        br = svc.breaker("fielddata")  # limit = 800
        br.add_estimate_and_maybe_break(700, "field_a")
        with pytest.raises(CircuitBreakingError):
            br.add_estimate_and_maybe_break(200, "field_b")
        br.release(700)
        br.add_estimate_and_maybe_break(200, "field_b")
        assert br.trip_count == 1
