"""HyperLogLog++ / t-digest sketches: accuracy, merging, bounded memory.

The reference snapshot predates the cardinality/percentiles aggs; later Elasticsearch
backs them with exactly these sketches and knobs (precision_threshold, compression).
The accuracy bounds asserted here are the standard ones: HLL relative error
~1.04/sqrt(2^p) (p=14 → ~0.8%), t-digest tail error well under 1% at δ=100.
"""

import pickle

import numpy as np
import pytest

from elasticsearch_tpu.common.sketches import (
    HyperLogLogPlusPlus,
    TDigest,
    hash64_ints,
    hash64_strs,
    precision_from_threshold,
)


class TestHLL:
    def test_small_range_exact(self):
        h = HyperLogLogPlusPlus(14)
        h.add_values(np.arange(2000))
        assert abs(h.cardinality() - 2000) <= 20  # linear counting ≈ exact

    def test_large_range_bounded_error(self):
        h = HyperLogLogPlusPlus(14)
        h.add_values(np.arange(1_000_000) * 31 + 7)
        assert abs(h.cardinality() - 1_000_000) / 1_000_000 < 0.02

    def test_duplicates_do_not_count(self):
        h = HyperLogLogPlusPlus(14)
        for _ in range(5):
            h.add_values(np.arange(10_000))
        assert abs(h.cardinality() - 10_000) / 10_000 < 0.02

    def test_strings(self):
        h = HyperLogLogPlusPlus(14)
        h.add_values([f"user-{i}" for i in range(50_000)])
        assert abs(h.cardinality() - 50_000) / 50_000 < 0.02

    def test_merge_with_overlap_and_wire(self):
        parts = [HyperLogLogPlusPlus(12) for _ in range(4)]
        for i, p in enumerate(parts):
            p.add_values(np.arange(i * 20_000, (i + 1) * 20_000 + 4_000))
        merged = parts[0]
        for p in parts[1:]:
            merged.merge(pickle.loads(pickle.dumps(p)))  # sketches cross the wire
        true = 84_000
        assert abs(merged.cardinality() - true) / true < 0.03

    def test_bounded_memory(self):
        h = HyperLogLogPlusPlus(14)
        h.add_values(np.arange(1_000_000))
        assert h.registers.nbytes == 1 << 14  # 16 KB no matter the cardinality

    def test_precision_mapping(self):
        assert precision_from_threshold(100) < precision_from_threshold(3000)
        assert 4 <= precision_from_threshold(1) <= 18
        assert precision_from_threshold(10_000_000) == 18

    def test_hash_stability(self):
        a = hash64_ints(np.array([1, 2, 3]))
        b = hash64_ints(np.array([1, 2, 3]))
        assert (a == b).all()
        s1 = hash64_strs(["abc", "abcd", "abc\x00"])
        assert len(set(s1.tolist())) == 3  # prefix/padding must not collide

    def test_merge_precision_mismatch_rejected(self):
        with pytest.raises(ValueError):
            HyperLogLogPlusPlus(10).merge(HyperLogLogPlusPlus(12))


class TestTDigest:
    def test_accuracy_normal(self):
        rng = np.random.default_rng(3)
        data = rng.normal(100, 15, 300_000)
        td = TDigest(100)
        for chunk in np.array_split(data, 30):
            td.add_values(chunk)
        for q in (0.01, 0.5, 0.95, 0.99):
            assert td.quantile(q) == pytest.approx(np.quantile(data, q), rel=0.01)

    def test_accuracy_heavy_tail(self):
        rng = np.random.default_rng(4)
        data = rng.pareto(3, 300_000)
        td = TDigest(100)
        td.add_values(data)
        for q in (0.5, 0.99):
            assert td.quantile(q) == pytest.approx(np.quantile(data, q), rel=0.02)

    def test_bounded_memory(self):
        td = TDigest(100)
        for chunk in np.array_split(np.random.default_rng(5).normal(0, 1, 500_000), 50):
            td.add_values(chunk)
        td._compress()
        assert len(td.means) <= 2 * td.compression

    def test_merge_matches_combined(self):
        rng = np.random.default_rng(6)
        data = rng.exponential(2.0, 200_000)
        parts = [TDigest(100) for _ in range(8)]
        for i, td in enumerate(parts):
            td.add_values(data[i::8])
        merged = parts[0]
        for td in parts[1:]:
            merged.merge(pickle.loads(pickle.dumps(td)))
        assert merged.total == len(data)
        for q in (0.5, 0.95, 0.99):
            assert merged.quantile(q) == pytest.approx(np.quantile(data, q), rel=0.02)

    def test_merge_small_digests_stays_sorted(self):
        # regression: merge() concatenates two sorted centroid runs; below the
        # compression threshold _compress() used to early-return without sorting,
        # so quantile() interpolated over an unsorted array (q25 > q75)
        a, b = TDigest(100), TDigest(100)
        a.add_values(np.array([100.0, 200.0]))
        b.add_values(np.array([1.0, 2.0]))
        a.merge(b)
        assert np.all(np.diff(a.means) >= 0)
        qs = [a.quantile(q) for q in (0.25, 0.5, 0.75)]
        assert qs == sorted(qs)
        assert a.quantile(0.25) < 100.0 < a.quantile(0.9)

    def test_tiny_inputs_exact_interpolation(self):
        td = TDigest(100)
        td.add_values(np.array([10.0, 20, 30, 40, 50, 60]))
        assert td.quantile(0.5) == pytest.approx(35.0)
        assert td.quantile(0.0) == pytest.approx(10.0)
        assert td.quantile(1.0) == pytest.approx(60.0)
        assert TDigest(100).quantile(0.5) is None

    def test_compression_knob(self):
        rng = np.random.default_rng(8)
        data = rng.normal(0, 1, 100_000)
        small, big = TDigest(20), TDigest(400)
        small.add_values(data)
        big.add_values(data)
        small._compress(); big._compress()
        assert len(small.means) < len(big.means)
