"""DFR / IB / LM similarity families (ref: index/similarity/DFRSimilarityProvider.java,
IBSimilarityProvider.java). These score on the host path; ranking sanity + monotonicity
properties are the contract (tf↑ ⇒ score↑, df↑ ⇒ weight↓)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
from elasticsearch_tpu.search.similarity import (
    DFRSimilarity,
    IBSimilarity,
    LMDirichletSimilarity,
    LMJelinekMercerSimilarity,
    SimilarityService,
)

DOCS = [
    "fox fox fox fox",                       # 0: high tf
    "fox",                                   # 1: low tf, short doc
    "fox and dog and cat and bird and bee",  # 2: low tf, long doc
    "dog dog dog",                           # 3: no fox
    "common common common fox",              # 4
    "common word soup without the animal",   # 5
]


def build(tmp_path, sim_type, extra=None):
    flat = {"index.similarity.default.type": sim_type}
    flat.update(extra or {})
    settings = Settings.from_flat(flat)
    svc = MapperService(settings)
    e = Engine(str(tmp_path / "s"), svc)
    for i, text in enumerate(DOCS):
        e.index("doc", str(i), {"body": text})
    e.refresh()
    return e, ShardContext(e.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))


# (type, settings, length_normalized) — the third flag gates ordering assertions that
# only hold when doc length enters the formula (BE+L with normalization "no"
# legitimately ranks tf=1 above tf=4: Laplace decays faster than BE grows).
FAMILIES = [
    ("DFR", {}, True),
    ("DFR", {"index.similarity.default.basic_model": "in",
             "index.similarity.default.after_effect": "b",
             "index.similarity.default.normalization": "h1"}, True),
    ("DFR", {"index.similarity.default.basic_model": "be",
             "index.similarity.default.normalization": "no"}, False),
    ("IB", {}, True),
    ("IB", {"index.similarity.default.distribution": "spl",
            "index.similarity.default.lambda": "ttf"}, True),
    # small mu: with the default 2000 every tiny doc's score clamps to 0 (Lucene
    # LMDirichlet does the same on toy corpora)
    ("LMDirichlet", {"index.similarity.default.mu": 10}, True),
    ("LMJelinekMercer", {}, True),
]


@pytest.mark.parametrize("sim_type,extra,length_norm", FAMILIES)
class TestFamilies:
    def test_ranking_sane(self, tmp_path, sim_type, extra, length_norm):
        e, ctx = build(tmp_path, sim_type, extra)
        td = search_shard(ctx, parse_query({"match": {"body": "fox"}}), 10)
        docs = [d for _, d in td.hits]
        scores = [s for s, _ in td.hits]
        # only fox docs match; scores non-negative (LM sims clamp negatives to 0,
        # exactly as Lucene's LMDirichletSimilarity does)
        assert set(docs) == {0, 1, 2, 4}
        assert all(s >= 0 for s in scores)
        assert scores[0] > 0
        if length_norm:
            # high-tf short doc first; single occurrence in a short doc beats long doc
            assert docs[0] == 0
            assert docs.index(1) < docs.index(2)

    def test_bool_composition(self, tmp_path, sim_type, extra, length_norm):
        e, ctx = build(tmp_path, sim_type, extra)
        td = search_shard(ctx, parse_query({"bool": {
            "must": [{"term": {"body": "fox"}}],
            "should": [{"term": {"body": "common"}}]}}), 10)
        docs = [d for _, d in td.hits]
        assert set(docs) == {0, 1, 2, 4}
        # doc 4 gets the "common" bonus over doc 2 (both single fox)
        assert docs.index(4) < docs.index(2)


class TestFormulaProperties:
    def test_tf_monotonic(self):
        for sim in (DFRSimilarity(), IBSimilarity(), LMDirichletSimilarity(),
                    LMJelinekMercerSimilarity()):
            freqs = np.array([1.0, 2.0, 5.0, 10.0], np.float32)
            dl = np.full(4, 10.0)

            class FS:
                doc_count, sum_ttf, sum_dfs = 100, 1000, 900

            s = sim.score_freqs(freqs, dl, df=10, ttf=50, field_stats=FS,
                                max_docs=100, boost=1.0)
            assert np.all(np.diff(s) > 0), (sim.name, s)

    def test_rare_term_scores_higher(self):
        for sim in (DFRSimilarity(), IBSimilarity()):
            freqs = np.array([2.0], np.float32)
            dl = np.array([10.0])

            class FS:
                doc_count, sum_ttf, sum_dfs = 1000, 10000, 9000

            rare = sim.score_freqs(freqs, dl, df=2, ttf=4, field_stats=FS,
                                   max_docs=1000, boost=1.0)
            common = sim.score_freqs(freqs, dl, df=800, ttf=5000, field_stats=FS,
                                     max_docs=1000, boost=1.0)
            assert rare[0] > common[0], sim.name

    def test_boost_scales(self):
        sim = DFRSimilarity()
        freqs = np.array([3.0], np.float32)
        dl = np.array([8.0])

        class FS:
            doc_count, sum_ttf, sum_dfs = 100, 900, 800

        s1 = sim.score_freqs(freqs, dl, 5, 20, FS, 100, 1.0)
        s2 = sim.score_freqs(freqs, dl, 5, 20, FS, 100, 2.0)
        assert np.isclose(s2[0], 2 * s1[0], rtol=1e-5)

    def test_unknown_type_rejected(self):
        from elasticsearch_tpu.common.errors import IllegalArgumentError

        settings = Settings.from_flat({"index.similarity.default.type": "bogus"})
        with pytest.raises(IllegalArgumentError):
            SimilarityService(settings)
