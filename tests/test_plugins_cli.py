"""Plugin system (plugins.py — ref: plugins/PluginsService.java) + CLI launcher."""

import textwrap

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.plugins import Plugin, PluginsService
from elasticsearch_tpu.transport.local import LocalTransportRegistry


class MarkerPlugin(Plugin):
    name = "marker"
    description = "test plugin"
    events: list = []

    def additional_settings(self):
        return {"marker.enabled": True, "node.name": "should-not-win"}

    def on_node_created(self, node):
        MarkerPlugin.events.append("created")

    def on_node_started(self, node):
        MarkerPlugin.events.append("started")

    def on_node_closed(self, node):
        MarkerPlugin.events.append("closed")

    def rest_routes(self, controller, node):
        controller.register("GET", "/_marker", lambda req: {"marker": True})


def test_plugin_lifecycle_and_routes(tmp_path):
    MarkerPlugin.events.clear()
    registry = LocalTransportRegistry()
    node = Node(name="plug_node", registry=registry,
                settings={"plugin.types": ["tests.test_plugins_cli.MarkerPlugin"]},
                data_path=str(tmp_path / "n"))
    try:
        node.start([node.local_node.transport_address])
        node.wait_for_master()
        # the class may be re-imported under another module name by the loader, so
        # assert via the node's own plugin instance
        events = type(node.plugins.plugins[0]).events
        assert events[:2] == ["created", "started"]
        # plugin settings merged, node settings win
        assert node.settings.get_bool("marker.enabled") is True
        assert node.name == "plug_node"
        # plugin appears in nodes_info
        info = node.client().nodes_info()
        assert any(p["name"] == "marker"
                   for p in info["nodes"][node.node_id]["plugins"])
        # plugin REST route live
        from elasticsearch_tpu.rest.controller import RestRequest, build_rest_controller

        rc = build_rest_controller(node)
        resp = rc.dispatch(RestRequest("GET", "/_marker"))
        assert resp.status == 200 and resp.body == {"marker": True}
        events_ref = type(node.plugins.plugins[0]).events
    finally:
        node.close()
    assert "closed" in events_ref


def test_plugin_dir_scan(tmp_path):
    pdir = tmp_path / "plugins"
    pdir.mkdir()
    (pdir / "hello.py").write_text(textwrap.dedent("""
        from elasticsearch_tpu.plugins import Plugin

        class HelloPlugin(Plugin):
            name = "hello"
    """))
    (pdir / "broken.py").write_text("raise RuntimeError('boom')")

    from elasticsearch_tpu.common.settings import Settings

    svc = PluginsService(Settings.from_flat({"path.plugins": str(pdir)}), str(tmp_path))
    assert [p.name for p in svc.plugins] == ["hello"]  # broken one skipped


def test_cli_builds_and_serves(tmp_path):
    """Drive main() in a thread with an ephemeral port, curl the root endpoint."""
    import json
    import signal
    import threading
    import urllib.request

    from elasticsearch_tpu import __main__ as cli

    # signal.signal only works on the main thread — patch it out for the test
    orig_signal = signal.signal
    signal.signal = lambda *a, **k: None
    captured = {}
    orig_node_cls = cli_node_holder = None

    from elasticsearch_tpu import node as node_mod

    orig_start_http = node_mod.Node.start_http

    def capture_http(self, port=0):
        server = orig_start_http(self, 0)
        captured["node"] = self
        return server

    node_mod.Node.start_http = capture_http
    t = None
    try:
        t = threading.Thread(target=cli.main, args=(
            ["--transport", "local", "--data", str(tmp_path / "d"),
             "-Dnode.name=cli_node", "--http-port", "0"],), daemon=True)
        t.start()
        import time

        for _ in range(100):
            if "node" in captured and captured["node"].http is not None:
                break
            time.sleep(0.1)
        node = captured["node"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{node.http.port}/") as resp:
            body = json.loads(resp.read())
        assert body["name"] == "cli_node"
        assert "version" in body
    finally:
        signal.signal = orig_signal
        node_mod.Node.start_http = orig_start_http
        if "node" in captured:
            captured["node"].close()
