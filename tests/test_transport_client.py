"""Sniffing TransportClient: discovery via sampling, round-robin, and node-death
failover — ref: client/transport/TransportClientNodesService.java:58 (NodeSampler)
and :100 (retry listener)."""

import time

import pytest

from elasticsearch_tpu.client import TransportClient
from elasticsearch_tpu.common.errors import NoNodeAvailableError
from elasticsearch_tpu.node import Node


@pytest.fixture()
def cluster(tmp_path):
    n1 = Node(name="tc1", settings={"transport.type": "tcp"},
              data_path=str(tmp_path / "n1"))
    n1.start([])
    n1.wait_for_master()
    seed = n1.local_node.transport_address
    n2 = Node(name="tc2", settings={
        "transport.type": "tcp",
        "discovery.zen.ping.unicast.hosts": [seed]}, data_path=str(tmp_path / "n2"))
    n2.start()
    n1.client().cluster_health(wait_for_nodes=2)
    yield n1, n2, seed
    for n in (n1, n2):
        try:
            n.close()
        except Exception:  # noqa: BLE001 — test may have closed one already
            pass


def test_whitelist_names_are_real_client_methods():
    """Every proxied name must exist on node.Client — a phantom entry passes the
    whitelist then AttributeErrors server-side on every call."""
    from elasticsearch_tpu.client import CLIENT_PROXY_METHODS, IDEMPOTENT_METHODS
    from elasticsearch_tpu.node import Client

    missing = [m for m in CLIENT_PROXY_METHODS | IDEMPOTENT_METHODS
               if not callable(getattr(Client, m, None))]
    assert not missing, missing


class TestTransportClient:
    def test_sniff_discovers_all_nodes(self, cluster):
        n1, n2, seed = cluster
        client = TransportClient([seed], sniff_interval=0.2)
        try:
            assert len(client.connected_nodes()) == 2  # seeded with 1, sniffed 2
        finally:
            client.close()

    def test_api_roundtrip_through_proxy(self, cluster):
        n1, n2, seed = cluster
        client = TransportClient([seed], sniff_interval=0.2)
        try:
            client.create_index(index="books", body={"settings": {
                "number_of_shards": 2, "number_of_replicas": 1}})
            client.cluster_health(wait_for_status="green")
            client.index(index="books", doc_type="doc",
                         body={"title": "snow crash"}, id="1")
            client.refresh(index="books")
            r = client.search(index="books",
                              body={"query": {"match": {"title": "snow"}}})
            assert r["hits"]["total"] == 1
            assert r["hits"]["hits"][0]["_id"] == "1"
            g = client.get(index="books", doc_type="doc", id="1")
            assert g["_source"]["title"] == "snow crash"
        finally:
            client.close()

    def test_unproxied_method_rejected(self, cluster):
        n1, n2, seed = cluster
        client = TransportClient([seed], sniff=False, sniff_interval=5)
        try:
            with pytest.raises(AttributeError):
                client.start_http()
        finally:
            client.close()

    def test_failover_when_node_dies(self, cluster):
        n1, n2, seed = cluster
        client = TransportClient([seed], sniff_interval=0.2)
        try:
            client.create_index(index="ha", body={"settings": {
                "number_of_shards": 1, "number_of_replicas": 1}})
            client.cluster_health(wait_for_status="green")
            client.index(index="ha", doc_type="doc", body={"x": 1}, id="1")
            client.refresh(index="ha")
            # kill the seed node — requests must re-route to the survivor
            n1.close()
            deadline = time.time() + 20
            got = None
            while time.time() < deadline:
                try:
                    got = client.count(index="ha")
                    break
                except NoNodeAvailableError:
                    time.sleep(0.3)
            assert got is not None and got["count"] == 1
            # the sampler eventually trims the dead node from the live set
            deadline = time.time() + 10
            while time.time() < deadline and len(client.connected_nodes()) != 1:
                time.sleep(0.2)
            assert len(client.connected_nodes()) == 1
        finally:
            client.close()
