"""REST + HTTP: the full API surface through real sockets (curl-equivalent)."""

import json
import urllib.error
import urllib.request

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry


@pytest.fixture(scope="module")
def http_node(tmp_path_factory):
    registry = LocalTransportRegistry()
    node = Node(name="rest_node", registry=registry,
                data_path=str(tmp_path_factory.mktemp("rest_node")))
    node.start([node.local_node.transport_address])
    node.wait_for_master()
    server = node.start_http(port=0)
    yield node, f"http://127.0.0.1:{server.port}"
    node.close()


def call(base, method, path, body=None, raw_body=None, ok_statuses=(200, 201)):
    data = None
    headers = {}
    if raw_body is not None:
        data = raw_body.encode()
        headers["Content-Type"] = "application/x-ndjson"
    elif body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(base + path, data=data, method=method, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            status = resp.status
            payload = resp.read().decode()
    except urllib.error.HTTPError as e:
        status = e.code
        payload = e.read().decode()
    try:
        parsed = json.loads(payload) if payload else None
    except ValueError:
        parsed = payload
    return status, parsed


class TestRestApi:
    def test_root(self, http_node):
        node, base = http_node
        status, body = call(base, "GET", "/")
        assert status == 200
        assert body["version"]["number"].startswith("0.")

    def test_document_crud_lifecycle(self, http_node):
        node, base = http_node
        status, body = call(base, "PUT", "/crud", {
            "settings": {"number_of_shards": 1, "number_of_replicas": 0}})
        assert status == 200 and body["acknowledged"]
        status, body = call(base, "PUT", "/crud/doc/1",
                            {"title": "hello world", "views": 3})
        assert status == 201 and body["created"] and body["_version"] == 1
        status, body = call(base, "GET", "/crud/doc/1")
        assert status == 200 and body["_source"]["title"] == "hello world"
        status, body = call(base, "GET", "/crud/doc/1/_source")
        assert body == {"title": "hello world", "views": 3}
        status, body = call(base, "PUT", "/crud/doc/1", {"title": "updated"})
        assert status == 200 and body["_version"] == 2
        status, body = call(base, "POST", "/crud/doc/1/_update",
                            {"doc": {"extra": True}})
        assert status == 200
        status, body = call(base, "GET", "/crud/doc/1")
        assert body["_source"] == {"title": "updated", "extra": True}
        status, body = call(base, "DELETE", "/crud/doc/1")
        assert status == 200 and body["found"]
        status, body = call(base, "GET", "/crud/doc/1")
        assert status == 404 and not body["found"]
        status, body = call(base, "PUT", "/crud/doc/2/_create", {"a": 1})
        assert status == 201
        status, body = call(base, "PUT", "/crud/doc/2/_create", {"a": 2})
        assert status == 409

    def test_search_with_aggs_and_q(self, http_node):
        node, base = http_node
        call(base, "PUT", "/lib", {"settings": {"number_of_shards": 2,
                                                "number_of_replicas": 0}})
        for i, (title, cat) in enumerate([
            ("the art of search", "tech"), ("cooking for two", "food"),
            ("search engines explained", "tech"), ("garden design", "home"),
        ]):
            call(base, "PUT", f"/lib/book/{i}", {"title": title, "category": cat,
                                                 "pages": (i + 1) * 100})
        call(base, "POST", "/lib/_refresh")
        status, body = call(base, "POST", "/lib/_search", {
            "query": {"match": {"title": "search"}},
            "aggs": {"cats": {"terms": {"field": "category"}},
                     "avg_pages": {"avg": {"field": "pages"}}},
            "highlight": {"fields": {"title": {}}},
        })
        assert status == 200
        assert body["hits"]["total"] == 2
        assert "<em>search</em>" in body["hits"]["hits"][0]["highlight"]["title"][0]
        cats = {b["key"]: b["doc_count"] for b in body["aggregations"]["cats"]["buckets"]}
        assert cats == {"tech": 2}
        # URI search (?q=)
        status, body = call(base, "GET", "/lib/_search?q=title:cooking")
        assert body["hits"]["total"] == 1
        # count
        status, body = call(base, "GET", "/lib/_count")
        assert body["count"] == 4

    def test_bulk_ndjson(self, http_node):
        node, base = http_node
        ndjson = "\n".join([
            json.dumps({"index": {"_index": "bulked", "_type": "d", "_id": "1"}}),
            json.dumps({"x": 1}),
            json.dumps({"index": {"_index": "bulked", "_type": "d", "_id": "2"}}),
            json.dumps({"x": 2}),
            json.dumps({"delete": {"_index": "bulked", "_type": "d", "_id": "2"}}),
        ]) + "\n"
        status, body = call(base, "POST", "/_bulk?refresh=true", raw_body=ndjson)
        assert status == 200
        assert not body["errors"]
        status, body = call(base, "GET", "/_cat/count/bulked")
        # epoch / HH:MM:SS / count columns (ref: cat.count format)
        assert str(body).strip().split()[-1] == "1"

    def test_mapping_settings_aliases(self, http_node):
        node, base = http_node
        call(base, "PUT", "/meta1", {"settings": {"number_of_shards": 1,
                                                  "number_of_replicas": 0}})
        status, body = call(base, "PUT", "/meta1/typ/_mapping", {
            "typ": {"properties": {"tag": {"type": "string",
                                           "index": "not_analyzed"}}}})
        assert status == 200
        status, body = call(base, "GET", "/meta1/_mapping")
        assert body["meta1"]["mappings"]["typ"]["properties"]["tag"]["type"] == "string"
        status, body = call(base, "PUT", "/meta1/_alias/m1")
        assert status == 200
        status, body = call(base, "GET", "/_aliases")
        assert "m1" in body["meta1"]["aliases"]
        # search through the alias
        status, _ = call(base, "PUT", "/meta1/typ/1", {"tag": "x"})
        assert status == 201
        call(base, "POST", "/meta1/_refresh")
        status, body = call(base, "GET", "/m1/_search")
        assert body["hits"]["total"] == 1
        # raising replicas beyond available nodes: settings apply, and writes are
        # rejected by the quorum consistency check (reference semantics)
        status, body = call(base, "PUT", "/meta1/_settings",
                            {"settings": {"number_of_replicas": 2}})
        assert status == 200
        status, body = call(base, "GET", "/meta1/_settings")
        assert str(body["meta1"]["settings"]["index"]["number_of_replicas"]) == "2"
        status, body = call(base, "PUT", "/meta1/typ/2", {"tag": "y"})
        assert status == 503  # quorum (2 of 3) not reachable on one node

    def test_analyze_api(self, http_node):
        node, base = http_node
        status, body = call(base, "GET", "/_analyze?text=Quick+Brown+Foxes&analyzer=standard")
        assert [t["token"] for t in body["tokens"]] == ["quick", "brown", "foxes"]

    def test_cluster_apis(self, http_node):
        node, base = http_node
        status, body = call(base, "GET", "/_cluster/health")
        assert body["status"] in ("green", "yellow")
        status, body = call(base, "GET", "/_cluster/state")
        assert body["nodes"]["master_id"] == "rest_node"
        status, body = call(base, "GET", "/_nodes")
        assert "rest_node" in body["nodes"]
        status, body = call(base, "GET", "/_nodes/stats")
        assert "indices" in body["nodes"]["rest_node"]

    def test_cat_apis(self, http_node):
        node, base = http_node
        for path in ("/_cat", "/_cat/health", "/_cat/nodes", "/_cat/indices",
                     "/_cat/shards", "/_cat/master", "/_cat/allocation",
                     "/_cat/pending_tasks", "/_cat/thread_pool", "/_cat/recovery"):
            status, body = call(base, "GET", path)
            assert status == 200, path
            assert isinstance(body, str), path
        status, body = call(base, "GET", "/_cat/master")
        assert "rest_node" in body

    def test_errors_are_structured(self, http_node):
        node, base = http_node
        status, body = call(base, "GET", "/missing_index/_search")
        assert status == 404
        assert body["error"]["type"] == "IndexMissingException"
        status, body = call(base, "POST", "/lib/_search",
                            {"query": {"bogus_query": {}}})
        assert status == 400
        assert "unknown query type" in body["error"]["reason"]
        status, body = call(base, "GET", "/_no_such_api")
        assert status in (400, 404)

    def test_validate_and_explain(self, http_node):
        node, base = http_node
        status, body = call(base, "POST", "/lib/_validate/query",
                            {"query": {"match": {"title": "x"}}})
        assert body["valid"] is True
        status, body = call(base, "POST", "/lib/_validate/query",
                            {"query": {"nope": {}}})
        assert body["valid"] is False
        status, body = call(base, "GET", "/lib/book/0/_explain",
                            {"query": {"match": {"title": "search"}}})
        assert body["matched"] is True

    def test_scroll_via_rest(self, http_node):
        node, base = http_node
        call(base, "PUT", "/scr", {"settings": {"number_of_shards": 1,
                                                "number_of_replicas": 0}})
        for i in range(25):
            call(base, "PUT", f"/scr/d/{i}", {"i": i})
        call(base, "POST", "/scr/_refresh")
        status, body = call(base, "POST", "/scr/_search?scroll=1m",
                            {"size": 10, "query": {"match_all": {}}})
        assert len(body["hits"]["hits"]) == 10
        sid = body["_scroll_id"]
        seen = {h["_id"] for h in body["hits"]["hits"]}
        while True:
            status, body = call(base, "POST", "/_search/scroll", {"scroll_id": sid})
            if not body["hits"]["hits"]:
                break
            seen.update(h["_id"] for h in body["hits"]["hits"])
        assert len(seen) == 25
