"""Quantized device postings: layout ladder, device-side byte315 decode, and
differential hit-ordering parity between the quantized device scorer and the
host scorer (the behavioral reference) — including the int overflow rungs and
the f32 escape hatch.

The resident layout (ops/device_index.py): docs i32 + tf u8/i16/f32 + norm
byte u8, tf→tfn decoded INSIDE the scan against the SimTables 256-entry LUT.
"""

from __future__ import annotations

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.common.smallfloat import (
    NORM_TABLE,
    byte315_to_float,
    float_to_byte315,
)
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.ops.device_index import (
    TF_F32,
    TF_I16,
    TF_U8,
    bytes_per_posting,
    choose_tf_layout,
    ensure_blk_freqs,
    pack_estimate_bytes,
    packed_for,
    packed_resident_bytes,
)
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
from elasticsearch_tpu.search.similarity import SimilarityService


def _mk_engine(tmp_path, docs):
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    eng = Engine(str(tmp_path / "qidx"), svc)
    for i, d in enumerate(docs):
        eng.index("doc", str(i), d)
    eng.refresh()
    ctx = ShardContext(eng.acquire_searcher(), svc,
                       SimilarityService(settings, mapper_service=svc))
    return eng, ctx


def _assert_device_host_parity(ctx, queries, k=25):
    """Same totals, same ranking — tolerant only to adjacent swaps among
    near-equal scores (multi-clause sums accumulate in segment-sum tree order
    on device vs sequential clause order on host; the repo-wide differential
    contract, see test_randomized_differential._tie_tolerant_equal)."""
    from tests.test_randomized_differential import _tie_tolerant_equal

    for q in queries:
        dev = search_shard(ctx, parse_query(q), k, use_device=True)
        host = search_shard(ctx, parse_query(q), k, use_device=False)
        assert dev.total == host.total, q
        assert _tie_tolerant_equal(dev, host), (q, dev.hits, host.hits)


class TestByte315DeviceDecode:
    def test_device_table_matches_host(self):
        from elasticsearch_tpu.common.smallfloat import (
            jnp_byte315_to_float, jnp_norm_table)

        all_bytes = np.arange(256, dtype=np.uint8)
        assert np.array_equal(np.asarray(jnp_norm_table()),
                              NORM_TABLE.astype(np.float32))
        assert np.array_equal(np.asarray(jnp_byte315_to_float(all_bytes)),
                              byte315_to_float(all_bytes))

    def test_round_trip_through_encode(self):
        """byte315 decode must round-trip float_to_byte315 EXACTLY — the
        quantized layout stores only the byte, so decode(encode(x)) is the
        value every scorer (host, composed, fused) must agree on."""
        from elasticsearch_tpu.common.smallfloat import jnp_byte315_to_float

        rng = np.random.default_rng(7)
        vals = (rng.random(4096).astype(np.float32) * 4.0) + 1e-4
        enc = float_to_byte315(vals)
        dec_host = byte315_to_float(enc)
        dec_dev = np.asarray(jnp_byte315_to_float(enc))
        assert np.array_equal(dec_host, dec_dev)
        # re-encoding the quantized value is a fixed point
        assert np.array_equal(float_to_byte315(dec_dev), enc)


class TestTfLayoutLadder:
    def test_choose_layout(self):
        assert choose_tf_layout(np.zeros(0, np.float32)) == TF_U8
        assert choose_tf_layout(np.array([1, 3, 255], np.float32)) == TF_U8
        assert choose_tf_layout(np.array([1, 256], np.float32)) == TF_I16
        assert choose_tf_layout(np.array([1, 32767], np.float32)) == TF_I16
        assert choose_tf_layout(np.array([1, 32768], np.float32)) == TF_F32
        assert choose_tf_layout(np.array([1.5], np.float32)) == TF_F32
        assert bytes_per_posting(TF_U8) == 6
        assert bytes_per_posting(TF_I16) == 7
        assert bytes_per_posting(TF_F32) == 9

    def test_u8_default_layout_and_parity(self, tmp_path):
        rng = np.random.default_rng(11)
        words = [f"w{i}" for i in range(40)]
        docs = [{"b": " ".join(rng.choice(words, size=15))} for _ in range(150)]
        eng, ctx = _mk_engine(tmp_path, docs)
        seg = ctx.searcher.segments[0]
        packed = packed_for(seg)
        assert packed.tf_layout == TF_U8
        assert np.asarray(packed.blk_tf).dtype == np.uint8
        _assert_device_host_parity(ctx, [
            {"match": {"b": "w1 w2 w3"}},
            {"bool": {"must": [{"term": {"b": "w4"}}],
                      "should": [{"term": {"b": "w5"}}, {"term": {"b": "w6"}}],
                      "must_not": [{"term": {"b": "w7"}}]}},
        ])
        eng.close()

    def test_i16_overflow_blocks_and_parity(self, tmp_path):
        """A term with tf > 255 pushes the segment to the int16 rung; scoring
        must stay identical to the host scorer (regression for the overflow
        escape: quantization must never clip a frequency)."""
        rng = np.random.default_rng(12)
        words = [f"w{i}" for i in range(20)]
        docs = [{"b": " ".join(rng.choice(words, size=10))} for _ in range(80)]
        docs[3] = {"b": "hot " * 300 + "w1 w2"}  # tf(hot)=300 > 255
        eng, ctx = _mk_engine(tmp_path, docs)
        seg = ctx.searcher.segments[0]
        assert float(seg.post_freqs.max()) > 255
        packed = packed_for(seg)
        assert packed.tf_layout == TF_I16
        assert np.asarray(packed.blk_tf).dtype == np.int16
        # the overflowing frequency survives quantization exactly
        assert int(np.asarray(packed.blk_tf).max()) == int(seg.post_freqs.max())
        _assert_device_host_parity(ctx, [
            {"match": {"b": "hot w1"}},
            {"match": {"b": "w1 w2 w3"}},
        ])
        eng.close()

    def test_f32_escape_hatch_and_parity(self, tmp_path):
        """Non-integral frequencies (synthetic corpora / index-time folding)
        take the f32 escape plane — bit-exact freqs, host parity intact."""
        rng = np.random.default_rng(13)
        words = [f"w{i}" for i in range(20)]
        docs = [{"b": " ".join(rng.choice(words, size=10))} for _ in range(60)]
        eng, ctx = _mk_engine(tmp_path, docs)
        seg = ctx.searcher.segments[0]
        # engineer fractional tf BEFORE the first pack (both scorers read the
        # same CSR, so parity still must hold)
        seg.post_freqs = seg.post_freqs + np.float32(0.5)
        seg._device_cache.clear()
        packed = packed_for(seg)
        assert packed.tf_layout == TF_F32
        assert np.asarray(packed.blk_tf).dtype == np.float32
        _assert_device_host_parity(ctx, [{"match": {"b": "w1 w2"}}])
        eng.close()


class TestLazyDensePlane:
    def test_sparse_only_segment_never_pays_dense_plane(self, tmp_path):
        rng = np.random.default_rng(14)
        words = [f"w{i}" for i in range(30)]
        docs = [{"b": " ".join(rng.choice(words, size=12)), "n": i}
                for i in range(100)]
        eng, ctx = _mk_engine(tmp_path, docs)
        seg = ctx.searcher.segments[0]
        search_shard(ctx, parse_query({"match": {"b": "w1 w2"}}), 10,
                     use_device=True)
        packed = packed_for(seg)
        assert packed.blk_freqs is None  # the blk_freqs-drop rule
        assert packed_resident_bytes(packed) == (
            np.asarray(packed.blk_docs).shape[0] * 128
            * bytes_per_posting(packed.tf_layout))
        # the dense fallback faults the f32 plane in, once
        plane = ensure_blk_freqs(packed)
        assert packed.blk_freqs is plane
        assert ensure_blk_freqs(packed) is plane
        assert np.asarray(plane).dtype == np.float32
        assert packed_resident_bytes(packed) == (
            np.asarray(packed.blk_docs).shape[0] * 128
            * bytes_per_posting(packed.tf_layout, dense_resident=True))
        eng.close()


class TestSimTables:
    def test_table_swap_is_cheap_and_stable(self, tmp_path):
        """avgdl drift re-ensures as a 1 KB LUT swap: fid rows stay stable for
        already-known fields and the postings planes are untouched."""
        from elasticsearch_tpu.ops.device_index import TFN_BM25, ensure_sim_tables

        rng = np.random.default_rng(15)
        docs = [{"b": " ".join(rng.choice([f"w{i}" for i in range(10)], size=8))}
                for _ in range(40)]
        eng, ctx = _mk_engine(tmp_path, docs)
        packed = packed_for(ctx.searcher.segments[0])
        c1 = np.ones(256, np.float32)
        t1 = ensure_sim_tables(packed, {"b": (TFN_BM25, c1)})
        assert ensure_sim_tables(packed, {"b": (TFN_BM25, c1)}) is t1
        tf_plane = packed.blk_tf
        c2 = np.full(256, 2.0, np.float32)  # the "avgdl moved" case
        t2 = ensure_sim_tables(packed, {"b": (TFN_BM25, c2), "other": (TFN_BM25, c1)})
        assert t2 is not t1
        assert t2.fid["b"] == t1.fid["b"]  # stable row for known fields
        assert packed.blk_tf is tf_plane  # no postings re-bake
        eng.close()


@pytest.mark.pallas
class TestFusedKernelQuantizedParity:
    def test_interpret_leg_overflow_segment(self, tmp_path, monkeypatch):
        """ESTPU_PALLAS=interpret end-to-end on an i16-overflow segment: the
        fused kernel must serve bit-identical hits to the composed path."""
        rng = np.random.default_rng(16)
        words = [f"w{i}" for i in range(15)]
        docs = [{"b": " ".join(rng.choice(words, size=10))} for _ in range(60)]
        docs[5] = {"b": "loud " * 280 + "w1"}
        eng, ctx = _mk_engine(tmp_path, docs)
        queries = [{"match": {"b": "loud w1"}},
                   {"bool": {"must": [{"term": {"b": "w2"}}],
                             "must_not": [{"term": {"b": "w3"}}]}}]
        # the CI pallas-interpret leg exports ESTPU_PALLAS for the whole job —
        # the baseline must be the COMPOSED path, not fused-vs-fused
        monkeypatch.delenv("ESTPU_PALLAS", raising=False)
        base = [search_shard(ctx, parse_query(q), 15, use_device=True)
                for q in queries]
        monkeypatch.setenv("ESTPU_PALLAS", "interpret")
        flagged = [search_shard(ctx, parse_query(q), 15, use_device=True)
                   for q in queries]
        for b, f in zip(base, flagged):
            assert b.total == f.total
            assert b.hits == f.hits
        eng.close()


class TestRandomizedQuantizedParity:
    def test_fuzz_multi_field_ordering(self, tmp_path):
        """Randomized differential: multi-field bool queries (distinct fid
        rows in one batch) — quantized device ordering == host ordering."""
        rng = np.random.default_rng(17)
        wa = [f"a{i}" for i in range(25)]
        wb = [f"b{i}" for i in range(25)]
        docs = [{"t": " ".join(rng.choice(wa, size=6)),
                 "b": " ".join(rng.choice(wb, size=14))} for _ in range(120)]
        eng, ctx = _mk_engine(tmp_path, docs)
        for _ in range(12):
            clauses = {"should": [
                {"term": {"t": wa[int(rng.integers(len(wa)))]}},
                {"term": {"b": wb[int(rng.integers(len(wb)))]}},
            ]}
            if rng.random() < 0.5:
                clauses["must"] = [{"term": {"b": wb[int(rng.integers(len(wb)))]}}]
            if rng.random() < 0.3:
                clauses["must_not"] = [{"term": {"t": wa[int(rng.integers(len(wa)))]}}]
            _assert_device_host_parity(ctx, [{"bool": clauses}], k=20)
        eng.close()
