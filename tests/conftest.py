"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference tests multi-node behavior with an
in-JVM TestCluster — SURVEY.md §4.2; we test multi-chip sharding with virtual XLA host
devices). Must be set before jax is imported anywhere.
"""

import os

# Hard-override: the container env pins JAX_PLATFORMS=axon (real TPU via tunnel) and jax
# is already imported at interpreter startup by the axon sitecustomize hook, so a plain
# environ set is not enough — update the live jax config too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
