"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference tests multi-node behavior with an
in-JVM TestCluster — SURVEY.md §4.2; we test multi-chip sharding with virtual XLA host
devices). Must be set before jax is imported anywhere.
"""

# Lock-trace sanitizer (common/locktrace.py), the runtime twin of the tpulint
# concurrency family: under ESTPU_LOCKTRACE=1 every repo-constructed
# threading.Lock/RLock records per-thread acquisition order and device pulls
# timed under a held lock; the session gate below fails the run on any
# lock-order cycle. Off by default — maybe_install() is a no-op then, so the
# recorder costs exactly nothing (same env-knob conventions as ESTPU_SANITIZE).
# Installed FIRST — before jaxenv is imported — so even module-import-time
# locks (jaxenv's _CompileCounter._lock) are constructed through the patched
# factory and participate in the order graph.
from elasticsearch_tpu.common.locktrace import TRACER, maybe_install

maybe_install()

from elasticsearch_tpu.common.jaxenv import force_cpu_platform

# Hard-override: the container env pins a real-TPU JAX platform and jax is already
# imported at interpreter startup by a sitecustomize hook — see jaxenv.py.
force_cpu_platform(n_devices=8)

# second call: now that jax is imported, the device_get timing wrapper can arm
# (the first call ran pre-jax so the threading patch covered all repo locks)
maybe_install()

# Collective-trace sanitizer (common/meshtrace.py), the runtime twin of the
# tpulint SPMD family (TPU014-016): under ESTPU_MESHTRACE=1 every shard_map
# trace records its collective launch sequence per program; the session gate
# below replays each program and fails the run on any sequence mismatch —
# the single-process rehearsal of the multi-host trace-divergence deadlock.
# Installed AFTER jax is up (it patches jax.lax collectives + shard_map).
from elasticsearch_tpu.common import meshtrace

meshtrace.maybe_install()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


# Device-heavy test modules run under the runtime sanitizer
# (common/jaxenv.sanitize): transfer-guard HARD "disallow" (the tpulint
# TPU001 baseline is empty — every hot-path pull is an explicit
# jax.device_get/.tolist() batch now, so any implicit transfer is a
# regression and raises) plus compile-event counting. Env knobs, both read
# by sanitize() itself: ESTPU_SANITIZE=log is the debugging escape hatch
# (warn instead of raise); ESTPU_COMPILE_BUDGET=<n> makes the compile count
# a hard per-test ceiling — the runtime twin of tpulint TPU001/TPU002.
_SANITIZED_MODULES = {
    "test_pallas_kernels",
    "test_quantized_postings",
    "test_device_aggs",
    "test_device_sort",
    "test_parallel_search",
    "test_mesh_serving",
}


@pytest.fixture(scope="session", autouse=True)
def lock_order_gate():
    """With ESTPU_LOCKTRACE=1, fail the run if the whole-session lock-order
    graph ever grew a cycle (TRACER.check raises LockOrderViolation naming
    both acquisition sites)."""
    yield
    if TRACER.enabled:
        TRACER.check()


@pytest.fixture(scope="session", autouse=True)
def collective_trace_gate():
    """With ESTPU_MESHTRACE=1, replay every mesh program the session traced
    and fail the run on any collective-sequence divergence
    (meshtrace.TRACER.check raises CollectiveTraceMismatch naming the first
    differing collective site in both traces)."""
    yield
    if meshtrace.TRACER.enabled:
        meshtrace.TRACER.replay_all()
        meshtrace.TRACER.check()


@pytest.fixture(scope="session", autouse=True)
def compile_surface_gate():
    """Runtime twin of the compile-surface manifest (tools/compile_surface.json,
    tpulint TPU018-TPU021): arm jaxenv's untagged-origin capture for the whole
    session, then assert (a) zero PACKAGE-originated untagged compile events —
    every elasticsearch_tpu/ launch site that compiled sat under a compile_tag
    scope the manifest knows — and (b) every observed family is in the
    COMPILE_FAMILIES vocabulary. Test-local eager jnp compiles have no package
    frame and are out of scope by construction (they are the tests' own, not
    serving-path, compiles)."""
    from elasticsearch_tpu.common import jaxenv

    jaxenv.record_untagged_origins(True)
    yield
    origins = jaxenv.untagged_package_origins()
    assert not origins, (
        "package-originated compile events outside every compile_tag scope "
        f"(site -> count): {origins} — wrap each launch in "
        "jaxenv.compile_tag(<family>) and regenerate the manifest with "
        "`python -m tools.tpulint --compile-surface --write`")
    observed = set(jaxenv.compile_events_by_family())
    unknown = observed - set(jaxenv.COMPILE_FAMILIES)
    assert not unknown, (
        f"compile families outside the COMPILE_FAMILIES vocabulary: {unknown}")


@pytest.fixture(autouse=True)
def jax_sanitizer(request):
    mod = request.module.__name__.rsplit(".", 1)[-1]
    if mod not in _SANITIZED_MODULES:
        yield None
        return
    from elasticsearch_tpu.common.jaxenv import sanitize

    with sanitize() as report:
        yield report
