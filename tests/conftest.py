"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference tests multi-node behavior with an
in-JVM TestCluster — SURVEY.md §4.2; we test multi-chip sharding with virtual XLA host
devices). Must be set before jax is imported anywhere.
"""

from elasticsearch_tpu.common.jaxenv import force_cpu_platform

# Hard-override: the container env pins a real-TPU JAX platform and jax is already
# imported at interpreter startup by a sitecustomize hook — see jaxenv.py.
force_cpu_platform(n_devices=8)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
