"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference tests multi-node behavior with an
in-JVM TestCluster — SURVEY.md §4.2; we test multi-chip sharding with virtual XLA host
devices). Must be set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
