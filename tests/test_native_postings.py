"""The C postings accumulator must produce BYTE-identical frozen segments to the
Python dict path — same term dictionary (field-name order, per-field term sort),
same CSR arrays, same stats. ref: the reference's equivalent hot loop lives in
native Lucene (SURVEY §2.8)."""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index import segment as segmod
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.native import get_native


def _build(docs, force_python: bool):
    svc = MapperService(Settings.from_flat({}))
    eng = Engine(tempfile.mkdtemp(), svc)
    orig = segmod.SegmentBuilder.__init__
    if force_python:
        def patched(self, gen):
            orig(self, gen)
            self._pb = None
        segmod.SegmentBuilder.__init__ = patched
    try:
        for i, d in enumerate(docs):
            eng.index("doc", str(i), d)
        eng.refresh()
    finally:
        segmod.SegmentBuilder.__init__ = orig
    return eng


def _assert_identical(a, b):
    assert a.term_dict == b.term_dict
    for name in ("post_offsets", "post_docs", "post_freqs", "pos_offsets",
                 "positions"):
        assert np.array_equal(getattr(a, name), getattr(b, name)), name
    assert a.field_stats == b.field_stats
    for f in b.norms:
        assert np.array_equal(a.norms[f], b.norms[f]), f


@pytest.mark.skipif(get_native() is None
                    or not hasattr(get_native(), "PostingsBuilder"),
                    reason="native extension unavailable")
def test_native_and_python_builders_agree():
    rng = np.random.default_rng(7)
    vocab = [f"w{i}" for i in range(800)] + ["café", "zürich", "Ωmega", "a'postrophe"]
    docs = []
    for i in range(300):
        d = {"body": " ".join(rng.choice(vocab, size=int(rng.integers(1, 40)))),
             "title": " ".join(rng.choice(vocab, size=3)),
             "tag": f"t{i % 9}", "n": int(i)}
        if i % 11 == 0:
            d["body"] = ""  # empty text
        d["always_empty"] = ""  # field that NEVER produces a token on any doc —
        # must not appear in term_dict on either path
        if i % 13 == 0:
            d["multi"] = ["alpha beta", "beta gamma"]  # position gaps between values
        if i % 17 == 0:
            d["nested_kids"] = [{"k": "x y"}, {"k": "y z"}]
        docs.append(d)
    e1 = _build(docs, force_python=False)
    e2 = _build(docs, force_python=True)
    s1 = e1.acquire_searcher().segments
    s2 = e2.acquire_searcher().segments
    assert len(s1) == len(s2)
    for a, b in zip(s1, s2):
        _assert_identical(a, b)
    e1.close()
    e2.close()


@pytest.mark.skipif(get_native() is None
                    or not hasattr(get_native(), "PostingsBuilder"),
                    reason="native extension unavailable")
def test_native_builder_survives_merge_roundtrip():
    # merge_segments rebuilds through a SegmentBuilder — the C path must
    # reproduce positions (phrase queries) and dv columns across the round trip
    svc = MapperService(Settings.from_flat({}))
    eng = Engine(tempfile.mkdtemp(), svc)
    for i in range(60):
        eng.index("doc", str(i), {"body": f"quick brown fox {i % 5} jumps"})
        if i in (19, 39):
            eng.refresh()
    eng.refresh()
    eng.optimize(max_num_segments=1)
    eng.refresh()
    searcher = eng.acquire_searcher()
    assert len(searcher.segments) == 1
    from elasticsearch_tpu.search import ShardContext, parse_query
    from elasticsearch_tpu.search.execute import search_shard
    from elasticsearch_tpu.search.similarity import SimilarityService

    ctx = ShardContext(searcher, svc,
                       SimilarityService(Settings.from_flat({}), mapper_service=svc))
    td = search_shard(ctx, parse_query({"match_phrase": {"body": "quick brown fox"}}),
                      100, use_device=False)
    assert td.total == 60
    eng.close()
