"""Live rebalancing + cluster stats + node shutdown, end to end.

ref: allocator/BalancedShardsAllocator.java (relocation pairs driven through
real peer recovery), TransportClusterStatsAction, TransportNodesShutdownAction."""

import time

from tests.harness import TestCluster


def test_node_join_triggers_relocation_to_balance(tmp_path):
    with TestCluster(n_nodes=2, data_root=tmp_path, seed=3) as cluster:
        client = cluster.client()
        client.create_index("reb", {"settings": {
            "number_of_shards": 3, "number_of_replicas": 1}})
        cluster.ensure_green("reb")
        for i in range(40):
            client.index("reb", "doc", {"n": i}, id=str(i))
        client.refresh("reb")

        n3 = cluster.add_node()
        # the join's reroute starts relocations; they complete via real peer
        # recovery and the cluster re-greens with copies on the new node
        deadline = time.time() + 30
        moved = 0
        while time.time() < deadline:
            state = n3.cluster_service.state
            on_n3 = [s for s in state.routing_table.all_shards()
                     if s.node_id == n3.local_node.id and s.state == "STARTED"]
            relocating = [s for s in state.routing_table.all_shards()
                          if s.state == "RELOCATING"]
            if on_n3 and not relocating:
                moved = len(on_n3)
                break
            time.sleep(0.2)
        assert moved >= 1, "no shard relocated to the new node"
        cluster.ensure_green("reb")
        # health stays consistent and data survived the move
        r = cluster.client().search("reb", {"query": {"match_all": {}},
                                            "size": 0})
        assert r["hits"]["total"] == 40


def test_health_stays_green_during_relocation(tmp_path):
    with TestCluster(n_nodes=2, data_root=tmp_path, seed=5) as cluster:
        client = cluster.client()
        client.create_index("grn", {"settings": {
            "number_of_shards": 3, "number_of_replicas": 1}})
        cluster.ensure_green("grn")
        cluster.add_node()
        # sample health while relocations are (maybe) in flight: a relocation
        # target must never drag status below green (reference behavior)
        for _ in range(20):
            h = cluster.client().cluster_health("grn")
            assert h["status"] == "green", h
            if h["relocating_shards"] == 0:
                break
            time.sleep(0.1)


def test_cluster_stats_aggregates_across_nodes(tmp_path):
    with TestCluster(n_nodes=2, data_root=tmp_path, seed=7) as cluster:
        client = cluster.client()
        client.create_index("cs", {"settings": {
            "number_of_shards": 2, "number_of_replicas": 1}})
        cluster.ensure_green("cs")
        for i in range(25):
            client.index("cs", "doc", {"n": i}, id=str(i))
        client.refresh("cs")
        stats = cluster.client().cluster_stats()
        assert stats["status"] == "green"
        assert stats["indices"]["count"] == 1
        assert stats["indices"]["shards"]["total"] == 4
        assert stats["indices"]["shards"]["primaries"] == 2
        assert stats["indices"]["docs"]["count"] == 25  # primaries only
        assert stats["nodes"]["count"]["total"] == 2
        assert stats["nodes"]["count"]["master_data"] == 2


def test_node_shutdown_action(tmp_path):
    with TestCluster(n_nodes=3, data_root=tmp_path, seed=9) as cluster:
        client = cluster.client()
        client.create_index("sd", {"settings": {
            "number_of_shards": 2, "number_of_replicas": 1}})
        cluster.ensure_green("sd")
        master = cluster.master_name()
        victim_name = next(n for n in cluster.nodes if n != master)
        victim = cluster.nodes[victim_name]
        r = cluster.nodes[master].client().nodes_shutdown(victim.local_node.id)
        assert victim.local_node.id in r["nodes"]
        deadline = time.time() + 20
        while time.time() < deadline:
            h = cluster.nodes[master].client().cluster_health("sd")
            if h["number_of_nodes"] == 2 and h["status"] == "green":
                break
            time.sleep(0.2)
        assert h["number_of_nodes"] == 2, h
        assert h["status"] == "green", h  # replicas re-spread after the leave
        cluster.nodes.pop(victim_name, None)  # already closed itself
