"""Snapshot / restore: incremental fs repository, restore to new + renamed indices."""

import pytest

from elasticsearch_tpu.common.errors import SnapshotError, SnapshotMissingError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry


@pytest.fixture()
def cluster(tmp_path):
    registry = LocalTransportRegistry()
    node = Node(name="snap_node", registry=registry, data_path=str(tmp_path / "node"))
    node.start([node.local_node.transport_address])
    node.wait_for_master()
    yield node, node.client(), str(tmp_path / "repo")
    node.close()


class TestSnapshots:
    def test_snapshot_restore_roundtrip(self, cluster):
        node, client, repo_path = cluster
        client.create_index("src", {"settings": {"number_of_shards": 2,
                                                 "number_of_replicas": 0}})
        client.cluster_health(wait_for_status="green")
        for i in range(15):
            client.index("src", "doc", {"n": i, "text": f"document {i}"}, id=str(i))
        client.put_repository("backup", {"type": "fs",
                                         "settings": {"location": repo_path}})
        assert client.verify_repository("backup")["nodes"]
        r = client.create_snapshot("backup", "snap1")
        assert r["snapshot"]["state"] == "SUCCESS"
        # delete the index, restore it
        client.delete_index("src")
        assert not client.exists_index("src")
        r = client.restore_snapshot("backup", "snap1")
        assert "src" in r["snapshot"]["indices"]
        client.cluster_health(wait_for_status="green")
        client.refresh("src")
        assert client.count("src")["count"] == 15
        g = client.get("src", "doc", "7")
        assert g["found"] and g["_source"]["n"] == 7

    def test_incremental_second_snapshot(self, cluster):
        node, client, repo_path = cluster
        client.create_index("inc", {"settings": {"number_of_shards": 1,
                                                 "number_of_replicas": 0}})
        client.cluster_health(wait_for_status="green")
        client.index("inc", "doc", {"v": 1}, id="1")
        client.put_repository("b2", {"type": "fs", "settings": {"location": repo_path}})
        client.create_snapshot("b2", "s1")
        client.index("inc", "doc", {"v": 2}, id="2")
        r = client.create_snapshot("b2", "s2")
        assert r["snapshot"]["state"] == "SUCCESS"
        snaps = client.get_snapshots("b2")
        assert [s["snapshot"] for s in snaps["snapshots"]] == ["s1", "s2"]
        # restore older snapshot under a new name
        r = client.restore_snapshot("b2", "s1", {"rename_pattern": "inc",
                                                 "rename_replacement": "inc_restored"})
        assert r["snapshot"]["indices"] == ["inc_restored"]
        client.refresh("inc_restored")
        assert client.count("inc_restored")["count"] == 1
        assert client.count("inc")["count"] == 2  # original untouched

    def test_delete_snapshot_prunes_orphans(self, cluster):
        node, client, repo_path = cluster
        client.create_index("p", {"settings": {"number_of_shards": 1,
                                               "number_of_replicas": 0}})
        client.cluster_health(wait_for_status="green")
        client.index("p", "doc", {"x": 1}, id="1")
        client.put_repository("b3", {"type": "fs", "settings": {"location": repo_path}})
        client.create_snapshot("b3", "only")
        client.delete_snapshot("b3", "only")
        with pytest.raises(SnapshotMissingError):
            client.get_snapshots("b3", "only")
        import os

        assert os.listdir(os.path.join(repo_path, "blobs")) == []

    def test_restore_refuses_existing_index(self, cluster):
        node, client, repo_path = cluster
        client.create_index("e", {"settings": {"number_of_shards": 1,
                                               "number_of_replicas": 0}})
        client.cluster_health(wait_for_status="green")
        client.index("e", "doc", {"x": 1}, id="1")
        client.put_repository("b4", {"type": "fs", "settings": {"location": repo_path}})
        client.create_snapshot("b4", "s")
        with pytest.raises(SnapshotError):
            client.restore_snapshot("b4", "s")


class TestUrlRepository:
    """ref: repositories/uri/URLRepository.java — read-only restore source.

    Regression anchor: an http:// address used to be joined as a local path,
    leaking a literal `http:` directory at the process cwd."""

    def test_fs_location_rejects_url(self, cluster):
        node, client, repo_path = cluster
        import os

        with pytest.raises(SnapshotError):
            client.put_repository("bad", {"type": "fs", "settings": {
                "location": "http://snapshot.test1/repo"}})
        assert not os.path.exists("http:")

    def test_file_url_restore_and_readonly(self, cluster):
        node, client, repo_path = cluster
        client.create_index("u", {"settings": {"number_of_shards": 1,
                                               "number_of_replicas": 0}})
        client.cluster_health(wait_for_status="green")
        client.index("u", "doc", {"x": 41}, id="1")
        client.put_repository("w", {"type": "fs", "settings": {"location": repo_path}})
        client.create_snapshot("w", "s1")
        client.delete_index("u")
        # re-register the same tree as a read-only url repo and restore from it
        client.put_repository("ro", {"type": "url",
                                     "settings": {"url": f"file://{repo_path}"}})
        assert client.verify_repository("ro")["nodes"]
        snaps = client.get_snapshots("ro")
        assert [s["snapshot"] for s in snaps["snapshots"]] == ["s1"]
        with pytest.raises(SnapshotError):
            client.create_snapshot("ro", "s2")  # refused before any blob write
        r = client.restore_snapshot("ro", "s1")
        assert r["snapshot"]["indices"] == ["u"]
        client.refresh("u")
        assert client.get("u", "doc", "1")["_source"]["x"] == 41

    def test_http_url_restore(self, cluster, tmp_path):
        """Serve the repo tree over a real local http server; restore through it."""
        import http.server
        import threading

        node, client, repo_path = cluster
        client.create_index("h", {"settings": {"number_of_shards": 1,
                                               "number_of_replicas": 0}})
        client.cluster_health(wait_for_status="green")
        client.index("h", "doc", {"x": 7}, id="1")
        client.put_repository("w2", {"type": "fs", "settings": {"location": repo_path}})
        client.create_snapshot("w2", "s1")
        client.delete_index("h")

        served = []

        class H(http.server.SimpleHTTPRequestHandler):
            def __init__(self, *a, **kw):
                # SimpleHTTPRequestHandler defaults directory to os.getcwd() when
                # the kwarg is absent — a class attribute is silently overwritten
                super().__init__(*a, directory=str(repo_path), **kw)

            def log_message(self, *a):
                served.append(self.path)

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            client.put_repository("httpro", {"type": "url", "settings": {
                "url": f"http://127.0.0.1:{port}"}})
            snaps = client.get_snapshots("httpro")
            assert [s["snapshot"] for s in snaps["snapshots"]] == ["s1"]
            r = client.restore_snapshot("httpro", "s1")
            assert r["snapshot"]["indices"] == ["h"]
            client.refresh("h")
            assert client.get("h", "doc", "1")["_source"]["x"] == 7
            # the restore must have actually ridden http, not a local-path fallback
            assert any(p.endswith("index.json") for p in served), served
            assert len(served) > 1, served
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_unsupported_scheme_rejected(self, cluster):
        node, client, repo_path = cluster
        with pytest.raises(SnapshotError):
            client.put_repository("bad2", {"type": "url", "settings": {
                "url": "ftp://example.com/repo"}})
