"""Mesh serving: a REST _search over a co-located multi-shard index executes the
SPMD shard_map program (DFS psum + all_gather top-k over the virtual 8-device CPU
mesh) and produces results identical to the transport scatter-gather path.

ref: the scatter-gather this replaces is TransportSearchTypeAction.java:117,135-216
with the reduce at SearchPhaseController.java:137."""

import numpy as np
import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry

N_SHARDS = 4
VOCAB = ("alpha beta gamma delta epsilon zeta eta theta iota kappa lamda mu nu xi "
         "omicron pi rho sigma tau upsilon phi chi psi omega").split()


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    registry = LocalTransportRegistry()
    n = Node(name="mesh_node", registry=registry,
             data_path=str(tmp_path_factory.mktemp("mesh_node")))
    n.start([n.local_node.transport_address])
    n.wait_for_master()
    client = n.client()
    client.create_index("library", {"settings": {
        "number_of_shards": N_SHARDS, "number_of_replicas": 0}})
    client.cluster_health(wait_for_status="green")
    rng = np.random.default_rng(7)
    for i in range(120):
        body = " ".join(rng.choice(VOCAB, size=rng.integers(5, 25)))
        client.index("library", "doc", {"body": body, "n": int(i)}, id=str(i))
    client.refresh("library")
    yield n, client
    n.close()


def _search_both_paths(node_, client, body, search_type="query_then_fetch"):
    """Run the same search with mesh serving on and off; return (mesh, transport)."""
    ms = node_.actions.mesh_serving
    before = ms.mesh_queries
    mesh = client.search("library", body, search_type=search_type)
    assert ms.mesh_queries == before + 1, "search did not ride the mesh program"
    ms.enabled = False
    try:
        transport = client.search("library", body, search_type=search_type)
    finally:
        ms.enabled = True
    return mesh, transport


def _assert_same_results(mesh, transport):
    assert mesh["hits"]["total"] == transport["hits"]["total"]
    m = [(h["_id"], h["_score"]) for h in mesh["hits"]["hits"]]
    t = [(h["_id"], h["_score"]) for h in transport["hits"]["hits"]]
    assert [i for i, _ in m] == [i for i, _ in t]
    assert np.allclose([s for _, s in m], [s for _, s in t], rtol=2e-6)


class TestMeshServing:
    def test_match_rides_mesh_and_agrees(self, node):
        n, client = node
        body = {"query": {"match": {"body": "alpha beta"}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        assert mesh["hits"]["total"] > 0
        _assert_same_results(mesh, transport)

    def test_bool_semantics_on_mesh(self, node):
        n, client = node
        body = {"query": {"bool": {
            "must": [{"term": {"body": "alpha"}}],
            "should": [{"term": {"body": "beta"}}, {"term": {"body": "gamma"}}],
            "must_not": [{"term": {"body": "omega"}}]}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        _assert_same_results(mesh, transport)

    def test_dfs_search_type_uses_global_stats(self, node):
        n, client = node
        body = {"query": {"match": {"body": "delta epsilon"}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body,
                                             search_type="dfs_query_then_fetch")
        _assert_same_results(mesh, transport)

    def test_metric_aggs_ride_mesh_and_match_transport(self, node):
        # metric aggs fuse into the SPMD program (stats + all_gather); results
        # must match the transport path within f32 kernel accumulation
        n, client = node
        ms = n.actions.mesh_serving
        before = ms.mesh_queries
        body = {"query": {"match": {"body": "alpha"}},
                "aggs": {"n_avg": {"avg": {"field": "n"}},
                         "n_stats": {"stats": {"field": "n"}}}}
        r = client.search("library", body)
        assert ms.mesh_queries == before + 1  # served by the mesh program
        ms.enabled = False
        try:
            r2 = client.search("library", body)
        finally:
            ms.enabled = True
        for name in ("n_avg", "n_stats"):
            a, b = r["aggregations"][name], r2["aggregations"][name]
            for k2 in a:
                if isinstance(a[k2], float):
                    assert abs(a[k2] - b[k2]) <= 1e-5 * max(abs(b[k2]), 1)
                else:
                    assert a[k2] == b[k2]

    def test_non_metric_aggs_fall_back_to_transport(self, node):
        n, client = node
        ms = n.actions.mesh_serving
        before = ms.mesh_queries
        r = client.search("library", {
            "query": {"match": {"body": "alpha"}},
            "aggs": {"by_body": {"terms": {"field": "body"}}}})
        assert ms.mesh_queries == before  # ineligible: bucket agg
        assert "by_body" in r["aggregations"]

    def test_fetch_phase_hydrates_mesh_hits(self, node):
        n, client = node
        mesh, _ = _search_both_paths(
            n, client, {"query": {"term": {"body": "alpha"}}, "size": 5})
        for h in mesh["hits"]["hits"]:
            assert "body" in h["_source"] and h["_index"] == "library"

    def test_deletes_invalidate_mesh_cache(self, node):
        n, client = node
        body = {"query": {"term": {"body": "alpha"}}, "size": 30}
        mesh, _ = _search_both_paths(n, client, body)
        victims = [h["_id"] for h in mesh["hits"]["hits"]][:2]
        for vid in victims:
            client.delete("library", "doc", vid)
        client.refresh("library")
        mesh2, transport2 = _search_both_paths(n, client, body)
        _assert_same_results(mesh2, transport2)
        ids = [h["_id"] for h in mesh2["hits"]["hits"]]
        assert not (set(victims) & set(ids))

    def test_filtered_query_rides_mesh(self, node):
        n, client = node
        body = {"query": {"filtered": {
            "query": {"match": {"body": "alpha beta"}},
            "filter": {"range": {"n": {"gte": 10, "lt": 80}}}}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        _assert_same_results(mesh, transport)
        assert mesh["hits"]["total"] > 0

    def test_recreated_index_never_serves_stale_cache(self, node):
        n, client = node
        for round_ in ("first", "second"):
            client.create_index("tmpidx", {"settings": {
                "number_of_shards": 2, "number_of_replicas": 0}})
            client.cluster_health(wait_for_status="green")
            for i in range(8):
                client.index("tmpidx", "doc", {"body": f"{round_} common"}, id=str(i))
            client.refresh("tmpidx")
            r = client.search("tmpidx", {"query": {"term": {"body": round_}},
                                         "size": 5})
            assert r["hits"]["total"] == 8, round_  # stale cache would return 0
            client.delete_index("tmpidx")

    def test_new_docs_visible_after_refresh(self, node):
        n, client = node
        client.index("library", "doc", {"body": "zzyzx alpha", "n": 999}, id="zz1")
        client.refresh("library")
        mesh, transport = _search_both_paths(
            n, client, {"query": {"term": {"body": "zzyzx"}}, "size": 5})
        assert mesh["hits"]["total"] == 1
        assert mesh["hits"]["hits"][0]["_id"] == "zz1"
        _assert_same_results(mesh, transport)
