"""Mesh serving: a REST _search over a co-located multi-shard index executes the
SPMD shard_map program (DFS psum + all_gather top-k over the virtual 8-device CPU
mesh) and produces results identical to the transport scatter-gather path.

ref: the scatter-gather this replaces is TransportSearchTypeAction.java:117,135-216
with the reduce at SearchPhaseController.java:137."""

import numpy as np
import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry

pytestmark = pytest.mark.mesh

N_SHARDS = 4
VOCAB = ("alpha beta gamma delta epsilon zeta eta theta iota kappa lamda mu nu xi "
         "omicron pi rho sigma tau upsilon phi chi psi omega").split()


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    registry = LocalTransportRegistry()
    n = Node(name="mesh_node", registry=registry,
             data_path=str(tmp_path_factory.mktemp("mesh_node")))
    n.start([n.local_node.transport_address])
    n.wait_for_master()
    client = n.client()
    client.create_index("library", {"settings": {
        "number_of_shards": N_SHARDS, "number_of_replicas": 0}})
    client.cluster_health(wait_for_status="green")
    rng = np.random.default_rng(7)
    for i in range(120):
        body = " ".join(rng.choice(VOCAB, size=rng.integers(5, 25)))
        client.index("library", "doc", {"body": body, "n": int(i)}, id=str(i))
    client.refresh("library")
    yield n, client
    n.close()


def _search_both_paths(node_, client, body, search_type="query_then_fetch"):
    """Run the same search with mesh serving on and off; return (mesh, transport)."""
    ms = node_.actions.mesh_serving
    before = ms.mesh_queries
    mesh = client.search("library", body, search_type=search_type)
    assert ms.mesh_queries == before + 1, "search did not ride the mesh program"
    ms.enabled = False
    try:
        transport = client.search("library", body, search_type=search_type)
    finally:
        ms.enabled = True
    return mesh, transport


def _assert_same_results(mesh, transport):
    assert mesh["hits"]["total"] == transport["hits"]["total"]
    m = [(h["_id"], h["_score"]) for h in mesh["hits"]["hits"]]
    t = [(h["_id"], h["_score"]) for h in transport["hits"]["hits"]]
    assert [i for i, _ in m] == [i for i, _ in t]
    assert np.allclose([s for _, s in m], [s for _, s in t], rtol=2e-6)


class TestMeshServing:
    def test_match_rides_mesh_and_agrees(self, node):
        n, client = node
        body = {"query": {"match": {"body": "alpha beta"}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        assert mesh["hits"]["total"] > 0
        _assert_same_results(mesh, transport)

    def test_bool_semantics_on_mesh(self, node):
        n, client = node
        body = {"query": {"bool": {
            "must": [{"term": {"body": "alpha"}}],
            "should": [{"term": {"body": "beta"}}, {"term": {"body": "gamma"}}],
            "must_not": [{"term": {"body": "omega"}}]}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        _assert_same_results(mesh, transport)

    def test_dfs_search_type_uses_global_stats(self, node):
        n, client = node
        body = {"query": {"match": {"body": "delta epsilon"}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body,
                                             search_type="dfs_query_then_fetch")
        _assert_same_results(mesh, transport)

    def test_metric_aggs_ride_mesh_and_match_transport(self, node):
        # metric aggs fuse into the SPMD program (stats + all_gather); results
        # must match the transport path within f32 kernel accumulation
        n, client = node
        ms = n.actions.mesh_serving
        before = ms.mesh_queries
        body = {"query": {"match": {"body": "alpha"}},
                "aggs": {"n_avg": {"avg": {"field": "n"}},
                         "n_stats": {"stats": {"field": "n"}}}}
        r = client.search("library", body)
        assert ms.mesh_queries == before + 1  # served by the mesh program
        ms.enabled = False
        try:
            r2 = client.search("library", body)
        finally:
            ms.enabled = True
        for name in ("n_avg", "n_stats"):
            a, b = r["aggregations"][name], r2["aggregations"][name]
            for k2 in a:
                if isinstance(a[k2], float):
                    assert abs(a[k2] - b[k2]) <= 1e-5 * max(abs(b[k2]), 1)
                else:
                    assert a[k2] == b[k2]

    def test_ineligible_aggs_fall_back_to_transport(self, node):
        # cardinality's HLL sketch can't ride the SPMD scatter; the whole
        # request declines to the transport path (which still answers)
        n, client = node
        ms = n.actions.mesh_serving
        before = ms.mesh_queries
        r = client.search("library", {
            "query": {"match": {"body": "alpha"}},
            "aggs": {"uniq": {"cardinality": {"field": "body"}}}})
        assert ms.mesh_queries == before  # ineligible: sketch agg
        assert "uniq" in r["aggregations"]

    def test_fetch_phase_hydrates_mesh_hits(self, node):
        n, client = node
        mesh, _ = _search_both_paths(
            n, client, {"query": {"term": {"body": "alpha"}}, "size": 5})
        for h in mesh["hits"]["hits"]:
            assert "body" in h["_source"] and h["_index"] == "library"

    def test_deletes_invalidate_mesh_cache(self, node):
        n, client = node
        body = {"query": {"term": {"body": "alpha"}}, "size": 30}
        mesh, _ = _search_both_paths(n, client, body)
        victims = [h["_id"] for h in mesh["hits"]["hits"]][:2]
        for vid in victims:
            client.delete("library", "doc", vid)
        client.refresh("library")
        mesh2, transport2 = _search_both_paths(n, client, body)
        _assert_same_results(mesh2, transport2)
        ids = [h["_id"] for h in mesh2["hits"]["hits"]]
        assert not (set(victims) & set(ids))

    def test_filtered_query_rides_mesh(self, node):
        n, client = node
        body = {"query": {"filtered": {
            "query": {"match": {"body": "alpha beta"}},
            "filter": {"range": {"n": {"gte": 10, "lt": 80}}}}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        _assert_same_results(mesh, transport)
        assert mesh["hits"]["total"] > 0

    def test_recreated_index_never_serves_stale_cache(self, node):
        n, client = node
        for round_ in ("first", "second"):
            client.create_index("tmpidx", {"settings": {
                "number_of_shards": 2, "number_of_replicas": 0}})
            client.cluster_health(wait_for_status="green")
            for i in range(8):
                client.index("tmpidx", "doc", {"body": f"{round_} common"}, id=str(i))
            client.refresh("tmpidx")
            r = client.search("tmpidx", {"query": {"term": {"body": round_}},
                                         "size": 5})
            assert r["hits"]["total"] == 8, round_  # stale cache would return 0
            client.delete_index("tmpidx")

    def test_new_docs_visible_after_refresh(self, node):
        n, client = node
        client.index("library", "doc", {"body": "zzyzx alpha", "n": 999}, id="zz1")
        client.refresh("library")
        mesh, transport = _search_both_paths(
            n, client, {"query": {"term": {"body": "zzyzx"}}, "size": 5})
        assert mesh["hits"]["total"] == 1
        assert mesh["hits"]["hits"][0]["_id"] == "zz1"
        _assert_same_results(mesh, transport)


class TestMeshServingRound5:
    """Round-5 mesh parity: sort, post_filter, min_score, bucket aggs and
    shard-subset serving all ride the SPMD program and match the transport
    path (ref: the per-feature logic these mirror lives in
    service.execute_query_phase's device branches)."""

    def test_field_sort_rides_mesh(self, node):
        n, client = node
        for order in ("asc", "desc"):
            body = {"query": {"match": {"body": "alpha"}},
                    "sort": [{"n": {"order": order}}], "size": 10}
            mesh, transport = _search_both_paths(n, client, body)
            assert mesh["hits"]["total"] == transport["hits"]["total"]
            m = [(h["_id"], h["sort"]) for h in mesh["hits"]["hits"]]
            t = [(h["_id"], h["sort"]) for h in transport["hits"]["hits"]]
            assert m == t, order
            assert len(m) > 0

    def test_sort_with_track_scores(self, node):
        n, client = node
        body = {"query": {"match": {"body": "alpha"}},
                "sort": [{"n": "desc"}], "track_scores": True, "size": 8}
        mesh, transport = _search_both_paths(n, client, body)
        m = [(h["_id"], h["sort"]) for h in mesh["hits"]["hits"]]
        t = [(h["_id"], h["sort"]) for h in transport["hits"]["hits"]]
        assert m == t
        ms = [h["_score"] for h in mesh["hits"]["hits"]]
        ts = [h["_score"] for h in transport["hits"]["hits"]]
        assert np.allclose(ms, ts, rtol=2e-6)

    def test_post_filter_rides_mesh(self, node):
        # post_filter gates hits/totals but not aggregations
        n, client = node
        body = {"query": {"match": {"body": "alpha"}},
                "post_filter": {"range": {"n": {"lt": 40}}},
                "aggs": {"n_avg": {"avg": {"field": "n"}}}, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        _assert_same_results(mesh, transport)
        assert abs(mesh["aggregations"]["n_avg"]["value"]
                   - transport["aggregations"]["n_avg"]["value"]) < 1e-4

    def test_min_score_rides_mesh(self, node):
        n, client = node
        probe = client.search("library", {"query": {"match": {"body": "alpha"}},
                                          "size": 5})
        # midpoint between two hit scores: robust to per-kernel f32 ulp drift
        # (an exact hit score would flip inclusion between execution paths)
        threshold = (probe["hits"]["hits"][2]["_score"]
                     + probe["hits"]["hits"][3]["_score"]) / 2.0
        body = {"query": {"match": {"body": "alpha"}},
                "min_score": threshold, "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        _assert_same_results(mesh, transport)
        assert mesh["hits"]["total"] < probe["hits"]["total"]

    def test_terms_agg_rides_mesh(self, node):
        n, client = node
        body = {"query": {"match": {"body": "alpha"}},
                "aggs": {"by_body": {"terms": {"field": "body", "size": 8}}},
                "size": 5}
        mesh, transport = _search_both_paths(n, client, body)
        _assert_same_results(mesh, transport)
        m = [(b["key"], b["doc_count"])
             for b in mesh["aggregations"]["by_body"]["buckets"]]
        t = [(b["key"], b["doc_count"])
             for b in transport["aggregations"]["by_body"]["buckets"]]
        assert m == t

    def test_histogram_with_metric_subagg_rides_mesh(self, node):
        n, client = node
        body = {"query": {"match": {"body": "alpha"}},
                "aggs": {"by_n": {"histogram": {"field": "n", "interval": 25},
                                  "aggs": {"navg": {"avg": {"field": "n"}}}}},
                "size": 0}
        mesh, transport = _search_both_paths(n, client, body)
        m = mesh["aggregations"]["by_n"]["buckets"]
        t = transport["aggregations"]["by_n"]["buckets"]
        assert [(b["key"], b["doc_count"]) for b in m] == \
            [(b["key"], b["doc_count"]) for b in t]
        for bm, bt in zip(m, t):
            assert abs(bm["navg"]["value"] - bt["navg"]["value"]) < 1e-4

    def test_range_agg_rides_mesh(self, node):
        # positional buckets: every range emits (zero-count included)
        n, client = node
        body = {"query": {"match": {"body": "alpha"}},
                "aggs": {"rng": {"range": {"field": "n", "ranges": [
                    {"to": 40}, {"from": 40, "to": 90},
                    {"from": 90}, {"from": 5000}]}}}, "size": 0}
        mesh, transport = _search_both_paths(n, client, body)
        m = mesh["aggregations"]["rng"]["buckets"]
        t = transport["aggregations"]["rng"]["buckets"]
        assert [(b.get("key"), b["doc_count"]) for b in m] == \
            [(b.get("key"), b["doc_count"]) for b in t]
        assert m[-1]["doc_count"] == 0  # zero-count range still emitted

    def test_filters_agg_rides_mesh(self, node):
        n, client = node
        body = {"query": {"match": {"body": "alpha"}},
                "aggs": {"f": {"filters": {"filters": {
                    "low": {"range": {"n": {"lt": 60}}},
                    "high": {"range": {"n": {"gte": 60}}}}}}}, "size": 0}
        mesh, transport = _search_both_paths(n, client, body)
        m = {k: b["doc_count"]
             for k, b in mesh["aggregations"]["f"]["buckets"].items()}
        t = {k: b["doc_count"]
             for k, b in transport["aggregations"]["f"]["buckets"].items()}
        assert m == t and set(m) == {"low", "high"}

    def test_significant_terms_declines_mesh(self, node):
        # per-segment background counts don't survive the shard-level merge
        n, client = node
        ms = n.actions.mesh_serving
        before = ms.mesh_queries
        r = client.search("library", {
            "query": {"match": {"body": "alpha"}},
            "aggs": {"sig": {"significant_terms": {"field": "body"}}}})
        assert ms.mesh_queries == before
        assert "sig" in r["aggregations"]

    def test_shard_subset_preference_rides_mesh(self, node):
        # routing/preference selecting a subset serves via the active mask
        n, client = node
        ms = n.actions.mesh_serving
        body = {"query": {"match": {"body": "alpha beta"}}, "size": 10}
        full = client.search("library", body)
        before = ms.mesh_queries
        subset = client.search("library", body, preference="_shards:0,2")
        assert ms.mesh_queries == before + 1
        ms.enabled = False
        try:
            subset_t = client.search("library", body, preference="_shards:0,2")
        finally:
            ms.enabled = True
        assert subset["hits"]["total"] == subset_t["hits"]["total"]
        assert [h["_id"] for h in subset["hits"]["hits"]] == \
            [h["_id"] for h in subset_t["hits"]["hits"]]
        assert subset["hits"]["total"] < full["hits"]["total"]

    def test_sort_asc_missing_last(self, node):
        # (k > doc_pad declines the mesh, so keep the result window small and
        # the query selective enough that the missing-value doc is in-window)
        n, client = node
        client.index("library", "doc", {"body": "zzyzx nofield"}, id="nm1")
        client.refresh("library")
        try:
            body = {"query": {"term": {"body": "zzyzx"}},
                    "sort": [{"n": {"order": "asc", "missing": "_last"}}],
                    "size": 10}
            mesh, transport = _search_both_paths(n, client, body)
            m = [(h["_id"], h["sort"]) for h in mesh["hits"]["hits"]]
            t = [(h["_id"], h["sort"]) for h in transport["hits"]["hits"]]
            assert m == t
            assert m[-1][0] == "nm1"  # missing ranks last
            assert len(m) >= 2
        finally:
            client.delete("library", "doc", "nm1")
            client.refresh("library")

    def test_sort_plus_post_filter_plus_min_score_composes(self, node):
        n, client = node
        body = {"query": {"match": {"body": "alpha"}},
                "post_filter": {"range": {"n": {"gte": 10}}},
                "min_score": 0.01,
                "sort": [{"n": "desc"}], "size": 10}
        mesh, transport = _search_both_paths(n, client, body)
        assert mesh["hits"]["total"] == transport["hits"]["total"]
        assert [h["_id"] for h in mesh["hits"]["hits"]] == \
            [h["_id"] for h in transport["hits"]["hits"]]


class TestRepackLockDiscipline:
    def test_repack_runs_outside_the_service_lock_and_racers_dedup(self, node,
                                                                   monkeypatch):
        """PR-6 TPU004 fix: the device repack (build_sharded_index +
        executor construction) must run with MeshServingService._lock
        RELEASED — under the lock it serialized every search on the node
        behind a multi-second pack — and concurrent searches racing the same
        rebuild must dedup onto ONE in-flight build (the rest park on its
        future, lock-free)."""
        import threading
        import time

        from elasticsearch_tpu.parallel import mesh_serving as ms_mod

        n, client = node
        ms = n.actions.mesh_serving
        real_build = ms_mod.build_sharded_index
        calls = []
        lock_free = []

        def spy(*args, **kwargs):
            calls.append(1)
            # timed acquire, NOT a non-blocking probe: a racing search thread
            # legitimately holds _lock for microseconds inside its own cache
            # check, which a blocking=False probe conflates with the bug. The
            # bug shape is the BUILDER thread holding the non-reentrant lock
            # across this whole call — then this same-thread acquire times out.
            got = ms._lock.acquire(timeout=2.0)
            if got:
                ms._lock.release()
            lock_free.append(got)
            time.sleep(0.3)  # widen the race window for the dedup half
            return real_build(*args, **kwargs)

        monkeypatch.setattr(ms_mod, "build_sharded_index", spy)
        with ms._lock:
            ms._executors.clear()  # force a rebuild on the next search
            ms._building.clear()

        body = {"query": {"match": {"body": "alpha"}}, "size": 5}
        results = []

        def run():
            results.append(client.search("library", body))

        threads = [threading.Thread(target=run) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        assert len(results) == 3
        assert all(r["hits"]["total"] > 0 for r in results)
        assert lock_free and all(lock_free), \
            "repack ran while holding MeshServingService._lock"
        assert len(calls) == 1, f"racers did not dedup: {len(calls)} builds"

    def test_stale_builder_does_not_clobber_newer_build(self, monkeypatch):
        """A refresh mid-pack lets a NEWER freshness register its own build;
        the stale builder's cleanup must neither overwrite the newer cache
        entry nor pop the newer in-flight record — but its own waiters still
        get answered. (Code-review finding on the PR-6 fix.)"""
        import threading

        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.parallel.mesh_serving import MeshServingService

        class FakeSearcher:
            def __init__(self, max_doc):
                self.segments = []
                self.max_doc = max_doc

        ms = MeshServingService(None, Settings.from_flat({}))
        svc = object()
        builds = []
        stale_started = threading.Event()
        release_stale = threading.Event()

        def fake_build(searchers, kind, default_sim):
            builds.append(searchers[0].max_doc)
            if searchers[0].max_doc == 1:  # the stale generation
                stale_started.set()
                assert release_stale.wait(10.0)
                return {False: "OLD", True: "OLD"}
            return {False: "NEW", True: "NEW"}

        monkeypatch.setattr(ms, "_build_executors", fake_build)
        out = {}
        t = threading.Thread(target=lambda: out.__setitem__(
            "stale", ms._executor_for("idx", svc, [FakeSearcher(1)],
                                      "bm25", None, False)))
        t.start()
        assert stale_started.wait(10.0)
        # a newer freshness registers AND completes while the stale pack runs
        assert ms._executor_for("idx", svc, [FakeSearcher(2)],
                                "bm25", None, False) == "NEW"
        release_stale.set()
        t.join(10.0)
        assert out["stale"] == "OLD"  # stale waiters still answered
        # the newer cache entry survived the stale finally: no third build
        assert ms._executor_for("idx", svc, [FakeSearcher(2)],
                                "bm25", None, False) == "NEW"
        assert builds == [1, 2], builds
        assert ms._building == {}
