"""Standalone conformance failure reporter — runs the YAML suite once and prints every
failing section's first error, grouped by file. Dev tool, not a pytest test.

Usage: python tests/conformance_report.py [substring-filter ...]
"""

import sys
import tempfile

from tests import restspec
from tests.test_rest_conformance import make_dispatch, wipe, BLACKLIST


def main():
    filters = sys.argv[1:]
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.transport.local import LocalTransportRegistry
    from elasticsearch_tpu.rest.controller import build_rest_controller

    registry = LocalTransportRegistry()
    node = Node(name="conformance", registry=registry,
                data_path=tempfile.mkdtemp(prefix="conf-"),
                settings={"index.number_of_shards": 2,
                          "index.number_of_replicas": 0})
    node.start([node.local_node.transport_address])
    node.wait_for_master()
    controller = build_rest_controller(node)
    dispatch = make_dispatch(controller)
    specs = restspec.load_specs()

    suites = restspec.discover_suites()
    if filters:
        suites = [s for s in suites if any(f in s for f in filters)]
    n_pass = n_fail = 0
    for rel_path in suites:
        setup, sections = restspec.load_suite(rel_path)
        failures = []
        for name, steps in sections:
            key = f"{rel_path}::{name}"
            if key in BLACKLIST or rel_path in BLACKLIST:
                continue
            wipe(dispatch)
            runner = restspec.YamlRunner(dispatch=dispatch, specs=specs)
            try:
                if setup:
                    runner.run_steps(setup)
                runner.run_steps(steps)
            except restspec.SkippedSection:
                pass
            except Exception as e:
                failures.append(f"  [{name}] {type(e).__name__}: {e}")
        if failures:
            n_fail += 1
            print(f"FAIL {rel_path}")
            for f in failures:
                print(f[:500])
        else:
            n_pass += 1
    print(f"\n{n_pass} passed, {n_fail} failed")
    node.close()


if __name__ == "__main__":
    main()
