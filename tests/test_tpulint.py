"""tpulint self-test: the seeded fixture corpus + the repo gate.

Tier-1 runs this, so CI enforces the analyzer with no new infrastructure:

- every rule family has a true-positive fixture whose `# TP`-marked lines must
  be flagged EXACTLY (no extras, no misses) and a false-positive fixture that
  must stay silent — the corpus is the rules' behavioral spec;
- the repo itself must be clean modulo tools/tpulint/baseline.json (new
  hot-path violations fail this test, which is the whole point);
- the CLI contract: `python -m tools.tpulint --check` exits non-zero on the
  violation corpus and 0 on the baselined repo, with --json output.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.tpulint import lint_paths, load_baseline  # noqa: E402
from tools.tpulint.engine import diff_baseline, parse_file  # noqa: E402

FIXDIR = os.path.join(REPO, "tests", "tpulint_fixtures")
RULES = ["TPU001", "TPU002", "TPU003", "TPU004", "TPU005",
         "TPU006", "TPU007", "TPU008", "TPU009", "TPU010",
         "TPU011", "TPU012", "TPU013", "TPU014", "TPU015",
         "TPU016", "TPU017", "TPU018", "TPU019", "TPU020", "TPU021"]


def _marked_lines(path: str) -> set:
    with open(path, encoding="utf-8") as f:
        return {i for i, ln in enumerate(f.read().splitlines(), 1)
                if "# TP" in ln}


# ---------------------------------------------------------------------------
# fixture corpus: exact line agreement per rule family
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule", RULES)
def test_true_positive_corpus_exact(rule):
    path = os.path.join(FIXDIR, f"tp_{rule.lower()}.py")
    flagged = {f.line for f in lint_paths([path]) if f.rule == rule}
    assert flagged == _marked_lines(path), (
        f"{rule}: flagged lines {sorted(flagged)} != "
        f"marked lines {sorted(_marked_lines(path))}")


@pytest.mark.parametrize("rule", RULES)
def test_false_positive_corpus_silent(rule):
    path = os.path.join(FIXDIR, f"fp_{rule.lower()}.py")
    findings = [f for f in lint_paths([path]) if f.rule == rule]
    assert not findings, [f.to_dict() for f in findings]


def test_suppression_comment(tmp_path):
    src = tmp_path / "supp.py"
    src.write_text(
        "def f(xs):\n"
        "    a = xs.item()  # tpulint: ignore[TPU001]\n"
        "    b = xs.item()  # tpulint: ignore\n"
        "    c = xs.item()\n"
        "    return a, b, c\n")
    findings = [f for f in lint_paths([str(src)]) if f.rule == "TPU001"]
    assert [f.line for f in findings] == [4]


def test_unparseable_file_is_skipped(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert parse_file(str(bad), explicit=True) is None
    assert lint_paths([str(bad)]) == []


# ---------------------------------------------------------------------------
# the interprocedural engine: hazards the file-local engine missed
# ---------------------------------------------------------------------------


def test_interproc_device_return_branch_hazard():
    """TPU001 rule d through the call graph: branching on a value returned by
    a jnp-producing helper (one and two hops) — the file-local engine kept
    device_names empty for the caller and missed both lines."""
    path = os.path.join(FIXDIR, "tp_tpu001_interproc.py")
    flagged = {f.line for f in lint_paths([path]) if f.rule == "TPU001"}
    assert flagged == _marked_lines(path), sorted(flagged)


def test_interproc_factory_not_device_returning(tmp_path):
    """A factory returning a device-producing CLOSURE is not itself
    device-returning — nested-def bodies must not be attributed to the parent
    (regression: the branch `if g:` on the returned function object must stay
    silent)."""
    src = tmp_path / "factory_case.py"
    src.write_text(
        "import jax.numpy as jnp\n"
        "def make_scorer():\n"
        "    def inner():\n"
        "        return jnp.zeros(3)\n"
        "    return inner\n"
        "def hot():\n"
        "    g = make_scorer()\n"
        "    if g:\n"
        "        return 1\n"
        "    return 0\n")
    assert [f for f in lint_paths([str(src)]) if f.rule == "TPU001"] == []


def test_interproc_cross_module_tracer_leak():
    """TPU003 across modules: a jit root in one file imports and calls a
    helper whose closure-append leak lives in another file. The helper alone
    is silent (nothing traced); together, the project-wide traced closure
    flags the leak IN THE HELPER FILE."""
    helper = os.path.join(FIXDIR, "tp_xmod_tpu003_helper.py")
    root = os.path.join(FIXDIR, "tp_xmod_tpu003_root.py")
    assert [f for f in lint_paths([helper]) if f.rule == "TPU003"] == []
    both = [f for f in lint_paths([helper, root]) if f.rule == "TPU003"]
    assert [(f.path.rsplit("/", 1)[-1], f.line) for f in both] == \
        [("tp_xmod_tpu003_helper.py", 17)], [f.to_dict() for f in both]


def test_interproc_lock_order_cycle_cross_module():
    """TPU004 across modules: the root holds a lock and calls a helper module
    whose function dispatches to the device. The helper alone is silent (no
    lock held there); linted together, the call site in the root is flagged
    AND the helper's dispatch line (its meet-over-call-sites context is the
    root's lock)."""
    helper = os.path.join(FIXDIR, "tp_xmod_tpu004_helper.py")
    root = os.path.join(FIXDIR, "tp_xmod_tpu004_root.py")
    assert [f for f in lint_paths([helper]) if f.rule == "TPU004"] == []
    both = [f for f in lint_paths([helper, root]) if f.rule == "TPU004"]
    got = sorted((f.path.rsplit("/", 1)[-1], f.line) for f in both)
    assert got == [("tp_xmod_tpu004_helper.py", 13),
                   ("tp_xmod_tpu004_root.py", 20)], \
        [f.to_dict() for f in both]


def test_interproc_collective_divergence_cross_module():
    """TPU014 across modules: the host-dependent branch lives in the root,
    the collective in the helper. The helper alone is silent (no branch
    there); linted together, the spmd reach fixpoint flags the CALL SITE in
    the root and names the helper's psum line as the origin."""
    helper = os.path.join(FIXDIR, "tp_xmod_tpu014_helper.py")
    root = os.path.join(FIXDIR, "tp_xmod_tpu014_root.py")
    assert [f for f in lint_paths([helper]) if f.rule == "TPU014"] == []
    both = [f for f in lint_paths([root, helper]) if f.rule == "TPU014"]
    assert [(f.path.rsplit("/", 1)[-1], f.line) for f in both] == \
        [("tp_xmod_tpu014_root.py", 25)], [f.to_dict() for f in both]
    assert "tp_xmod_tpu014_helper.py:13" in both[0].message, both[0].message


def test_interproc_unbucketed_dim_cross_module():
    """TPU018 across modules: the raw request length is computed by a helper
    in another file. The helper alone is silent (no executable constructed
    there); linted together, the return-calls fixpoint classifies the helper
    as unbounded-returning and the root's allocation is flagged at its own
    line."""
    helper = os.path.join(FIXDIR, "tp_xmod_tpu018_helper.py")
    root = os.path.join(FIXDIR, "tp_xmod_tpu018_root.py")
    assert [f for f in lint_paths([helper]) if f.rule == "TPU018"] == []
    both = [f for f in lint_paths([root, helper]) if f.rule == "TPU018"]
    assert {(f.path.rsplit("/", 1)[-1], f.line) for f in both} == \
        {("tp_xmod_tpu018_root.py", ln)
         for ln in _marked_lines(root)}, [f.to_dict() for f in both]


def test_abba_fixture_is_a_tpu004_true_positive():
    """The runnable ABBA deadlock fixture (tests/test_locktrace.py drives it
    under ESTPU_LOCKTRACE=1) is ALSO flagged statically: both inner
    acquisitions of the cycle, at their exact lines."""
    path = os.path.join(FIXDIR, "tp_abba_deadlock.py")
    flagged = {f.line for f in lint_paths([path]) if f.rule == "TPU004"}
    assert flagged == _marked_lines(path), sorted(flagged)


# ---------------------------------------------------------------------------
# the repo gate (this IS the CI enforcement)
# ---------------------------------------------------------------------------


def test_repo_clean_under_baseline():
    findings = lint_paths(None)
    new, _stale = diff_baseline(findings, load_baseline())
    assert not new, (
        "new tpulint findings — fix them or (for deliberate exceptions) add a "
        "`# tpulint: ignore[RULE]` comment; do NOT grow baseline.json:\n  "
        + "\n  ".join(f"{f.key}  {f.message}" for f in new))


def test_resilience_modules_scan_clean():
    """The PR-3 resilience layer (deadline/retry/faults) is host-only control
    code: deadline checks use time.monotonic on host paths and must never leak
    into traced regions — pin that the repo scan covers these modules and finds
    nothing (the baseline stays empty)."""
    paths = [os.path.join(REPO, "elasticsearch_tpu", *parts) for parts in (
        ("common", "deadline.py"), ("common", "retry.py"),
        ("transport", "faults.py"), ("transport", "service.py"))]
    for p in paths:
        assert os.path.exists(p), p
    assert lint_paths(paths) == []


def test_baseline_is_empty_and_stays_empty():
    """PR 2 burned the 20 grandfathered TPU001 findings down to zero; the
    baseline must never regrow (new findings already fail
    test_repo_clean_under_baseline — this pins the EMPTY state itself)."""
    assert load_baseline() == set(), (
        "baseline.json regrew — fix the findings instead of grandfathering")


def test_baseline_entries_not_stale_in_bulk():
    """A mostly-stale baseline means fingerprints drifted wholesale (e.g. a
    big refactor) — regenerate it so the grandfather list stays honest."""
    findings = lint_paths(None)
    baseline = load_baseline()
    _new, stale = diff_baseline(findings, baseline)
    if baseline:
        assert len(stale) < max(3, len(baseline) // 2), (
            f"{len(stale)}/{len(baseline)} baseline entries no longer fire — "
            "run `python -m tools.tpulint --update-baseline`")


# ---------------------------------------------------------------------------
# fingerprint-stable baseline
# ---------------------------------------------------------------------------


_VIOLATION = ("import jax.numpy as jnp\n"
              "def f(xs):\n"
              "    return xs.item()\n")


def test_fingerprint_survives_line_shift(tmp_path):
    """Inserting lines ABOVE a grandfathered finding must not invalidate the
    baseline (the PR-1 path:line:rule keys broke on every unrelated edit)."""
    from tools.tpulint.engine import lint_paths as lp, save_baseline

    src = tmp_path / "mod.py"
    src.write_text(_VIOLATION)
    first = lp([str(src)])
    assert len(first) == 1 and first[0].rule == "TPU001"
    bl = tmp_path / "bl.json"
    save_baseline(first, str(bl))
    # unrelated edit above the finding: line number moves, fingerprint doesn't
    src.write_text("# a new comment\nX = 1\n" + _VIOLATION)
    shifted = lp([str(src)])
    assert shifted[0].line == first[0].line + 2
    new, stale = diff_baseline(shifted, load_baseline(str(bl)))
    assert new == [] and stale == []


def test_fingerprint_duplicate_lines_occurrence_indexed(tmp_path):
    """Two identical violating lines get distinct #n-suffixed fingerprints so
    fixing one of them cannot hide the other behind the baseline."""
    src = tmp_path / "dup.py"
    src.write_text("def f(a, b):\n"
                   "    x = a.item()\n"
                   "    y = b.item()\n"
                   "    x = a.item()\n"
                   "    return x, y\n")
    fs = lint_paths([str(src)])
    fps = [f.fingerprint for f in fs if f.rule == "TPU001"]
    assert len(fps) == len(set(fps)) == 3, fps
    assert sum(1 for fp in fps if "#" in fp) == 1  # the repeated line


def test_parse_cache_hits_on_unchanged_file(tmp_path):
    """Re-linting an unchanged file must hit the mtime-keyed parse cache
    (no re-read, no re-parse) — the suite re-lints the fixture corpus dozens
    of times per run."""
    from tools.tpulint.engine import PARSE_CACHE_STATS

    src = tmp_path / "cached.py"
    src.write_text(_VIOLATION)
    lint_paths([str(src)])
    before = dict(PARSE_CACHE_STATS)
    lint_paths([str(src)])
    assert PARSE_CACHE_STATS["hits"] == before["hits"] + 1
    assert PARSE_CACHE_STATS["misses"] == before["misses"]


def test_parse_cache_invalidates_on_edit(tmp_path):
    """Editing a file must invalidate its cache entry: after inserting lines
    above the violation, the finding MOVES with the edit (a stale tree would
    keep reporting the old line)."""
    src = tmp_path / "edited.py"
    src.write_text(_VIOLATION)
    first = [f.line for f in lint_paths([str(src)]) if f.rule == "TPU001"]
    assert first == [3]
    src.write_text("# pad\nX = 1\n" + _VIOLATION)
    moved = [f.line for f in lint_paths([str(src)]) if f.rule == "TPU001"]
    assert moved == [5], moved


def test_old_format_baseline_migrates_on_load(tmp_path):
    """PR-1 path:line:rule baselines load as fingerprints (one-shot) so the
    gate never breaks mid-upgrade."""
    import json as _json

    from tools.tpulint.engine import REPO as _REPO

    src = tmp_path / "legacy.py"
    src.write_text(_VIOLATION)
    rel = os.path.relpath(str(src), _REPO).replace(os.sep, "/")
    bl = tmp_path / "old.json"
    bl.write_text(_json.dumps({"findings": [f"{rel}:3:TPU001"]}))
    migrated = load_baseline(str(bl))
    findings = lint_paths([str(src)])
    new, _stale = diff_baseline(findings, migrated)
    assert new == [], (migrated, [f.fingerprint for f in findings])


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120)


def test_cli_check_fails_on_violation_corpus():
    tp_files = [os.path.join(FIXDIR, f"tp_{r.lower()}.py") for r in RULES]
    res = _run_cli("--check", "--json", "--no-baseline", *tp_files)
    assert res.returncode == 1, res.stderr
    data = json.loads(res.stdout)
    assert data["ok"] is False
    assert {f["rule"] for f in data["findings"]} == set(RULES)


def test_cli_check_passes_on_repo():
    res = _run_cli("--check")
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_json_shape():
    res = _run_cli("--json")
    data = json.loads(res.stdout)
    for key in ("findings", "new", "grandfathered", "stale_baseline", "ok"):
        assert key in data
    for f in data["findings"]:
        assert set(f) == {"path", "line", "rule", "message", "key",
                          "fingerprint"}


def test_cli_github_format_annotations():
    """--format github emits one ::error workflow-annotation line per NEW
    finding, parseable by GitHub Actions with no extra tooling."""
    tp = os.path.join(FIXDIR, "tp_tpu001.py")
    res = _run_cli("--format", "github", "--no-baseline", tp)
    lines = [ln for ln in res.stdout.splitlines() if ln]
    assert lines and all(ln.startswith("::error file=") for ln in lines)
    assert all(",line=" in ln and "title=tpulint TPU" in ln and "::" in ln[8:]
               for ln in lines)


def test_cli_exit_code_contract():
    """0 = clean (and ALWAYS 0 without --check), 1 = --check with new
    findings, 2 = usage error — documented in the module docstring."""
    tp = os.path.join(FIXDIR, "tp_tpu001.py")
    assert _run_cli("--no-baseline", tp).returncode == 0  # findings, no --check
    assert _run_cli("--check", "--no-baseline", tp).returncode == 1
    assert _run_cli("--json", "--format", "text").returncode == 2
    assert _run_cli("--update-baseline", tp).returncode == 2


def test_cli_rules_table():
    res = _run_cli("--rules")
    assert res.returncode == 0
    for rule in RULES:
        assert rule in res.stdout


def test_cli_explain_prints_doc_and_examples():
    """--explain TPU0NN makes findings self-documenting at the terminal: the
    rule's docstring plus one tp/fp example from the fixture corpus."""
    for rule in ("TPU004", "TPU011", "TPU012", "TPU013",
                 "TPU014", "TPU015", "TPU016", "TPU017"):
        res = _run_cli("--explain", rule)
        assert res.returncode == 0, res.stderr
        assert rule in res.stdout
        assert "TRUE POSITIVE" in res.stdout and "# TP" in res.stdout
        assert "FALSE POSITIVE" in res.stdout
        assert "tests/tpulint_fixtures/" in res.stdout


def test_cli_explain_unknown_rule_exits_2():
    res = _run_cli("--explain", "TPU999")
    assert res.returncode == 2
    assert "unknown rule" in res.stderr


def test_cli_update_baseline_refuses_subset_scope():
    """A path-restricted --update-baseline would truncate every other file's
    grandfathered entries — it must refuse, leaving baseline.json untouched."""
    baseline_path = os.path.join(REPO, "tools", "tpulint", "baseline.json")
    with open(baseline_path, encoding="utf-8") as f:
        before = f.read()
    res = _run_cli("--update-baseline",
                   os.path.join(FIXDIR, "tp_tpu001.py"))
    assert res.returncode == 2
    with open(baseline_path, encoding="utf-8") as f:
        assert f.read() == before


def test_cli_subset_run_reports_no_stale_entries():
    """Linting one file must not advise deleting baseline entries that belong
    to files outside the subset."""
    res = _run_cli("--json", os.path.join(FIXDIR, "fp_tpu001.py"))
    data = json.loads(res.stdout)
    assert data["stale_baseline"] == []


def test_duplicate_findings_on_one_line_collapse():
    findings = lint_paths(
        [os.path.join(REPO, "elasticsearch_tpu", "parallel", "mesh_search.py")])
    keys = [f.key for f in findings]
    assert len(keys) == len(set(keys)), keys
