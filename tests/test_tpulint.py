"""tpulint self-test: the seeded fixture corpus + the repo gate.

Tier-1 runs this, so CI enforces the analyzer with no new infrastructure:

- every rule family has a true-positive fixture whose `# TP`-marked lines must
  be flagged EXACTLY (no extras, no misses) and a false-positive fixture that
  must stay silent — the corpus is the rules' behavioral spec;
- the repo itself must be clean modulo tools/tpulint/baseline.json (new
  hot-path violations fail this test, which is the whole point);
- the CLI contract: `python -m tools.tpulint --check` exits non-zero on the
  violation corpus and 0 on the baselined repo, with --json output.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.tpulint import lint_paths, load_baseline  # noqa: E402
from tools.tpulint.engine import diff_baseline, parse_file  # noqa: E402

FIXDIR = os.path.join(REPO, "tests", "tpulint_fixtures")
RULES = ["TPU001", "TPU002", "TPU003", "TPU004", "TPU005"]


def _marked_lines(path: str) -> set:
    with open(path, encoding="utf-8") as f:
        return {i for i, ln in enumerate(f.read().splitlines(), 1)
                if "# TP" in ln}


# ---------------------------------------------------------------------------
# fixture corpus: exact line agreement per rule family
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule", RULES)
def test_true_positive_corpus_exact(rule):
    path = os.path.join(FIXDIR, f"tp_{rule.lower()}.py")
    flagged = {f.line for f in lint_paths([path]) if f.rule == rule}
    assert flagged == _marked_lines(path), (
        f"{rule}: flagged lines {sorted(flagged)} != "
        f"marked lines {sorted(_marked_lines(path))}")


@pytest.mark.parametrize("rule", RULES)
def test_false_positive_corpus_silent(rule):
    path = os.path.join(FIXDIR, f"fp_{rule.lower()}.py")
    findings = [f for f in lint_paths([path]) if f.rule == rule]
    assert not findings, [f.to_dict() for f in findings]


def test_suppression_comment(tmp_path):
    src = tmp_path / "supp.py"
    src.write_text(
        "def f(xs):\n"
        "    a = xs.item()  # tpulint: ignore[TPU001]\n"
        "    b = xs.item()  # tpulint: ignore\n"
        "    c = xs.item()\n"
        "    return a, b, c\n")
    findings = [f for f in lint_paths([str(src)]) if f.rule == "TPU001"]
    assert [f.line for f in findings] == [4]


def test_unparseable_file_is_skipped(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert parse_file(str(bad), explicit=True) is None
    assert lint_paths([str(bad)]) == []


# ---------------------------------------------------------------------------
# the repo gate (this IS the CI enforcement)
# ---------------------------------------------------------------------------


def test_repo_clean_under_baseline():
    findings = lint_paths(None)
    new, _stale = diff_baseline(findings, load_baseline())
    assert not new, (
        "new tpulint findings — fix them or (for deliberate exceptions) add a "
        "`# tpulint: ignore[RULE]` comment; do NOT grow baseline.json:\n  "
        + "\n  ".join(f"{f.key}  {f.message}" for f in new))


def test_baseline_entries_not_stale_in_bulk():
    """A mostly-stale baseline means line numbers drifted wholesale (e.g. a
    big refactor) — regenerate it so the grandfather list stays honest."""
    findings = lint_paths(None)
    baseline = load_baseline()
    _new, stale = diff_baseline(findings, baseline)
    if baseline:
        assert len(stale) < max(3, len(baseline) // 2), (
            f"{len(stale)}/{len(baseline)} baseline entries no longer fire — "
            "run `python -m tools.tpulint --update-baseline`")


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120)


def test_cli_check_fails_on_violation_corpus():
    tp_files = [os.path.join(FIXDIR, f"tp_{r.lower()}.py") for r in RULES]
    res = _run_cli("--check", "--json", "--no-baseline", *tp_files)
    assert res.returncode == 1, res.stderr
    data = json.loads(res.stdout)
    assert data["ok"] is False
    assert {f["rule"] for f in data["findings"]} == set(RULES)


def test_cli_check_passes_on_repo():
    res = _run_cli("--check")
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_json_shape():
    res = _run_cli("--json")
    data = json.loads(res.stdout)
    for key in ("findings", "new", "grandfathered", "stale_baseline", "ok"):
        assert key in data
    for f in data["findings"]:
        assert set(f) == {"path", "line", "rule", "message", "key"}


def test_cli_rules_table():
    res = _run_cli("--rules")
    assert res.returncode == 0
    for rule in RULES:
        assert rule in res.stdout


def test_cli_update_baseline_refuses_subset_scope():
    """A path-restricted --update-baseline would truncate every other file's
    grandfathered entries — it must refuse, leaving baseline.json untouched."""
    baseline_path = os.path.join(REPO, "tools", "tpulint", "baseline.json")
    with open(baseline_path, encoding="utf-8") as f:
        before = f.read()
    res = _run_cli("--update-baseline",
                   os.path.join(FIXDIR, "tp_tpu001.py"))
    assert res.returncode == 2
    with open(baseline_path, encoding="utf-8") as f:
        assert f.read() == before


def test_cli_subset_run_reports_no_stale_entries():
    """Linting one file must not advise deleting baseline entries that belong
    to files outside the subset."""
    res = _run_cli("--json", os.path.join(FIXDIR, "fp_tpu001.py"))
    data = json.loads(res.stdout)
    assert data["stale_baseline"] == []


def test_duplicate_findings_on_one_line_collapse():
    findings = lint_paths(
        [os.path.join(REPO, "elasticsearch_tpu", "parallel", "mesh_search.py")])
    keys = [f.key for f in findings]
    assert len(keys) == len(set(keys)), keys
