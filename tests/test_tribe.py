"""Tribe node: inner member per cluster, merged read view, first-wins conflicts,
write/metadata blocks. ref: tribe/TribeService.java."""

import pytest

from elasticsearch_tpu.common.errors import ClusterBlockError, IndexMissingError
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry


@pytest.fixture()
def two_clusters(tmp_path):
    reg_a, reg_b = LocalTransportRegistry(), LocalTransportRegistry()
    a = Node(name="ca1", registry=reg_a, data_path=str(tmp_path / "a"))
    a.start([a.local_node.transport_address])
    a.wait_for_master()
    b = Node(name="cb1", registry=reg_b, data_path=str(tmp_path / "b"))
    b.start([b.local_node.transport_address])
    b.wait_for_master()
    ca, cb = a.client(), b.client()
    for c, idx, word in ((ca, "books", "novel"), (cb, "films", "cinema")):
        c.create_index(idx, {"settings": {"number_of_shards": 1,
                                          "number_of_replicas": 0}})
        c.cluster_health(wait_for_status="green")
        c.index(idx, "doc", {"t": f"{word} common"}, id="1")
        c.index(idx, "doc", {"t": f"{word} extra"}, id="2")
        c.refresh(idx)
    # same-named index in BOTH clusters: tribe must keep the FIRST (t1 = cluster a)
    for c, val in ((ca, "alpha"), (cb, "beta")):
        c.create_index("shared", {"settings": {"number_of_shards": 1,
                                               "number_of_replicas": 0}})
        c.cluster_health(wait_for_status="green")
        c.index("shared", "doc", {"t": val}, id="1")
        c.refresh("shared")
    yield (a, reg_a), (b, reg_b), tmp_path
    a.close()
    b.close()


def make_tribe(tmp_path, reg_a, reg_b, extra=None):
    settings = {"tribe.t1.cluster.group": "a", "tribe.t2.cluster.group": "b"}
    settings.update(extra or {})
    t = Node(name="tr1", settings=settings, data_path=str(tmp_path / "tr"),
             registry=LocalTransportRegistry(),
             tribe_registries={"t1": reg_a, "t2": reg_b})
    t.start([t.local_node.transport_address])
    return t


class TestTribe:
    def test_reads_route_and_merge(self, two_clusters):
        (a, reg_a), (b, reg_b), tmp = two_clusters
        t = make_tribe(tmp, reg_a, reg_b)
        try:
            c = t.client()
            # single-index reads route to the owning cluster
            r = c.search("books", {"query": {"term": {"t": "novel"}}})
            assert r["hits"]["total"] == 2
            g = c.get("films", "doc", "1")
            assert g["_source"]["t"] == "cinema common"
            # cross-tribe search merges both clusters
            r = c.search("_all", {"query": {"term": {"t": "common"}}, "size": 10})
            assert r["hits"]["total"] == 2
            found = {h["_index"] for h in r["hits"]["hits"]}
            assert found == {"books", "films"}
            assert c.count("_all")["count"] >= 5
        finally:
            t.close()

    def test_conflicting_index_first_wins(self, two_clusters):
        (a, reg_a), (b, reg_b), tmp = two_clusters
        t = make_tribe(tmp, reg_a, reg_b)
        try:
            g = t.client().get("shared", "doc", "1")
            assert g["_source"]["t"] == "alpha"  # t1 configured first
        finally:
            t.close()

    def test_writes_route_unless_blocked(self, two_clusters):
        (a, reg_a), (b, reg_b), tmp = two_clusters
        t = make_tribe(tmp, reg_a, reg_b)
        try:
            c = t.client()
            c.index("books", "doc", {"t": "novel added"}, id="3")
            c.refresh("books")
            assert a.client().get("books", "doc", "3")["found"]
            with pytest.raises(ClusterBlockError):
                c.create_index("newidx", {})  # metadata ops: no master on a tribe
            with pytest.raises(IndexMissingError):
                c.get("nowhere", "doc", "1")
        finally:
            t.close()

    def test_write_block_setting(self, two_clusters):
        (a, reg_a), (b, reg_b), tmp = two_clusters
        t = make_tribe(tmp, reg_a, reg_b, {"tribe.blocks.write": True})
        try:
            with pytest.raises(ClusterBlockError):
                t.client().index("books", "doc", {"t": "x"}, id="9")
        finally:
            t.close()

    def test_cross_tribe_sorted_search(self, two_clusters):
        (a, reg_a), (b, reg_b), tmp = two_clusters
        ca, cb = a.client(), b.client()
        for c, idx, vals in ((ca, "books", (30, 10)), (cb, "films", (20, 40))):
            for i, v in enumerate(vals):
                c.index(idx, "doc", {"t": "sortme", "rank": v}, id=f"s{i}")
            c.refresh(idx)
        t = make_tribe(tmp, reg_a, reg_b)
        try:
            r = t.client().search("_all", {
                "query": {"term": {"t": "sortme"}},
                "sort": [{"rank": "asc"}], "size": 10})
            ranks = [h["sort"][0] for h in r["hits"]["hits"]]
            assert ranks == [10, 20, 30, 40]  # interleaved across tribes, asc
            r = t.client().search("_all", {
                "query": {"term": {"t": "sortme"}},
                "sort": [{"rank": {"order": "desc"}}], "size": 2, "from": 1})
            assert [h["sort"][0] for h in r["hits"]["hits"]] == [30, 20]
        finally:
            t.close()

    def test_merged_health(self, two_clusters):
        (a, reg_a), (b, reg_b), tmp = two_clusters
        t = make_tribe(tmp, reg_a, reg_b)
        try:
            h = t.client().cluster_health()
            assert h["status"] in ("green", "yellow")
            assert h["number_of_nodes"] >= 4  # 2 cluster nodes + 2 inner members
        finally:
            t.close()
