"""TCP transport backend tests (transport/tcp.py — NettyTransport's role).

Covers framing, request/response correlation under concurrency, remote error
reconstruction, compression, connection failure, and a full two-node cluster formed
over real sockets (the reference's ES_TEST_LOCAL=false Netty path, TESTING.asciidoc).
"""

import threading

import pytest

from elasticsearch_tpu.common.errors import (
    IndexMissingError,
    NodeNotConnectedError,
    ReceiveTimeoutError,
)
from elasticsearch_tpu.transport.service import TransportService, fut_result
from elasticsearch_tpu.transport.tcp import TcpTransport


@pytest.fixture()
def pair():
    a = TransportService(TcpTransport())
    b = TransportService(TcpTransport())
    yield a, b
    a.close()
    b.close()


def addr(service):
    return service.backend.address


def test_request_response_roundtrip(pair):
    a, b = pair
    b.register_handler("test/echo", lambda req, ch: {"echo": req["msg"], "n": req["n"] + 1})
    resp = a.submit_request(addr(b), "test/echo", {"msg": "hi", "n": 41}, timeout=10)
    assert resp == {"echo": "hi", "n": 42}


def test_concurrent_requests_correlate(pair):
    a, b = pair
    b.register_handler("test/id", lambda req, ch: {"v": req["v"] * 2})
    futs = [a.send_request(addr(b), "test/id", {"v": i}) for i in range(64)]
    for i, f in enumerate(futs):
        assert fut_result(f, 10)["v"] == 2 * i


def test_remote_error_reconstructed(pair):
    a, b = pair

    def boom(req, ch):
        raise IndexMissingError("nope")

    b.register_handler("test/boom", boom)
    with pytest.raises(IndexMissingError):
        a.submit_request(addr(b), "test/boom", {}, timeout=10)


def test_unknown_action_errors(pair):
    a, b = pair
    with pytest.raises(Exception) as ei:
        a.submit_request(addr(b), "test/missing", {}, timeout=10)
    assert "no handler" in str(ei.value)


def test_large_payload_and_compression():
    a = TransportService(TcpTransport(compress=True))
    b = TransportService(TcpTransport(compress=True))
    try:
        b.register_handler("test/big", lambda req, ch: {"size": len(req["blob"])})
        blob = "x" * (2 * 1024 * 1024)
        resp = a.submit_request(addr(b), "test/big", {"blob": blob}, timeout=30)
        assert resp["size"] == len(blob)
    finally:
        a.close()
        b.close()


def test_dead_node_raises_not_connected(pair):
    a, b = pair
    dead = addr(b)
    b.close()
    with pytest.raises((NodeNotConnectedError, ReceiveTimeoutError)):
        a.submit_request(dead, "test/echo", {}, timeout=5)


def test_handler_slow_response_timeout(pair):
    """Response-timeout path, made deterministic: instead of a wall-clock
    handler sleep racing teardown, a FaultPolicy recv-delay rule on the remote
    service postpones the handler past the request timeout. The timeout is
    enforced on the request future itself (ReceiveTimeoutError, not a leaked
    concurrent.futures.TimeoutError — the pre-3.11 alias bug this test caught),
    and the late response is then discarded, not delivered."""
    from elasticsearch_tpu.transport.faults import FaultPolicy

    a, b = pair
    handled = threading.Event()

    def slow(req, ch):
        handled.set()
        return {"late": True}

    b.register_handler("test/slow", slow)
    FaultPolicy(seed=0).install(b)
    b.fault_policy.delay(1.0, action="test/slow", direction="recv")
    with pytest.raises(ReceiveTimeoutError):
        a.submit_request(addr(b), "test/slow", {}, timeout=0.2)
    # the delayed handler still runs — its answer must land nowhere
    assert handled.wait(5.0)


def test_fault_disconnect_rule_over_tcp(pair):
    """A send-side disconnect rule fails fast with NodeNotConnectedError
    without touching the (healthy) socket; removing the rule heals the path."""
    from elasticsearch_tpu.transport.faults import FaultPolicy

    a, b = pair
    b.register_handler("test/echo", lambda req, ch: {"ok": True})
    policy = FaultPolicy(seed=0).install(a)
    rule = policy.disconnect(action="test/echo", max_hits=1)
    with pytest.raises(NodeNotConnectedError):
        a.submit_request(addr(b), "test/echo", {}, timeout=5)
    assert rule.hits == 1
    assert a.submit_request(addr(b), "test/echo", {}, timeout=5) == {"ok": True}


def test_two_node_cluster_over_tcp(tmp_path):
    """Full integration: two Nodes over real sockets — election, join, replicated
    index, search from the non-primary node."""
    from elasticsearch_tpu.node import Node

    n1 = Node(name="tcp1", settings={"transport.type": "tcp"},
              data_path=str(tmp_path / "n1"))
    seed = n1.local_node.transport_address
    n2 = Node(name="tcp2",
              settings={"transport.type": "tcp",
                        "discovery.zen.ping.unicast.hosts": [seed]},
              data_path=str(tmp_path / "n2"))
    try:
        n1.start(seeds=[])
        n2.start()
        assert n1.cluster_service.state.nodes.master_id is not None
        assert n2.cluster_service.state.nodes.master_id == \
            n1.cluster_service.state.nodes.master_id
        assert len(n2.cluster_service.state.nodes.nodes) == 2

        client = n1.client()
        client.create_index("tcpidx", {"settings": {"index.number_of_shards": 2,
                                                    "index.number_of_replicas": 1}})
        client.cluster_health(wait_for_status="green", timeout=30)
        for i in range(20):
            client.index("tcpidx", "doc", {"title": f"hello world {i}"}, id=str(i))
        client.refresh("tcpidx")
        resp = n2.client().search("tcpidx", {"query": {"match": {"title": "hello"}}})
        assert resp["hits"]["total"] == 20
    finally:
        n2.close()
        n1.close()
