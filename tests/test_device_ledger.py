"""Device capacity ledger (ops/device_index + common/jaxenv) — ISSUE 13
tentpole (b).

Covers: the per-segment tier-bytes breakdown (consistent with
packed_resident_bytes), the pack/repack timing ledger (bounds, per-index
attribution, forget-on-delete), compile-event attribution by plan family
(jaxenv.compile_tag), the capacity report walk, the /_nodes/stats `device`
section + /{index}/_stats device stanza, and the per-index Prometheus
families' cardinality bound under index create/delete churn.
"""

from __future__ import annotations

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.ops.device_index import (
    PACK_LEDGER, PackLedger, capacity_report, ensure_blk_freqs,
    packed_for, packed_resident_bytes, packed_tier_bytes, segment_capacity)

from .harness import TestCluster

_SEG_SEQ = [0]


def _segment(tmp_path, n_docs=40):
    """One frozen segment via a throwaway engine (the _mk_engine idiom)."""
    _SEG_SEQ[0] += 1
    svc = MapperService(Settings.from_flat({}))
    eng = Engine(str(tmp_path / f"seg{_SEG_SEQ[0]}"), svc)
    for i in range(n_docs):
        eng.index("doc", str(i), {"body": f"alpha{i % 5} beta{i % 3}"})
    eng.refresh()
    seg = eng.acquire_searcher().segments[0]
    eng.close()
    return seg


class TestTierBytes:
    def test_tiers_sum_to_resident_postings_planes(self, tmp_path):
        seg = _segment(tmp_path)
        packed = packed_for(seg)
        tiers = packed_tier_bytes(packed)
        # postings tier == the resident planes packed_resident_bytes counts
        # (dense plane not faulted yet)
        assert tiers["postings"] == packed_resident_bytes(packed)
        assert tiers["dense_plane"] == 0
        ensure_blk_freqs(packed)
        tiers = packed_tier_bytes(packed)
        assert tiers["dense_plane"] > 0
        assert tiers["postings"] + tiers["dense_plane"] == \
            packed_resident_bytes(packed)
        # norms: live mask + per-field norm columns are accounted
        assert tiers["norms"] > 0

    def test_segment_capacity_row(self, tmp_path):
        seg = _segment(tmp_path)
        assert segment_capacity(_segment(tmp_path)) is None  # never packed
        packed = packed_for(seg)
        row = segment_capacity(seg)
        assert row is not None
        assert row["generation"] == seg.gen
        assert row["tf_layout"] == packed.tf_layout
        assert row["tiers"]["filter_masks"] == 0
        assert row["total_bytes"] == sum(row["tiers"].values())


class TestPackLedger:
    def test_record_and_stats(self):
        led = PackLedger()
        led.record("idx", 3, 1.5, 1024, "u8")
        led.record("idx", 4, 0.5, 2048, "u8", kind="remask")
        st = led.stats("idx")
        assert st["packs"] == 1 and st["remasks"] == 1
        assert st["pack_ms_total"] == 2.0
        assert [e["kind"] for e in st["recent"]] == ["pack", "remask"]
        assert led.stats("other") == {}

    def test_bounds(self):
        led = PackLedger()
        for i in range(PackLedger.MAX_INDICES + 10):
            led.record(f"i{i}", 1, 0.1, 10, "u8")
        assert len(led.stats()) == PackLedger.MAX_INDICES
        assert "i0" not in led.stats()  # LRU-evicted
        for _ in range(PackLedger.RING + 5):
            led.record("ring", 1, 0.1, 10, "u8")
        assert len(led.stats("ring")["recent"]) == PackLedger.RING

    def test_forget(self):
        led = PackLedger()
        led.record("gone", 1, 0.1, 10, "u8")
        led.forget("gone")
        assert led.stats("gone") == {}

    def test_packed_for_attributes_owner(self, tmp_path):
        seg = _segment(tmp_path)
        PACK_LEDGER.forget("owner-test")
        packed_for(seg, owner="owner-test")
        st = PACK_LEDGER.stats("owner-test")
        assert st["packs"] == 1
        assert st["recent"][0]["bytes"] > 0
        assert st["recent"][0]["tf_layout"] == "u8"
        PACK_LEDGER.forget("owner-test")


class TestCompileAttribution:
    def test_compile_tag_buckets_events(self):
        import jax
        import jax.numpy as jnp

        from elasticsearch_tpu.common.jaxenv import (
            compile_events_by_family, compile_tag)

        before = compile_events_by_family().get("aggs", 0)
        # a fresh jit with a process-unique shape guarantees one real compile
        n = 577  # odd prime-ish size no other test uses

        @jax.jit
        def f(x):
            return (x * 2.0).sum()

        with compile_tag("aggs"):
            f(jnp.zeros((n,), jnp.float32)).block_until_ready()
        after = compile_events_by_family().get("aggs", 0)
        assert after >= before + 1

    def test_unknown_tag_folds_to_untagged_and_outermost_wins(self):
        from elasticsearch_tpu.common import jaxenv

        with jaxenv.compile_tag("not-a-family"):
            assert jaxenv._tag_local.tag == "untagged"
        assert jaxenv._tag_local.tag is None
        # outermost scope wins: a percolation's inner sparse launch must
        # stay attributed to the workload that triggered it
        with jaxenv.compile_tag("percolate"):
            assert jaxenv._tag_local.tag == "percolate"
            with jaxenv.compile_tag("sparse"):
                assert jaxenv._tag_local.tag == "percolate"
            assert jaxenv._tag_local.tag == "percolate"
        assert jaxenv._tag_local.tag is None


# ---------------------------------------------------------------------------
# live cluster
# ---------------------------------------------------------------------------


def _boot(tmp_path, settings=None, indices=("led",)):
    cluster = TestCluster(n_nodes=1, data_root=tmp_path, seed=9,
                          settings=settings or {})
    cluster.start()
    c = cluster.client()
    for name in indices:
        c.create_index(name, {"settings": {"number_of_shards": 1,
                                           "number_of_replicas": 0}})
        cluster.ensure_green(name)
        for i in range(25):
            c.index(name, "doc", {"body": f"alpha{i % 4}", "n": i},
                    id=str(i))
        c.refresh(name)
    return cluster, c


@pytest.mark.insights
class TestLiveLedger:
    def test_capacity_report_and_stats_surfaces(self, tmp_path):
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            c.search("led", {"query": {"match": {"body": "alpha1"}},
                             "size": 3})
            report = capacity_report(node.indices)
            assert "led" in report["indices"]
            led = report["indices"]["led"]
            assert led["totals"]["postings"] > 0
            assert led["totals"]["sim_tables"] > 0
            assert led["pack"]["packs"] >= 1
            assert led["pack"]["recent"][0]["ms"] >= 0
            assert report["total_bytes"] >= led["total_bytes"]
            # per-segment rows carry the tier taxonomy
            (shard_rows,) = led["shards"].values()
            for row in shard_rows:
                assert set(row["tiers"]) == {
                    "postings", "dense_plane", "sim_tables", "agg_rows",
                    "norms", "filter_masks"}

            # /_nodes/stats device section (+ compile family rollup)
            st = c.nodes_stats(metric="device")
            (sections,) = st["nodes"].values()
            dev = sections["device"]
            assert dev["indices"]["led"]["totals"]["postings"] > 0
            assert "by_family" in dev["compile"]
            assert dev["compile"]["by_family"].get("sparse", 0) >= 1

            # /{index}/_stats device stanza (through the filtering Client)
            idx_stats = c.stats("led")
            assert set(idx_stats) == {"led"}
            assert idx_stats["led"]["device"]["totals"]["postings"] > 0
        finally:
            cluster.close()

    def test_filter_masks_tier_counts_resident_masks(self, tmp_path):
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            filt = {"query": {"filtered": {
                "query": {"match": {"body": "alpha1"}},
                "filter": {"term": {"n": 3}}}}, "size": 3}
            for _ in range(3):  # 2nd sighting promotes to device residency
                c.search("led", filt)
            assert node.filter_cache.stats()["masks"] >= 1
            report = capacity_report(node.indices)
            assert report["indices"]["led"]["totals"]["filter_masks"] > 0
        finally:
            cluster.close()

    def test_prometheus_cardinality_bounded_under_index_churn(self, tmp_path):
        """The satellite bound: create/delete of many indices keeps the
        per-index device-ledger families at their documented caps — labels
        exist only for LIVE indices, and the emission caps at
        telemetry.device.max_label_indices (overflow counted)."""
        from elasticsearch_tpu.rest.controller import _prometheus_text
        from tools.obs_smoke import _parse_prometheus

        names = tuple(f"churn{i}" for i in range(6))
        cluster, c = _boot(
            tmp_path, settings={"telemetry.device.max_label_indices": 3},
            indices=names)
        node = next(iter(cluster.nodes.values()))
        try:
            for name in names:
                c.search(name, {"query": {"match": {"body": "alpha1"}},
                                "size": 2})
            text = _prometheus_text(node)
            _parse_prometheus(text)

            def labels(fam):
                return {ln.split('index="', 1)[1].split('"', 1)[0]
                        for ln in text.splitlines()
                        if ln.startswith(fam + "{")}

            assert len(labels("estpu_device_index_bytes")) == 3
            assert len(labels("estpu_device_pack_total")) == 3
            assert "estpu_device_ledger_omitted_indices 3" in text

            # delete most indices: labels track the LIVE set, and the pack
            # ledger forgets the deleted ones
            for name in names[1:]:
                c.delete_index(name)
            text = _prometheus_text(node)
            _parse_prometheus(text)
            assert labels("estpu_device_index_bytes") == {names[0]}
            assert PACK_LEDGER.stats(names[1]) == {}
            assert "estpu_device_ledger_omitted_indices 0" in text
        finally:
            cluster.close()

    def test_remask_recorded_on_tombstone_refresh(self, tmp_path):
        cluster, c = _boot(tmp_path)
        try:
            c.search("led", {"query": {"match": {"body": "alpha1"}},
                             "size": 3})
            packs0 = PACK_LEDGER.stats("led").get("packs", 0)
            c.delete("led", "doc", "3")
            c.refresh("led")
            c.search("led", {"query": {"match": {"body": "alpha1"}},
                             "size": 3})
            st = PACK_LEDGER.stats("led")
            # the tombstone refresh either remasked the packed segment or a
            # new view repacked — either way the ledger saw the work
            assert st.get("remasks", 0) >= 1 or st.get("packs", 0) > packs0
        finally:
            cluster.close()


class TestTierMathProperties:
    def test_plane_bytes_agree_with_numpy(self, tmp_path):
        seg = _segment(tmp_path, 10)
        packed = packed_for(seg)
        tiers = packed_tier_bytes(packed)
        expect = sum(int(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
                     for p in (packed.blk_docs, packed.blk_tf, packed.blk_nb))
        assert tiers["postings"] == expect
