"""Concurrent scatter-gather: the query phase must fan to all shards at once.

The reference dispatches every shard's first phase asynchronously and reduces on
completion (TransportSearchTypeAction.java:135-216) — N-shard latency is max(shard),
not sum(shard). These tests inject a per-shard delay and assert wall-clock stays far
under the sequential sum, and that per-shard failover still works when dispatch is
concurrent.
"""

import time

import pytest

from elasticsearch_tpu.actions import A_QUERY_PHASE
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry

pytestmark = pytest.mark.mesh

SHARDS = 6
DELAY = 0.25


@pytest.fixture()
def node(tmp_path):
    registry = LocalTransportRegistry()
    n = Node(name="par0", registry=registry, data_path=str(tmp_path),
             settings={"index.number_of_shards": SHARDS,
                       "index.number_of_replicas": 0})
    n.start([n.local_node.transport_address])
    n.wait_for_master()
    yield n
    n.close()


def _slow_query_phase(node, delay=DELAY):
    """Re-register the query-phase handler with an injected per-shard delay.
    This test targets the TRANSPORT scatter-gather specifically — disable the mesh
    serving path, which would otherwise bypass A_QUERY_PHASE entirely (and put its
    first XLA compile inside the timed region)."""
    node.actions.mesh_serving.enabled = False
    original = node.transport.handlers[A_QUERY_PHASE].fn

    def slow(request, channel):
        time.sleep(delay)
        return original(request, channel)

    node.transport.register_handler(A_QUERY_PHASE, slow, executor="search")


def test_query_phase_is_concurrent(node):
    client = node.client()
    client.create_index("t", {"settings": {"index.number_of_shards": SHARDS,
                                           "index.number_of_replicas": 0}})
    for i in range(SHARDS * 3):
        client.index("t", "doc", {"body": f"term{i} common"}, id=str(i))
    client.refresh("t")

    _slow_query_phase(node)
    # warm the exact query once OUTSIDE the timed region: whether the device
    # program is already compiled depends on which tests ran earlier in the
    # process, and a cold first compile (~0.7s) dwarfs the concurrency margin
    client.search(["t"], {"query": {"match": {"body": "common"}}})
    t0 = time.monotonic()
    r = client.search(["t"], {"query": {"match": {"body": "common"}}})
    took = time.monotonic() - t0
    assert r["_shards"]["successful"] == SHARDS
    assert r["hits"]["total"] == SHARDS * 3
    # sequential would be >= SHARDS * DELAY (1.5 s); concurrent ≈ DELAY + overhead
    assert took < SHARDS * DELAY * 0.6, f"search took {took:.2f}s — looks sequential"


def test_failover_still_works_under_concurrent_dispatch(tmp_path):
    registry = LocalTransportRegistry()
    n1 = Node(name="fo1", registry=registry, data_path=str(tmp_path / "n1"),
              settings={"index.number_of_shards": 2,
                        "index.number_of_replicas": 1})
    n1.start([n1.local_node.transport_address])
    n1.wait_for_master()
    n2 = Node(name="fo2", registry=registry, data_path=str(tmp_path / "n2"))
    n2.start([n1.local_node.transport_address])
    n2.wait_for_master()
    client = n1.client()
    client.create_index("t", {"settings": {"index.number_of_shards": 2,
                                           "index.number_of_replicas": 1}})
    for i in range(8):
        client.index("t", "doc", {"body": "common"}, id=str(i))
    node_for = {n1.node_id: n1, n2.node_id: n2}

    # wait for replicas to go green so both copies hold data
    h = client.cluster_health("t", wait_for_status="green")
    assert h["status"] == "green"
    client.refresh("t")

    # make every query attempt against n2 fail: the coordinator must fail over to
    # the other copy concurrently and still return full results
    from elasticsearch_tpu.common.errors import SearchEngineError

    def broken(request, channel):
        raise SearchEngineError("injected shard failure")

    n2.transport.register_handler(A_QUERY_PHASE, broken, executor="search")
    for _ in range(6):  # preference rotation may or may not pick n2 first; try a few
        r = client.search(["t"], {"query": {"match": {"body": "common"}}})
        assert r["hits"]["total"] == 8
        assert r["_shards"]["successful"] == 2

    # a HUNG copy (accepts the request, never responds) must also fail over — the
    # per-attempt timer, not the error path, advances the chain
    def hung(request, channel):
        time.sleep(30)

    n2.transport.register_handler(A_QUERY_PHASE, hung, executor="search")
    old_timeout = type(n1.actions).QUERY_ATTEMPT_TIMEOUT
    type(n1.actions).QUERY_ATTEMPT_TIMEOUT = 0.3
    try:
        t0 = time.monotonic()
        r = client.search(["t"], {"query": {"match": {"body": "common"}}})
        took = time.monotonic() - t0
        assert r["hits"]["total"] == 8
        assert r["_shards"]["successful"] == 2
        assert took < 5.0
    finally:
        type(n1.actions).QUERY_ATTEMPT_TIMEOUT = old_timeout
    n2.close()
    n1.close()
