"""Device metric aggregations: differential tests vs the host collectors.

Eligible requests (metric aggs on numeric columns, no other mask consumers) are
served by ONE fused device program per segment — scoring + top-k + masked stat
reductions (ops/scoring.score_agg_batch over device_index.agg_doc_rows) — instead
of host-side mask materialization. Results must match the host collectors within
float32 kernel accumulation (double-typed columns round to 7 significant digits;
int/float columns are exact).

ref: search/aggregations/AggregationPhase.java + metrics collectors; SURVEY §5.7
"shard-level parallel reduce of aggregations".
"""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.search import ShardContext
from elasticsearch_tpu.search.aggregations import reduce_aggs
from elasticsearch_tpu.search.service import (
    _try_device_aggs,
    execute_query_phase,
    parse_search_body,
)
from elasticsearch_tpu.search.similarity import SimilarityService


@pytest.fixture(scope="module")
def ctx():
    tmp = tempfile.mkdtemp()
    settings = Settings.from_flat({"index.similarity.default.type": "BM25"})
    svc = MapperService(settings)
    eng = Engine(tmp, svc)
    rng = np.random.default_rng(17)
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    for i in range(400):
        d = {"body": " ".join(rng.choice(words, size=5)),
             "price": float(np.round(rng.uniform(1, 99), 2)),
             "label": words[i % 5]}
        if i % 3 == 0:
            d["tags_n"] = [int(x) for x in rng.integers(1, 10, size=3)]
        if i % 7 != 0:
            d["pop"] = int(rng.integers(1, 100))
        eng.index("doc", str(i), d)
        if i == 199:
            eng.refresh()  # second segment
    for i in (4, 44, 250):
        eng.delete("doc", str(i))
    eng.refresh()
    out = ShardContext(eng.acquire_searcher(), svc,
                       SimilarityService(settings, mapper_service=svc))
    yield out
    eng.close()


def _agg_equal(a, b, path=""):
    if isinstance(a, dict) and isinstance(b, dict):
        assert set(a) == set(b), (path, a, b)
        for k2 in a:
            _agg_equal(a[k2], b[k2], f"{path}.{k2}")
    elif isinstance(a, list) and isinstance(b, list):
        assert len(a) == len(b), (path, a, b)
        for i, (x, y) in enumerate(zip(a, b)):
            _agg_equal(x, y, f"{path}[{i}]")
    elif a is None or b is None:
        assert a is None and b is None, (path, a, b)
    elif isinstance(a, float) or isinstance(b, float):
        assert a == pytest.approx(b, rel=1e-5), (path, a, b)
    else:
        assert a == b, (path, a, b)


def _both(ctx, body):
    req = parse_search_body(body)
    dev = execute_query_phase(ctx, req, use_device=True)
    host = execute_query_phase(ctx, req, use_device=False)
    assert dev.total == host.total
    assert [(round(s, 5), d) for s, d, _ in dev.docs] == \
        [(round(s, 5), d) for s, d, _ in host.docs]
    dr = reduce_aggs(req.aggs, dev.agg_partials)
    hr = reduce_aggs(req.aggs, host.agg_partials)
    for name in dr:
        _agg_equal(dr[name], hr[name], name)
    return req


def test_all_metric_types_parity(ctx):
    req = _both(ctx, {
        "query": {"match": {"body": "alpha beta"}}, "size": 5,
        "aggs": {"p_avg": {"avg": {"field": "price"}},
                 "p_sum": {"sum": {"field": "price"}},
                 "p_stats": {"stats": {"field": "price"}},
                 "pop_min": {"min": {"field": "pop"}},
                 "pop_max": {"max": {"field": "pop"}},
                 "p_count": {"value_count": {"field": "price"}}}})
    # and the device path really served it
    assert _try_device_aggs(ctx, req, 5, None, 0) is not None


def test_multivalued_column_exact(ctx):
    # per-doc folds happen host-side, so multi-valued sums/counts are exact
    req = _both(ctx, {
        "query": {"match": {"body": "gamma"}}, "size": 3,
        "aggs": {"t_sum": {"sum": {"field": "tags_n"}},
                 "t_cnt": {"value_count": {"field": "tags_n"}},
                 "t_min": {"min": {"field": "tags_n"}},
                 "t_max": {"max": {"field": "tags_n"}}}})
    assert _try_device_aggs(ctx, req, 3, None, 0) is not None


def test_missing_column_docs(ctx):
    # `pop` is absent on every 7th doc: masked counts skip them on both paths
    _both(ctx, {
        "query": {"match": {"body": "delta epsilon"}}, "size": 3,
        "aggs": {"s": {"stats": {"field": "pop"}}}})


def test_no_matches_yields_empty_stats(ctx):
    req = _both(ctx, {
        "query": {"match": {"body": "zzzznope"}}, "size": 3,
        "aggs": {"s": {"stats": {"field": "price"}},
                 "m": {"min": {"field": "price"}}}})
    r = reduce_aggs(req.aggs, execute_query_phase(ctx, req).agg_partials)
    assert r["s"]["count"] == 0 and r["s"]["min"] is None
    assert r["m"]["value"] is None


@pytest.mark.parametrize("aggs", [
    {"x": {"extended_stats": {"field": "price"}}},  # variance: host-only
    {"x": {"avg": {"script": "doc['price'].value * 2"}}},  # script agg
    {"x": {"terms": {"field": "label"},
           "aggs": {"s": {"cardinality": {"field": "pop"}}}}},  # sketch sub-agg
    {"x": {"terms": {"field": "label"},
           "aggs": {"s": {"terms": {"field": "pop"}}}}},  # bucket sub-agg
    {"x": {"value_count": {"field": "label"}}},  # string column
    {"x": {"cardinality": {"field": "pop"}}},  # sketch agg
    {"x": {"percentiles": {"field": "pop"}}},  # sketch agg
])
def test_ineligible_aggs_fall_back(ctx, aggs):
    body = {"query": {"match": {"body": "alpha"}}, "size": 3, "aggs": aggs}
    req = parse_search_body(body)
    assert _try_device_aggs(ctx, req, 3, None, 0) is None
    # and the host path still serves them correctly end to end
    res = execute_query_phase(ctx, req, use_device=True)
    assert reduce_aggs(req.aggs, res.agg_partials)["x"] is not None


def test_terms_agg_parity(ctx):
    # terms on a string column AND on a numeric column, plus multi-valued docs
    # (duplicate values in one doc must count the doc ONCE)
    req = _both(ctx, {
        "query": {"match": {"body": "alpha"}}, "size": 0,
        "aggs": {"by_label": {"terms": {"field": "label", "size": 20}},
                 "by_pop": {"terms": {"field": "pop", "size": 50}},
                 "by_tag": {"terms": {"field": "tags_n", "size": 20}}}})
    assert _try_device_aggs(ctx, req, 1, None, 0) is not None


def test_histogram_parity(ctx):
    req = _both(ctx, {
        "query": {"match": {"body": "beta gamma"}}, "size": 0,
        "aggs": {"h": {"histogram": {"field": "price", "interval": 10}},
                 "hm": {"histogram": {"field": "tags_n", "interval": 2}}}})
    assert _try_device_aggs(ctx, req, 1, None, 0) is not None


def test_range_agg_parity(ctx):
    # overlapping + unbounded + keyed + empty ranges; zero-count buckets survive
    req = _both(ctx, {
        "query": {"match": {"body": "alpha"}}, "size": 0,
        "aggs": {"r": {"range": {"field": "price", "ranges": [
            {"to": 30}, {"from": 20, "to": 60}, {"from": 50},
            {"key": "none", "from": 4000, "to": 5000}]}},
                 "rm": {"range": {"field": "tags_n", "ranges": [
                     {"from": 1, "to": 5}, {"from": 5}]}}}})
    assert _try_device_aggs(ctx, req, 1, None, 0) is not None


def test_mixed_metric_and_bucket_aggs(ctx):
    req = _both(ctx, {
        "query": {"match": {"body": "delta"}}, "size": 3,
        "aggs": {"by_label": {"terms": {"field": "label"}},
                 "p_avg": {"avg": {"field": "price"}},
                 "h": {"histogram": {"field": "price", "interval": 25}}}})
    assert _try_device_aggs(ctx, req, 3, None, 0) is not None


def test_filtered_query_with_aggs(ctx):
    # the classic analytics shape: query + filter + aggs, fused in one launch
    req = _both(ctx, {
        "query": {"filtered": {"query": {"match": {"body": "alpha"}},
                               "filter": {"range": {"pop": {"gte": 50}}}}},
        "size": 3,
        "aggs": {"p_avg": {"avg": {"field": "price"}},
                 "by_label": {"terms": {"field": "label"}}}})
    assert _try_device_aggs(ctx, req, 3, None, 0) is not None


def test_filtered_query_device_topk(ctx):
    from elasticsearch_tpu.search.execute import lower_flat, search_shard
    from elasticsearch_tpu.search import parse_query

    qd = {"filtered": {"query": {"match": {"body": "beta gamma"}},
                       "filter": {"term": {"label": "L3"}}, "boost": 1.5}}
    q = parse_query(qd)
    plan = lower_flat(q, ctx)
    assert plan is not None and plan.filt is not None
    dev = search_shard(ctx, q, 10, use_device=True)
    host = search_shard(ctx, q, 10, use_device=False)
    assert dev.total == host.total and dev.hits == host.hits


def test_date_histogram_parity():
    import tempfile

    svc = MapperService(Settings.from_flat({}))
    eng = Engine(tempfile.mkdtemp(), svc)
    for i in range(90):
        eng.index("doc", str(i), {"body": "alpha",
                                  "ts": f"2014-{(i % 3) + 1:02d}-{(i % 27) + 1:02d}"})
    eng.refresh()
    c = ShardContext(eng.acquire_searcher(), svc,
                     SimilarityService(Settings.from_flat({}), mapper_service=svc))
    req = _both(c, {"query": {"match": {"body": "alpha"}}, "size": 0,
                    "aggs": {"d": {"date_histogram": {"field": "ts",
                                                      "interval": "month"}}}})
    assert _try_device_aggs(c, req, 1, None, 0) is not None
    eng.close()


def test_trailing_valueless_docs_dont_truncate_minmax():
    # regression: reduceat index clipping truncated the PREVIOUS doc's value run
    # when trailing docs lacked the field — max([1, 9]) came back as 1
    import tempfile

    from elasticsearch_tpu.ops.device_index import agg_doc_rows

    svc = MapperService(Settings.from_flat({}))
    eng = Engine(tempfile.mkdtemp(), svc)
    eng.index("doc", "0", {"body": "alpha", "v": [1, 9]})
    eng.index("doc", "1", {"body": "alpha"})  # no v — trailing value-less doc
    eng.refresh()
    seg = eng.acquire_searcher().segments[0]
    rows = agg_doc_rows(seg, "v")
    assert rows[3][0] == 9.0 and rows[2][0] == 1.0
    ctx2 = ShardContext(eng.acquire_searcher(), svc,
                        SimilarityService(Settings.from_flat({}), mapper_service=svc))
    _ = ctx2
    req = parse_search_body({"query": {"match": {"body": "alpha"}},
                             "aggs": {"m": {"max": {"field": "v"}}}})
    res = execute_query_phase(ctx2, req, use_device=True)
    assert reduce_aggs(req.aggs, res.agg_partials)["m"]["value"] == 9.0
    eng.close()


def test_f32_inexact_column_falls_back_to_host():
    # values past 2^24 (longs/dates) are not float32-exact: the device path must
    # refuse and the host collectors serve the exact numbers
    import tempfile

    from elasticsearch_tpu.search.service import _try_device_aggs as try_dev

    svc = MapperService(Settings.from_flat({}))
    eng = Engine(tempfile.mkdtemp(), svc)
    big = 1_700_000_000_123  # epoch-millis-sized long
    for i in range(5):
        eng.index("doc", str(i), {"body": "alpha", "ts_l": big + i})
    eng.refresh()
    c = ShardContext(eng.acquire_searcher(), svc,
                     SimilarityService(Settings.from_flat({}), mapper_service=svc))
    req = parse_search_body({"query": {"match": {"body": "alpha"}},
                             "aggs": {"m": {"max": {"field": "ts_l"}}}})
    assert try_dev(c, req, 3, None, 0) is None  # refused at row build
    res = execute_query_phase(c, req, use_device=True)
    assert reduce_aggs(req.aggs, res.agg_partials)["m"]["value"] == big + 4  # exact
    eng.close()


def test_unlowerable_query_falls_back(ctx):
    req = parse_search_body({
        "query": {"match_all": {}},
        "aggs": {"a": {"avg": {"field": "price"}}}})
    assert _try_device_aggs(ctx, req, 3, None, 0) is None
    # host path agrees with itself (sanity that fallback serves)
    res = execute_query_phase(ctx, req, use_device=True)
    assert reduce_aggs(req.aggs, res.agg_partials)["a"]["value"] is not None


def test_date_math_range_bounds_stay_host(ctx):
    # "now"-relative bounds re-resolve per query on the host; the device pair
    # cache is per segment generation, so such specs must refuse the device
    from elasticsearch_tpu.search.aggregations import device_bucket_eligible, parse_aggs

    aggs = parse_aggs({"r": {"date_range": {"field": "pop", "ranges": [
        {"from": "now-1h"}]}}})
    assert not device_bucket_eligible(aggs["r"])
    aggs2 = parse_aggs({"r": {"range": {"field": "pop", "ranges": [
        {"from": 10, "to": 20}]}}})
    assert device_bucket_eligible(aggs2["r"])


def test_mask_shaped_bucket_aggs_parity(ctx):
    # filter / filters / missing ride the device scatter with host-built masks
    req = _both(ctx, {
        "query": {"match": {"body": "alpha"}}, "size": 0,
        "aggs": {"f": {"filter": {"range": {"pop": {"gte": 50}}}},
                 "fs": {"filters": {"filters": {
                     "cheap": {"range": {"price": {"lte": 30}}},
                     "tagged": {"exists": {"field": "tags_n"}}}}},
                 "no_pop": {"missing": {"field": "pop"}}}})
    assert _try_device_aggs(ctx, req, 1, None, 0) is not None


def test_mask_bucket_with_date_math_stays_host(ctx):
    from elasticsearch_tpu.search.aggregations import device_bucket_eligible, parse_aggs

    aggs = parse_aggs({"f": {"filter": {"range": {"pop": {"gte": "now-1h"}}}}})
    assert not device_bucket_eligible(aggs["f"])


def test_geo_bucket_aggs_parity():
    import tempfile

    svc = MapperService(Settings.from_flat({}))
    svc.put_mapping("doc", {"properties": {"loc": {"type": "geo_point"}}})
    eng = Engine(tempfile.mkdtemp(), svc)
    rng = np.random.default_rng(9)
    for i in range(150):
        eng.index("doc", str(i), {
            "body": "alpha" if i % 2 else "alpha beta",
            "loc": {"lat": float(rng.uniform(40, 60)),
                    "lon": float(rng.uniform(-5, 25))}})
    eng.refresh()
    c = ShardContext(eng.acquire_searcher(), svc,
                     SimilarityService(Settings.from_flat({}), mapper_service=svc))
    req = _both(c, {
        "query": {"match": {"body": "alpha"}}, "size": 0,
        "aggs": {"d": {"geo_distance": {"field": "loc",
                                        "origin": {"lat": 50, "lon": 10},
                                        "unit": "km",
                                        "ranges": [{"to": 300},
                                                   {"from": 300, "to": 900},
                                                   {"from": 900}]}},
                 "g": {"geohash_grid": {"field": "loc", "precision": 2}}}})
    assert _try_device_aggs(c, req, 1, None, 0) is not None
    eng.close()


def test_significant_terms_parity(ctx):
    req = _both(ctx, {
        "query": {"match": {"body": "alpha beta"}}, "size": 0,
        "aggs": {"sig": {"significant_terms": {"field": "label", "size": 10}}}})
    assert _try_device_aggs(ctx, req, 1, None, 0) is not None
    # bg_count present in the reduced output
    r = reduce_aggs(req.aggs, execute_query_phase(ctx, req).agg_partials)
    assert all("bg_count" in b and b["bg_count"] >= b["doc_count"] >= 1
               for b in r["sig"]["buckets"])


def test_metric_sub_aggs_under_buckets_parity(ctx):
    # the canonical analytics tree: buckets with metric sub-aggs, all in-kernel
    req = _both(ctx, {
        "query": {"match": {"body": "alpha beta"}}, "size": 0,
        "aggs": {
            "by_label": {"terms": {"field": "label", "size": 20},
                         "aggs": {"p_avg": {"avg": {"field": "price"}},
                                  "p_stats": {"stats": {"field": "price"}},
                                  "pop_max": {"max": {"field": "pop"}}}},
            "by_range": {"range": {"field": "price",
                                   "ranges": [{"to": 40}, {"from": 40}]},
                         "aggs": {"t_sum": {"sum": {"field": "tags_n"}}}},
            "no_pop": {"missing": {"field": "pop"},
                       "aggs": {"p_min": {"min": {"field": "price"}}}},
        }})
    assert _try_device_aggs(ctx, req, 1, None, 0) is not None


def test_sub_agg_empty_buckets_parity(ctx):
    # zero-count range buckets must carry the same empty sub partials as host
    _both(ctx, {
        "query": {"match": {"body": "gamma"}}, "size": 0,
        "aggs": {"r": {"range": {"field": "price",
                                 "ranges": [{"from": 5000, "to": 6000}]},
                       "aggs": {"a": {"avg": {"field": "pop"}},
                                "m": {"min": {"field": "pop"}}}}}})


def test_sub_agg_multivalued_exact(ctx):
    # multi-valued sub-agg sums within buckets stay exact (per-doc host folds)
    _both(ctx, {
        "query": {"match": {"body": "delta"}}, "size": 0,
        "aggs": {"by_label": {"terms": {"field": "label"},
                              "aggs": {"t": {"sum": {"field": "tags_n"}},
                                       "tc": {"value_count": {"field": "tags_n"}}}}}})


def test_post_filter_device_parity(ctx):
    # hits post-filtered, aggs over the FULL match set — the faceting idiom
    req = _both(ctx, {
        "query": {"match": {"body": "alpha"}}, "size": 10,
        "post_filter": {"range": {"pop": {"gte": 50}}},
        "aggs": {"by_label": {"terms": {"field": "label"}},
                 "p_avg": {"avg": {"field": "price"}}}})
    # total reflects the post filter; aggs don't
    full = execute_query_phase(ctx, parse_search_body(
        {"query": {"match": {"body": "alpha"}}, "size": 0}))
    res = execute_query_phase(ctx, req)
    assert res.total < full.total
    dr = reduce_aggs(req.aggs, res.agg_partials)
    assert sum(b["doc_count"] for b in dr["by_label"]["buckets"]) == full.total


def test_post_filter_with_filtered_query(ctx):
    _both(ctx, {
        "query": {"filtered": {"query": {"match": {"body": "beta"}},
                               "filter": {"range": {"price": {"lte": 70}}}}},
        "size": 8,
        "post_filter": {"term": {"label": "gamma"}},
        "aggs": {"s": {"stats": {"field": "pop"}}}})


def test_min_score_device_parity(ctx):
    body = {"query": {"match": {"body": "alpha beta"}}, "size": 10,
            "min_score": 0.8}
    req = parse_search_body(body)
    dev = execute_query_phase(ctx, req, use_device=True)
    host = execute_query_phase(ctx, req, use_device=False)
    assert dev.total == host.total and dev.total > 0
    assert [(round(s, 5), d) for s, d, _ in dev.docs] == \
        [(round(s, 5), d) for s, d, _ in host.docs]
    loose = execute_query_phase(ctx, parse_search_body(
        {"query": {"match": {"body": "alpha beta"}}, "size": 0}))
    assert dev.total < loose.total  # the threshold really trims


def test_batched_device_percolation_parity():
    # many registered queries percolate as ONE kernel batch; results must match
    # the pure host loop exactly
    from elasticsearch_tpu.mapper.core import MapperService
    from elasticsearch_tpu.percolator import PercolatorRegistry
    from elasticsearch_tpu.search.service import SERVING_COUNTERS

    svc = MapperService(Settings.from_flat({}))
    reg = PercolatorRegistry()
    rng = np.random.default_rng(13)
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    for i in range(200):
        kind = i % 4
        if kind == 0:
            q = {"match": {"body": str(rng.choice(words))}}
        elif kind == 1:
            q = {"bool": {"must": [{"term": {"body": str(rng.choice(words))}}],
                          "must_not": [{"term": {"body": str(rng.choice(words))}}]}}
        elif kind == 2:
            q = {"term": {"body": str(rng.choice(words))}}
        else:  # not flat-lowerable → host within the same percolation
            q = {"match_phrase": {"body": f"{rng.choice(words)} {rng.choice(words)}"}}
        reg.register(f"q{i}", {"query": q})
    assert reg.count() >= reg.DEVICE_BATCH_MIN

    doc = {"body": "alpha beta gamma"}
    before = SERVING_COUNTERS["device_percolate"]
    batched = reg.percolate(doc, svc)
    # the device batch really ran (the wholesale fallback would otherwise make
    # this test compare host against host)
    assert SERVING_COUNTERS["device_percolate"] == before + 1
    assert SERVING_COUNTERS["device_percolate_fallbacks"] == 0
    # force the pure host loop by lowering the gate
    orig = PercolatorRegistry.DEVICE_BATCH_MIN
    PercolatorRegistry.DEVICE_BATCH_MIN = 10**9
    try:
        host = reg.percolate(doc, svc)
    finally:
        PercolatorRegistry.DEVICE_BATCH_MIN = orig
    assert batched == host and len(batched) > 0


def test_device_failure_falls_back_to_host(ctx, monkeypatch):
    # a broken device backend (dead TPU tunnel, OOM, plugin init) must degrade
    # to the host scorer, visibly (device_errors counter), never fail searches
    import elasticsearch_tpu.search.service as svc_mod
    from elasticsearch_tpu.search.service import SERVING_COUNTERS

    def boom(*a, **k):
        raise RuntimeError("device backend unavailable")

    monkeypatch.setattr(svc_mod, "execute_flat_batch", boom)
    monkeypatch.setattr(svc_mod, "_try_device_aggs", boom)
    monkeypatch.setattr(svc_mod, "_try_device_sort", boom)
    before = SERVING_COUNTERS["device_errors"]
    for body in (
        {"query": {"match": {"body": "alpha"}}, "size": 5},
        {"query": {"match": {"body": "alpha"}}, "size": 0,
         "aggs": {"m": {"max": {"field": "pop"}}}},
        {"query": {"match": {"body": "alpha"}}, "sort": [{"pop": "asc"}],
         "size": 5},
    ):
        req = parse_search_body(body)
        res = execute_query_phase(ctx, req, use_device=True)
        host = execute_query_phase(ctx, req, use_device=False)
        assert res.total == host.total
    assert SERVING_COUNTERS["device_errors"] >= before + 3
