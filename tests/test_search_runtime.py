"""Search runtime: aggregations, facets, sort, fetch/highlight, suggest, rescore, scroll."""

import math

import numpy as np
import pytest

from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext
from elasticsearch_tpu.search.service import (
    SearchService,
    execute_query_phase,
    parse_search_body,
    reduce_and_respond,
)

PRODUCTS = [
    {"name": "red widget deluxe", "category": "widgets", "price": 10, "stock": 5,
     "created": "2014-01-15", "loc": {"lat": 40.7, "lon": -74.0}},
    {"name": "blue widget", "category": "widgets", "price": 20, "stock": 0,
     "created": "2014-01-20", "loc": {"lat": 40.8, "lon": -73.9}},
    {"name": "green gadget", "category": "gadgets", "price": 30, "stock": 7,
     "created": "2014-02-05", "loc": {"lat": 34.0, "lon": -118.2}},
    {"name": "red gadget pro", "category": "gadgets", "price": 40, "stock": 2,
     "created": "2014-02-10", "loc": {"lat": 37.7, "lon": -122.4}},
    {"name": "yellow gizmo", "category": "gizmos", "price": 50, "stock": 1,
     "created": "2014-03-01", "loc": {"lat": 41.8, "lon": -87.6}},
    {"name": "red gizmo mini widget", "category": "gizmos", "price": 60, "stock": 9,
     "created": "2014-03-15", "loc": {"lat": 29.7, "lon": -95.3}},
]


@pytest.fixture()
def ctx(tmp_path):
    svc = MapperService()
    svc.put_mapping("product", {"properties": {
        "name": {"type": "string"},
        "category": {"type": "string", "index": "not_analyzed"},
        "price": {"type": "long"},
        "stock": {"type": "long"},
        "created": {"type": "date"},
        "loc": {"type": "geo_point"},
    }})
    e = Engine(str(tmp_path / "products"), svc)
    for i, p in enumerate(PRODUCTS):
        e.index("product", str(i), p)
        if i == 2:
            e.refresh()  # two segments
    e.refresh()
    return ShardContext(e.acquire_searcher(), svc)


def run(ctx, body):
    req = parse_search_body(body)
    result = execute_query_phase(ctx, req)
    return reduce_and_respond(ctx, req, result)


class TestAggregations:
    def test_metrics(self, ctx):
        r = run(ctx, {"size": 0, "aggs": {
            "avg_price": {"avg": {"field": "price"}},
            "sum_price": {"sum": {"field": "price"}},
            "minmax": {"stats": {"field": "price"}},
            "ext": {"extended_stats": {"field": "price"}},
            "n": {"value_count": {"field": "price"}},
            "card": {"cardinality": {"field": "category"}},
            "pct": {"percentiles": {"field": "price", "percents": [50]}},
        }})
        a = r["aggregations"]
        assert a["avg_price"]["value"] == pytest.approx(35.0)
        assert a["sum_price"]["value"] == 210.0
        assert a["minmax"] == {"count": 6, "sum": 210.0, "min": 10.0, "max": 60.0,
                               "avg": 35.0}
        assert a["ext"]["std_deviation"] == pytest.approx(math.sqrt(np.var([10, 20, 30, 40, 50, 60])))
        assert a["n"]["value"] == 6
        assert a["card"]["value"] == 3
        assert a["pct"]["values"]["50.0"] == pytest.approx(35.0)

    def test_terms_with_subagg_and_order(self, ctx):
        r = run(ctx, {"size": 0, "aggs": {
            "cats": {"terms": {"field": "category", "order": {"avg_price": "desc"}},
                     "aggs": {"avg_price": {"avg": {"field": "price"}}}},
        }})
        buckets = r["aggregations"]["cats"]["buckets"]
        assert [b["key"] for b in buckets] == ["gizmos", "gadgets", "widgets"]
        assert buckets[0]["avg_price"]["value"] == pytest.approx(55.0)
        assert buckets[0]["doc_count"] == 2

    def test_terms_agg_respects_query(self, ctx):
        r = run(ctx, {"query": {"match": {"name": "red"}}, "size": 0, "aggs": {
            "cats": {"terms": {"field": "category"}}}})
        buckets = {b["key"]: b["doc_count"] for b in r["aggregations"]["cats"]["buckets"]}
        assert buckets == {"widgets": 1, "gadgets": 1, "gizmos": 1}

    def test_range_histogram_date_histogram(self, ctx):
        r = run(ctx, {"size": 0, "aggs": {
            "ranges": {"range": {"field": "price", "ranges": [
                {"to": 25}, {"from": 25, "to": 45}, {"from": 45}]}},
            "hist": {"histogram": {"field": "price", "interval": 20}},
            "by_month": {"date_histogram": {"field": "created", "interval": "month"}},
        }})
        a = r["aggregations"]
        assert [b["doc_count"] for b in a["ranges"]["buckets"]] == [2, 2, 2]
        hist = {b["key"]: b["doc_count"] for b in a["hist"]["buckets"]}
        assert hist == {0.0: 1, 20.0: 2, 40.0: 2, 60.0: 1}
        months = [b["key_as_string"][:7] for b in a["by_month"]["buckets"]]
        assert months == ["2014-01", "2014-02", "2014-03"]
        assert [b["doc_count"] for b in a["by_month"]["buckets"]] == [2, 2, 2]

    def test_filter_global_missing(self, ctx):
        r = run(ctx, {"query": {"term": {"category": "widgets"}}, "size": 0, "aggs": {
            "expensive": {"filter": {"range": {"price": {"gte": 15}}}},
            "all_docs": {"global": {}, "aggs": {"n": {"value_count": {"field": "price"}}}},
        }})
        a = r["aggregations"]
        assert a["expensive"]["doc_count"] == 1  # only blue widget among widgets
        assert a["all_docs"]["doc_count"] == 6  # global escapes the query
        assert a["all_docs"]["n"]["value"] == 6

    def test_filters_agg(self, ctx):
        r = run(ctx, {"size": 0, "aggs": {"groups": {"filters": {"filters": {
            "cheap": {"range": {"price": {"lt": 30}}},
            "red": {"query": {"match": {"name": "red"}}},
        }}}}})
        b = r["aggregations"]["groups"]["buckets"]
        assert b["cheap"]["doc_count"] == 2
        assert b["red"]["doc_count"] == 3

    def test_geo_distance_agg(self, ctx):
        r = run(ctx, {"size": 0, "aggs": {"near_nyc": {"geo_distance": {
            "field": "loc", "origin": {"lat": 40.7, "lon": -74.0}, "unit": "km",
            "ranges": [{"to": 100}, {"from": 100}]}}}})  # noqa: E501
        buckets = r["aggregations"]["near_nyc"]["buckets"]
        assert buckets[0]["doc_count"] == 2  # the two NYC-ish widgets
        assert buckets[1]["doc_count"] == 4

    def test_top_hits(self, ctx):
        r = run(ctx, {"size": 0, "aggs": {
            "cats": {"terms": {"field": "category", "order": {"_term": "asc"}},
                     "aggs": {"top": {"top_hits": {"size": 1}}}}}})
        buckets = r["aggregations"]["cats"]["buckets"]
        assert buckets[0]["key"] == "gadgets"
        assert len(buckets[0]["top"]["hits"]["hits"]) == 1

    def test_facets_legacy_api(self, ctx):
        r = run(ctx, {"size": 0, "facets": {
            "cats": {"terms": {"field": "category"}},
            "price_stats": {"statistical": {"field": "price"}},
        }})
        f = r["facets"]
        assert f["cats"]["_type"] == "terms"
        assert {t["term"]: t["count"] for t in f["cats"]["terms"]} == {
            "widgets": 2, "gadgets": 2, "gizmos": 2}
        assert f["price_stats"]["avg"] == pytest.approx(35.0)


class TestSort:
    def test_field_sort_asc_desc(self, ctx):
        r = run(ctx, {"sort": [{"price": "desc"}], "size": 3})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["5", "4", "3"]
        assert r["hits"]["hits"][0]["sort"] == [60.0]
        r = run(ctx, {"sort": [{"price": "asc"}], "size": 2})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["0", "1"]

    def test_sort_with_score_tiebreak(self, ctx):
        r = run(ctx, {"query": {"match": {"name": "red"}},
                      "sort": [{"category": "asc"}, "_score"], "size": 10})
        ids = [h["_id"] for h in r["hits"]["hits"]]
        assert ids[0] == "3"  # gadgets first alphabetically

    def test_geo_distance_sort(self, ctx):
        r = run(ctx, {"sort": [{"_geo_distance": {
            "loc": {"lat": 40.7, "lon": -74.0}, "order": "asc", "unit": "km"}}],
            "size": 3})
        assert [h["_id"] for h in r["hits"]["hits"]][:2] == ["0", "1"]

    def test_from_pagination(self, ctx):
        r = run(ctx, {"sort": [{"price": "asc"}], "from": 2, "size": 2})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["2", "3"]
        assert r["hits"]["total"] == 6


class TestFetch:
    def test_source_filtering(self, ctx):
        r = run(ctx, {"query": {"ids": {"values": ["0"]}},
                      "_source": {"includes": ["name", "price"]}})
        src = r["hits"]["hits"][0]["_source"]
        assert set(src) == {"name", "price"}
        r = run(ctx, {"query": {"ids": {"values": ["0"]}}, "_source": False})
        assert "_source" not in r["hits"]["hits"][0]

    def test_fields_and_version(self, ctx):
        r = run(ctx, {"query": {"ids": {"values": ["2"]}},
                      "fields": ["category", "price"], "version": True})
        h = r["hits"]["hits"][0]
        assert h["fields"] == {"category": ["gadgets"], "price": [30]}
        assert h["_version"] == 1

    def test_script_fields(self, ctx):
        r = run(ctx, {"query": {"ids": {"values": ["1"]}}, "script_fields": {
            "double_price": {"script": "doc['price'].value * 2"}}})
        assert r["hits"]["hits"][0]["fields"]["double_price"] == [40.0]

    def test_highlight(self, ctx):
        r = run(ctx, {"query": {"match": {"name": "red"}},
                      "highlight": {"fields": {"name": {}}}})
        for h in r["hits"]["hits"]:
            assert "<em>red</em>" in h["highlight"]["name"][0]

    def test_post_filter_does_not_affect_aggs(self, ctx):
        r = run(ctx, {"query": {"match_all": {}},
                      "filter": {"term": {"category": "widgets"}},
                      "aggs": {"cats": {"terms": {"field": "category"}}}})
        assert r["hits"]["total"] == 2  # post filter applied to hits
        assert len(r["aggregations"]["cats"]["buckets"]) == 3  # but not to aggs

    def test_min_score(self, ctx):
        r_all = run(ctx, {"query": {"match": {"name": "red widget"}}, "size": 10})
        scores = [h["_score"] for h in r_all["hits"]["hits"]]
        cutoff = sorted(scores)[len(scores) // 2]
        r = run(ctx, {"query": {"match": {"name": "red widget"}}, "min_score": cutoff,
                      "size": 10})
        assert all(h["_score"] >= cutoff for h in r["hits"]["hits"])
        assert r["hits"]["total"] == sum(1 for s in scores if s >= cutoff)


class TestRescore:
    def test_rescore_total(self, ctx):
        base = run(ctx, {"query": {"match": {"name": "red"}}, "size": 10})
        r = run(ctx, {"query": {"match": {"name": "red"}}, "size": 10, "rescore": {
            "window_size": 10,
            "query": {"rescore_query": {"match": {"name": "widget"}},
                      "query_weight": 1.0, "rescore_query_weight": 100.0},
        }})
        # docs matching "widget" must jump ahead
        top = r["hits"]["hits"][0]
        assert "widget" in top["_source"]["name"]
        assert top["_score"] > base["hits"]["hits"][0]["_score"]


class TestSuggest:
    def test_term_suggester(self, ctx):
        r = run(ctx, {"size": 0, "suggest": {
            "fix": {"text": "widgit", "term": {"field": "name"}}}})
        opts = r["suggest"]["fix"][0]["options"]
        assert opts and opts[0]["text"] == "widget"

    def test_phrase_suggester(self, ctx):
        r = run(ctx, {"size": 0, "suggest": {
            "fix": {"text": "red widgit", "phrase": {"field": "name"}}}})
        texts = [o["text"] for o in r["suggest"]["fix"][0]["options"]]
        assert "red widget" in texts


class TestScroll:
    def test_scroll_pages_through_everything(self, ctx):
        svc = SearchService()
        req = parse_search_body({"query": {"match_all": {}}, "size": 2,
                                 "sort": [{"price": "asc"}]})
        cid, first = svc.create_scroll(ctx, req)
        seen = [d[1] for d in first.docs]
        done = False
        while not done:
            page, done = svc.scroll(cid)
            seen.extend(d[1] for d in page.docs)
        assert len(seen) == 6 and len(set(seen)) == 6
        assert svc.free(cid)
        with pytest.raises(Exception):
            svc.scroll(cid)
