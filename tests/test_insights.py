"""Always-on query-shape insights (common/insights.py) — ISSUE 13 tentpole (a).

Covers: shape normalization (literal erasure, structural preservation,
volatile-key stripping, clause-count bucketing), the bounded LRU registry
(demotion past max_shapes with honest fold-into-other), the thread-local
observation handoff, live classification of EVERY search with outcome mix /
cache attribution / batcher queue+device phases, the REST + nodes-stats
surfaces, the slowlog shape join + runtime cluster-settings thresholds, the
fuzzed Prometheus label-cardinality bound, and the hot-path invariant: a
warmed serving loop with insights + ledger + watchdog all armed adds zero
compiles, zero device pulls, and zero syncs over the disabled baseline under
hard transfer_guard("disallow").
"""

from __future__ import annotations

import logging
import threading
import time

import pytest

from elasticsearch_tpu.common.insights import (
    Observation, QueryShapeInsights, activate, current, normalize_shape,
    shape_fingerprint)
from elasticsearch_tpu.common.settings import Settings

from .harness import TestCluster


# ---------------------------------------------------------------------------
# shape normalization
# ---------------------------------------------------------------------------


class TestShapeNormalization:
    def test_literals_erased_structure_kept(self):
        a, _ = shape_fingerprint({"query": {"match": {"body": "alpha"}}})
        b, _ = shape_fingerprint({"query": {"match": {"body": "zebra zw"}}})
        c, _ = shape_fingerprint({"query": {"term": {"body": "alpha"}}})
        assert a == b
        assert a != c

    def test_field_names_are_structural(self):
        a, _ = shape_fingerprint({"query": {"match": {"body": "x"}}})
        b, _ = shape_fingerprint({"query": {"match": {"title": "x"}}})
        assert a != b

    def test_key_order_and_volatile_knobs_do_not_matter(self):
        a, _ = shape_fingerprint({"size": 5, "query": {"match": {"b": "x"}}})
        b, _ = shape_fingerprint({"query": {"match": {"b": "y"}}, "size": 5,
                                  "timeout": "100ms", "profile": True,
                                  "request_cache": False, "trace": True})
        assert a == b

    def test_size_zero_is_a_distinct_shape(self):
        q = {"query": {"match": {"b": "x"}}}
        a, _ = shape_fingerprint({**q, "size": 0})
        b, _ = shape_fingerprint({**q, "size": 10})
        c, _ = shape_fingerprint({**q, "size": 3})
        assert a != b
        assert b == c  # both paged; the literal page size is erased

    def test_clause_lists_bucket_by_pow2(self):
        def body(n):
            return {"query": {"bool": {"should": [
                {"term": {"b": f"t{i}"}} for i in range(n)]}}}

        s5, _ = shape_fingerprint(body(5))
        s7, _ = shape_fingerprint(body(7))
        s2, _ = shape_fingerprint(body(2))
        s40, _ = shape_fingerprint(body(40))
        assert s5 == s7  # both bucket to x8
        assert s2 != s40

    def test_list_valued_structural_keys_survive(self):
        """multi_match over different field SETS must be different shapes —
        list elements inherit the parent key's structural status."""
        a, _ = shape_fingerprint({"query": {"multi_match": {
            "query": "x", "fields": ["title", "body"]}}})
        b, _ = shape_fingerprint({"query": {"multi_match": {
            "query": "y", "fields": ["tag", "other"]}}})
        c, _ = shape_fingerprint({"query": {"multi_match": {
            "query": "z", "fields": ["title", "body"]}}})
        assert a != b
        assert a == c  # the query literal still erases

    def test_structural_values_survive(self):
        shape = normalize_shape({"sort": [{"n": {"order": "desc"}}],
                                 "query": {"match": {"b": "x"}}})
        assert "desc" in str(shape)
        a, _ = shape_fingerprint({"sort": [{"n": {"order": "desc"}}]})
        b, _ = shape_fingerprint({"sort": [{"n": {"order": "asc"}}]})
        assert a != b


# ---------------------------------------------------------------------------
# bounded registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def _reg(self, max_shapes=4):
        return QueryShapeInsights(Settings.from_flat(
            {"search.insights.max_shapes": max_shapes}))

    def test_record_accumulates(self):
        reg = self._reg()
        sid, shape = reg.fingerprint({"query": {"match": {"b": "x"}}})
        obs = Observation()
        obs.outcome = "device_sparse"
        obs.queue_s = 0.001
        obs.device_s = 0.002
        obs.occupancy = 3
        reg.record(sid, shape, 0.01, obs, cache="miss")
        reg.record(sid, shape, cache="hit")
        (entry,) = reg.top(5)
        assert entry["count"] == 2
        assert entry["cache"] == {"hits": 1, "misses": 1, "hit_rate": 0.5}
        assert entry["outcomes"] == {"device_sparse": 1, "cache_hit": 1}
        assert entry["coalesced"] == 1
        assert entry["latency"]["count"] == 1
        assert entry["queue"]["count"] == 1
        assert entry["device"]["count"] == 1

    def test_lru_demotion_is_bounded_and_honest(self):
        reg = self._reg(max_shapes=4)
        for i in range(10):
            sid, shape = reg.fingerprint(
                {"query": {"match": {f"f{i}": "x"}}})
            reg.record(sid, shape, 0.01)
        st = reg.stats()
        assert st["shapes"] == 4
        assert st["demotions"] == 6
        assert st["other"]["count"] == 6
        assert st["other"]["cost_ms"] > 0
        assert len(reg.prom_series()) == 4

    def test_resighting_moves_to_end(self):
        reg = self._reg(max_shapes=2)
        ids = []
        for i in range(2):
            sid, shape = reg.fingerprint({"query": {"match": {f"f{i}": "x"}}})
            ids.append(sid)
            reg.record(sid, shape, 0.01)
        # touch the oldest, then insert a third: the UNtouched one demotes
        sid0, shape0 = reg.fingerprint({"query": {"match": {"f0": "x"}}})
        reg.record(sid0, shape0, 0.01)
        sid2, shape2 = reg.fingerprint({"query": {"match": {"f2": "x"}}})
        reg.record(sid2, shape2, 0.01)
        resident = {sid for sid, _ in reg.prom_series()}
        assert ids[0] in resident and sid2 in resident
        assert ids[1] not in resident

    def test_unknown_outcome_folds_to_unknown(self):
        reg = self._reg()
        sid, shape = reg.fingerprint({})
        obs = Observation()
        obs.outcome = "weird_new_path"
        reg.record(sid, shape, 0.01, obs)
        (entry,) = reg.top(1)
        assert entry["outcomes"] == {"unknown": 1}

    def test_observation_thread_local(self):
        assert current() is None
        obs = Observation()
        with activate(obs):
            assert current() is obs
            seen = []
            t = threading.Thread(target=lambda: seen.append(current()))
            t.start()
            t.join()
            assert seen == [None]  # thread-local, not global
        assert current() is None


# ---------------------------------------------------------------------------
# live cluster
# ---------------------------------------------------------------------------


def _boot(tmp_path, settings=None, shards=2):
    # mesh SPMD off by default here: these tests pin SHARD-path semantics
    # (per-shard counts, slowlog, request-cache attribution) — the mesh
    # path's coordinator-side recording has its own test below
    cluster = TestCluster(n_nodes=1, data_root=tmp_path, seed=5,
                          settings={"search.mesh.enabled": False,
                                    **(settings or {})})
    cluster.start()
    c = cluster.client()
    c.create_index("ins", {"settings": {"number_of_shards": shards,
                                        "number_of_replicas": 0}})
    cluster.ensure_green("ins")
    for i in range(40):
        c.index("ins", "doc", {"body": f"alpha{i % 4} beta", "n": i},
                id=str(i))
    c.refresh("ins")
    return cluster, c


MATCH = {"query": {"match": {"body": "alpha1"}}, "size": 3}
COUNT = {"query": {"match": {"body": "alpha2"}}, "size": 0}


@pytest.mark.insights
class TestLiveInsights:
    def test_every_search_classified_no_opt_in(self, tmp_path):
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            for i in range(4):
                c.search("ins", {"query": {"match": {"body": f"alpha{i}"}},
                                 "size": 3})
            c.search("ins", COUNT)  # miss + store
            c.search("ins", COUNT)  # cache hit
            c.search("ins", {"query": {"fuzzy": {"body": "alphaa"}},
                             "size": 2})  # host path
            entries = node.insights.top(10)
            assert len(entries) >= 3
            by_count = {e["shape_id"]: e for e in entries}
            # the 4 match searches share ONE shape (literals erased) + every
            # shard phase counted (2 shards per search)
            match_entry = max(entries, key=lambda e: e["count"])
            assert match_entry["count"] == 8
            assert match_entry["outcomes"].get("device_sparse", 0) >= 1
            # the cached count query carries hit + miss attribution
            cached = [e for e in entries if e["cache"]["hits"] >= 1]
            assert cached, [e["cache"] for e in entries]
            assert cached[0]["outcomes"].get("cache_hit", 0) >= 1
            # the fuzzy query fell off the fused path -> host outcome
            assert any(e["outcomes"].get("host", 0) >= 1 for e in entries), \
                [e["outcomes"] for e in entries]
            # batcher-phase attribution: queue + device histograms populated
            # from the drainer's existing clocks
            assert match_entry["queue"]["count"] >= 1
            assert match_entry["device"]["count"] >= 1
            assert by_count  # keep the var (readability of failures above)
        finally:
            cluster.close()

    def test_mesh_served_searches_classify_too(self, tmp_path):
        """A mesh-SPMD-served search never reaches _s_query_phase — the
        coordinator records it instead, outcome mesh_spmd (once per search,
        not per shard)."""
        cluster = TestCluster(n_nodes=1, data_root=tmp_path, seed=5)
        cluster.start()
        c = cluster.client()
        node = next(iter(cluster.nodes.values()))
        try:
            c.create_index("mesh", {"settings": {"number_of_shards": 2,
                                                 "number_of_replicas": 0}})
            cluster.ensure_green("mesh")
            for i in range(20):
                c.index("mesh", "doc", {"body": f"alpha{i % 3}"}, id=str(i))
            c.refresh("mesh")
            c.search("mesh", MATCH)
            entries = node.insights.top(5)
            assert entries, "mesh-served search was not classified"
            outcomes = {}
            for e in entries:
                for o, n in e["outcomes"].items():
                    outcomes[o] = outcomes.get(o, 0) + n
            # conftest pins an 8-device CPU mesh, so the 2-shard co-located
            # flat search rides the SPMD program (test_mesh_serving pins it)
            assert outcomes.get("mesh_spmd", 0) >= 1, outcomes
        finally:
            cluster.close()

    def test_rest_surfaces(self, tmp_path):
        from elasticsearch_tpu.rest.controller import (RestRequest,
                                                       build_rest_controller)

        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            for _ in range(3):
                c.search("ins", MATCH)
            rc = build_rest_controller(node)
            r = rc.dispatch(RestRequest(method="GET",
                                        path="/_insights/queries",
                                        params={}))
            assert r.status == 200
            assert r.body["insights"]["shapes"] >= 1
            assert r.body["shapes"][0]["cost_ms"] >= \
                r.body["shapes"][-1]["cost_ms"]  # top-N by cost
            r1 = rc.dispatch(RestRequest(method="GET",
                                         path="/_insights/queries",
                                         params={"size": "1"}))
            assert len(r1.body["shapes"]) == 1
            bad = rc.dispatch(RestRequest(method="GET",
                                          path="/_insights/queries",
                                          params={"size": "-2"}))
            assert bad.status == 400
            # /_nodes/stats search.shapes section
            st = node.client().nodes_stats(metric="search")
            (sections,) = st["nodes"].values()
            shapes = sections["search"]["shapes"]
            assert shapes["shapes"] >= 1 and shapes["top"]
            assert shapes["max_shapes"] == 128
        finally:
            cluster.close()

    def test_slowlog_carries_shape_id_and_cluster_runtime_thresholds(
            self, tmp_path):
        """The satellite pair: slowlog lines join /_insights/queries via
        shape[<id>], and PUT /_cluster/settings arms the threshold at
        runtime with NO index-level setting and no restart."""
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            records = []

            class _Capture(logging.Handler):
                def emit(self, record):
                    records.append(record.getMessage())

            # cluster-level transient threshold — no index setting at all
            c.cluster_update_settings({"transient": {
                "index.search.slowlog.threshold.query.warn": "0ms"}})
            handler = _Capture()
            logging.getLogger("estpu.action").addHandler(handler)
            try:
                c.search("ins", MATCH)
            finally:
                logging.getLogger("estpu.action").removeHandler(handler)
                c.cluster_update_settings({"transient": {
                    "index.search.slowlog.threshold.query.warn": "-1"}})
            slow = [m for m in records if "slowlog" in m]
            assert slow, records
            sid, _ = node.insights.fingerprint(MATCH)
            assert f"shape[{sid}]" in slow[0], slow[0]

            # after disarming (-1), no further lines
            records.clear()
            logging.getLogger("estpu.action").addHandler(handler)
            try:
                c.search("ins", MATCH)
            finally:
                logging.getLogger("estpu.action").removeHandler(handler)
            assert not [m for m in records if "slowlog" in m], records
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# Prometheus label-cardinality bound (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.insights
class TestPrometheusCardinality:
    def test_fuzzed_shape_burst_stays_at_max_shapes(self, tmp_path, rng):
        from elasticsearch_tpu.rest.controller import _prometheus_text
        from tools.obs_smoke import _parse_prometheus

        cluster, c = _boot(
            tmp_path, settings={"search.insights.max_shapes": 12}, shards=1)
        node = next(iter(cluster.nodes.values()))
        try:
            # a burst of far more distinct shapes than the registry holds:
            # random field names + random clause structures
            for i in range(40):
                field = f"f{int(rng.integers(0, 1000))}_{i}"
                if i % 3 == 0:
                    body = {"query": {"bool": {"should": [
                        {"term": {field: "x"}}
                        for _ in range(int(rng.integers(1, 6)))]}},
                        "size": int(rng.integers(0, 2))}
                else:
                    body = {"query": {"match": {field: "x"}},
                            "size": int(rng.integers(0, 3))}
                c.search("ins", body)
            assert node.insights.stats()["demotions"] > 0
            text = _prometheus_text(node)
            _parse_prometheus(text)  # contiguity + well-formedness pinned
            for fam in ("estpu_query_shape_count_total",
                        "estpu_query_shape_cost_seconds_total",
                        "estpu_query_shape_device_seconds_total",
                        "estpu_query_shape_cache_hits_total"):
                labels = {ln.split("{", 1)[1] for ln in text.splitlines()
                          if ln.startswith(fam + "{")}
                assert 0 < len(labels) <= 12, (fam, len(labels))
            assert "estpu_query_shape_demotions_total" in text
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# the hot-path invariant (acceptance pin)
# ---------------------------------------------------------------------------


@pytest.mark.insights
class TestHotPathInvariant:
    def test_armed_trio_adds_no_compiles_pulls_or_syncs(self, tmp_path,
                                                        monkeypatch):
        """Acceptance: a warmed serving loop with insights + capacity ledger
        + watchdog ALL armed shows 0 recompiles and 0 added device_get/sync
        calls under hard transfer_guard("disallow") — the armed loop performs
        exactly as many pulls as the same loop with insights disabled."""
        import jax

        from elasticsearch_tpu.common.jaxenv import sanitize
        from elasticsearch_tpu.search import execute as execute_mod

        cluster, c = _boot(tmp_path, settings={"watchdog.interval": "50ms"})
        node = next(iter(cluster.nodes.values()))
        try:
            assert node.insights.enabled and node.watchdog.enabled
            # warm every executable this loop will need (both shapes)
            for _ in range(3):
                c.search("ins", MATCH)
                c.search("ins", COUNT)

            pulls = []
            orig_get = jax.device_get
            monkeypatch.setattr(jax, "device_get",
                                lambda *a, **k: (pulls.append(1),
                                                 orig_get(*a, **k))[1])
            syncs = []
            orig_sync = execute_mod._PendingFlat.sync
            monkeypatch.setattr(execute_mod._PendingFlat, "sync",
                                lambda self: (syncs.append(1),
                                              orig_sync(self))[1])

            def loop(n=8):
                pulls.clear()
                for _ in range(n):
                    c.search("ins", MATCH)
                    c.search("ins", COUNT)  # request-cache hit: 0 pulls
                return len(pulls)

            ticks_before = node.watchdog.ticks
            jax.config.update("jax_transfer_guard", "disallow")
            try:
                with sanitize(max_compiles=0, transfers="disallow") as rep:
                    armed_pulls = loop()
            finally:
                jax.config.update("jax_transfer_guard", "allow")
            assert rep.compiles == 0, rep.compile_events
            assert syncs == [], "telemetry must never sync"
            # the watchdog really ran during the loop (always-on, not idle)
            time.sleep(0.15)
            assert node.watchdog.ticks > ticks_before

            # identical loop with insights disabled: pull count must match
            node.insights.enabled = False
            try:
                baseline_pulls = loop()
            finally:
                node.insights.enabled = True
            assert armed_pulls == baseline_pulls, \
                (armed_pulls, baseline_pulls)
            # one batched pull per (uncached search x shard); cached searches
            # pull nothing
            assert armed_pulls == 8 * 2
        finally:
            cluster.close()
