"""Allocation decider chain + rebalancing — pure functions over fake
ClusterStates (the reference's ElasticsearchAllocationTestCase trick).

ref: cluster/routing/allocation/decider/ShardsLimitAllocationDecider.java,
SnapshotInProgressAllocationDecider.java, NodeVersionAllocationDecider.java,
ClusterRebalanceAllocationDecider.java,
ConcurrentRebalanceAllocationDecider.java and
allocator/BalancedShardsAllocator.java's rebalance step."""

import pytest

from elasticsearch_tpu.cluster.allocation import (
    AllocationService,
    new_index_routing,
)
from elasticsearch_tpu.cluster.state import (
    INITIALIZING,
    RELOCATING,
    STARTED,
    UNASSIGNED,
    ClusterState,
    DiscoveryNode,
    DiscoveryNodes,
    IndexMetaData,
    MetaData,
    RoutingTable,
)
from elasticsearch_tpu.common.settings import Settings


def _node(i, version_id=10000, attrs=()):
    return DiscoveryNode(id=f"n{i}", name=f"n{i}", transport_address=f"local://n{i}",
                         attrs=attrs, version_id=version_id)


def _state(n_nodes=3, shards=2, replicas=1, index="idx", index_settings=None,
           node_versions=None):
    nodes = tuple(
        _node(i, version_id=(node_versions or {}).get(i, 10000))
        for i in range(n_nodes))
    settings_map = {"index.number_of_shards": shards,
                    "index.number_of_replicas": replicas,
                    **(index_settings or {})}
    meta = IndexMetaData(name=index,
                         settings_map=tuple(settings_map.items()))
    return ClusterState(
        cluster_name="test",
        nodes=DiscoveryNodes(nodes=nodes, master_id="n0", local_id="n0"),
        metadata=MetaData(indices=((index, meta),)),
        routing_table=RoutingTable(
            ((index, new_index_routing(index, shards, replicas)),)),
    )


def _start_all(svc, state):
    for _ in range(4):
        state = svc.reroute(state)
        init = [s for s in state.routing_table.all_shards()
                if s.state == INITIALIZING and s.relocating_node is None]
        if not init:
            break
        state = svc.apply_started_shards(state, init)
    return state


class TestShardsLimit:
    def test_total_shards_per_node_caps_allocation(self):
        # 4 shards x 1 copy on 2 nodes with limit 1: only 2 can place
        svc = AllocationService()
        state = svc.reroute(_state(
            n_nodes=2, shards=4, replicas=0,
            index_settings={"index.routing.allocation.total_shards_per_node": 1}))
        assigned = [s for s in state.routing_table.all_shards() if s.assigned]
        unassigned = [s for s in state.routing_table.all_shards()
                      if s.state == UNASSIGNED]
        assert len(assigned) == 2 and len(unassigned) == 2
        per_node = {}
        for s in assigned:
            per_node[s.node_id] = per_node.get(s.node_id, 0) + 1
        assert all(v == 1 for v in per_node.values())

    def test_unlimited_by_default(self):
        svc = AllocationService()
        state = _start_all(svc, _state(n_nodes=1, shards=4, replicas=0))
        assert all(s.state == STARTED
                   for s in state.routing_table.all_shards())


class TestNodeVersion:
    def test_replica_refuses_older_node_than_primary(self):
        # n0 new (10100), n1 old (10000): if the primary lands on n0, the
        # replica cannot go to the older n1
        svc = AllocationService()
        state = _state(n_nodes=2, shards=1, replicas=1,
                       node_versions={0: 10100, 1: 10000})
        state = svc.reroute(state)
        state = svc.apply_started_shards(
            state, [s for s in state.routing_table.all_shards() if s.primary])
        state = svc.reroute(state)
        group = state.routing_table.index("idx").shard(0)
        primary = group.primary
        replica = [s for s in group.shards if not s.primary][0]
        if primary.node_id == "n0":
            assert replica.state == UNASSIGNED  # n1 is older — refused
        else:
            assert replica.assigned  # n0 is newer — fine

    def test_same_version_allocates(self):
        svc = AllocationService()
        state = _start_all(svc, _state(n_nodes=2, shards=1, replicas=1))
        assert all(s.state == STARTED
                   for s in state.routing_table.all_shards())


class TestSnapshotInProgress:
    def test_snapshotting_index_never_rebalances(self):
        svc = AllocationService()
        state = _start_all(svc, _state(n_nodes=2, shards=3, replicas=1))
        # imbalance arrives with a third empty node joining (replicas present:
        # the rebalancer moves replicas only — primaries stay put by design)
        state = ClusterState(
            cluster_name=state.cluster_name,
            nodes=DiscoveryNodes(nodes=(*state.nodes.nodes, _node(2)),
                                 master_id="n0", local_id="n0"),
            metadata=state.metadata, routing_table=state.routing_table,
            version=state.version + 1)
        svc.snapshotting_indices.add("idx")
        state2 = svc.reroute(state)
        assert not [s for s in state2.routing_table.all_shards()
                    if s.state == RELOCATING]
        svc.snapshotting_indices.clear()
        state3 = svc.reroute(state)
        assert [s for s in state3.routing_table.all_shards()
                if s.state == RELOCATING]


class TestRebalance:
    def _imbalanced(self, svc, shards=3):
        state = _start_all(svc, _state(n_nodes=2, shards=shards, replicas=1))
        # a fresh empty node joins: weights are now lopsided
        return ClusterState(
            cluster_name=state.cluster_name,
            nodes=DiscoveryNodes(nodes=(*state.nodes.nodes, _node(2)),
                                 master_id="n0", local_id="n0"),
            metadata=state.metadata, routing_table=state.routing_table,
            version=state.version + 1)

    def test_rebalance_relocates_to_new_node(self):
        svc = AllocationService()
        state = svc.reroute(self._imbalanced(svc))
        relocating = [s for s in state.routing_table.all_shards()
                      if s.state == RELOCATING]
        targets = [s for s in state.routing_table.all_shards()
                   if s.state == INITIALIZING and s.relocating_node is not None]
        assert len(relocating) == 1 and len(targets) == 1
        assert targets[0].node_id == "n2"
        assert targets[0].relocating_node == relocating[0].node_id

    def test_relocation_completes_on_target_start(self):
        svc = AllocationService()
        state = svc.reroute(self._imbalanced(svc))
        target = [s for s in state.routing_table.all_shards()
                  if s.state == INITIALIZING and s.relocating_node][0]
        state = svc.apply_started_shards(state, [target])
        group = state.routing_table.index("idx").shard(target.shard_id)
        assert len(group.shards) == 2  # primary + the relocated replica
        moved = [s for s in group.shards if not s.primary]
        assert [s.node_id for s in moved] == ["n2"]
        assert moved[0].state == STARTED and moved[0].relocating_node is None
        assert not [s for s in group.shards if s.state == RELOCATING]

    def test_relocation_target_failure_reverts_source(self):
        svc = AllocationService()
        state = svc.reroute(self._imbalanced(svc))
        target = [s for s in state.routing_table.all_shards()
                  if s.state == INITIALIZING and s.relocating_node][0]
        state = svc.apply_failed_shard(state, target)
        group = state.routing_table.index("idx").shard(target.shard_id)
        # the data-bearing source copy survived on its original node (reverted
        # to STARTED — the trailing reroute may legitimately retry, putting it
        # straight back into RELOCATING with a fresh target pair)
        src = [s for s in group.shards
               if not s.primary and s.node_id == target.relocating_node]
        assert len(src) == 1 and src[0].state in (STARTED, RELOCATING)
        retry_targets = [s for s in group.shards
                         if s.state == INITIALIZING and s.relocating_node]
        for t in retry_targets:
            assert t.relocating_node == src[0].node_id

    def test_concurrent_rebalance_limit(self):
        svc = AllocationService(Settings.from_flat(
            {"cluster.routing.allocation.cluster_concurrent_rebalance": 0}))
        state = svc.reroute(self._imbalanced(svc))
        assert not [s for s in state.routing_table.all_shards()
                    if s.state == RELOCATING]

    def test_cluster_rebalance_waits_for_all_active(self):
        svc = AllocationService()
        state = self._imbalanced(svc)
        # one shard back to UNASSIGNED: indices_all_active (default) gates
        from dataclasses import replace as dc_replace

        name, table = state.routing_table.indices[0]
        g0 = table.shards[0]
        broken = dc_replace(g0.shards[0], node_id=None, state=UNASSIGNED)
        from elasticsearch_tpu.cluster.state import (IndexRoutingTable,
                                                     IndexShardRoutingTable)

        new_groups = (IndexShardRoutingTable((broken,)),) + table.shards[1:]
        state = ClusterState(
            cluster_name=state.cluster_name, nodes=state.nodes,
            metadata=state.metadata,
            routing_table=RoutingTable(
                ((name, IndexRoutingTable(name, new_groups)),)),
            version=state.version + 1)
        # remove n2's capacity problem: the unassigned shard will allocate to
        # n2 (fine) but NO relocation may start while anything is inactive
        state2 = svc.reroute(state)
        assert not [s for s in state2.routing_table.all_shards()
                    if s.state == RELOCATING]

    def test_balanced_cluster_does_not_thrash(self):
        svc = AllocationService()
        state = _start_all(svc, _state(n_nodes=2, shards=4, replicas=1))
        state2 = svc.reroute(state)
        assert not [s for s in state2.routing_table.all_shards()
                    if s.state == RELOCATING]
