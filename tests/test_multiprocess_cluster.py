"""True multi-process cluster: N `bin/estpu` OS processes over real TCP.

Every other cluster test runs N nodes in ONE process (tests/harness.py). This
suite boots the production topology — separate interpreters, unicast seeds,
TCP transport, HTTP — and drives it end to end: form cluster, index,
replicate, search, kill a node, recover.

ref: discovery/zen/ZenDiscovery.java:294 (the join flow this crosses a real
process boundary for) + bootstrap/Bootstrap.java:143 (the launcher)."""

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class EstpuProc:
    """One `python -m elasticsearch_tpu` OS process with ephemeral ports."""

    def __init__(self, name: str, data: str, seeds: str | None):
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
               "PYTHONUNBUFFERED": "1"}
        cmd = [sys.executable, "-m", "elasticsearch_tpu",
               f"-Dnode.name={name}", "--data", data, "--http-port", "0",
               "--transport", "tcp"]
        if seeds:
            cmd += ["--seeds", seeds]
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=subprocess.DEVNULL, text=True,
                                     env=env, cwd=REPO)
        self.name = name
        self.transport_addr = None
        self.http_port = None

    def wait_started(self, timeout: float = 90.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                if self.proc.poll() is not None:
                    raise RuntimeError(f"{self.name} died rc={self.proc.returncode}")
                time.sleep(0.1)
                continue
            m = re.search(r"started — transport (\S+), http port (\d+)", line)
            if m:
                self.transport_addr = m.group(1).rstrip(",")
                self.http_port = int(m.group(2))
                return self
        raise TimeoutError(f"{self.name} did not start in {timeout}s")

    def kill(self):
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def terminate(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.kill()


def _req(port: int, method: str, path: str, body=None, timeout=15.0):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return json.loads(e.read() or b"{}")


def _wait_status(port: int, want: set, index=None, timeout: float = 60.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            path = f"/_cluster/health{'/' + index if index else ''}"
            last = _req(port, "GET", path, timeout=5.0)
            if last.get("status") in want:
                return last
        except Exception:  # noqa: BLE001 — node may still be booting
            pass
        time.sleep(0.5)
    raise TimeoutError(f"cluster never reached {want}: {last}")


def test_three_process_cluster_lifecycle(tmp_path):
    procs: list[EstpuProc] = []
    try:
        n1 = EstpuProc("mp1", str(tmp_path / "mp1"), None)
        procs.append(n1)
        n1.wait_started()
        seed = n1.transport_addr
        n2 = EstpuProc("mp2", str(tmp_path / "mp2"), seed)
        n3 = EstpuProc("mp3", str(tmp_path / "mp3"), seed)
        procs += [n2, n3]
        n2.wait_started()
        n3.wait_started()

        # cluster forms across process boundaries
        h = _wait_status(n1.http_port, {"green", "yellow"})
        deadline = time.time() + 60
        while time.time() < deadline:
            h = _req(n1.http_port, "GET", "/_cluster/health", timeout=5.0)
            if h.get("number_of_nodes") == 3:
                break
            time.sleep(0.5)
        assert h["number_of_nodes"] == 3, h

        # index with replicas spread over the processes
        r = _req(n1.http_port, "PUT", "/mpidx", {"settings": {
            "number_of_shards": 2, "number_of_replicas": 1}})
        assert r.get("acknowledged") is True, r
        _wait_status(n1.http_port, {"green"}, index="mpidx")
        for i in range(30):
            r = _req(n2.http_port, "PUT", f"/mpidx/doc/{i}",
                     {"n": i, "body": f"payload {i}"})
            assert r.get("_id") == str(i), r
        _req(n1.http_port, "POST", "/mpidx/_refresh")

        # search served from a DIFFERENT process than the writer used
        r = _req(n3.http_port, "GET", "/mpidx/_search",
                 {"query": {"match_all": {}}, "size": 0})
        assert r["hits"]["total"] == 30, r

        # kill a process hard; the survivors promote/reallocate back to green
        victims = [p for p in procs if p is not n1]
        victims[0].kill()
        _wait_status(n1.http_port, {"green"}, index="mpidx", timeout=90.0)
        r = _req(n1.http_port, "GET", "/mpidx/_search",
                 {"query": {"match_all": {}}, "size": 0})
        assert r["hits"]["total"] == 30, r

        # the cluster still accepts writes after the node loss
        r = _req(n1.http_port, "PUT", "/mpidx/doc/after",
                 {"n": 99, "body": "post-failure write"})
        assert r.get("_version") == 1, r
        _req(n1.http_port, "POST", "/mpidx/_refresh")
        r = _req(n1.http_port, "GET", "/mpidx/_count",
                 {"query": {"match_all": {}}})
        assert r["count"] == 31, r
    finally:
        for p in procs:
            p.terminate()
