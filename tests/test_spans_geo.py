"""Span query family + geo_shape/geohash_cell — the query-DSL long tail.

ref: SpanOrQueryParser.java:1, SpanFirstQueryParser.java:1, SpanNotQueryParser.java:1,
SpanMultiTermQueryParser.java:1, FieldMaskingSpanQueryParser.java:1,
GeoShapeQueryParser.java:1, GeohashCellFilter.java:1."""

import numpy as np
import pytest

from elasticsearch_tpu.common.geo import (
    geohash_bbox,
    geohash_decode,
    geohash_encode,
    geohash_neighbors,
    normalize_shape,
    shape_within,
    shapes_intersect,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
from elasticsearch_tpu.search.queries import parse_filter
from elasticsearch_tpu.search.similarity import SimilarityService


@pytest.fixture(scope="module")
def ctx(tmp_path_factory):
    path = tmp_path_factory.mktemp("spans_geo")
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    svc.put_mapping("doc", {"properties": {
        "body": {"type": "string"},
        "spot": {"type": "geo_point"},
        "area": {"type": "geo_shape"},
    }})
    eng = Engine(str(path), svc)
    docs = [
        # 0: quick brown fox jumps
        {"body": "quick brown fox jumps over the lazy dog"},
        # 1: fox ... quick (reverse order, far apart)
        {"body": "fox stole the extremely well hidden quick cheese"},
        # 2: quick quack (prefix family)
        {"body": "quick quack quartz"},
        # 3: brown at position 0
        {"body": "brown bear brown bread"},
        {"body": "lazy days of summer", "spot": {"lat": 52.37, "lon": 4.89},
         "area": {"type": "envelope", "coordinates": [[4.0, 53.0], [5.0, 52.0]]}},
        {"body": "dog house", "spot": "52.52,13.40",
         "area": {"type": "polygon", "coordinates":
                  [[[13.0, 52.0], [14.0, 52.0], [14.0, 53.0], [13.0, 53.0],
                    [13.0, 52.0]]]}},
        {"body": "far away", "spot": [-122.42, 37.77],  # GeoJSON [lon, lat]
         "area": {"type": "point", "coordinates": [-122.42, 37.77]}},
    ]
    for i, d in enumerate(docs):
        eng.index("doc", str(i), d)
    eng.refresh()
    c = ShardContext(eng.acquire_searcher(), svc,
                     SimilarityService(settings, mapper_service=svc))
    yield c
    eng.close()


def ids(td):
    return sorted(d for _, d in td.hits)


class TestSpanQueries:
    def test_span_or(self, ctx):
        td = search_shard(ctx, parse_query({"span_or": {"clauses": [
            {"span_term": {"body": "fox"}},
            {"span_term": {"body": "bear"}}]}}), 10, use_device=False)
        assert ids(td) == [0, 1, 3]

    def test_span_first(self, ctx):
        # "brown" within the first 1 position → only doc 3 (position 0)
        td = search_shard(ctx, parse_query({"span_first": {
            "match": {"span_term": {"body": "brown"}}, "end": 1}}), 10,
            use_device=False)
        assert ids(td) == [3]
        td2 = search_shard(ctx, parse_query({"span_first": {
            "match": {"span_term": {"body": "brown"}}, "end": 2}}), 10,
            use_device=False)
        assert ids(td2) == [0, 3]  # doc 0 has brown at position 1

    def test_span_not(self, ctx):
        # quick not followed-within-a-span-of brown: doc 0 has "quick brown";
        # span_not(include=quick, exclude=near(quick, brown, slop 0)) drops doc 0
        td = search_shard(ctx, parse_query({"span_not": {
            "include": {"span_term": {"body": "quick"}},
            "exclude": {"span_near": {"clauses": [
                {"span_term": {"body": "quick"}},
                {"span_term": {"body": "brown"}}], "slop": 0,
                "in_order": True}}}}), 10, use_device=False)
        assert ids(td) == [1, 2]

    def test_span_near_ordered_slop(self, ctx):
        q = {"span_near": {"clauses": [
            {"span_term": {"body": "quick"}},
            {"span_term": {"body": "fox"}}], "slop": 1, "in_order": True}}
        td = search_shard(ctx, parse_query(q), 10, use_device=False)
        assert ids(td) == [0]  # quick [brown] fox = 1 gap; doc 1 is out of order

    def test_span_near_unordered(self, ctx):
        q = {"span_near": {"clauses": [
            {"span_term": {"body": "quick"}},
            {"span_term": {"body": "fox"}}], "slop": 10, "in_order": False}}
        td = search_shard(ctx, parse_query(q), 10, use_device=False)
        assert ids(td) == [0, 1]

    def test_span_multi(self, ctx):
        td = search_shard(ctx, parse_query({"span_multi": {
            "match": {"prefix": {"body": {"value": "qua"}}}}}), 10,
            use_device=False)
        assert ids(td) == [2]
        # composed inside span_near: quick + qua* adjacent
        td2 = search_shard(ctx, parse_query({"span_near": {"clauses": [
            {"span_term": {"body": "quick"}},
            {"span_multi": {"match": {"prefix": {"body": {"value": "qua"}}}}}],
            "slop": 0, "in_order": True}}), 10, use_device=False)
        assert ids(td2) == [2]

    def test_field_masking_span(self, ctx):
        # masked field reports "body", so it can compose with body spans
        td = search_shard(ctx, parse_query({"span_near": {"clauses": [
            {"span_term": {"body": "quick"}},
            {"field_masking_span": {
                "query": {"span_term": {"body": "brown"}}, "field": "body"}}],
            "slop": 0, "in_order": True}}), 10, use_device=False)
        assert ids(td) == [0]

    def test_span_scores_positive_and_ranked(self, ctx):
        td = search_shard(ctx, parse_query({"span_or": {"clauses": [
            {"span_term": {"body": "brown"}}]}}), 10, use_device=False)
        scores = [s for s, _ in td.hits]
        assert all(s > 0 for s in scores)
        assert scores == sorted(scores, reverse=True)
        # doc 3 has two "brown" occurrences → higher freq → higher score
        assert td.hits[0][1] == 3


class TestGeohash:
    def test_roundtrip(self):
        h = geohash_encode(52.37, 4.89, 7)
        lat, lon = geohash_decode(h)
        assert abs(lat - 52.37) < 0.01 and abs(lon - 4.89) < 0.01

    def test_known_value(self):
        # canonical example: u09tvw0 ≈ Paris; check a stable well-known cell
        assert geohash_encode(57.64911, 10.40744, 11) == "u4pruydqqvj"

    def test_bbox_contains_center(self):
        h = geohash_encode(37.77, -122.42, 6)
        lat_lo, lat_hi, lon_lo, lon_hi = geohash_bbox(h)
        assert lat_lo <= 37.77 <= lat_hi and lon_lo <= -122.42 <= lon_hi

    def test_neighbors(self):
        n = geohash_neighbors("u4pruy")
        assert len(n) == 8 and all(len(x) == 6 for x in n) and "u4pruy" not in n


class TestGeoFilters:
    def test_geohash_cell(self, ctx):
        cell = geohash_encode(52.37, 4.89, 5)
        td = search_shard(ctx, parse_query({"filtered": {
            "query": {"match_all": {}},
            "filter": {"geohash_cell": {"spot": {"lat": 52.37, "lon": 4.89},
                                        "precision": 5}}}}), 10, use_device=False)
        assert ids(td) == [4]
        # berlin pin at coarse precision w/ neighbors still only finds berlin doc
        td2 = search_shard(ctx, parse_query({"filtered": {
            "query": {"match_all": {}},
            "filter": {"geohash_cell": {"spot": "u33", "neighbors": True}}}}),
            10, use_device=False)
        assert ids(td2) == [5]
        assert parse_filter({"geohash_cell": {"spot": cell}}).geohash == cell

    def test_geo_shape_envelope_query(self, ctx):
        td = search_shard(ctx, parse_query({"geo_shape": {"area": {
            "shape": {"type": "envelope",
                      "coordinates": [[4.5, 52.5], [4.9, 52.1]]}}}}), 10,
            use_device=False)
        assert ids(td) == [4]

    def test_geo_shape_polygon_vs_point(self, ctx):
        td = search_shard(ctx, parse_query({"geo_shape": {"area": {
            "shape": {"type": "polygon", "coordinates":
                      [[[-123.0, 37.0], [-122.0, 37.0], [-122.0, 38.0],
                        [-123.0, 38.0], [-123.0, 37.0]]]}}}}), 10,
            use_device=False)
        assert ids(td) == [6]

    def test_geo_shape_within_and_disjoint(self, ctx):
        big = {"type": "envelope", "coordinates": [[3.0, 54.0], [6.0, 51.0]]}
        td = search_shard(ctx, parse_query({"filtered": {
            "query": {"match_all": {}},
            "filter": {"geo_shape": {"area": {"shape": big,
                                              "relation": "within"}}}}}), 10,
            use_device=False)
        assert ids(td) == [4]
        td2 = search_shard(ctx, parse_query({"filtered": {
            "query": {"match_all": {}},
            "filter": {"geo_shape": {"area": {"shape": big,
                                              "relation": "disjoint"}}}}}), 10,
            use_device=False)
        assert ids(td2) == [5, 6]

    def test_geo_point_accepts_geohash_string(self, ctx):
        # doc 5's spot was given as "lat,lon"; verify geohash input parses too by
        # querying through a cell computed from an encoded hash
        h = geohash_encode(37.77, -122.42, 4)
        td = search_shard(ctx, parse_query({"filtered": {
            "query": {"match_all": {}},
            "filter": {"geohash_cell": {"spot": h}}}}), 10, use_device=False)
        assert ids(td) == [6]


class TestReviewRegressions:
    def test_multi_valued_geo_points(self, tmp_path):
        from elasticsearch_tpu.common.errors import MapperParsingError
        from elasticsearch_tpu.common.settings import Settings as _S

        svc = MapperService(_S.from_flat({}))
        svc.put_mapping("doc", {"properties": {"spot": {"type": "geo_point"}}})
        dm = svc.mappers["doc"]
        d = dm.parse({"spot": [{"lat": 1.0, "lon": 2.0}, {"lat": 3.0, "lon": 4.0}]},
                     "1")
        assert d.doc_values_num["spot.lat"] == [1.0, 3.0]
        assert d.doc_values_num["spot.lon"] == [2.0, 4.0]
        # GeoJSON bare pair stays a single point
        d2 = dm.parse({"spot": [4.89, 52.37]}, "2")
        assert d2.doc_values_num["spot.lat"] == [52.37]
        with pytest.raises(MapperParsingError):
            dm.parse({"spot": ""}, "3")  # empty geohash must not become (0, 0)

    def test_within_respects_holes(self):
        donut = normalize_shape({"type": "polygon", "coordinates": [
            [[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]],
            [[4, 4], [6, 4], [6, 6], [4, 6], [4, 4]]]})
        covers_hole = normalize_shape({"type": "envelope",
                                       "coordinates": [[3, 7], [7, 3]]})
        clear = normalize_shape({"type": "envelope", "coordinates": [[1, 3], [3, 1]]})
        assert not shape_within(covers_hole, donut)
        assert shape_within(clear, donut)

    def test_malformed_binary_body_gets_400(self, ctx):
        # server-level behavior is covered in test_xcontent; here assert the codec
        # raises (the http handler converts it to 400, not a dropped connection)
        from elasticsearch_tpu.common.xcontent import cbor_loads, smile_loads
        with pytest.raises(Exception):
            cbor_loads(b"\xa5\x01")
        with pytest.raises(Exception):
            smile_loads(b"garbage")


class TestShapeGeometry:
    def test_polygon_hole(self):
        donut = normalize_shape({"type": "polygon", "coordinates": [
            [[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]],
            [[4, 4], [6, 4], [6, 6], [4, 6], [4, 4]],
        ]})
        inside_hole = normalize_shape({"type": "point", "coordinates": [5, 5]})
        in_ring = normalize_shape({"type": "point", "coordinates": [2, 2]})
        assert not shapes_intersect(donut, inside_hole)
        assert shapes_intersect(donut, in_ring)

    def test_edge_crossing_polygons(self):
        a = normalize_shape({"type": "polygon", "coordinates":
                             [[[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]]]})
        b = normalize_shape({"type": "polygon", "coordinates":
                             [[[2, -1], [3, -1], [3, 5], [2, 5], [2, -1]]]})
        assert shapes_intersect(a, b)
        assert not shape_within(b, a)
        assert shape_within(
            normalize_shape({"type": "envelope", "coordinates": [[1, 3], [3, 1]]}), a)
