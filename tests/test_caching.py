"""Multi-tier caching (ISSUE 11): shard request cache, device-resident filter
cache, and cache-affinity replica routing.

Unit half: fingerprint stability (key order / volatile knobs), the
size==0-unless-opted-in cache policy, LRU byte bounds + breaker accounting
(trip at store time skips caching; eviction/clear releases), view-keyed
invalidation, filter-mask sighting promotion + shared-holder eviction
semantics, and rendezvous affinity (same fingerprint → same copy within the
healthy spread set; health dominates; probes unchanged).

Chaos half (live cluster): repeated hot queries hit before the device (the
warmed hit loop is pinned at 0 device launches / 0 recompiles / 0 syncs under
hard transfer_guard("disallow")), a bulk write + refresh invalidates (a stale
hit is NEVER served), `POST /_cache/clear` drains both tiers' breaker bytes
to 0, filter-cache warm hits score bitwise-identically to the cold path, and
the observability surfaces (`/_nodes/stats` indices.request_cache /
indices.filter_cache, `/_cat/caches`, `estpu_request_cache_*` /
`estpu_filter_cache_*` Prometheus families, `?profile=true` cache events)
all report the traffic.
"""

from __future__ import annotations

import threading
import time

import pytest

from elasticsearch_tpu.cluster.routing import OperationRouting
from elasticsearch_tpu.cluster.state import STARTED, ShardRouting
from elasticsearch_tpu.cluster.stats import AdaptiveReplicaSelector
from elasticsearch_tpu.common.breaker import CircuitBreakerService
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.ops.device_index import DeviceFilterCache
from elasticsearch_tpu.rest.controller import (RestRequest,
                                               build_rest_controller)
from elasticsearch_tpu.search.request_cache import (ShardRequestCache,
                                                    cache_policy,
                                                    request_fingerprint)

from .harness import TestCluster

pytestmark = pytest.mark.caching


# ---------------------------------------------------------------------------
# fingerprint + policy units
# ---------------------------------------------------------------------------


class TestFingerprint:
    def test_key_order_invariant(self):
        a = {"query": {"match": {"body": "x"}}, "size": 0, "from": 0}
        b = {"from": 0, "size": 0, "query": {"match": {"body": "x"}}}
        assert request_fingerprint(a) == request_fingerprint(b)

    def test_volatile_knobs_do_not_change_identity(self):
        base = {"query": {"match": {"body": "x"}}, "size": 0}
        assert request_fingerprint(base) == request_fingerprint(
            {**base, "profile": True, "timeout": "50ms",
             "request_cache": True})

    def test_semantic_changes_change_identity(self):
        base = {"query": {"match": {"body": "x"}}, "size": 0}
        for variant in (
            {**base, "size": 5},
            {**base, "from": 10},
            {**base, "query": {"match": {"body": "y"}}},
            {**base, "aggs": {"m": {"max": {"field": "n"}}}},
            {**base, "sort": [{"n": "asc"}]},
        ):
            assert request_fingerprint(variant) != request_fingerprint(base)

    def test_policy_size_zero_default_and_overrides(self):
        assert cache_policy({"query": {}, "size": 0})
        assert not cache_policy({"query": {}, "size": 10})
        assert not cache_policy({"query": {}})  # size defaults to 10
        assert cache_policy({"query": {}, "size": 10, "request_cache": True})
        assert not cache_policy({"query": {}, "size": 0,
                                 "request_cache": False})


# ---------------------------------------------------------------------------
# request-cache units: LRU bound, breaker accounting, invalidation
# ---------------------------------------------------------------------------


def _svc(budget="1mb"):
    return CircuitBreakerService(Settings.from_flat(
        {"indices.breaker.total_budget": budget}))


class TestShardRequestCacheUnits:
    def test_store_hit_and_breaker_accounting(self):
        svc = _svc()
        rc = ShardRequestCache(Settings.EMPTY, breaker=svc.breaker("request"),
                               total_budget=1 << 20)
        key = ("i", 0, 1, "fp")
        assert rc.get(key) is None
        assert rc.put(key, b"x" * 100)
        assert svc.breaker("request").used == 100 + rc.ENTRY_OVERHEAD
        assert rc.get(key) == b"x" * 100
        st = rc.stats()
        assert st["hits"] == 1 and st["misses"] == 1 and st["stores"] == 1

    def test_lru_eviction_releases_breaker(self):
        svc = _svc()
        rc = ShardRequestCache(
            Settings.from_flat({"indices.requests.cache.size": "2kb"}),
            breaker=svc.breaker("request"), total_budget=1 << 20)
        for i in range(10):
            assert rc.put(("i", 0, 1, f"fp{i}"), b"v" * 512)
        st = rc.stats()
        assert st["evictions"] > 0
        assert st["memory_size_in_bytes"] <= rc.size_bytes
        # breaker tracks exactly the resident bytes
        assert svc.breaker("request").used == st["memory_size_in_bytes"]
        # oldest entries gone, newest present
        assert rc.get(("i", 0, 1, "fp0")) is None
        assert rc.get(("i", 0, 1, "fp9")) is not None

    def test_breaker_trip_skips_store(self):
        svc = _svc(budget="4kb")  # request child = 60% of 70% parent
        rc = ShardRequestCache(
            Settings.from_flat({"indices.requests.cache.size": "1mb"}),
            breaker=svc.breaker("request"), total_budget=1 << 20)
        # fill the breaker so the store trips; incompressible bytes — a
        # compressible value would (correctly) deflate under the floor and
        # fit, which is the compression feature, not the trip under test
        import os as _os
        svc.breaker("request").add_estimate_and_maybe_break(1500, "pin")
        assert not rc.put(("i", 0, 1, "fp"), _os.urandom(1200))
        assert rc.stats()["rejections"] == 1
        assert rc.get(("i", 0, 1, "fp")) is None
        svc.breaker("request").release(1500)
        assert svc.breaker("request").used == 0

    def test_view_invalidation_is_selective(self):
        rc = ShardRequestCache(Settings.EMPTY, total_budget=1 << 20)
        rc.put(("i", 0, 1, "a"), b"old")
        rc.put(("i", 0, 2, "a"), b"new")
        rc.put(("i", 1, 1, "a"), b"other-shard")
        rc.put(("j", 0, 1, "a"), b"other-index")
        assert rc.invalidate_shard("i", 0, current_view=2) == 1
        assert rc.get(("i", 0, 2, "a")) == b"new"
        assert rc.get(("i", 1, 1, "a")) == b"other-shard"
        assert rc.get(("j", 0, 1, "a")) == b"other-index"
        # shard removal drops every view
        assert rc.invalidate_shard("i", 0, current_view=None) == 1
        assert rc.stats()["invalidations"] == 2

    def test_clear_drains_to_zero(self):
        svc = _svc()
        rc = ShardRequestCache(Settings.EMPTY, breaker=svc.breaker("request"),
                               total_budget=1 << 20)
        for i in range(5):
            rc.put(("i", 0, 1, f"fp{i}"), b"v" * 64)
        assert svc.breaker("request").used > 0
        rc.clear()
        assert rc.stats()["memory_size_in_bytes"] == 0
        assert rc.stats()["entries"] == 0
        assert svc.breaker("request").used == 0

    def test_disabled_by_setting(self):
        rc = ShardRequestCache(Settings.from_flat(
            {"indices.requests.cache.enable": "false"}))
        assert rc.enabled is False


# ---------------------------------------------------------------------------
# filter-cache units: sighting promotion, shared-holder eviction
# ---------------------------------------------------------------------------


class _FakeSeg:
    def __init__(self):
        self._device_cache = {}


class TestDeviceFilterCacheUnits:
    def test_second_sighting_promotes(self):
        import numpy as np

        svc = _svc()
        fc = DeviceFilterCache(Settings.EMPTY,
                               breaker=svc.breaker("fielddata"))
        seg = _FakeSeg()
        mask = np.zeros(128, dtype=bool)
        mask[3] = True
        assert fc.lookup(seg, "term:f:v") is None  # sighting 1
        assert fc.maybe_store(seg, "term:f:v", mask) is None  # still cold
        assert fc.lookup(seg, "term:f:v") is None  # sighting 2
        row = fc.maybe_store(seg, "term:f:v", mask)
        assert row is not None
        assert svc.breaker("fielddata").used == mask.nbytes
        got = fc.lookup(seg, "term:f:v")
        assert got is row
        st = fc.stats()
        assert st["builds"] == 1 and st["hits"] == 1 and st["misses"] == 2
        assert st["memory_size_in_bytes"] == mask.nbytes

    def test_shared_holder_survives_tombstone_view(self):
        """with_deletes shallow-copies _device_cache: the successor view
        SHARES the filter-mask holder, so dropping the predecessor segment
        must NOT evict masks the live view still serves."""
        import numpy as np

        svc = _svc()
        fc = DeviceFilterCache(Settings.EMPTY,
                               breaker=svc.breaker("fielddata"))
        old = _FakeSeg()
        mask = np.ones(128, dtype=bool)
        fc.lookup(old, "k")
        fc.lookup(old, "k")
        assert fc.maybe_store(old, "k", mask) is not None
        new = _FakeSeg()
        new._device_cache = dict(old._device_cache)  # the with_deletes copy
        assert fc.evict_dropped([old], [new]) == 0  # holder still referenced
        assert fc.lookup(new, "k") is not None
        assert svc.breaker("fielddata").used == mask.nbytes
        # now the view drops it for real (merge) — bytes come back and the
        # dead holder refuses re-population from stale searchers
        assert fc.evict_dropped([new], []) == 1
        assert svc.breaker("fielddata").used == 0
        assert fc.maybe_store(new, "k", mask) is None
        assert fc.stats()["memory_size_in_bytes"] == 0

    def test_breaker_trip_serves_host_mask(self):
        import numpy as np

        svc = _svc(budget="1kb")
        fc = DeviceFilterCache(Settings.EMPTY,
                               breaker=svc.breaker("fielddata"))
        seg = _FakeSeg()
        big = np.zeros(1 << 20, dtype=bool)
        fc.lookup(seg, "k")
        fc.lookup(seg, "k")
        assert fc.maybe_store(seg, "k", big) is None  # tripped, not stored
        assert fc.stats()["rejections"] == 1
        assert svc.breaker("fielddata").used == 0


# ---------------------------------------------------------------------------
# affinity units: rendezvous within the spread set, health dominance
# ---------------------------------------------------------------------------


def _copies(n=3, index="i", shard=0):
    return [ShardRouting(index, shard, f"n{i + 1}", i == 0, STARTED)
            for i in range(n)]


def _warm(sel, copies, seconds=0.01, n=None):
    for _ in range(n if n is not None else sel.min_samples):
        for c in copies:
            sel.observe(c, seconds)


class TestAffinityRouting:
    def test_same_fingerprint_same_copy(self):
        sel = AdaptiveReplicaSelector(Settings.from_flat(
            {"search.adaptive.min_samples": 2,
             "search.adaptive.probe_every": 10**9}))
        copies = _copies(3)
        _warm(sel, copies)
        fp = request_fingerprint({"query": {"match": {"b": "hot"}},
                                  "size": 0})
        picks = {sel.select(copies, affinity=fp).node_id for _ in range(20)}
        assert len(picks) == 1
        assert sel.stats()["selections"]["affinity"] >= 20

    def test_different_fingerprints_spread(self):
        sel = AdaptiveReplicaSelector(Settings.from_flat(
            {"search.adaptive.min_samples": 2,
             "search.adaptive.probe_every": 10**9}))
        copies = _copies(3)
        _warm(sel, copies)
        targets = {sel.select(
            copies,
            affinity=request_fingerprint({"q": i})).node_id
            for i in range(32)}
        assert len(targets) >= 2  # rendezvous partitions the fingerprints

    def test_health_dominates_affinity(self):
        """The affinity target going sick moves the fingerprint to the next
        healthy copy — and recovery moves it back (rendezvous stability)."""
        sel = AdaptiveReplicaSelector(Settings.from_flat(
            {"search.adaptive.min_samples": 2,
             "search.adaptive.probe_every": 10**9}))
        copies = _copies(3)
        _warm(sel, copies)
        fp = request_fingerprint({"query": {"match": {"b": "hot"}},
                                  "size": 0})
        home = sel.select(copies, affinity=fp)
        # the home copy turns slow: its score leaves the spread set
        for _ in range(6):
            sel.observe(home, 2.0)
        moved = sel.select(copies, affinity=fp)
        assert moved.node_id != home.node_id
        # recovery: fast samples decay the EWMA back into the spread
        for _ in range(40):
            sel.observe(home, 0.01)
        back = sel.select(copies, affinity=fp)
        assert back.node_id == home.node_id

    def test_probe_turns_still_fire_with_affinity(self):
        sel = AdaptiveReplicaSelector(Settings.from_flat(
            {"search.adaptive.min_samples": 2,
             "search.adaptive.probe_every": 4}))
        copies = _copies(3)
        _warm(sel, copies)
        sick = copies[2]
        for _ in range(6):
            sel.observe(sick, 5.0)  # excluded from the spread set
        fp = request_fingerprint({"q": "hot"})
        before = sel.stats()["probes"]
        for _ in range(16):
            sel.select(copies, affinity=fp)
        assert sel.stats()["probes"] > before

    def test_cold_group_round_robins_despite_affinity(self):
        routing = OperationRouting(selector=AdaptiveReplicaSelector(
            Settings.from_flat({"search.adaptive.min_samples": 5})))
        copies = _copies(3)
        picks = {routing._pick(copies, affinity="fp").node_id
                 for _ in range(9)}
        assert len(picks) == 3  # RR warms every copy; affinity waits

    def test_selectorless_rendezvous_is_stable(self):
        routing = OperationRouting(selector=None)
        copies = _copies(3)
        fp = request_fingerprint({"q": "x"})
        picks = {routing._pick(copies, affinity=fp).node_id
                 for _ in range(10)}
        assert len(picks) == 1
        # and None affinity keeps plain round-robin
        rr = {routing._pick(copies).node_id for _ in range(6)}
        assert len(rr) == 3


# ---------------------------------------------------------------------------
# live cluster: hit path, invalidation-under-writes, clear, observability
# ---------------------------------------------------------------------------


HOT = {"query": {"match": {"body": "alpha"}}, "size": 0,
       "aggs": {"m": {"max": {"field": "n"}}}}
HOT_HITS = {"query": {"match": {"body": "alpha"}}, "size": 5,
            "request_cache": True}
FILTERED = {"query": {"filtered": {"query": {"match": {"body": "alpha"}},
                                   "filter": {"term": {"tag": "t1"}}}},
            "size": 8}


def _boot(tmp_path, nodes=1, settings=None):
    # the warmer's post-refresh re-prime (warmer.py, ISSUE 14) would
    # asynchronously re-store hot entries this suite populates/invalidates
    # BY HAND — these tests pin the raw tier mechanics, so the re-prime is
    # off here (tests/test_writes.py covers the warmed behavior)
    cluster = TestCluster(n_nodes=nodes, data_root=tmp_path, seed=11,
                          settings={"indices.warmer.enabled": "false",
                                    **(settings or {})})
    cluster.start()
    c = cluster.client()
    c.create_index("hot", {"settings": {"number_of_shards": 1,
                                        "number_of_replicas": nodes - 1}})
    cluster.ensure_green("hot")
    for i in range(60):
        c.index("hot", "doc",
                {"body": f"alpha beta{i % 4}", "n": i, "tag": f"t{i % 3}"},
                id=str(i))
    c.refresh("hot")
    return cluster, c


class TestLiveRequestCache:
    def test_hit_path_zero_launches_zero_recompiles_zero_syncs(
            self, tmp_path, monkeypatch):
        """The acceptance pin: a warmed hot-query loop is served entirely
        from the request cache — execute_query_phase never runs, the batcher
        never launches, no pending handle syncs, and the loop holds 0
        compiles under hard transfer_guard("disallow")."""
        import jax

        from elasticsearch_tpu import actions as actions_mod
        from elasticsearch_tpu.common.jaxenv import sanitize
        from elasticsearch_tpu.search import execute as execute_mod
        from elasticsearch_tpu.search.service import SERVING_COUNTERS

        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            exec_calls = []
            orig_exec = actions_mod.execute_query_phase
            monkeypatch.setattr(
                actions_mod, "execute_query_phase",
                lambda *a, **k: (exec_calls.append(1),
                                 orig_exec(*a, **k))[1])
            sync_calls = []
            orig_sync = execute_mod._PendingFlat.sync
            monkeypatch.setattr(
                execute_mod._PendingFlat, "sync",
                lambda self: (sync_calls.append(1), orig_sync(self))[1])

            for body in (HOT, HOT_HITS):
                warm = c.search("hot", body)  # miss + store
                again = c.search("hot", body)  # hit
                assert again["hits"]["total"] == warm["hits"]["total"]
            assert node.request_cache.stats()["hits"] >= 2

            exec_calls.clear()
            sync_calls.clear()
            serving_before = dict(SERVING_COUNTERS)
            launches_before = node.search_batcher.stats()["launches"]
            results = []
            jax.config.update("jax_transfer_guard", "disallow")
            try:
                with sanitize(max_compiles=0, transfers="disallow") as rep:
                    for _ in range(10):
                        results.append(c.search("hot", HOT))
                        results.append(c.search("hot", HOT_HITS))
            finally:
                jax.config.update("jax_transfer_guard", "allow")
            assert rep.compiles == 0, rep.compile_events
            assert exec_calls == [], "hit path reached execute_query_phase"
            assert sync_calls == [], "hit path synced"
            assert node.search_batcher.stats()["launches"] == launches_before
            assert dict(SERVING_COUNTERS) == serving_before
            # every cached answer is the warmed answer
            for r in results[::2]:
                assert r["aggregations"]["m"]["value"] == 59.0
            for r in results[1::2]:
                assert len(r["hits"]["hits"]) == 5
        finally:
            cluster.close()

    def test_writes_invalidate_and_clear_drains_breaker(self, tmp_path):
        """index → search → hit → bulk write + refresh → the next search
        sees the new doc (a stale hit is NEVER served) → _cache/clear
        returns the request breaker to 0."""
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            r1 = c.search("hot", HOT)
            assert r1["hits"]["total"] == 60
            r2 = c.search("hot", HOT)
            assert r2["hits"]["total"] == 60
            st = node.request_cache.stats()
            assert st["hits"] >= 1 and st["stores"] >= 1

            c.bulk([{"action": {"index": {"_index": "hot", "_type": "doc",
                                          "_id": "new1"}},
                     "source": {"body": "alpha fresh", "n": 100,
                                "tag": "t9"}}])
            c.refresh("hot")
            r3 = c.search("hot", HOT)
            assert r3["hits"]["total"] == 61, "stale cached partial served!"
            assert r3["aggregations"]["m"]["value"] == 100.0
            assert node.request_cache.stats()["invalidations"] >= 1

            # repopulate, then clear both tiers over REST with selectors
            c.search("hot", HOT)
            c.search("hot", FILTERED)
            c.search("hot", FILTERED)
            c.search("hot", FILTERED)
            req_br = node.breakers.breaker("request")
            assert req_br.used > 0
            rc = build_rest_controller(node)
            resp = rc.dispatch(RestRequest(
                method="POST", path="/hot/_cache/clear",
                params={"request": "true", "filter": "true"}, body=None))
            assert resp.status == 200
            assert resp.body["_shards"]["successful"] >= 1
            assert node.request_cache.stats()["memory_size_in_bytes"] == 0
            assert node.filter_cache.stats()["memory_size_in_bytes"] == 0
            assert req_br.used == 0
            assert node.breakers.breaker("fielddata").used == 0
            # the node still answers correctly after the clear
            r4 = c.search("hot", HOT)
            assert r4["hits"]["total"] == 61
        finally:
            cluster.close()

    def test_opt_out_and_default_policy_live(self, tmp_path):
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            stores0 = node.request_cache.stats()["stores"]
            # hit-bearing without opt-in: never cached
            body = {"query": {"match": {"body": "alpha"}}, "size": 5}
            c.search("hot", body)
            c.search("hot", body)
            assert node.request_cache.stats()["stores"] == stores0
            # size==0 with explicit opt-OUT: never cached
            c.search("hot", {**HOT, "request_cache": False})
            assert node.request_cache.stats()["stores"] == stores0
        finally:
            cluster.close()

    def test_profile_records_cache_events_and_still_executes(self, tmp_path):
        cluster, c = _boot(tmp_path)
        try:
            c.search("hot", HOT)  # store
            r = c.search("hot", {**HOT, "profile": True})
            shard = r["profile"]["shards"][0]
            events = [e for e in shard["cache"]["events"]
                      if e["kind"] == "request_cache"]
            assert events and events[0]["cache"] == "hit", shard["cache"]
            # profiled requests execute for real: the plan section is present
            assert shard["plan"]["outcome"] != "unknown"
            # a profiled MISS records miss + store
            r2 = c.search("hot", {"query": {"match": {"body": "beta1"}},
                                  "size": 0, "profile": True})
            ev2 = [e for e in r2["profile"]["shards"][0]["cache"]["events"]
                   if e["kind"] == "request_cache"]
            kinds = [e["cache"] for e in ev2]
            assert kinds == ["miss", "store"], kinds
        finally:
            cluster.close()


class TestLiveFilterCache:
    def test_warm_hits_bitwise_identical_and_evicted_on_merge(
            self, tmp_path):
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            cold = c.search("hot", FILTERED)
            st0 = node.filter_cache.stats()
            warm1 = c.search("hot", FILTERED)  # 2nd sighting: builds
            warm2 = c.search("hot", FILTERED)  # resident hit
            st = node.filter_cache.stats()
            assert st["builds"] > st0["builds"]
            assert st["hits"] >= 1
            # bitwise-identical hits + scores cold vs resident-mask warm
            for warm in (warm1, warm2):
                assert warm["hits"]["total"] == cold["hits"]["total"]
                assert [(h["_id"], h["_score"]) for h in
                        warm["hits"]["hits"]] == \
                    [(h["_id"], h["_score"]) for h in cold["hits"]["hits"]]
            assert node.breakers.breaker("fielddata").used > 0
            # optimize merges segments away → masks evicted with them,
            # breaker drains, and the query still answers identically
            c.index("hot", "doc", {"body": "alpha tail", "n": 200,
                                   "tag": "t1"}, id="tail")
            c.refresh("hot")
            c.optimize("hot")
            st2 = node.filter_cache.stats()
            assert st2["evictions"] > st0["evictions"]
            after = c.search("hot", FILTERED)
            assert after["hits"]["total"] == cold["hits"]["total"] + 1
        finally:
            cluster.close()


class TestObservabilitySurfaces:
    def test_nodes_stats_cat_and_prometheus(self, tmp_path):
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            c.search("hot", HOT)
            c.search("hot", HOT)
            c.search("hot", FILTERED)
            c.search("hot", FILTERED)
            rc = build_rest_controller(node)
            r = rc.dispatch(RestRequest(method="GET", path="/_nodes/stats",
                                        params={}))
            assert r.status == 200
            indices = r.body["nodes"][node.node_id]["indices"]
            for tier, keys in (
                ("request_cache", ("memory_size_in_bytes", "hits", "misses",
                                   "stores", "evictions", "invalidations",
                                   "hit_rate", "entries")),
                ("filter_cache", ("memory_size_in_bytes", "hits", "misses",
                                  "builds", "evictions", "hit_rate",
                                  "masks")),
            ):
                assert tier in indices, sorted(indices)
                for k in keys:
                    assert k in indices[tier], (tier, k)
            assert indices["request_cache"]["hits"] >= 1
            # narrow metric filter still works with the tier keys inside
            r = rc.dispatch(RestRequest(method="GET",
                                        path="/_nodes/stats/indices",
                                        params={}))
            assert r.status == 200
            assert "request_cache" in r.body["nodes"][node.node_id]["indices"]

            r = rc.dispatch(RestRequest(method="GET", path="/_cat/caches",
                                        params={"v": ""}))
            assert r.status == 200
            lines = r.body.strip().splitlines()
            assert lines[0].split()[:3] == ["host", "ip", "tier"]
            tiers = {ln.split()[2] for ln in lines[1:]}
            assert tiers == {"request", "filter"}
            r = rc.dispatch(RestRequest(method="GET", path="/_cat/caches",
                                        params={"help": ""}))
            assert r.status == 200 and "tier" in r.body

            r = rc.dispatch(RestRequest(method="GET",
                                        path="/_prometheus/metrics",
                                        params={}))
            assert r.status == 200
            for fam in ("estpu_request_cache_hits_total",
                        "estpu_request_cache_misses_total",
                        "estpu_request_cache_stores_total",
                        "estpu_request_cache_evictions_total",
                        "estpu_request_cache_bytes",
                        "estpu_filter_cache_hits_total",
                        "estpu_filter_cache_builds_total",
                        "estpu_filter_cache_bytes"):
                assert f"# TYPE {fam} " in r.body, fam
        finally:
            cluster.close()

    def test_trace_tags_cache_served_shard(self, tmp_path):
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            rc = build_rest_controller(node)
            rc.dispatch(RestRequest(method="POST", path="/hot/_search",
                                    params={}, body=HOT))
            r = rc.dispatch(RestRequest(method="POST", path="/hot/_search",
                                        params={"trace": "true"}, body=HOT))
            assert r.status == 200

            def walk(n):
                yield n
                for ch in n.get("children", []):
                    yield from walk(ch)

            spans = [s for s in walk(r.body["trace"]["tree"])
                     if s.get("name") == "shard"]
            assert spans, r.body["trace"]
            assert any(s.get("tags", {}).get("request_cache") == "hit"
                       for s in spans), spans
        finally:
            cluster.close()


class TestLiveAffinity:
    def test_replica_affinity_and_hit_rate_piggyback(self, tmp_path):
        """2-node, 1 shard + 1 replica: warmed cache-eligible traffic for ONE
        fingerprint lands on one copy (selections.affinity moves), and the
        piggybacked per-copy request-cache hit rate surfaces in
        /_nodes/stats adaptive_routing."""
        cluster, c = _boot(tmp_path, nodes=2)
        coord = next(iter(cluster.nodes.values()))
        try:
            sel = coord.adaptive_routing
            # warm every copy's stats with DIVERSE eligible traffic (RR)
            for i in range(24):
                c2 = coord.client()
                c2.search("hot", {"query": {"match": {"body": f"beta{i % 4}"}},
                                  "size": 0})
                copies = sel.stats()["copies"]
                if len(copies) >= 2 and all(
                        v["samples"] >= sel.min_samples
                        for v in copies.values()):
                    break
            before = sel.stats()["selections"]["affinity"]
            served = set()
            for _ in range(12):
                coord.client().search("hot", HOT)
            after = sel.stats()
            assert after["selections"]["affinity"] > before
            # the hot fingerprint concentrated on one copy: at most one
            # copy's selected count moved by more than the probe floor
            served = {k: v["selected"] for k, v in after["copies"].items()}
            assert len(served) == 2
            # piggybacked hit rate reported per copy
            assert all("rc_hit_rate" in v for v in after["copies"].values())
            assert any(v["rc_hit_rate"] > 0 for v in
                       after["copies"].values()), after["copies"]
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# lint: the new cache modules stay clean
# ---------------------------------------------------------------------------


def test_cache_modules_scan_clean():
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.tpulint import lint_paths

    paths = [os.path.join(repo, "elasticsearch_tpu", p) for p in (
        "search/request_cache.py", "ops/device_index.py",
        "search/execute.py", "cluster/routing.py", "cluster/stats.py",
        "index/engine.py", "indices_service.py",
    )]
    findings = lint_paths(paths)
    assert not findings, [f.to_dict() for f in findings]
