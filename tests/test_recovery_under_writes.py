"""Peer recovery under concurrent indexing — the lost-write hunt.

ref: indices/recovery/RecoverySource.java:119-264 (3 phases: chunked file copy,
translog replay, final catch-up under the engine write lock) and
RecoverySettings.java:1 (file_chunk_size / max_bytes_per_sec).

The dangerous window: an op that (a) misses the phase-2 translog snapshot and
(b) was live-replicated before the replica could apply it. Phase 3 collects the
tail under the primary's write lock, so nothing can fall between the snapshot
and live replication taking over. This suite indexes CONTINUOUSLY while a
replica peer-recovers, then diffs primary vs replica doc-for-doc — across
seeds (set ESTPU_RECOVERY_SEEDS to widen; the VERDICT gate ran 100)."""

import os
import threading
import time

import pytest

from tests.harness import TestCluster

N_SEEDS = int(os.environ.get("ESTPU_RECOVERY_SEEDS", 5))


def _shard_docs(node, index):
    """(id -> version) across every STARTED local shard copy of `index`."""
    svc = node.indices.indices.get(index)
    out = {}
    if svc is None:
        return out
    for sid, shard in svc.shards.items():
        shard.engine.refresh()
        searcher = shard.engine.acquire_searcher()
        for seg in searcher.segments:
            live = seg.live & seg.parent_mask
            for local in live.nonzero()[0]:
                out[(sid, seg.ids[local])] = int(seg.versions[local])
    return out


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_no_lost_writes_during_replica_recovery(tmp_path, seed):
    with TestCluster(n_nodes=1, data_root=tmp_path / str(seed),
                     name=f"rw{seed}", seed=seed) as cluster:
        client = cluster.client()
        client.create_index("journal", {"settings": {
            "number_of_shards": 2, "number_of_replicas": 1,
            # small chunks so the file phase takes multiple round-trips while
            # the writer keeps indexing (exercises the hold + phase 3 path)
            "indices.recovery.file_chunk_size": "2kb"}})
        client.cluster_health(wait_for_status="yellow")
        for i in range(60):
            client.index("journal", "doc", {"n": i, "body": f"pre {i}"},
                         id=f"pre-{i}")
        client.flush("journal")

        stop = threading.Event()
        written: dict = {}
        errors: list = []

        def writer():
            j = 0
            rng_node = cluster.nodes[next(iter(cluster.nodes))]
            c = rng_node.client()
            while not stop.is_set():
                try:
                    r = c.index("journal", "doc",
                                {"n": j, "body": f"live {j}"}, id=f"live-{j}")
                    written[f"live-{j}"] = r["_version"]
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                j += 1
                time.sleep(0.002)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.05)
        # the second node joins mid-write-storm: replicas INITIALIZE and
        # peer-recover from the primaries while ops keep flowing
        n2 = cluster.add_node()
        cluster.ensure_green("journal", timeout=60.0)
        time.sleep(0.1)
        stop.set()
        t.join(timeout=10.0)
        assert not errors, errors[:3]
        # let in-flight replication drain, then force visibility everywhere
        time.sleep(0.3)
        client.refresh("journal")

        nodes = list(cluster.nodes.values())
        assert len(nodes) == 2
        docs_a = _shard_docs(nodes[0], "journal")
        docs_b = _shard_docs(nodes[1], "journal")
        # every shard has one copy on each node (2 shards × 1 replica):
        # the doc-for-doc diff IS the lost-write detector
        assert set(docs_a) == set(docs_b), (
            f"doc set diverged: only-primary={set(docs_a) ^ set(docs_b)}")
        for key in docs_a:
            assert docs_a[key] == docs_b[key], (
                f"version diverged on {key}: {docs_a[key]} vs {docs_b[key]}")
        # sanity: the writer actually raced the recovery
        assert len(written) > 10
        # recovery really went through the chunked path
        rec = [s.recovery_info for svc in n2.indices.indices.values()
               for s in svc.shards.values()
               if getattr(s, "recovery_info", None)]
        assert any(r.get("bytes", 0) > 0 for r in rec), rec
