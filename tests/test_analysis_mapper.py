"""Analysis chain + document mapper tests."""

import pytest

from elasticsearch_tpu.analysis import AnalysisService, get_analyzer
from elasticsearch_tpu.common.errors import MapperParsingError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.mapper import DocumentMapper, MapperService, parse_date
from elasticsearch_tpu.mapper.core import parse_date_math, parse_ip, format_ip


class TestAnalysis:
    def test_standard_analyzer(self):
        a = get_analyzer("standard")
        assert a.terms("The Quick-Brown Fox, jumped! Over 2 dogs.") == [
            "the", "quick", "brown", "fox", "jumped", "over", "2", "dogs"]

    def test_whitespace_keeps_case_and_punct(self):
        assert get_analyzer("whitespace").terms("Foo BAR-baz") == ["Foo", "BAR-baz"]

    def test_keyword_analyzer(self):
        assert get_analyzer("keyword").terms("New York") == ["New York"]

    def test_stop_analyzer(self):
        assert get_analyzer("stop").terms("the quick fox") == ["quick", "fox"]

    def test_english_stems(self):
        terms = get_analyzer("english").terms("the running dogs jumped")
        assert terms == ["run", "dog", "jump"]

    def test_positions_tracked(self):
        toks = get_analyzer("standard").analyze("alpha beta gamma")
        assert [(t.term, t.position) for t in toks] == [("alpha", 0), ("beta", 1), ("gamma", 2)]

    def test_custom_analyzer_from_settings(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.my.tokenizer": "whitespace",
            "index.analysis.analyzer.my.filter": ["lowercase", "my_stop"],
            "index.analysis.filter.my_stop.type": "stop",
            "index.analysis.filter.my_stop.stopwords": ["foo"],
        }))
        assert svc.analyzer("my").terms("Foo BAR") == ["bar"]

    def test_ngram_and_shingle(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.ng.tokenizer": "standard",
            "index.analysis.analyzer.ng.filter": ["lowercase", "eg"],
            "index.analysis.filter.eg.type": "edge_ngram",
            "index.analysis.filter.eg.min_gram": 2,
            "index.analysis.filter.eg.max_gram": 4,
            "index.analysis.analyzer.sh.tokenizer": "standard",
            "index.analysis.analyzer.sh.filter": ["lowercase", "shingle"],
        }))
        assert svc.analyzer("ng").terms("hello") == ["he", "hel", "hell"]
        assert "quick brown" in svc.analyzer("sh").terms("Quick Brown Fox")

    def test_elision_filter(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.fr.tokenizer": "standard",
            "index.analysis.analyzer.fr.filter": ["lowercase", "el"],
            "index.analysis.filter.el.type": "elision",
            "index.analysis.filter.el.articles": ["l", "d"],
        }))
        assert svc.analyzer("fr").terms("L'avion d'essai") == ["avion", "essai"]

    def test_common_grams_filter(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.cg.tokenizer": "standard",
            "index.analysis.analyzer.cg.filter": ["lowercase", "cg"],
            "index.analysis.filter.cg.type": "common_grams",
            "index.analysis.filter.cg.common_words": ["the", "of"],
        }))
        terms = svc.analyzer("cg").terms("king of spain")
        assert "king_of" in terms and "of_spain" in terms
        assert "king" in terms and "spain" in terms  # unigrams preserved

    def test_stemmer_override_filter(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.so.tokenizer": "standard",
            "index.analysis.analyzer.so.filter": ["lowercase", "so", "porter_stem"],
            "index.analysis.filter.so.type": "stemmer_override",
            "index.analysis.filter.so.rules": ["running => sprint"],
            # no stemmer after the override: the keyword mark must never be indexed
            "index.analysis.analyzer.so2.tokenizer": "standard",
            "index.analysis.analyzer.so2.filter": ["lowercase", "so"],
        }))
        # overridden term bypasses the stemmer; others still stem
        assert svc.analyzer("so").terms("running jumping") == ["sprint", "jump"]
        assert svc.analyzer("so2").terms("running") == ["sprint"]

    def test_common_grams_case_and_query_mode(self):
        svc = AnalysisService(Settings.from_flat({
            # case-sensitive by default: configured words match as-given
            "index.analysis.analyzer.cs.tokenizer": "whitespace",
            "index.analysis.analyzer.cs.filter": ["cs"],
            "index.analysis.filter.cs.type": "common_grams",
            "index.analysis.filter.cs.common_words": ["The"],
            # query_mode: bigram-covered unigrams drop (CommonGramsQueryFilter)
            "index.analysis.analyzer.qm.tokenizer": "whitespace",
            "index.analysis.analyzer.qm.filter": ["lowercase", "qm"],
            "index.analysis.filter.qm.type": "common_grams",
            "index.analysis.filter.qm.common_words": ["of"],
            "index.analysis.filter.qm.query_mode": True,
        }))
        assert "The_cat" in svc.analyzer("cs").terms("The cat")
        # CommonGramsQueryFilter: the final unigram drops when a bigram ends at
        # it, but a MIDDLE unigram that only ends a bigram survives
        assert svc.analyzer("qm").terms("king of spain") == ["king_of", "of_spain"]
        assert svc.analyzer("qm").terms("king of") == ["king_of"]
        assert svc.analyzer("qm").terms("of spain") == ["of_spain"]
        assert svc.analyzer("qm").terms("of") == ["of"]
        assert svc.analyzer("qm").terms("of quick brown") == \
            ["of_quick", "quick", "brown"]

    def test_pattern_capture_filter(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.pc.tokenizer": "whitespace",
            "index.analysis.analyzer.pc.filter": ["lowercase", "pc"],
            "index.analysis.filter.pc.type": "pattern_capture",
            "index.analysis.filter.pc.patterns": ["(\\w+)@(\\w+)"],
        }))
        terms = svc.analyzer("pc").terms("user@example")
        assert set(terms) == {"user@example", "user", "example"}

    def test_synonym_filter(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.syn.tokenizer": "standard",
            "index.analysis.analyzer.syn.filter": ["lowercase", "mysyn"],
            "index.analysis.filter.mysyn.type": "synonym",
            "index.analysis.filter.mysyn.synonyms": ["quick,fast"],
        }))
        assert set(svc.analyzer("syn").terms("quick")) == {"quick", "fast"}

    def test_html_strip(self):
        svc = AnalysisService(Settings.from_flat({
            "index.analysis.analyzer.h.tokenizer": "standard",
            "index.analysis.analyzer.h.char_filter": ["html_strip"],
            "index.analysis.analyzer.h.filter": ["lowercase"],
        }))
        assert svc.analyzer("h").terms("<b>Bold</b> move") == ["bold", "move"]


class TestDates:
    def test_iso(self):
        assert parse_date("2014-01-01") == 1388534400000
        assert parse_date("2014-01-01T12:30:45Z") == 1388579445000
        assert parse_date(1388534400000) == 1388534400000

    def test_date_math(self):
        now = 1388534400000
        assert parse_date_math("now", now) == now
        assert parse_date_math("now-1d", now) == now - 86400_000
        assert parse_date_math("now/d", now + 3600_000) == now

    def test_ip(self):
        assert parse_ip("192.168.1.1") == (192 << 24) | (168 << 16) | (1 << 8) | 1
        assert format_ip(parse_ip("10.0.0.255")) == "10.0.0.255"


class TestMapper:
    def _mapper(self, mapping=None):
        return DocumentMapper("doc", mapping or {}, AnalysisService())

    def test_parse_with_explicit_mapping(self):
        m = self._mapper({"properties": {
            "title": {"type": "string"},
            "tag": {"type": "string", "index": "not_analyzed"},
            "views": {"type": "long"},
            "published": {"type": "date"},
        }})
        doc = m.parse({"title": "Hello World", "tag": "New York", "views": 42,
                       "published": "2014-01-01"}, doc_id="1")
        assert [t for t, _ in doc.postings["title"]] == ["hello", "world"]
        assert doc.postings["tag"] == [("New York", 0)]
        assert doc.doc_values_num["views"] == [42.0]
        assert doc.doc_values_num["published"] == [1388534400000.0]
        assert doc.field_lengths["title"] == 2
        assert doc.uid == "doc#1"
        # _all collects analyzed + keyword terms
        assert "hello" in [t for t, _ in doc.postings["_all"]]

    def test_dynamic_mapping(self):
        m = self._mapper()
        doc = m.parse({"name": "bob", "age": 30, "score": 1.5, "active": True,
                       "joined": "2014-02-03"}, doc_id="1")
        assert m.fields["name"].type == "string"
        assert m.fields["age"].type == "long"
        assert m.fields["score"].type == "double"
        assert m.fields["active"].type == "boolean"
        assert m.fields["joined"].type == "date"
        assert doc.doc_values_num["active"] == [1.0]

    def test_strict_dynamic_raises(self):
        m = self._mapper({"dynamic": "strict", "properties": {"a": {"type": "string"}}})
        with pytest.raises(MapperParsingError):
            m.parse({"a": "x", "b": "boom"}, doc_id="1")

    def test_object_flattening_and_nested(self):
        m = self._mapper({"properties": {
            "user": {"properties": {"name": {"type": "string"}}},
            "comments": {"type": "nested", "properties": {"text": {"type": "string"}}},
        }})
        doc = m.parse({"user": {"name": "alice smith"},
                       "comments": [{"text": "first post"}, {"text": "second"}]}, doc_id="1")
        assert [t for t, _ in doc.postings["user.name"]] == ["alice", "smith"]
        assert len(doc.nested_docs) == 2
        path, sub = doc.nested_docs[0]
        assert path == "comments"
        assert [t for t, _ in sub.postings["comments.text"]] == ["first", "post"]

    def test_multi_value_position_gap(self):
        m = self._mapper({"properties": {"tags": {"type": "string"}}})
        doc = m.parse({"tags": ["alpha beta", "gamma"]}, doc_id="1")
        positions = [p for _, p in doc.postings["tags"]]
        assert positions[0] == 0 and positions[1] == 1
        assert positions[2] > positions[1] + 50  # gap between values

    def test_copy_to(self):
        m = self._mapper({"properties": {
            "first": {"type": "string", "copy_to": "full_name"},
            "last": {"type": "string", "copy_to": "full_name"},
        }})
        doc = m.parse({"first": "john", "last": "doe"}, doc_id="1")
        assert [t for t, _ in doc.postings["full_name"]] == ["john", "doe"]

    def test_merge_conflicts(self):
        m = self._mapper({"properties": {"a": {"type": "string"}}})
        conflicts = m.merge({"properties": {"a": {"type": "long"}}}, simulate=True)
        assert conflicts and "different type" in conflicts[0]

    def test_mapper_service_roundtrip(self):
        svc = MapperService()
        svc.put_mapping("doc", {"properties": {"title": {"type": "string"}}})
        svc.mapper_for("doc").parse({"title": "x", "extra": 5}, doc_id="1")
        out = svc.mappings_dict()
        assert out["doc"]["properties"]["title"]["type"] == "string"
        assert out["doc"]["properties"]["extra"]["type"] == "long"
        assert svc.field_type("extra").type == "long"


class TestNativeTokenizer:
    """The C fast path (native/estpu_native.c) must be token-identical to the Python
    standard chain — it silently accelerates the bulk-index hot path."""

    def test_native_matches_python(self):
        from elasticsearch_tpu.native import get_native

        native = get_native()
        if native is None:
            pytest.skip("C toolchain unavailable")
        texts = [
            "The Quick-Brown Fox, jumped! Over 2 dogs.",
            "rock'n'roll and Bob's burgers",
            "unicode Déjà vu naïve café",
            "",
            "trailing space ",
            "123 456-789",
        ]
        a = get_analyzer("standard")
        for text in texts:
            fast = native.tokenize_batch([text])[0]
            slow = [t.term for t in a.analyze(text)]
            assert fast == slow, text

    def test_native_djb2_matches_python(self):
        from elasticsearch_tpu.cluster.routing import djb2_hash
        from elasticsearch_tpu.native import get_native

        native = get_native()
        if native is None:
            pytest.skip("C toolchain unavailable")
        for s in ("", "a", "doc_12345", "routing-key", "ünïcode"):
            assert native.djb2(s) == djb2_hash(s), s
