"""Termvector + more-like-this APIs (ref: action/termvector/, action/mlt/ — §2.6)."""

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    registry = LocalTransportRegistry()
    n = Node(name="tv_node", registry=registry,
             data_path=str(tmp_path_factory.mktemp("tv_node")))
    n.start([n.local_node.transport_address])
    n.wait_for_master()
    client = n.client()
    client.create_index("tv", {"settings": {"index.number_of_shards": 1}})
    client.index("tv", "doc", {"title": "the quick brown fox fox",
                               "body": "jumps over the lazy dog",
                               "n": 3}, id="1")
    client.index("tv", "doc", {"title": "quick quick red fox"}, id="2")
    client.index("tv", "doc", {"title": "slow green turtle"}, id="3")
    client.refresh("tv")
    yield n, client
    n.close()


class TestTermvector:
    def test_basic_terms_and_freqs(self, node):
        _, client = node
        r = client.termvector("tv", "doc", "1")
        assert r["found"] and r["_id"] == "1"
        terms = r["term_vectors"]["title"]["terms"]
        # ES 1.x standard analyzer keeps stopwords (empty default list)
        assert terms["fox"]["term_freq"] == 2
        assert terms["quick"]["term_freq"] == 1
        assert terms["the"]["term_freq"] == 1
        # positions and offsets present
        tok = terms["quick"]["tokens"][0]
        assert tok["position"] == 1
        assert "start_offset" in tok and "end_offset" in tok

    def test_field_selection(self, node):
        _, client = node
        r = client.termvector("tv", "doc", "1", fields=["body"])
        assert set(r["term_vectors"]) == {"body"}
        assert "lazy" in r["term_vectors"]["body"]["terms"]

    def test_term_and_field_statistics(self, node):
        _, client = node
        r = client.termvector("tv", "doc", "1", term_statistics=True)
        terms = r["term_vectors"]["title"]["terms"]
        assert terms["fox"]["doc_freq"] == 2  # docs 1 and 2
        fs = r["term_vectors"]["title"]["field_statistics"]
        assert fs["doc_count"] == 3

    def test_missing_doc(self, node):
        _, client = node
        r = client.termvector("tv", "doc", "999")
        assert r["found"] is False

    def test_mtermvectors(self, node):
        _, client = node
        r = client.mtermvectors([{"_index": "tv", "_type": "doc", "_id": "1"},
                                 {"_index": "tv", "_type": "doc", "_id": "2"}])
        assert len(r["docs"]) == 2
        assert all(d["found"] for d in r["docs"])


class TestMlt:
    def test_mlt_finds_similar_excludes_self(self, node):
        _, client = node
        r = client.mlt("tv", "doc", "1", min_term_freq=1, min_doc_freq=1)
        ids = [h["_id"] for h in r["hits"]["hits"]]
        assert "1" not in ids
        assert "2" in ids  # shares quick/fox
        assert "3" not in ids  # nothing in common

    def test_mlt_missing_doc_raises(self, node):
        from elasticsearch_tpu.common.errors import DocumentMissingError

        _, client = node
        with pytest.raises(DocumentMissingError):
            client.mlt("tv", "doc", "999")


class TestRestSurface:
    def test_http_termvector_and_mlt(self, node):
        import json
        import urllib.request

        n, _ = node
        server = n.start_http(0)
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/tv/doc/1/_termvector?term_statistics=true") as resp:
            r = json.loads(resp.read())
        assert r["term_vectors"]["title"]["terms"]["fox"]["term_freq"] == 2
        with urllib.request.urlopen(
                base + "/tv/doc/1/_mlt?min_term_freq=1&min_doc_freq=1") as resp:
            r = json.loads(resp.read())
        assert any(h["_id"] == "2" for h in r["hits"]["hits"])
