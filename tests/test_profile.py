"""Search Profile API (common/profile.py, PR 9).

Covers: ProfileCollector units (phase accumulation, additive per-segment
counters, event/reservation caps), the fallback-reason vocabulary
(execute.lower_fallback_reason), the live-cluster acceptance path —
`?profile=true` against a multi-shard cluster returns a merged `profile`
section with per-shard per-segment path/counters/cache attribution, the
explicit batcher bypass (`reason: profile`), precise per-phase device timings
— the mesh path's plan/repack attribution, the real `/_segments` +
`/_cat/segments` views (packed-layout report), the `_cat` table renderer
contract (`?help`, `?v`, `?h=` with aliases), the rewritten two-snapshot
`hot_threads`, tracer ring-eviction counters (+ Prometheus family), the
zero-new-syncs/zero-recompile unprofiled invariant under hard
transfer_guard("disallow"), and a tpulint-clean scan over every instrumented
file."""

import threading
import time

import pytest

from elasticsearch_tpu.common import profile as profiling
from elasticsearch_tpu.common.profile import ProfileCollector
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.common.tracing import Tracer
from elasticsearch_tpu.rest.controller import RestRequest, build_rest_controller

from .harness import TestCluster

WORDS = ["quick", "brown", "fox", "lazy", "dog", "summer", "red", "bear"]


# ---------------------------------------------------------------------------
# collector units
# ---------------------------------------------------------------------------


class TestCollectorUnits:
    def test_current_is_none_off_thread(self):
        assert profiling.current() is None
        prof = ProfileCollector(node="n", index="i", shard=3)
        with profiling.activate(prof):
            assert profiling.current() is prof
        assert profiling.current() is None

    def test_phases_accumulate_and_round(self):
        prof = ProfileCollector()
        prof.phase_s("lower", 0.001)
        prof.phase_s("lower", 0.002)
        d = prof.to_dict()
        assert d["phases_ms"]["lower"] == pytest.approx(3.0, abs=0.01)
        assert d["phases_ms"]["total"] >= 0

    def test_segment_counters_additive_identity_overwrites(self):
        prof = ProfileCollector()
        prof.segment(7, docs=100, path="sparse_composed", blocks_scanned=3,
                     ms=1.0)
        prof.segment(7, docs=100, path="dense_filtered", blocks_scanned=2,
                     ms=0.5)
        (seg,) = prof.to_dict()["segments"]
        assert seg["generation"] == 7
        assert seg["blocks_scanned"] == 5  # additive across launches
        assert seg["ms"] == pytest.approx(1.5, abs=0.01)
        assert seg["docs"] == 100  # identity overwrites, not 200
        assert seg["path"] == "dense_filtered"  # last launch wins

    def test_event_and_reservation_caps(self):
        prof = ProfileCollector()
        for i in range(ProfileCollector.MAX_EVENTS + 5):
            prof.event("scratch", cache="reuse")
        for i in range(ProfileCollector.MAX_RESERVATIONS + 3):
            prof.breaker_reserve("request", "<x>", 10)
        d = prof.to_dict()
        assert len(d["cache"]["events"]) == ProfileCollector.MAX_EVENTS
        assert d["cache"]["dropped"] == 5
        assert len(d["breakers"]["reservations"]) == \
            ProfileCollector.MAX_RESERVATIONS
        assert d["breakers"]["dropped"] == 3
        # the byte total keeps counting past the cap
        assert d["breakers"]["reserved_bytes_total"] == \
            (ProfileCollector.MAX_RESERVATIONS + 3) * 10

    def test_first_writer_wins_for_plan_outcome_fallback(self):
        prof = ProfileCollector()
        prof.outcome("device_sparse")
        prof.outcome("host")
        prof.set_plan({"query_type": "A"})
        prof.set_plan({"query_type": "B"})
        prof.fallback("numeric_term")
        prof.fallback("fuzzy_match")
        d = prof.to_dict()
        assert d["plan"]["outcome"] == "device_sparse"
        assert d["plan"]["query_type"] == "A"
        assert d["plan"]["fallback_reason"] == "numeric_term"


# ---------------------------------------------------------------------------
# fallback-reason vocabulary
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shard_ctx(tmp_path_factory):
    from elasticsearch_tpu.index import Engine
    from elasticsearch_tpu.mapper import MapperService
    from elasticsearch_tpu.search import ShardContext
    from elasticsearch_tpu.search.similarity import SimilarityService

    settings = Settings.from_flat({})
    svc = MapperService(settings)
    svc.put_mapping("doc", {"doc": {"properties": {"n": {"type": "long"}}}})
    e = Engine(str(tmp_path_factory.mktemp("profctx") / "shard0"), svc)
    for i in range(30):
        e.index("doc", str(i),
                {"body": f"{WORDS[i % 8]} {WORDS[(i + 1) % 8]}", "n": i})
    e.refresh()
    return ShardContext(e.acquire_searcher(), svc,
                        SimilarityService(settings, mapper_service=svc))


class TestFallbackReasons:
    def _reason(self, ctx, qdict):
        from elasticsearch_tpu.search import parse_query
        from elasticsearch_tpu.search.execute import (lower_flat,
                                                      lower_fallback_reason)

        q = parse_query(qdict)
        assert lower_flat(q, ctx) is None, "query unexpectedly lowered flat"
        return lower_fallback_reason(q, ctx)

    def test_vocabulary(self, shard_ctx):
        assert self._reason(shard_ctx, {"match_phrase": {"body": "a b"}}) \
            == "unsupported_query:PhraseQuery"
        assert self._reason(shard_ctx, {"term": {"n": 3}}) == "numeric_term"
        assert self._reason(
            shard_ctx, {"match": {"body": {"query": "quik",
                                           "fuzziness": "AUTO"}}}) \
            == "fuzzy_match"
        assert self._reason(
            shard_ctx, {"bool": {"must": [{"term": {"body": "quick"}}],
                                 "filter": {"term": {"body": "fox"}}}}) \
            == "bool_filter_clause"
        assert self._reason(
            shard_ctx, {"bool": {"must": [
                {"match_phrase": {"body": "quick brown"}}]}}) \
            == "non_term_subclause"
        assert self._reason(
            shard_ctx, {"bool": {"must_not": [{"term": {"body": "quick"}}]}}) \
            == "must_not_only"
        assert self._reason(
            shard_ctx, {"function_score": {
                "query": {"match_phrase": {"body": "quick brown"}},
                "functions": [{"weight": 2.0}]}}) == "non_flat_subquery"


# ---------------------------------------------------------------------------
# live cluster: the ?profile=true contract (transport path, 2 nodes)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("profile")
    with TestCluster(n_nodes=2, data_root=tmp, seed=11, settings={
        # profiles must come from the per-shard transport path here; the
        # mesh path has its own fixture below
        "search.mesh.enabled": "false",
    }) as cluster:
        node = next(iter(cluster.nodes.values()))
        client = node.client()
        client.create_index("profiled", {"settings": {
            "number_of_shards": 2, "number_of_replicas": 0}})
        cluster.ensure_green("profiled")
        for i in range(60):
            client.index("profiled", "doc",
                         {"body": f"{WORDS[i % 8]} {WORDS[(i + 1) % 8]}",
                          "n": i},
                         id=str(i))
        client.refresh("profiled")
        rc = build_rest_controller(node)
        yield cluster, node, rc


SEARCH_BODY = {"query": {"match": {"body": "quick brown"}}, "size": 5}


def _search(rc, params=None, body=None):
    return rc.dispatch(RestRequest(
        method="POST", path="/profiled/_search", params=params or {},
        body=dict(body or SEARCH_BODY)))


class TestLiveProfile:
    def test_profile_true_merges_every_shard(self, live):
        _cluster, node, rc = live
        resp = _search(rc, params={"profile": "true"})
        assert resp.status == 200, resp.body
        prof = resp.body.get("profile")
        assert prof is not None and len(prof["shards"]) == 2, resp.body
        for shard in prof["shards"]:
            plan = shard["plan"]
            assert plan["outcome"] == "device_sparse", shard
            assert plan["fallback_reason"] is None
            assert {c["term"] for c in plan["clauses"]} == {"quick", "brown"}
            assert plan["msm"] == 1 and plan["coord"] is True
            # per-segment execution counters + cache attribution
            assert shard["segments"], shard
            for seg in shard["segments"]:
                assert seg["path"] in ("sparse_composed", "sparse_fused")
                assert seg["tf_layout"] == "u8"
                assert seg["blocks_scanned"] >= 1
                assert seg["postings_scanned"] >= 1
                assert seg["staged_bytes"] > 0
            kinds = {(e["kind"], e["cache"])
                     for e in shard["cache"]["events"]}
            assert any(k == "packed_segment" for k, _c in kinds)
            assert any(k == "sim_tables" for k, _c in kinds)
            assert any(k == "scratch" for k, _c in kinds)
            # precise per-phase device attribution (the per-request sync)
            phases = shard["phases_ms"]
            for name in ("parse", "lower", "dispatch", "device", "pull",
                         "merge", "total"):
                assert name in phases and phases[name] >= 0, phases
            # the explicit batcher interaction
            assert shard["batcher"] == {"bypassed": True, "reason": "profile"}
            # breaker attribution: the sparse staging reservation is visible
            labels = {r["label"] for r in
                      shard["breakers"]["reservations"]}
            assert "<sparse_staging>" in labels, labels
        # the two entries are distinct shards
        assert {s["shard"] for s in prof["shards"]} == {0, 1}

    def test_profile_body_flag_equivalent(self, live):
        _cluster, _node, rc = live
        resp = _search(rc, body={**SEARCH_BODY, "profile": True})
        assert resp.status == 200
        assert len(resp.body["profile"]["shards"]) == 2

    def test_unprofiled_has_no_profile_section(self, live):
        _cluster, _node, rc = live
        resp = _search(rc)
        assert resp.status == 200
        assert "profile" not in resp.body

    def test_hits_identical_with_and_without_profile(self, live):
        _cluster, _node, rc = live
        plain = _search(rc).body
        profiled = _search(rc, params={"profile": "true"}).body
        assert profiled["hits"]["total"] == plain["hits"]["total"]
        assert [h["_id"] for h in profiled["hits"]["hits"]] == \
            [h["_id"] for h in plain["hits"]["hits"]]

    def test_host_fallback_reasons(self, live):
        _cluster, _node, rc = live
        # a phrase query never lowers flat — vocabulary reason
        resp = _search(rc, params={"profile": "true"},
                       body={"query": {"match_phrase": {
                           "body": "quick brown"}}})
        for shard in resp.body["profile"]["shards"]:
            assert shard["plan"]["outcome"] == "host"
            assert shard["plan"]["fallback_reason"] == \
                "unsupported_query:PhraseQuery"
            assert any(s["path"] == "host" for s in shard["segments"])
        # a lowerable query forced host by a mask-needing feature
        resp = _search(rc, params={"profile": "true"},
                       body={**SEARCH_BODY, "rescore": {"query": {
                           "rescore_query": {"match": {"body": "fox"}}}}})
        for shard in resp.body["profile"]["shards"]:
            assert shard["plan"]["outcome"] == "host"
            assert shard["plan"]["fallback_reason"] == "features:rescore"

    def test_batcher_counts_profile_bypass(self, live):
        cluster, _node, rc = live
        before = [n.search_batcher.stats()["profile_bypassed"]
                  for n in cluster.nodes.values()]
        resp = _search(rc, params={"profile": "true"})
        assert resp.status == 200
        after = [n.search_batcher.stats()["profile_bypassed"]
                 for n in cluster.nodes.values()]
        assert sum(after) >= sum(before) + 2  # one bypass per shard


# ---------------------------------------------------------------------------
# mesh path: plan/repack attribution
# ---------------------------------------------------------------------------


class TestMeshProfile:
    def test_mesh_profile_attribution(self, tmp_path):
        with TestCluster(n_nodes=1, data_root=tmp_path, seed=5) as cluster:
            node = next(iter(cluster.nodes.values()))
            client = node.client()
            client.create_index("meshed", {"settings": {
                "number_of_shards": 2, "number_of_replicas": 0}})
            cluster.ensure_green("meshed")
            for i in range(40):
                client.index("meshed", "doc",
                             {"body": f"{WORDS[i % 8]} {WORDS[(i + 2) % 8]}"},
                             id=str(i))
            client.refresh("meshed")
            rc = build_rest_controller(node)
            resp = rc.dispatch(RestRequest(
                method="POST", path="/meshed/_search",
                params={"profile": "true"}, body=dict(SEARCH_BODY)))
            assert resp.status == 200, resp.body
            shards = resp.body["profile"]["shards"]
            assert len(shards) == 2
            assert {s["shard"] for s in shards} == {0, 1}
            for shard in shards:
                assert shard["plan"]["outcome"] == "mesh_spmd", shard
                mesh = shard["mesh"]
                assert mesh["shards"] == 2
                assert mesh["tf_layout"] in ("u8", "i16", "f32")
                assert mesh["resident_postings_bytes"] > 0
                assert shard["phases_ms"]["mesh_launch"] > 0
                execs = [e for e in shard["cache"]["events"]
                         if e["kind"] == "mesh_executor"]
                assert execs and execs[0]["cache"] in ("hit", "build")
                # a plain profiled mesh search skips the coalescing queue —
                # recorded exactly like the transport path's bypass
                assert shard["batcher"] == {"bypassed": True,
                                            "reason": "profile"}
            # filtered queries must report the REQUEST's shape (the mesh
            # rebinds to the inner query and applies the filter via masks)
            resp_f = rc.dispatch(RestRequest(
                method="POST", path="/meshed/_search",
                params={"profile": "true"},
                body={"query": {"filtered": {
                    "query": {"match": {"body": "quick brown"}},
                    "filter": {"term": {"body": "fox"}}}}}))
            assert resp_f.status == 200, resp_f.body
            for shard in resp_f.body["profile"]["shards"]:
                assert shard["plan"]["outcome"] == "mesh_spmd", shard
                assert shard["plan"]["query_type"] == "FilteredQuery"
                assert shard["plan"]["filtered"] is True
            # second profiled search hits the cached executor
            resp2 = rc.dispatch(RestRequest(
                method="POST", path="/meshed/_search",
                params={"profile": "true"}, body=dict(SEARCH_BODY)))
            execs = [e for e in
                     resp2.body["profile"]["shards"][0]["cache"]["events"]
                     if e["kind"] == "mesh_executor"]
            assert execs[0]["cache"] == "hit"


# ---------------------------------------------------------------------------
# /_segments + /_cat/segments (+ the _cat renderer contract)
# ---------------------------------------------------------------------------


class TestSegmentsApi:
    def test_segments_reports_packed_layout(self, live):
        cluster, _node, rc = live
        # a device search packs the segments first
        assert _search(rc).status == 200
        # /_segments is node-local (like _stats): union both nodes' views to
        # cover every shard of the 2-node cluster
        seen_shards: set = set()
        seen_packed = 0
        for n in cluster.nodes.values():
            node_rc = build_rest_controller(n)
            resp = node_rc.dispatch(RestRequest(
                method="GET", path="/_segments", params={}))
            assert resp.status == 200
            shards = resp.body["indices"]["profiled"]["shards"]
            # total counts every assigned copy CLUSTER-WIDE while the body is
            # node-local: shards hosted on the other node show up as
            # unreported (total > successful), never as silently complete
            hdr = resp.body["_shards"]
            assert hdr["total"] == 2 and hdr["failed"] == 0, hdr
            assert hdr["successful"] == len(shards), hdr
            seen_shards |= set(shards)
            for copies in shards.values():
                (copy,) = copies
                assert copy["routing"]["primary"] is True
                assert copy["num_search_segments"] == len(copy["segments"])
                for seg in copy["segments"].values():
                    assert seg["num_docs"] > 0
                    assert seg["postings"] > 0
                    assert seg["deleted_docs"] == 0
                    dev = seg["device"]
                    if dev["packed"]:
                        seen_packed += 1
                        assert dev["tf_layout"] == "u8"
                        assert dev["bytes_per_posting"] == 6
                        assert dev["resident_bytes"] > 0
                        assert dev["dense_plane"] in ("lazy", "resident")
                        assert dev["sim_tables"] is None or \
                            isinstance(dev["sim_tables"]["fields"], list)
        assert seen_shards == {"0", "1"}
        # the profiled searches above packed every serving shard copy
        assert seen_packed >= 2
        # index-scoped variant
        scoped = rc.dispatch(RestRequest(
            method="GET", path="/profiled/_segments", params={}))
        assert scoped.status == 200
        assert list(scoped.body["indices"]) == ["profiled"]

    def test_cat_segments_view(self, live):
        _cluster, _node, rc = live
        assert _search(rc).status == 200
        resp = rc.dispatch(RestRequest(method="GET", path="/_cat/segments",
                                       params={"v": ""}))
        assert resp.status == 200
        lines = resp.body.strip().splitlines()
        header, rows = lines[0].split(), lines[1:]
        assert header[:4] == ["index", "shard", "prirep", "segment"]
        assert rows and all(r.split()[0] == "profiled" for r in rows)

    def test_cat_table_renderer_contract(self, live):
        """?help lists columns, ?v adds the header, ?h= selects by name OR
        alias — the shared RestTable contract, exercised on /_cat/segments."""
        _cluster, _node, rc = live
        help_resp = rc.dispatch(RestRequest(
            method="GET", path="/_cat/segments", params={"help": ""}))
        assert help_resp.status == 200
        help_lines = help_resp.body.strip().splitlines()
        assert any(l.startswith("tf.layout | tf |") for l in help_lines), \
            help_lines
        assert all("|" in l for l in help_lines)
        # no ?v: no header row
        plain = rc.dispatch(RestRequest(
            method="GET", path="/_cat/segments", params={}))
        assert not plain.body.startswith("index")
        # ?h= selects columns by ALIAS; unknown names are ignored
        sel = rc.dispatch(RestRequest(
            method="GET", path="/_cat/segments",
            params={"v": "", "h": "i,s,tf,bp,nosuchcol"}))
        header = sel.body.splitlines()[0].split()
        assert header == ["i", "s", "tf", "bp"]
        # selecting by full name works too
        sel2 = rc.dispatch(RestRequest(
            method="GET", path="/_cat/segments",
            params={"v": "", "h": "index,generation"}))
        assert sel2.body.splitlines()[0].split() == ["index", "generation"]


# ---------------------------------------------------------------------------
# hot_threads: two-snapshot sampling
# ---------------------------------------------------------------------------


class TestHotThreads:
    def test_busy_thread_ranks_and_idle_skipped(self, live):
        _cluster, _node, rc = live
        stop = threading.Event()

        def burn():
            x = 0
            while not stop.is_set():
                x = (x * 31 + 7) % 1000003
            return x

        t = threading.Thread(target=burn, name="estpu[hot-burner]",
                             daemon=True)
        t.start()
        try:
            resp = rc.dispatch(RestRequest(
                method="GET", path="/_nodes/hot_threads",
                params={"interval": "250ms", "threads": "4"}))
        finally:
            stop.set()
            t.join(5)
        assert resp.status == 200
        assert resp.content_type.startswith("text/plain")
        assert resp.body.startswith(":::")
        assert "idle/parked skipped" in resp.body
        # the spinning thread must make the busiest list, with real cpu%
        assert "estpu[hot-burner]" in resp.body, resp.body
        burner_line = next(l for l in resp.body.splitlines()
                           if "hot-burner" in l)
        pct = float(burner_line.strip().split("%")[0])
        assert pct > 0.0, burner_line

    def test_threads_param_bounds_report(self, live):
        _cluster, _node, rc = live
        resp = rc.dispatch(RestRequest(
            method="GET", path="/_nodes/hot_threads",
            params={"interval": "50ms", "threads": "1"}))
        assert resp.status == 200
        # exactly one thread entry (lines starting with cpu%)
        entries = [l for l in resp.body.splitlines()
                   if "% cpu usage" in l]
        assert len(entries) <= 1

    def test_bad_interval_is_400(self, live):
        _cluster, _node, rc = live
        resp = rc.dispatch(RestRequest(
            method="GET", path="/_nodes/hot_threads",
            params={"interval": "bogus"}))
        assert resp.status == 400


# ---------------------------------------------------------------------------
# tracer ring observability
# ---------------------------------------------------------------------------


class TestTracerRingStats:
    def test_ring_eviction_counted(self):
        tr = Tracer(Settings.from_flat({"search.trace.ring_size": "2"}),
                    node_name="t")
        tr.sample_rate = 0.0
        for _ in range(5):
            trace = tr.start_trace("rest", force=True)
            trace.root.end()
        st = tr.stats()
        assert st["ring"] == 2
        assert st["finished"] == 5
        assert st["ring_evicted"] == 3
        assert st["late_stitch_dropped"] == 0

    def test_late_stitch_drop_counted(self):
        tr = Tracer(Settings.from_flat({"search.trace.ring_size": "2"}),
                    node_name="t")
        tr.sample_rate = 0.0
        trace = tr.start_trace("rest", force=True)
        root_id = trace.root.span_id
        trace.root.end()
        for _ in range(2):  # evict the first trace
            t2 = tr.start_trace("rest", force=True)
            t2.root.end()
        trace.add_remote([{"id": 9, "parent": root_id, "name": "late",
                           "t0": 0.0, "t1": 0.1, "duration_ms": 100.0,
                           "tags": {}}])
        assert tr.stats()["late_stitch_dropped"] == 1

    def test_prometheus_traces_family(self, live):
        _cluster, _node, rc = live
        resp = rc.dispatch(RestRequest(
            method="GET", path="/_prometheus/metrics", params={}))
        assert resp.status == 200
        for family in ("estpu_traces_sampled_total",
                       "estpu_traces_finished_total",
                       "estpu_traces_in_flight",
                       "estpu_traces_ring_evicted_total",
                       "estpu_traces_late_stitch_dropped_total"):
            assert family in resp.body, family


# ---------------------------------------------------------------------------
# sanitizer: the unprofiled path adds zero syncs / zero recompiles
# ---------------------------------------------------------------------------


class TestUnprofiledSanitized:
    def test_warmed_unprofiled_loop_zero_syncs_zero_recompiles(
            self, tmp_path, monkeypatch):
        """The serving invariant: a warmed UNPROFILED concurrent loop through
        the batcher performs 0 backend compiles under hard
        transfer_guard("disallow") AND never calls the pending handle's
        sync() — the per-request sync belongs exclusively to profiled
        requests, which bypass the batcher and opt in."""
        import jax

        from elasticsearch_tpu.common.jaxenv import sanitize
        from elasticsearch_tpu.index import Engine
        from elasticsearch_tpu.mapper import MapperService
        from elasticsearch_tpu.search import ShardContext, parse_query
        from elasticsearch_tpu.search import execute as execute_mod
        from elasticsearch_tpu.search.batcher import DeviceBatcher
        from elasticsearch_tpu.search.execute import lower_flat
        from elasticsearch_tpu.search.similarity import SimilarityService

        sync_calls = []
        orig_sync = execute_mod._PendingFlat.sync
        monkeypatch.setattr(
            execute_mod._PendingFlat, "sync",
            lambda self: (sync_calls.append(1), orig_sync(self))[1])

        settings = Settings.from_flat({})
        svc = MapperService(settings)
        e = Engine(str(tmp_path / "shard0"), svc)
        for i in range(50):
            e.index("doc", str(i),
                    {"body": f"{WORDS[i % 8]} {WORDS[(i + 2) % 8]}"})
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))
        batcher = DeviceBatcher(Settings.from_flat(
            {"search.batch.linger_ms": "25", "search.batch.max_batch": "8"}))
        texts = ["quick brown", "lazy dog", "red bear", "fox dog"]
        plans = [lower_flat(parse_query({"match": {"body": t}}), ctx)
                 for t in texts]

        def unprofiled_round():
            out = [None] * len(plans)
            errs = [None] * len(plans)

            def worker(i):
                try:
                    out[i] = batcher.execute(plans[i], ctx, 10)
                except Exception as err:  # noqa: BLE001 — assert below
                    errs[i] = err

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(plans))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert all(e2 is None for e2 in errs), errs
            return out

        try:
            warm = unprofiled_round()
            jax.config.update("jax_transfer_guard", "disallow")
            try:
                with sanitize(max_compiles=0, transfers="disallow") as rep:
                    again = unprofiled_round()
            finally:
                jax.config.update("jax_transfer_guard", "allow")
            assert rep.compiles == 0, rep.compile_events
            assert sync_calls == [], "unprofiled serving path called sync()"
            for w, a in zip(warm, again):
                assert a.hits == w.hits and a.total == w.total

            # ...and a PROFILED request of the same plan syncs exactly
            # because it opted in, returning identical results
            prof = ProfileCollector(node="n", index="i", shard=0)
            with profiling.activate(prof):
                from elasticsearch_tpu.search.execute import \
                    execute_flat_batch

                got = execute_flat_batch([plans[0]], ctx, 10)[0]
            assert len(sync_calls) >= 1
            assert got.hits == warm[0].hits and got.total == warm[0].total
            d = prof.to_dict()
            assert d["phases_ms"]["device"] >= 0
            assert d["segments"] and \
                d["segments"][0]["path"].startswith("sparse")
        finally:
            batcher.shutdown()


# ---------------------------------------------------------------------------
# tpulint: the instrumented files stay clean
# ---------------------------------------------------------------------------


def test_profile_files_tpulint_clean():
    """The profiler hooks sit in the device hot path (execute, scoring,
    device_index, mesh serving): every instrumented file must stay free of
    findings so the empty baseline holds."""
    from tools.tpulint import lint_paths

    wanted = {
        "elasticsearch_tpu/common/profile.py",
        "elasticsearch_tpu/common/breaker.py",
        "elasticsearch_tpu/common/tracing.py",
        "elasticsearch_tpu/ops/device_index.py",
        "elasticsearch_tpu/ops/scoring.py",
        "elasticsearch_tpu/search/execute.py",
        "elasticsearch_tpu/search/service.py",
        "elasticsearch_tpu/search/batcher.py",
        "elasticsearch_tpu/search/controller.py",
        "elasticsearch_tpu/parallel/mesh_serving.py",
        "elasticsearch_tpu/actions.py",
        "elasticsearch_tpu/rest/controller.py",
        "elasticsearch_tpu/node.py",
    }
    findings = [f for f in lint_paths(None) if f.path in wanted]
    assert findings == [], [f.to_dict() for f in findings]
