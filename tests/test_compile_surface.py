"""Compile-surface manifest + runtime untagged-compile gate self-tests.

Tier-1 runs this, so CI pins the whole PR-17 contract with no new
infrastructure:

- COVERAGE: an independent AST sweep of the package (not the analyzer's own
  entry enumeration) must agree with tools/compile_surface.json exactly — a
  new jit/shard_map/pallas_call ctor anywhere in elasticsearch_tpu/ that the
  manifest misses fails here;
- DETERMINISM: two consecutive builds are byte-identical, with the parse
  cache cold or hot, and both match the committed file;
- the CLI exit-code contract for `--compile-surface` (0 in-sync / 1 drift /
  2 usage), documented in tools/tpulint/__main__.py;
- the jaxenv runtime half: `_package_origin` frame attribution, the
  `record_untagged_origins` / `untagged_package_origins` accessors, and the
  COMPILE_FAMILIES vocabulary the manifest's `runtime_families` mirrors.
"""

import ast
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from elasticsearch_tpu.common import jaxenv  # noqa: E402
from tools.tpulint import compilesurface as cs  # noqa: E402
from tools.tpulint.engine import clear_parse_cache  # noqa: E402

PKG = os.path.join(REPO, "elasticsearch_tpu")

# the same ctor vocabulary compilesurface.py recognizes — restated here so
# this sweep stays independent of the analyzer's own entry enumeration
_CTOR_NAMES = {"jit", "pjit", "shard_map", "xmap", "pallas_call"}


def _last_name(node):
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _sweep_package_entry_points() -> set:
    """(relpath, line) of every executable-ctor call site in the package,
    found by a plain AST walk — no shared code with the analyzer beyond the
    ctor-name vocabulary."""
    found = set()
    for dirpath, _dirs, names in os.walk(PKG):
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) \
                        and _last_name(node.func) in _CTOR_NAMES:
                    found.add((rel, node.lineno))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if _last_name(dec) in _CTOR_NAMES or (
                                isinstance(dec, ast.Call)
                                and any(_last_name(a) in _CTOR_NAMES
                                        for a in dec.args)):
                            found.add((rel, node.lineno))
    return found


def _committed() -> dict:
    with open(cs.MANIFEST_PATH, encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# coverage: the manifest IS the package's compile surface
# ---------------------------------------------------------------------------


def test_manifest_covers_every_entry_point():
    swept = _sweep_package_entry_points()
    assert swept, "package sweep found no entry points — sweep is broken"
    listed = {(r["file"], r["line"]) for r in _committed()["entry_points"]}
    assert swept == listed, (
        f"manifest/package disagree — missing from manifest: "
        f"{sorted(swept - listed)}; stale in manifest: "
        f"{sorted(listed - swept)}; regenerate with "
        "`python -m tools.tpulint --compile-surface --write`")


def test_every_entry_point_has_a_family():
    man = _committed()
    untagged = [r for r in man["entry_points"] if not r["families"]]
    assert not untagged, [f"{r['file']}:{r['line']}" for r in untagged]
    vocab = set(man["runtime_families"])
    for r in man["entry_points"]:
        assert set(r["families"]) <= vocab, (r["qualname"], r["families"])
        assert "untagged" not in r["families"], r["qualname"]


def test_runtime_vocabulary_matches_jaxenv():
    man = _committed()
    assert set(man["runtime_families"]) == set(jaxenv.COMPILE_FAMILIES)
    assert "untagged" in man["runtime_families"]


# ---------------------------------------------------------------------------
# determinism: committed == rebuilt, cold or hot parse cache
# ---------------------------------------------------------------------------


def test_manifest_deterministic_and_in_sync():
    clear_parse_cache()
    cold = cs.canonical_json(cs.build_manifest())
    hot = cs.canonical_json(cs.build_manifest())
    assert cold == hot, "parse-cache hot/cold builds differ"
    again = cs.canonical_json(cs.build_manifest())
    assert hot == again, "two consecutive builds differ"
    assert cs.load_committed() == cold, (
        "tools/compile_surface.json is stale — regenerate with "
        "`python -m tools.tpulint --compile-surface --write`")


# ---------------------------------------------------------------------------
# CLI exit-code contract
# ---------------------------------------------------------------------------


def _cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *argv],
        cwd=REPO, capture_output=True, text=True)


def test_cli_in_sync_exits_zero():
    p = _cli("--compile-surface")
    assert p.returncode == 0, p.stderr
    assert "in sync" in p.stderr


def test_cli_json_prints_canonical_manifest():
    p = _cli("--compile-surface", "--json")
    assert p.returncode == 0, p.stderr
    assert p.stdout == cs.load_committed()
    assert json.loads(p.stdout)["version"] == 1


def test_cli_drift_exits_one():
    with open(cs.MANIFEST_PATH, encoding="utf-8") as f:
        saved = f.read()
    try:
        with open(cs.MANIFEST_PATH, "w", encoding="utf-8") as f:
            f.write(saved.replace('"version": 1', '"version": 0'))
        p = _cli("--compile-surface")
        assert p.returncode == 1, (p.returncode, p.stderr)
        assert "DRIFT" in p.stderr
    finally:
        with open(cs.MANIFEST_PATH, "w", encoding="utf-8") as f:
            f.write(saved)


def test_cli_usage_errors_exit_two():
    assert _cli("--write").returncode == 2
    assert _cli("--compile-surface", "--check").returncode == 2
    assert _cli("--compile-surface", "elasticsearch_tpu").returncode == 2
    assert _cli("--compile-surface", "--update-baseline").returncode == 2


# ---------------------------------------------------------------------------
# the runtime half: package-origin attribution for untagged compiles
# ---------------------------------------------------------------------------


def _fake_package_fn(body: str, relname: str):
    """Compile `body` (a function named probe) under a filename inside a
    fictitious elasticsearch_tpu/ tree, so its frames read as package frames
    to jaxenv._package_origin."""
    path = os.path.join(os.sep + "nonexistent", "elasticsearch_tpu", relname)
    ns: dict = {}
    exec(compile(body, path, "exec"), ns)
    return ns["probe"]


def test_package_origin_sees_package_frames_only():
    # a test frame has no elasticsearch_tpu/ path component -> None
    assert jaxenv._package_origin() is None
    probe = _fake_package_fn(
        "from elasticsearch_tpu.common import jaxenv\n"
        "def probe():\n"
        "    return jaxenv._package_origin()\n",
        os.path.join("ops", "fake_probe.py"))
    assert probe() == "elasticsearch_tpu/ops/fake_probe.py:3"


def test_untagged_package_compile_is_attributed_and_capped():
    """An eager jnp launch from a (fake) package frame, outside every
    compile_tag scope, lands in untagged_package_origins under its
    package-relative site; a tagged launch does not. White-box cleanup keeps
    the session-scoped conftest gate green."""
    probe = _fake_package_fn(
        "import jax.numpy as jnp\n"
        "def probe(n, tag):\n"
        "    from elasticsearch_tpu.common.jaxenv import compile_tag\n"
        "    if tag is None:\n"
        "        return jnp.arange(n, dtype=jnp.float32) * 3.0\n"
        "    with compile_tag(tag):\n"
        "        return jnp.arange(n, dtype=jnp.float32) * 3.0\n",
        os.path.join("ops", "fake_untagged.py"))
    jaxenv.record_untagged_origins(True)
    before = jaxenv.untagged_package_origins()
    try:
        probe(733, None)  # unique shape: forces a fresh executable
        after = jaxenv.untagged_package_origins()
        new = {k: v for k, v in after.items() if k not in before}
        assert any(k.startswith("elasticsearch_tpu/ops/fake_untagged.py:")
                   for k in new), (before, after)
        probe(737, "pack")  # tagged: attributed to the family, no origin
        after2 = jaxenv.untagged_package_origins()
        assert {k: v for k, v in after2.items() if k not in after} == {}
        assert jaxenv.compile_events_by_family().get("pack", 0) >= 1
    finally:
        # scrub the fabricated origins so the session gate stays meaningful
        with jaxenv._counter._lock:
            for k in list(jaxenv._counter.untagged_origins):
                if k.startswith("elasticsearch_tpu/ops/fake_untagged.py:"):
                    del jaxenv._counter.untagged_origins[k]


def test_origin_dict_is_capped():
    assert jaxenv._ORIGIN_CAP == 64
    # the recording branch refuses NEW keys at the cap but keeps counting
    # existing ones — sanity-check the guard expression directly
    d = {f"elasticsearch_tpu/x.py:{i}": 1 for i in range(jaxenv._ORIGIN_CAP)}
    assert not ("elasticsearch_tpu/y.py:1" in d
                or len(d) < jaxenv._ORIGIN_CAP)
    assert ("elasticsearch_tpu/x.py:0" in d
            or len(d) < jaxenv._ORIGIN_CAP)


def test_scalar_f32_idiom_is_committed():
    """The TPU021 fix idiom: jax.device_put(np.float32(x)) produces a
    committed float32, not a weak-typed scalar — the dtype family every
    call site of a shared executable should agree on."""
    import jax

    v = jax.device_put(np.float32(0.5))
    assert v.dtype == np.float32
    assert not getattr(v, "weak_type", False)
