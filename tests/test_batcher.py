"""Cross-request device micro-batching (search/batcher.py).

Covers the flush triad (full / linger / deadline-leaves-merge-budget), fan-out
ordering parity with per-request execution, the breaker-split rule (a trip
inside a coalesced launch fails ONLY the oversized request), the
staging-scratch pool (a warmed repeat batch performs 0 new host allocations
and the request breaker drains to 0), mesh coalescing through a live cluster,
and the serving invariant: a WARMED concurrent serving loop through the
batcher neither recompiles nor implicitly transfers under
transfer_guard("disallow")."""

import threading
import time

import pytest

from elasticsearch_tpu.common.breaker import CircuitBreakerService
from elasticsearch_tpu.common.deadline import NO_DEADLINE, Deadline
from elasticsearch_tpu.common.errors import CircuitBreakingError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query
from elasticsearch_tpu.search.batcher import DeviceBatcher, _Item, _k_bucket
from elasticsearch_tpu.search.execute import execute_flat_batch, lower_flat
from elasticsearch_tpu.search.similarity import SimilarityService

pytestmark = pytest.mark.serving

WORDS = ["quick", "brown", "fox", "lazy", "dog", "summer", "red", "bear",
         "snack", "cat"]


@pytest.fixture
def shard_ctx(tmp_path):
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    e = Engine(str(tmp_path / "shard0"), svc)
    for i in range(60):
        text = f"{WORDS[i % 10]} {WORDS[(i + 1) % 10]} {WORDS[(i + 3) % 10]}"
        e.index("doc", str(i), {"body": text})
    e.refresh()
    return ShardContext(e.acquire_searcher(), svc,
                        SimilarityService(settings, mapper_service=svc))


def make_batcher(**flat):
    return DeviceBatcher(Settings.from_flat(
        {str(k): str(v) for k, v in flat.items()}))


def plan_for(ctx, text):
    plan = lower_flat(parse_query({"match": {"body": text}}), ctx)
    assert plan is not None
    return plan


def run_concurrent(batcher, ctx, texts, k=10, deadline=None):
    """Submit one plan per text from its own thread; returns TopDocs per text."""
    plans = [plan_for(ctx, t) for t in texts]
    out = [None] * len(plans)
    errs = [None] * len(plans)

    def worker(i):
        try:
            out[i] = batcher.execute(plans[i], ctx, k,
                                     deadline=deadline or NO_DEADLINE)
        except Exception as e:  # noqa: BLE001 — surfaced by the assert below
            errs[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(plans))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert all(e is None for e in errs), errs
    return out


# ---------------------------------------------------------------------------
# flush triggers
# ---------------------------------------------------------------------------


class TestFlushTriggers:
    def test_flush_on_full(self, shard_ctx):
        # linger far beyond the test horizon: only batch-full can flush
        b = make_batcher(**{"search.batch.linger_ms": 5000,
                            "search.batch.max_batch": 4})
        try:
            texts = ["quick brown", "lazy dog", "red bear", "summer snack"]
            out = run_concurrent(b, shard_ctx, texts)
            assert all(td is not None for td in out)
            st = b.stats()
            assert st["full_flushes"] >= 1, st
            assert st["coalesced"] == 4 and st["launches"] >= 1
        finally:
            b.shutdown()

    def test_flush_on_linger(self, shard_ctx):
        b = make_batcher(**{"search.batch.linger_ms": 40,
                            "search.batch.max_batch": 64})
        try:
            t0 = time.monotonic()
            out = run_concurrent(b, shard_ctx, ["quick brown", "lazy dog"])
            elapsed = time.monotonic() - t0
            assert all(td is not None for td in out)
            st = b.stats()
            assert st["linger_flushes"] >= 1, st
            # nothing else could flush a 2-item batch below max_batch=64
            assert st["full_flushes"] == 0 and st["deadline_flushes"] == 0
            assert elapsed < 20.0
        finally:
            b.shutdown()

    def test_flush_on_deadline_leaves_merge_budget(self, shard_ctx):
        # warm the executable cache first so the flush timing, not a cold XLA
        # compile, dominates the measured latency
        warm_plan = plan_for(shard_ctx, "quick brown")
        execute_flat_batch([warm_plan], shard_ctx, _k_bucket(10))
        # linger 10s: only the deadline flush can release the batch
        b = make_batcher(**{"search.batch.linger_ms": 10_000,
                            "search.batch.max_batch": 64})
        try:
            budget_s = 0.4
            t0 = time.monotonic()
            td = b.execute(warm_plan, shard_ctx, 10,
                           deadline=Deadline.after(budget_s))
            elapsed = time.monotonic() - t0
            assert td.total > 0
            st = b.stats()
            assert st["deadline_flushes"] == 1, st
            # flushed at deadline - EWMA(batch service): the answer lands
            # BEFORE the budget expires (launch + merge fit in what was left),
            # and the batch demonstrably waited (didn't flush immediately)
            assert elapsed < budget_s + 0.25, elapsed
            assert elapsed > 0.05, elapsed
        finally:
            b.shutdown()

    def test_lone_request_pays_at_most_linger(self, shard_ctx):
        plan = plan_for(shard_ctx, "quick brown")
        execute_flat_batch([plan], shard_ctx, _k_bucket(10))  # warm
        t0 = time.monotonic()
        direct = execute_flat_batch([plan], shard_ctx, 10)[0]
        direct_s = time.monotonic() - t0
        linger_s = 0.05
        b = make_batcher(**{"search.batch.linger_ms": linger_s * 1000})
        try:
            t0 = time.monotonic()
            td = b.execute(plan, shard_ctx, 10)
            batched_s = time.monotonic() - t0
            assert td.hits == direct.hits[:10]
            # a lone request pays at most the linger (plus scheduling slack)
            assert batched_s <= direct_s + linger_s + 0.5, (batched_s, direct_s)
        finally:
            b.shutdown()


# ---------------------------------------------------------------------------
# fan-out correctness
# ---------------------------------------------------------------------------


class TestFanOut:
    def test_fanout_matches_per_request_ordering(self, shard_ctx):
        texts = ["quick brown", "lazy dog", "red bear", "summer snack",
                 "fox dog", "cat bear"]
        b = make_batcher(**{"search.batch.linger_ms": 60,
                            "search.batch.max_batch": 8})
        try:
            out = run_concurrent(b, shard_ctx, texts, k=10)
        finally:
            b.shutdown()
        for text, td in zip(texts, out):
            plan = plan_for(shard_ctx, text)
            direct = execute_flat_batch([plan], shard_ctx, 10)[0]
            assert td.total == direct.total, text
            assert td.hits == direct.hits[:10], text
            assert (td.max_score == direct.max_score
                    or (td.max_score != td.max_score
                        and direct.max_score != direct.max_score)), text

    def test_post_shutdown_serves_inline(self, shard_ctx):
        b = make_batcher(**{"search.batch.linger_ms": 20})
        plan = plan_for(shard_ctx, "quick brown")
        assert b.execute(plan, shard_ctx, 5).total > 0
        b.shutdown()
        # a shut-down batcher must not strand searches — they serve directly
        td = b.execute(plan, shard_ctx, 5)
        assert td.total > 0
        assert b.stats()["bypassed"] >= 1


# ---------------------------------------------------------------------------
# breaker split: a trip inside a coalesced launch fails only the oversized item
# ---------------------------------------------------------------------------


class _TrippingFamily:
    """Batch dispatch always trips the breaker; individually only the marked
    payload does — the exact shape of one oversized request coalesced with
    healthy neighbors."""

    name = "fake"

    def dispatch(self, items, kb):
        raise CircuitBreakingError(
            "[request] coalesced batch would exceed the limit")

    def fan_out(self, handle, items):  # pragma: no cover — dispatch raises
        raise AssertionError("unreachable")

    def execute_single(self, item):
        if item.payload == "oversized":
            err = CircuitBreakingError("[request] data would be larger than limit")
            err.breaker = "request"
            raise err
        return f"ok:{item.payload}"


class TestBreakerSplit:
    def test_trip_fails_only_the_oversized_request(self):
        b = make_batcher(**{"search.batch.linger_ms": 5000,
                            "search.batch.max_batch": 3})
        fam = _TrippingFamily()
        try:
            payloads = ["a", "oversized", "b"]
            out = [None] * 3
            errs = [None] * 3

            def worker(i):
                item = _Item(fam, ("fake", "key"), payloads[i], 10, 16,
                             NO_DEADLINE)
                try:
                    out[i] = b._submit(item)
                except Exception as e:  # noqa: BLE001
                    errs[i] = e

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert out[0] == "ok:a" and out[2] == "ok:b", (out, errs)
            assert isinstance(errs[1], CircuitBreakingError), errs
            assert errs[0] is None and errs[2] is None
            assert b.stats()["splits"] == 1
        finally:
            b.shutdown()


# ---------------------------------------------------------------------------
# staging scratch pool (satellite bugfix): warmed repeat = 0 new allocations
# ---------------------------------------------------------------------------


class TestStagingScratch:
    def test_warmed_repeat_batch_zero_new_host_allocations(self, shard_ctx):
        from elasticsearch_tpu.ops.device_index import packed_for

        # wire real breakers so the staging reserve rides the accounting path
        breakers = CircuitBreakerService(Settings.from_flat({}))
        shard_ctx.breakers = breakers
        plans = [plan_for(shard_ctx, t) for t in
                 ("quick brown", "lazy dog", "red bear")]
        execute_flat_batch(plans, shard_ctx, 10)  # warm: pools fill here
        seg = shard_ctx.searcher.segments[0]
        pool = packed_for(seg).sparse_scratch
        assert pool is not None and pool.allocs >= 1
        allocs_before = pool.allocs
        for _ in range(3):  # warmed repeats re-pad pooled arrays in place
            execute_flat_batch(plans, shard_ctx, 10)
        assert pool.allocs == allocs_before, (
            f"warmed repeat batch allocated {pool.allocs - allocs_before} new "
            "staging arrays — the scratch pool regressed")
        assert pool.reuses >= 3
        # transient accounting: the per-batch staging reservation fully drains
        assert breakers.breaker("request").stats()["estimated"] == 0

    def test_results_identical_with_and_without_pool_reuse(self, shard_ctx):
        plans = [plan_for(shard_ctx, t) for t in ("quick brown", "fox dog")]
        first = execute_flat_batch(plans, shard_ctx, 10)
        again = execute_flat_batch(plans, shard_ctx, 10)  # pooled arrays
        for a, c in zip(first, again):
            assert a.hits == c.hits and a.total == c.total


# ---------------------------------------------------------------------------
# mesh path rides the same queue
# ---------------------------------------------------------------------------


class TestMeshCoalescing:
    def test_concurrent_mesh_searches_coalesce(self, tmp_path):
        from tests.harness import TestCluster

        with TestCluster(n_nodes=1, data_root=tmp_path, seed=7) as cluster:
            node = next(iter(cluster.nodes.values()))
            c = node.client()
            c.create_index("meshidx", {"settings": {
                "number_of_shards": 2, "number_of_replicas": 0}})
            cluster.ensure_green("meshidx")
            for i in range(40):
                c.index("meshidx", "doc",
                        {"body": f"{WORDS[i % 10]} {WORDS[(i + 2) % 10]}"},
                        id=str(i))
            c.refresh("meshidx")
            body = {"query": {"match": {"body": "quick brown"}}}
            expected = c.search("meshidx", body)  # warm + reference answer
            assert node.actions.mesh_serving.mesh_queries >= 1
            st0 = node.search_batcher.stats()
            out = [None] * 8

            def worker(i):
                out[i] = c.search("meshidx", body)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            for r in out:
                assert r["hits"]["total"] == expected["hits"]["total"]
                assert ([h["_id"] for h in r["hits"]["hits"]]
                        == [h["_id"] for h in expected["hits"]["hits"]])
            st1 = node.search_batcher.stats()
            served = st1["coalesced"] - st0["coalesced"]
            launches = st1["launches"] - st0["launches"]
            assert served == 8, (st0, st1)
            # coalescing happened: fewer launches than requests
            assert launches < served, (st0, st1)


# ---------------------------------------------------------------------------
# serving invariant: warmed concurrent loop = 0 recompiles, no implicit pulls
# ---------------------------------------------------------------------------


class TestSanitized:
    def test_warmed_concurrent_loop_zero_recompiles(self, shard_ctx):
        import jax

        from elasticsearch_tpu.common.jaxenv import sanitize

        texts = ["quick brown", "lazy dog", "red bear", "summer snack",
                 "fox dog", "cat bear", "quick fox", "brown dog"]
        b = make_batcher(**{"search.batch.linger_ms": 30,
                            "search.batch.max_batch": 8})
        try:
            warm = run_concurrent(b, shard_ctx, texts, k=10)
            # the transfer guard context is thread-local; the drainer thread
            # needs the GLOBAL config so its dispatch half is guarded too
            jax.config.update("jax_transfer_guard", "disallow")
            try:
                with sanitize(max_compiles=0, transfers="disallow") as rep:
                    again = run_concurrent(b, shard_ctx, texts, k=10)
            finally:
                jax.config.update("jax_transfer_guard", "allow")
            assert rep.compiles == 0, rep.compile_events
            for w, a in zip(warm, again):
                assert a.hits == w.hits and a.total == w.total
        finally:
            b.shutdown()

    def test_batcher_module_tpulint_clean(self):
        """search/batcher.py is a registered hot-path file: the dispatch half
        must stay free of implicit pulls so the baseline stays empty."""
        from tools.tpulint import lint_paths
        from tools.tpulint.engine import HOT_FILES

        assert "elasticsearch_tpu/search/batcher.py" in HOT_FILES
        findings = [f for f in lint_paths(None)
                    if f.path == "elasticsearch_tpu/search/batcher.py"]
        assert findings == [], [f.to_dict() for f in findings]


# ---------------------------------------------------------------------------
# pending-merge flush (PR 6): batch N's merge must not wait out batch N+1's
# linger window
# ---------------------------------------------------------------------------


class TestPendingMergeFlush:
    def test_merge_not_delayed_by_next_batch_linger(self, shard_ctx):
        """With batch N dispatched and awaiting its merge, the collector must
        flush the queue IMMEDIATELY (reason `pending`) instead of lingering
        for batch N+1 — before the fix, batch N's already-answered futures
        waited out the full linger window behind the next batch's collect.

        Giant linger (1.5 s floor 1.2 s) makes the two behaviors unambiguous:
        old code cannot finish 3 requests under ~1.2 s, fixed code finishes in
        launch time. The pending window depends on thread scheduling, so the
        attempt retries; the old behavior can never pass any attempt (a lone
        third item always pays the full linger)."""
        from elasticsearch_tpu.search.execute import execute_flat_batch

        b = make_batcher(**{"search.batch.linger_ms": 1500,
                            "search.batch.min_linger_ms": 1200,
                            "search.batch.max_batch": 2})
        try:
            texts = ["quick brown", "lazy dog", "red bear"]
            plans = [plan_for(shard_ctx, t) for t in texts]
            # warm BOTH drainer shapes (Q=2 batch, Q=1 batch) at the k bucket
            # the batcher will use, so the timed runs measure flush policy,
            # not XLA compiles
            kb = _k_bucket(10)
            execute_flat_batch(plans[:2], shard_ctx, kb)
            execute_flat_batch(plans[2:], shard_ctx, kb)
            ok = False
            for _attempt in range(3):
                t0 = time.monotonic()
                out = run_concurrent(b, shard_ctx, texts)
                elapsed = time.monotonic() - t0
                assert all(td is not None for td in out)
                if elapsed < 0.8 and b.stats()["pending_flushes"] >= 1:
                    ok = True
                    break
            assert ok, (elapsed, b.stats())
        finally:
            b.shutdown()
