"""Completion suggester (weighted trie, fuzzy) + FVH highlighter."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext
from elasticsearch_tpu.search.suggest import CompletionIndex, run_suggest


class TestCompletionTrie:
    def setup_method(self):
        self.c = CompletionIndex()
        for t, w in [("nirvana", 10), ("nevermind", 8), ("nine inch nails", 9),
                     ("nina simone", 7), ("queen", 5), ("nirvana live", 6)]:
            self.c.add(t, t, w)

    def test_prefix_topk_by_weight(self):
        opts = self.c.suggest("ni", 3)
        assert [o["text"] for o in opts] == ["nirvana", "nine inch nails",
                                            "nina simone"]
        assert [o["score"] for o in opts] == [10.0, 9.0, 7.0]

    def test_size_limits(self):
        assert len(self.c.suggest("n", 2)) == 2

    def test_no_match(self):
        assert self.c.suggest("xyz", 5) == []

    def test_empty_prefix_returns_global_topk(self):
        opts = self.c.suggest("", 2)
        assert [o["text"] for o in opts] == ["nirvana", "nine inch nails"]

    def test_fuzzy_one_edit(self):
        opts = self.c.suggest("nevermnd", 3, fuzzy={"fuzziness": 1})
        assert [o["text"] for o in opts] == ["nevermind"]

    def test_fuzzy_prefix_length_guard(self):
        # first char must match exactly with prefix_length=1
        assert self.c.suggest("xevermind", 3, fuzzy={"fuzziness": 1}) == []

    def test_fuzzy_auto(self):
        opts = self.c.suggest("nirvana", 3, fuzzy={"fuzziness": "AUTO"})
        assert opts[0]["text"] == "nirvana"

    def test_dedup_outputs(self):
        c = CompletionIndex()
        c.add("foo bar", "foo", 5)
        c.add("foo baz", "foo", 3)
        opts = c.suggest("foo", 5)
        assert len(opts) == 1 and opts[0]["score"] == 5.0


@pytest.fixture()
def engine_ctx(tmp_path):
    svc = MapperService(Settings.EMPTY)
    svc.put_mapping("song", {"properties": {
        "suggest": {"type": "completion"},
        "title": {"type": "string"},
        "body": {"type": "string"}}})
    e = Engine(str(tmp_path / "s"), svc)
    e.index("song", "1", {"suggest": {"input": ["Nirvana", "Nevermind"],
                                      "output": "Nirvana - Nevermind",
                                      "weight": 34, "payload": {"id": 1}},
                          "title": "Nevermind"})
    e.index("song", "2", {"suggest": "Nine Inch Nails", "title": "NIN"})
    e.refresh()
    e.index("song", "3", {"suggest": {"input": "Nina Simone", "weight": 50}})
    e.refresh()  # second segment: exercises cross-segment merge
    yield ShardContext(e.acquire_searcher(), svc)
    e.close()


class TestCompletionField:
    def test_multi_input_payload(self, engine_ctx):
        r = run_suggest(engine_ctx, {"s": {"text": "nev",
                                           "completion": {"field": "suggest"}}})
        opts = r["s"][0]["options"]
        assert opts[0]["text"] == "Nirvana - Nevermind"
        assert opts[0]["payload"] == {"id": 1}

    def test_cross_segment_weight_order(self, engine_ctx):
        r = run_suggest(engine_ctx, {"s": {"text": "ni",
                                           "completion": {"field": "suggest"}}})
        opts = r["s"][0]["options"]
        assert [o["text"] for o in opts] == ["Nina Simone", "Nirvana - Nevermind",
                                            "Nine Inch Nails"]

    def test_fuzzy_through_api(self, engine_ctx):
        r = run_suggest(engine_ctx, {"s": {"text": "nrvana", "completion": {
            "field": "suggest", "fuzzy": {"fuzziness": 1, "prefix_length": 1}}}})
        assert r["s"][0]["options"][0]["text"] == "Nirvana - Nevermind"


class TestFvhHighlight:
    def _search(self, tmp_path, body):
        from elasticsearch_tpu.search.service import execute_query_phase, \
            execute_fetch_phase, parse_search_body

        svc = MapperService(Settings.EMPTY)
        e = Engine(str(tmp_path / "h"), svc)
        e.index("doc", "1", {
            "body": "The quick brown fox. A lazy dog sleeps here. "
                    "Quick thinking saves the quick brown fox again."})
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc)
        req = parse_search_body(body)
        qr = execute_query_phase(ctx, req, shard_id=0)
        return execute_fetch_phase(ctx, req, qr.docs)

    def test_phrase_highlighted_as_unit(self, tmp_path):
        hits = self._search(tmp_path, {
            "query": {"match_phrase": {"body": "quick brown fox"}},
            "highlight": {"type": "fvh", "fields": {"body": {}}}})
        frags = hits[0]["highlight"]["body"]
        joined = " ".join(frags)
        assert "<em>quick brown fox</em>" in joined.lower().replace(
            "<em>quick</em> <em>brown</em> <em>fox</em>", "MULTI")
        assert "MULTI" not in joined

    def test_fragment_scoring_prefers_denser(self, tmp_path):
        hits = self._search(tmp_path, {
            "query": {"match": {"body": "quick"}},
            "highlight": {"type": "fvh",
                          "fields": {"body": {"fragment_size": 45,
                                              "number_of_fragments": 1}}}})
        frag = hits[0]["highlight"]["body"][0]
        # the densest window has two "quick"s
        assert frag.lower().count("<em>quick</em>") >= 2

    def test_plain_still_works(self, tmp_path):
        hits = self._search(tmp_path, {
            "query": {"match": {"body": "fox"}},
            "highlight": {"fields": {"body": {}}}})
        assert any("<em>fox</em>" in f.lower()
                   for f in hits[0]["highlight"]["body"])
