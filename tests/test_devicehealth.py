"""Device fault domains (common/devicehealth): classification, per-domain
circuits with probed recovery, and the seeded device-chaos invariant.

The pinned invariant (ISSUE 18): with a persistent device fault armed on a
serving domain, every search keeps returning 200 with bitwise-identical hits
(the host scorer is the same math), the domain trips within its strike budget,
`_shards.degraded` stays honest, the degraded window compiles nothing and
packs nothing on the query path, and disarming the fault recovers the domain
through the half-open probe protocol — with matching journal events.

ref: the containment stance mirrors how the reference engine treats a shard
copy (per-copy failures in `_shards`, failover instead of 500s); here the
accelerator itself is the failing copy."""

import random
import threading
import time

import pytest

from elasticsearch_tpu.common.devicehealth import (CLOSED, HALF_OPEN, OPEN,
                                                   DEVICE_HEALTH, DeviceHealth,
                                                   classify_device_error,
                                                   tag_domain)
from elasticsearch_tpu.common.retry import is_transient
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.faults import (DEVICE_ERROR_KINDS,
                                                DEVICE_FAULTS, DeviceFaults,
                                                make_device_error)
from elasticsearch_tpu.transport.local import LocalTransportRegistry

pytestmark = pytest.mark.device

VOCAB = ("alpha beta gamma delta epsilon zeta eta theta iota kappa lamda mu "
         "nu xi omicron pi rho sigma tau upsilon phi chi psi omega").split()


@pytest.fixture(autouse=True)
def _device_state_hygiene():
    """The health tracker and fault injector are process-wide singletons —
    every test starts and ends with closed circuits and disarmed faults."""
    DEVICE_FAULTS.disarm()
    DEVICE_HEALTH.reset()
    yield
    DEVICE_FAULTS.disarm()
    DEVICE_HEALTH.reset()


# ---------------------------------------------------------------------------
# unit: classification + tagging
# ---------------------------------------------------------------------------

class TestClassification:
    def test_taxonomy(self):
        expected = {"oom": "transient", "timeout": "transient",
                    "unavailable": "transient", "launch": "persistent",
                    "transfer": "persistent", "internal": "persistent"}
        for kind in DEVICE_ERROR_KINDS:
            got = classify_device_error(make_device_error(kind))
            assert got == expected[kind], (kind, got)

    def test_host_errors_never_classify(self):
        # a host-side bug must not quarantine the accelerator, even when the
        # message mimics an XLA status prefix
        for e in (ValueError("INTERNAL: not actually xla"),
                  KeyError("x"), TimeoutError("deadline")):
            assert classify_device_error(e) is None

    def test_retry_is_transient_learns_the_taxonomy(self):
        assert is_transient(make_device_error("oom")) is True
        assert is_transient(make_device_error("unavailable")) is True
        assert is_transient(make_device_error("launch")) is False
        assert is_transient(make_device_error("transfer")) is False

    def test_tag_domain_first_tag_wins(self):
        e = make_device_error("oom")
        assert tag_domain(e, "pull:a") is e  # returns the error for re-raise
        tag_domain(e, "mesh:b")
        assert e._estpu_device_domain == "pull:a"


# ---------------------------------------------------------------------------
# unit: circuit lifecycle (injected clock + rng — no sleeps)
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _fresh_health():
    clock = _FakeClock()
    dh = DeviceHealth(base_s=0.05, cap_s=5.0, rng=random.Random(7),
                      clock=clock)
    events = []
    dh.register_publisher("t", lambda type_, message, **kw:
                          events.append((type_, kw)))
    return dh, clock, events


class TestCircuit:
    def test_transient_strike_budget(self):
        dh, clock, events = _fresh_health()
        for _ in range(DeviceHealth.TRANSIENT_STRIKES - 1):
            assert dh.record_failure("pull:i", make_device_error("oom")) \
                == "transient"
        assert dh.state("pull:i") == CLOSED and not dh.any_open
        dh.record_failure("pull:i", make_device_error("oom"))
        assert dh.state("pull:i") == OPEN and dh.any_open
        assert [t for t, _ in events] == ["device_degraded"]
        assert events[0][1]["domain"] == "pull:i"

    def test_success_resets_closed_strikes(self):
        dh, clock, _ = _fresh_health()
        dh.record_failure("pull:i", make_device_error("oom"))
        dh.record_failure("pull:i", make_device_error("oom"))
        dh.note_success(("pull:i",))
        dh.record_failure("pull:i", make_device_error("oom"))
        assert dh.state("pull:i") == CLOSED  # strikes restarted from zero

    def test_persistent_trips_immediately(self):
        dh, clock, _ = _fresh_health()
        assert dh.record_failure("mesh:i", make_device_error("launch")) \
            == "persistent"
        assert dh.state("mesh:i") == OPEN and dh.any_open
        assert dh.stats()["trips"] == 1

    def test_host_error_never_moves_a_circuit(self):
        dh, clock, _ = _fresh_health()
        assert dh.record_failure("pull:i", ValueError("host bug")) is None
        assert dh.state("pull:i") == CLOSED
        assert not dh.dirty and not dh.any_open

    def test_probe_admission_one_caller_per_window(self):
        dh, clock, events = _fresh_health()
        dh.record_failure("pull:i", make_device_error("transfer"))
        # inside the backoff window every caller degrades
        assert dh.blocked(("pull:i",)) == "pull:i"
        clock.t += 10.0
        # window due: exactly one caller is admitted as the probe...
        assert dh.blocked(("pull:i",)) is None
        assert dh.state("pull:i") == HALF_OPEN
        # ...and a concurrent caller keeps degrading until it reports
        assert dh.blocked(("pull:i",)) == "pull:i"
        dh.note_success(("pull:i",))
        assert dh.state("pull:i") == CLOSED and not dh.any_open
        st = dh.stats()
        assert st["probes"] == 1 and st["recoveries"] == 1
        assert [t for t, _ in events] == ["device_degraded",
                                          "device_recovered"]

    def test_failed_probe_reopens_with_grown_backoff(self):
        dh, clock, _ = _fresh_health()
        dh.record_failure("pull:i", make_device_error("transfer"))
        clock.t += 10.0
        assert dh.blocked(("pull:i",)) is None  # probe admitted
        dh.record_failure("pull:i", make_device_error("transfer"))
        assert dh.state("pull:i") == OPEN
        # the re-armed window is decorrelated jitter (NOT monotonic), but
        # always at least base_s and capped at cap_s
        backoff_ms = dh.stats()["domains"]["pull:i"]["backoff_ms"]
        assert 50.0 <= backoff_ms <= 5000.0, backoff_ms
        # still blocked until the re-armed window elapses
        assert dh.blocked(("pull:i",)) == "pull:i"
        # no duplicate trip event for a failed probe (already open)
        assert dh.stats()["trips"] == 1

    def test_closed_world_gate_is_lock_free_none(self):
        dh, clock, _ = _fresh_health()
        assert dh.blocked(("pull:i", "compile:sparse")) is None

    def test_stats_shape_and_reset(self):
        dh, clock, _ = _fresh_health()
        dh.record_failure("pack:i", make_device_error("internal"))
        st = dh.stats()
        for key in ("any_open", "failures", "trips", "probes", "recoveries",
                    "domains"):
            assert key in st
        dom = st["domains"]["pack:i"]
        for key in ("state", "failures", "trips", "probes", "recoveries",
                    "backoff_ms", "last_error"):
            assert key in dom
        assert st["failures"]["persistent"] == 1
        dh.reset()
        assert dh.stats()["domains"] == {} and not dh.any_open


class TestDeviceFaults:
    def test_glob_countdown_and_auto_disarm(self):
        df = DeviceFaults()
        df.arm(error="oom", domain="pull:*", times=2)
        df.check("pack:idx")  # no match: budget untouched, nothing raised
        with pytest.raises(Exception) as ei:
            df.check("pull:idx")
        assert classify_device_error(ei.value) == "transient"
        assert df.active
        with pytest.raises(Exception):
            df.check("pull:other")
        assert not df.active  # budget drained → auto-disarm
        df.check("pull:idx")  # disarmed: free
        assert df.injected == 2

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            DeviceFaults().arm(error="gremlins")


# ---------------------------------------------------------------------------
# live chaos: one node, four indices, seeded faults per domain
# ---------------------------------------------------------------------------

IDX_PIN, IDX_SPLIT, IDX_PACK, IDX_MESH = "dpin", "dsplit", "dpack", "dmesh"


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    registry = LocalTransportRegistry()
    n = Node(name="device_node", registry=registry,
             settings={"search.batch.linger_ms": 20.0},
             data_path=str(tmp_path_factory.mktemp("device_node")))
    n.start([n.local_node.transport_address])
    n.wait_for_master()
    client = n.client()
    rng = random.Random(18)
    for name, shards, docs, extra in (
            (IDX_PIN, 1, 80, {}), (IDX_SPLIT, 1, 60, {}),
            (IDX_PACK, 1, 50, {"index.refresh_interval": -1}),
            (IDX_MESH, 4, 120, {})):
        client.create_index(name, {"settings": {
            "number_of_shards": shards, "number_of_replicas": 0, **extra}})
        client.cluster_health(wait_for_status="green")
        for i in range(docs):
            body = " ".join(rng.choice(VOCAB)
                            for _ in range(rng.randint(5, 20)))
            client.index(name, "doc", {"body": body, "n": i}, id=str(i))
        client.refresh(name)
    yield n, client
    n.close()


def _hits(r):
    return [(h["_id"], h["_score"]) for h in r["hits"]["hits"]]


def _wait(pred, timeout=15.0, interval=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


class TestPinnedDeviceChaosInvariant:
    def test_degrade_never_500_then_probed_recovery(self, node):
        from elasticsearch_tpu.common.jaxenv import sanitize
        from elasticsearch_tpu.ops.device_index import PACK_LEDGER
        from elasticsearch_tpu.search.service import SERVING_COUNTERS

        n, client = node
        domain = f"pull:{IDX_PIN}"
        queries = [{"query": {"match": {"body": f"{a} {b}"}}, "size": 10}
                   for a, b in zip(VOCAB[:8], VOCAB[8:16])]
        # warm every shape on the device path and pin the expected hits
        baseline = [_hits(client.search(IDX_PIN, q)) for q in queries]
        ev0 = n.events.stats()["by_type"]
        deg0 = SERVING_COUNTERS["degraded"]

        DEVICE_FAULTS.arm(error="transfer", domain=domain, times=1_000_000)
        try:
            # trip within budget: transfer is persistent → the FIRST failing
            # search trips the domain, and its response is already degraded
            # with the bitwise-identical host hits
            r = client.search(IDX_PIN, queries[0])
            assert _hits(r) == baseline[0]
            assert r["_shards"]["degraded"] >= 1, r["_shards"]
            assert DEVICE_HEALTH.state(domain) == OPEN
            assert DEVICE_HEALTH.stats()["failures"]["persistent"] >= 1

            # degraded window: continuous 200s, identical hits, zero compiles,
            # zero query-path packs — concurrent callers included
            PACK_LEDGER.forget(IDX_PIN)
            errors, degraded_seen = [], []

            def chaos_loop():
                stop = time.monotonic() + 0.6
                i = 0
                try:
                    while time.monotonic() < stop:
                        r = client.search(IDX_PIN, queries[i % len(queries)])
                        assert _hits(r) == baseline[i % len(queries)]
                        if r["_shards"].get("degraded"):
                            degraded_seen.append(1)
                        i += 1
                except Exception as e:  # noqa: BLE001 — re-raised below
                    errors.append(e)

            with sanitize(max_compiles=0) as rep:
                threads = [threading.Thread(target=chaos_loop)
                           for _ in range(3)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            assert not errors, errors[:1]
            assert degraded_seen, "no degraded responses during open window"
            assert rep.compiles == 0
            # nothing packed on ANY pool during the window — the degraded
            # path is pure host scoring
            assert PACK_LEDGER.stats(IDX_PIN) == {}, PACK_LEDGER.stats(IDX_PIN)
            assert SERVING_COUNTERS["degraded"] > deg0
            ev = n.events.stats()["by_type"]
            assert ev.get("device_degraded", 0) > ev0.get("device_degraded", 0)
        finally:
            DEVICE_FAULTS.disarm()

        # probed recovery: searches past the backoff window ARE the probes
        _wait(lambda: (client.search(IDX_PIN, queries[0]),
                       DEVICE_HEALTH.state(domain) == CLOSED)[1],
              what=f"{domain} probe recovery")
        assert not DEVICE_HEALTH.any_open
        st = DEVICE_HEALTH.stats()
        assert st["probes"] >= 1 and st["recoveries"] >= 1
        ev = n.events.stats()["by_type"]
        assert ev.get("device_recovered", 0) > ev0.get("device_recovered", 0)
        r = client.search(IDX_PIN, queries[0])
        assert _hits(r) == baseline[0]
        assert r["_shards"]["degraded"] == 0


class TestCoalescedNeighborContainment:
    def test_one_poisoned_plan_cannot_fail_or_trip_neighbors(self, node):
        """A device failure on a coalesced batch replays the members
        individually: neighbors of the poisoned plan still succeed on the
        device, only genuinely-failing members degrade, and the batch-level
        collateral is never recorded against the circuit."""
        from elasticsearch_tpu.search.service import SERVING_COUNTERS

        n, client = node
        bat = n.search_batcher
        body = {"query": {"match": {"body": "alpha beta"}}, "size": 5}
        expected = _hits(client.search(IDX_SPLIT, body))  # warm + pin
        deg0 = SERVING_COUNTERS["degraded"]
        splits0 = bat.stats()["device_splits"]

        DEVICE_FAULTS.arm(error="oom", domain=f"pull:{IDX_SPLIT}", times=2)
        barrier = threading.Barrier(6)
        results, errors = [], []

        def worker():
            barrier.wait()
            try:
                results.append(client.search(IDX_SPLIT, body))
            except Exception as e:  # noqa: BLE001 — asserted below
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        assert not errors, errors[:1]
        assert len(results) == 6
        for r in results:
            assert _hits(r) == expected
        assert not DEVICE_FAULTS.active  # both injections were consumed
        # containment: without member replay, 6 neighbor failures would blow
        # the 3-strike budget; with it at most the 2 injected hits degrade
        # and the circuit stays closed
        deg = SERVING_COUNTERS["degraded"] - deg0
        assert deg <= 2, deg
        assert DEVICE_HEALTH.state(f"pull:{IDX_SPLIT}") == CLOSED
        assert not DEVICE_HEALTH.any_open
        # some failure observably landed: either a multi-member batch was
        # split for replay or a lone-member batch degraded
        assert bat.stats()["device_splits"] > splits0 or deg >= 1


class TestWarmPackRetry:
    def test_transient_pack_failure_retries_on_pool(self, node):
        n, client = node
        w = n.warmer
        q = {"query": {"match": {"body": "gamma"}}, "size": 5}
        client.search(IDX_PACK, q)  # opens the warm gate (search_active)
        retries0, fails0, done0 = w.pack_retries, w.pack_failures, w.packs_done

        DEVICE_FAULTS.arm(error="oom", domain=f"pack:{IDX_PACK}", times=1)
        client.index(IDX_PACK, "doc", {"body": "gamma gamma delta", "n": 900},
                     id="900")
        client.refresh(IDX_PACK)
        _wait(lambda: w.pack_retries > retries0 and w.packs_done > done0,
              what="warmer pack retry")
        assert w.pack_failures == fails0  # the retry healed it
        assert DEVICE_HEALTH.state(f"pack:{IDX_PACK}") == CLOSED
        r = client.search(IDX_PACK, q)
        assert r["_shards"]["degraded"] == 0
        assert any(h["_id"] == "900" for h in r["hits"]["hits"])

    def test_persistent_pack_failure_trips_then_degrades_then_recovers(
            self, node):
        n, client = node
        w = n.warmer
        domain = f"pack:{IDX_PACK}"
        q = {"query": {"match": {"body": "delta"}}, "size": 10}
        client.search(IDX_PACK, q)  # gate open, steady state packed
        fails0 = w.pack_failures

        DEVICE_FAULTS.arm(error="launch", domain=domain, times=1_000)
        client.index(IDX_PACK, "doc", {"body": "delta delta zeta", "n": 901},
                     id="901")
        client.refresh(IDX_PACK)
        # budget (initial + pack_retry_budget attempts) exhausts → final
        # failure is recorded and the persistent error trips the domain
        _wait(lambda: w.pack_failures > fails0, what="warmer final failure")
        assert DEVICE_HEALTH.state(domain) == OPEN
        # the index still serves — host path, honest _shards, doc visible
        # (half-packed state was never published; host scores the live view)
        r = client.search(IDX_PACK, q)
        assert any(h["_id"] == "901" for h in r["hits"]["hits"])
        assert r["_shards"]["failed"] == 0

        DEVICE_FAULTS.disarm()
        # probe recovery: an admitted search legally packs inline and closes
        _wait(lambda: (client.search(IDX_PACK, q),
                       DEVICE_HEALTH.state(domain) == CLOSED)[1],
              what=f"{domain} probe recovery")
        r = client.search(IDX_PACK, q)
        assert r["_shards"]["degraded"] == 0
        assert any(h["_id"] == "901" for h in r["hits"]["hits"])


def _same_mesh_hits(got, expected):
    """Mesh vs transport agreement contract (same as tests/test_mesh_serving):
    identical ids/order, scores within f32 kernel-accumulation tolerance."""
    import numpy as np
    assert [i for i, _ in got] == [i for i, _ in expected]
    assert np.allclose([s for _, s in got], [s for _, s in expected],
                       rtol=2e-6)


class TestMeshLaunchContainment:
    def test_rebuild_once_heals_a_transient_launch_fault(self, node):
        n, client = node
        ms = n.actions.mesh_serving
        body = {"query": {"match": {"body": "alpha beta"}}, "size": 10}
        mq0 = ms.mesh_queries
        r0 = client.search(IDX_MESH, body)
        assert ms.mesh_queries == mq0 + 1, "search did not ride the mesh"
        expected = _hits(r0)
        rb0 = ms.mesh_rebuilds

        DEVICE_FAULTS.arm(error="oom", domain=f"mesh:{IDX_MESH}", times=1)
        r1 = client.search(IDX_MESH, body)
        _same_mesh_hits(_hits(r1), expected)
        assert ms.mesh_queries == mq0 + 2  # still served by the mesh program
        assert ms.mesh_rebuilds == rb0 + 1  # via one executor rebuild
        assert DEVICE_HEALTH.state(f"mesh:{IDX_MESH}") == CLOSED

    def test_persistent_launch_trips_and_degrades_to_transport(self, node):
        n, client = node
        ms = n.actions.mesh_serving
        domain = f"mesh:{IDX_MESH}"
        body = {"query": {"match": {"body": "gamma delta"}}, "size": 10}
        mq0 = ms.mesh_queries
        baseline = _hits(client.search(IDX_MESH, body))
        assert ms.mesh_queries == mq0 + 1
        fb0, rb0 = ms.mesh_fallbacks, ms.mesh_rebuilds

        DEVICE_FAULTS.arm(error="launch", domain=domain, times=1_000)
        try:
            # rebuild-once-then-degrade: both launch attempts fail, the
            # failure is recorded (persistent → trip), the transport
            # scatter-gather serves the same hits
            r = client.search(IDX_MESH, body)
            _same_mesh_hits(_hits(r), baseline)
            assert ms.mesh_rebuilds == rb0 + 1
            assert DEVICE_HEALTH.state(domain) == OPEN
            # while open, searches keep succeeding WITHOUT riding the mesh
            # (gate fallback, or a failed probe falling back mid-flight)
            r = client.search(IDX_MESH, body)
            _same_mesh_hits(_hits(r), baseline)
            assert ms.mesh_queries == mq0 + 1
            assert ms.mesh_fallbacks >= fb0 + 2
        finally:
            DEVICE_FAULTS.disarm()

        # probe recovery: an admitted search rides the mesh again and closes
        _wait(lambda: (client.search(IDX_MESH, body),
                       DEVICE_HEALTH.state(domain) == CLOSED)[1],
              what=f"{domain} probe recovery")
        mq = ms.mesh_queries
        r = client.search(IDX_MESH, body)
        _same_mesh_hits(_hits(r), baseline)
        assert ms.mesh_queries == mq + 1  # mesh path restored
