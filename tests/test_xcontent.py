"""XContent formats: CBOR/SMILE/YAML round-trips, RFC 7049 test vectors, format
auto-detection, and HTTP content negotiation end to end (ref: common/xcontent/)."""

import json
import urllib.request

import pytest

from elasticsearch_tpu.common import xcontent
from elasticsearch_tpu.common.xcontent import (
    CBOR,
    JSON,
    SMILE,
    YAML,
    cbor_dumps,
    cbor_loads,
    detect,
    smile_dumps,
    smile_loads,
)

DOCS = [
    None, True, False, 0, 1, -1, 15, -16, 16, -17, 23, 24, 255, 256, 65535, 65536,
    2 ** 31 - 1, -(2 ** 31), 2 ** 40, -(2 ** 40), 1.5, -0.25, 3.141592653589793,
    "", "a", "hello", "x" * 32, "x" * 33, "x" * 64, "x" * 65, "x" * 500,
    "héllo wörld", "ünï" * 20, "日本語テキスト" * 30,
    [], [1, 2, 3], {"a": 1}, {},
    {"settings": {"number_of_shards": 3}, "mappings": {"doc": {"properties": {
        "title": {"type": "string"}, "n": {"type": "long"}}}}},
    {"query": {"bool": {"must": [{"match": {"t": "x"}}], "boost": 1.5}},
     "size": 10, "ids": [1, 2, 3], "flag": True, "nothing": None},
    {"long_key_" + "k" * 80: ["v", {"日本": [1.25, None, False]}]},
]


class TestRoundTrips:
    @pytest.mark.parametrize("fmt", [CBOR, SMILE, YAML, JSON])
    def test_roundtrip(self, fmt):
        for doc in DOCS:
            raw = xcontent.dumps(doc, fmt)
            back = xcontent.loads(raw, fmt)
            assert back == doc, (fmt, doc, back)

    def test_bytes_cbor_only(self):
        assert cbor_loads(cbor_dumps(b"\x00\x01\xff")) == b"\x00\x01\xff"

    def test_huge_integers(self):
        # beyond int64: CBOR uses RFC 7049 bignum tags; SMILE an extended vint
        for n in (2 ** 64, -(2 ** 64), 2 ** 63, -(2 ** 63) - 1, 10 ** 30,
                  -(10 ** 30)):
            assert cbor_loads(cbor_dumps(n)) == n, n
            assert smile_loads(smile_dumps(n)) == n, n
        assert cbor_dumps(2 ** 64).hex().startswith("c249")  # tag 2 + 9-byte bstr

    def test_detect_eleven_element_cbor_array(self):
        # regression: 0x8b (array-of-11) was excluded from sniffing
        assert detect(cbor_dumps([1] * 11)) == CBOR


class TestCborVectors:
    """Appendix A of RFC 7049 — encodings are normative for the definite-length
    canonical forms this encoder emits."""

    VECTORS = [
        (0, "00"), (1, "01"), (10, "0a"), (23, "17"), (24, "1818"), (25, "1819"),
        (100, "1864"), (1000, "1903e8"), (1000000, "1a000f4240"),
        (-1, "20"), (-10, "29"), (-100, "3863"), (-1000, "3903e7"),
        (1.1, "fb3ff199999999999a"), (False, "f4"), (True, "f5"), (None, "f6"),
        ("", "60"), ("a", "6161"), ("IETF", "6449455446"), ("ü", "62c3bc"),
        ([], "80"), ([1, 2, 3], "83010203"),
        ({}, "a0"), ({"a": 1, "b": [2, 3]}, "a26161016162820203"),
    ]

    def test_encode_matches_rfc(self):
        for obj, hexa in self.VECTORS:
            assert cbor_dumps(obj).hex() == hexa, obj

    def test_decode_matches_rfc(self):
        for obj, hexa in self.VECTORS:
            assert cbor_loads(bytes.fromhex(hexa)) == obj

    def test_decode_foreign_forms(self):
        # indefinite-length array + string chunks + half floats (decode-only)
        assert cbor_loads(bytes.fromhex("9f018202039f0405ffff")) == [1, [2, 3], [4, 5]]
        assert cbor_loads(bytes.fromhex("7f657374726561646d696e67ff")) == "streaming"
        assert cbor_loads(bytes.fromhex("f90000")) == 0.0
        assert cbor_loads(bytes.fromhex("f93c00")) == 1.0
        # self-describe tag is transparent
        assert cbor_loads(bytes.fromhex("d9d9f783010203")) == [1, 2, 3]


class TestSmile:
    def test_header(self):
        raw = smile_dumps({"a": 1})
        assert raw[:3] == b":)\n" and raw[3] == 0x00

    def test_small_ints_one_byte(self):
        # zigzag range -16..15 fits the 0xC0 token band
        for n in (-16, -1, 0, 1, 15):
            assert len(smile_dumps(n)) == 5  # 4 header + 1 token

    def test_detection(self):
        assert detect(smile_dumps({"a": 1})) == SMILE
        assert detect(cbor_dumps({"a": 1})) == CBOR
        assert detect(b'{"a": 1}') == JSON
        assert detect(b"---\na: 1\n") == YAML
        assert xcontent.from_content_type("application/smile") == SMILE
        assert xcontent.from_content_type("application/x-jackson-smile") == SMILE
        assert xcontent.from_content_type("application/cbor") == CBOR
        assert xcontent.from_content_type("text/yaml") == YAML
        assert xcontent.from_content_type("application/json; charset=UTF-8") == JSON


@pytest.fixture(scope="module")
def http_base(tmp_path_factory):
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.transport.local import LocalTransportRegistry

    node = Node(name="xc_node", registry=LocalTransportRegistry(),
                data_path=str(tmp_path_factory.mktemp("xc")))
    node.start([node.local_node.transport_address])
    node.wait_for_master()
    server = node.start_http(port=0)
    yield f"http://127.0.0.1:{server.port}"
    node.close()


def _call(base, method, path, data=None, ctype=None, accept_fmt=None):
    url = base + path + (f"?format={accept_fmt}" if accept_fmt else "")
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": ctype} if ctype else {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.headers.get("Content-Type"), r.read()
    except urllib.error.HTTPError as e:  # noqa: F821 — urllib.error via urllib.request
        return e.code, e.headers.get("Content-Type"), e.read()


class TestHttpNegotiation:
    def test_cbor_request_cbor_response(self, http_base):
        body = cbor_dumps({"settings": {"number_of_shards": 1,
                                        "number_of_replicas": 0}})
        s, ct, raw = _call(http_base, "PUT", "/cb", body, "application/cbor")
        assert s == 200 and ct == "application/cbor"
        assert cbor_loads(raw)["acknowledged"] is True

    def test_smile_document_roundtrip(self, http_base):
        doc = smile_dumps({"title": "binary json", "n": 7})
        s, ct, raw = _call(http_base, "PUT", "/cb/doc/1", doc, "application/smile")
        assert s in (200, 201) and ct == "application/smile"
        assert smile_loads(raw)["_id"] == "1"
        _call(http_base, "POST", "/cb/_refresh")
        q = smile_dumps({"query": {"match": {"title": "binary"}}})
        s, ct, raw = _call(http_base, "POST", "/cb/_search", q, "application/smile")
        assert s == 200
        r = smile_loads(raw)
        assert r["hits"]["total"] == 1
        assert r["hits"]["hits"][0]["_source"]["n"] == 7

    def test_yaml_body_and_format_param(self, http_base):
        import yaml

        y = b"query:\n  match_all: {}\n"
        s, ct, raw = _call(http_base, "POST", "/cb/_search", y,
                           "application/yaml")
        assert s == 200 and ct == "application/yaml"
        assert yaml.safe_load(raw)["hits"]["total"] == 1
        # JSON body, yaml response via ?format=
        s, ct, raw = _call(http_base, "POST", "/cb/_search",
                           json.dumps({"query": {"match_all": {}}}).encode(),
                           "application/json", accept_fmt="yaml")
        assert ct == "application/yaml"
        assert yaml.safe_load(raw)["hits"]["total"] == 1

    def test_json_still_default(self, http_base):
        s, ct, raw = _call(http_base, "GET", "/cb/doc/1")
        assert s == 200 and ct == "application/json"
        assert json.loads(raw)["found"] is True

    def test_malformed_binary_body_is_400_not_dropped_connection(self, http_base):
        s, ct, raw = _call(http_base, "POST", "/cb/_search", b"\xa5\x01\x02",
                           "application/cbor")
        assert s == 400
        assert json.loads(raw)["error"]["type"] == "parse_exception"

    def test_sniffed_binary_without_content_type(self, http_base):
        body = cbor_dumps({"query": {"match_all": {}}})
        s, ct, raw = _call(http_base, "POST", "/cb/_search", body)
        assert s == 200
        assert cbor_loads(raw)["hits"]["total"] == 1
