"""Multi-shard: SearchPhaseController merge + DFS aggregation + mesh executor on a
virtual 8-device CPU mesh.

Parity chain: mesh program (psum DFS + all_gather top-k) must agree with the host
reference (per-shard search with DFS-global stats, merged by sort_docs) — the same
agreement the reference guarantees between DfsQueryThenFetch and its controller."""

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query
from elasticsearch_tpu.search.controller import (
    aggregate_dfs,
    collect_dfs,
    merge_responses,
    sort_docs,
)
from elasticsearch_tpu.search.execute import lower_flat, search_shard
from elasticsearch_tpu.search.service import (
    ShardQueryResult,
    execute_query_phase,
    parse_search_body,
)
from elasticsearch_tpu.search.similarity import SimilarityService

VOCAB = ("alpha beta gamma delta epsilon zeta eta theta iota kappa lamda mu nu xi "
         "omicron pi rho sigma tau upsilon phi chi psi omega").split()

N_SHARDS = 4
DOCS_PER_SHARD = 30


def make_shards(tmp_path, similarity="BM25", n_shards=N_SHARDS):
    rng = np.random.default_rng(123)
    settings = Settings.from_flat({"index.similarity.default.type": similarity})
    shards = []
    for si in range(n_shards):
        svc = MapperService(settings)
        e = Engine(str(tmp_path / f"shard{si}"), svc)
        for i in range(DOCS_PER_SHARD):
            body = " ".join(rng.choice(VOCAB, size=rng.integers(5, 20)))
            e.index("doc", f"{si}-{i}", {"body": body, "shard": si})
            if i == 15:
                e.refresh()
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))
        shards.append((e, svc, ctx))
    return shards


def host_reference_search(shards, query_dict, k, similarity="BM25"):
    """DFS phase (host) + per-shard query with global stats + controller merge."""
    q = parse_query(query_dict)
    dfs = [collect_dfs(ctx, q, shard_id=si) for si, (_, _, ctx) in enumerate(shards)]
    global_stats = aggregate_dfs(dfs)
    results = []
    for si, (e, svc, ctx) in enumerate(shards):
        gctx = ShardContext(ctx.searcher, svc, ctx.similarity_service, global_stats)
        td = search_shard(gctx, q, k, use_device=False)
        results.append(ShardQueryResult(
            total=td.total, docs=[(s, d, None) for s, d in td.hits],
            max_score=td.max_score, shard_id=si))
    req = parse_search_body({"query": query_dict, "size": k})
    return sort_docs(req, results), results


class TestController:
    def test_dfs_aggregation(self, tmp_path):
        shards = make_shards(tmp_path)
        q = parse_query({"match": {"body": "alpha beta"}})
        dfs = [collect_dfs(ctx, q, si) for si, (_, _, ctx) in enumerate(shards)]
        agg = aggregate_dfs(dfs)
        assert agg["max_doc"] == sum(ctx.searcher.max_doc for _, _, ctx in shards)
        total_df = sum(ctx.searcher.doc_freq("body", "alpha") for _, _, ctx in shards)
        assert agg["df"][("body", "alpha")] == total_df

    def test_global_idf_changes_scores(self, tmp_path):
        """Without DFS, per-shard idf differs; with global stats all shards agree."""
        shards = make_shards(tmp_path)
        merged, results = host_reference_search(shards, {"match": {"body": "alpha"}}, 10)
        # same analysed term must produce CONSISTENT scores across shards for docs
        # with identical (freq, dl): verified indirectly — merge is strictly ordered
        scores = [h[0] for h in merged.hits]
        assert scores == sorted(scores, reverse=True)
        assert merged.total == sum(r.total for r in results)

    def test_merge_tie_break_by_shard_then_doc(self):
        req = parse_search_body({"size": 4})
        r0 = ShardQueryResult(total=2, docs=[(1.0, 5, None), (0.5, 9, None)],
                              max_score=1.0, shard_id=1)
        r1 = ShardQueryResult(total=2, docs=[(1.0, 3, None), (0.5, 1, None)],
                              max_score=1.0, shard_id=0)
        merged = sort_docs(req, [r0, r1])
        assert [(h[1], h[2]) for h in merged.hits] == [(0, 3), (1, 5), (0, 1), (1, 9)]

    def test_field_sort_merge(self):
        req = parse_search_body({"size": 4, "sort": [{"price": "asc"}]})
        r0 = ShardQueryResult(total=2, docs=[(float("nan"), 1, [10.0]),
                                             (float("nan"), 2, [30.0])],
                              max_score=float("nan"), shard_id=0)
        r1 = ShardQueryResult(total=2, docs=[(float("nan"), 1, [5.0]),
                                             (float("nan"), 2, [20.0])],
                              max_score=float("nan"), shard_id=1)
        merged = sort_docs(req, [r0, r1])
        assert [h[3][0] for h in merged.hits] == [5.0, 10.0, 20.0, 30.0]

    def test_agg_reduce_across_shards(self, tmp_path):
        shards = make_shards(tmp_path)
        body = {"size": 0, "aggs": {"by_shard": {"terms": {"field": "shard"}},
                                    "n": {"value_count": {"field": "shard"}}}}
        req = parse_search_body(body)
        results = []
        for si, (_, _, ctx) in enumerate(shards):
            r = execute_query_phase(ctx, req, shard_id=si)
            results.append(r)
        merged = sort_docs(req, results)
        resp = merge_responses(req, merged, results, [], took_ms=1,
                               total_shards=len(shards), successful=len(shards))
        assert resp["aggregations"]["n"]["value"] == N_SHARDS * DOCS_PER_SHARD
        buckets = {b["key"]: b["doc_count"] for b in
                   resp["aggregations"]["by_shard"]["buckets"]}
        assert buckets == {si: DOCS_PER_SHARD for si in range(N_SHARDS)}


@pytest.mark.parametrize("similarity", ["BM25", "default"])
class TestMeshExecutor:
    def test_mesh_matches_host_reference(self, tmp_path, similarity):
        import jax
        from jax.sharding import Mesh

        shards = make_shards(tmp_path, similarity=similarity)
        devices = np.array(jax.devices()[: N_SHARDS])
        mesh = Mesh(devices, ("shards",))
        from elasticsearch_tpu.parallel import MeshSearchExecutor, build_sharded_index

        sidx = build_sharded_index([ctx.searcher for _, _, ctx in shards],
                                   fields=["body"], mesh=mesh)
        ex = MeshSearchExecutor(sidx, mesh, similarity=similarity)
        queries = [
            {"match": {"body": "alpha beta gamma"}},
            {"match": {"body": {"query": "delta epsilon", "operator": "and"}}},
            {"term": {"body": "omega"}},
            {"bool": {"must": [{"term": {"body": "pi"}}],
                      "must_not": [{"term": {"body": "rho"}}]}},
        ]
        ctx0 = shards[0][2]
        plans = [lower_flat(parse_query(qd), ctx0) for qd in queries]
        assert all(p is not None for p in plans)
        k = 10
        out = ex.search(plans, k)
        for qi, qd in enumerate(queries):
            merged, _ = host_reference_search(shards, qd, k, similarity)
            assert out.totals[qi] == merged.total, qd
            # compare (shard, local_doc) hit lists; scores within a few ulps
            mesh_hits = [(int(out.shard[qi, j]), int(out.doc[qi, j]))
                         for j in range(k) if out.shard[qi, j] >= 0]
            ref_hits = [(h[1], h[2]) for h in merged.hits]
            ref_scores = [h[0] for h in merged.hits]
            assert len(mesh_hits) == len(ref_hits), qd
            for mh, ms, rh, rs in zip(mesh_hits, out.scores[qi], ref_hits, ref_scores):
                assert ms == pytest.approx(rs, rel=3e-6), qd
                if mh != rh:
                    # only near-tie swaps permitted
                    assert any(abs(ms - s2) <= 3e-6 * abs(ms) for s2 in ref_scores
                               if s2 != rs) or ms == pytest.approx(rs, rel=3e-6), qd
