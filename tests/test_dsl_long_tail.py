"""Query/filter DSL long tail — round-5 closures.

ref: HasChildFilterParser.java:1, HasParentFilterParser.java:1,
TermsFilterParser.java:1 (+ IndicesTermsFilterCache.java:1),
GeoPolygonFilterParser.java:1, GeoDistanceRangeFilterParser.java:1,
IndicesFilterParser.java:1, WrapperQueryParser.java:1,
SimpleQueryStringParser.java:1, FuzzyLikeThisQueryParser.java:1,
FuzzyLikeThisFieldQueryParser.java:1, MoreLikeThisFieldQueryParser.java:1.

Each construct gets a differential check against independently-computed
expectations on the host scorer; terms-lookup goes through the real get path
on a node."""

import base64
import json

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
from elasticsearch_tpu.search.execute import QueryParsingError
from elasticsearch_tpu.search.filters import segment_mask
from elasticsearch_tpu.search.queries import parse_filter, resolve_terms_lookups
from elasticsearch_tpu.search.similarity import SimilarityService
from elasticsearch_tpu.transport.local import LocalTransportRegistry


@pytest.fixture(scope="module")
def ctx(tmp_path_factory):
    path = tmp_path_factory.mktemp("dsl_tail")
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    svc.put_mapping("doc", {"properties": {
        "body": {"type": "string"},
        "tag": {"type": "string", "index": "not_analyzed"},
        "n": {"type": "integer"},
        "loc": {"type": "geo_point"},
    }})
    eng = Engine(str(path), svc)
    docs = [
        {"body": "quick brown fox", "tag": "a", "n": 1,
         "loc": {"lat": 52.37, "lon": 4.89}},     # Amsterdam
        {"body": "lazy brown dog", "tag": "b", "n": 2,
         "loc": {"lat": 52.52, "lon": 13.40}},    # Berlin
        {"body": "quick red wolf", "tag": "a", "n": 3,
         "loc": {"lat": 48.85, "lon": 2.35}},     # Paris
        {"body": "slow green turtle", "tag": "c", "n": 4,
         "loc": {"lat": 37.77, "lon": -122.42}},  # SF
        {"body": "quick quince quest", "tag": "b", "n": 5},  # no loc
    ]
    for i, d in enumerate(docs):
        eng.index("doc", str(i), d)
    eng.refresh()
    c = ShardContext(eng.acquire_searcher(), svc,
                     SimilarityService(settings, mapper_service=svc),
                     index_name="dsl_tail")
    yield c
    eng.close()


def _ids(ctx, td):
    out = []
    for _s, g in td.hits:
        seg, local = ctx.searcher.resolve(g)
        out.append(seg.ids[local])
    return out


def _mask_ids(ctx, f):
    ids = []
    for seg, base in zip(ctx.searcher.segments, ctx.searcher.bases):
        m = segment_mask(seg, f, ctx)
        ids.extend(seg.ids[i] for i in m.nonzero()[0])
    return sorted(ids)


class TestSimpleQueryString:
    def test_default_or(self, ctx):
        q = parse_query({"simple_query_string": {
            "query": "fox turtle", "fields": ["body"]}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert sorted(_ids(ctx, td)) == ["0", "3"]

    def test_plus_forces_and(self, ctx):
        q = parse_query({"simple_query_string": {
            "query": "quick + brown", "fields": ["body"]}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert sorted(_ids(ctx, td)) == ["0"]

    def test_negation(self, ctx):
        q = parse_query({"simple_query_string": {
            "query": "quick -red", "fields": ["body"]}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert sorted(_ids(ctx, td)) == ["0", "4"]

    def test_phrase_and_prefix(self, ctx):
        q = parse_query({"simple_query_string": {
            "query": '"brown fox"', "fields": ["body"]}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert _ids(ctx, td) == ["0"]
        q2 = parse_query({"simple_query_string": {
            "query": "quin*", "fields": ["body"]}})
        td2 = search_shard(ctx, q2, 10, use_device=False)
        assert _ids(ctx, td2) == ["4"]

    def test_default_operator_and(self, ctx):
        q = parse_query({"simple_query_string": {
            "query": "quick brown", "fields": ["body"],
            "default_operator": "and"}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert sorted(_ids(ctx, td)) == ["0"]

    def test_stray_operators_degrade_gracefully(self, ctx):
        q = parse_query({"simple_query_string": {
            "query": "+ | - fox", "fields": ["body"]}})
        td = search_shard(ctx, q, 10, use_device=False)  # must not raise
        assert td.total >= 0

    def test_explicit_or_overrides_default_and(self, ctx):
        # "fox | turtle" with default AND must still be an OR (Lucene's
        # SimpleQueryParser: the explicit | releases its left operand)
        q = parse_query({"simple_query_string": {
            "query": "fox | turtle", "fields": ["body"],
            "default_operator": "and"}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert sorted(_ids(ctx, td)) == ["0", "3"]


class TestFuzzyLikeThis:
    def test_flt_matches_fuzzy_neighborhood(self, ctx):
        # "quik"~"quick" (1 edit), "brown"~"brown" (1 edit)
        q = parse_query({"fuzzy_like_this": {
            "fields": ["body"], "like_text": "quik brown", "fuzziness": 1}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert set(_ids(ctx, td)) == {"0", "1", "2", "4"}

    def test_flt_field_form(self, ctx):
        q = parse_query({"fuzzy_like_this_field": {
            "body": {"like_text": "foxx", "fuzziness": 1}}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert _ids(ctx, td) == ["0"]

    def test_legacy_similarity_float(self, ctx):
        # 0.5 similarity on len-5 "quick" → 2 edits: "qck" misses (3 edits from
        # quick... actually 2 deletions) — use "quicky" (1 edit) to stay clear
        q = parse_query({"flt": {"fields": ["body"], "like_text": "quicky"}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert "0" in _ids(ctx, td)


class TestMoreLikeThisField:
    def test_mlt_field(self, ctx):
        q = parse_query({"more_like_this_field": {"body": {
            "like_text": "quick brown fox", "min_term_freq": 1,
            "min_doc_freq": 1, "minimum_should_match": 1}}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert "0" in _ids(ctx, td) and td.total >= 3


class TestWrapper:
    def test_wrapper_query_base64(self, ctx):
        raw = json.dumps({"term": {"tag": "a"}})
        q = parse_query({"wrapper": {
            "query": base64.b64encode(raw.encode()).decode()}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert sorted(_ids(ctx, td)) == ["0", "2"]

    def test_wrapper_filter_raw_json(self, ctx):
        f = parse_filter({"wrapper": {"query": '{"term": {"tag": "b"}}'}})
        assert _mask_ids(ctx, f) == ["1", "4"]

    def test_wrapper_malformed_raises(self, ctx):
        with pytest.raises(QueryParsingError):
            parse_query({"wrapper": {"query": "not json at all {"}})


class TestGeoFilters:
    def test_geo_polygon(self, ctx):
        # triangle around western Europe: Amsterdam, Berlin, Paris in; SF out
        f = parse_filter({"geo_polygon": {"loc": {"points": [
            {"lat": 60.0, "lon": 0.0}, {"lat": 60.0, "lon": 20.0},
            {"lat": 40.0, "lon": 10.0}, {"lat": 40.0, "lon": -5.0}]}}})
        assert _mask_ids(ctx, f) == ["0", "1", "2"]

    def test_geo_distance_range(self, ctx):
        # from Amsterdam: Berlin ~577km, Paris ~430km, SF ~8800km
        f = parse_filter({"geo_distance_range": {
            "from": "500km", "to": "1000km",
            "loc": {"lat": 52.37, "lon": 4.89}}})
        assert _mask_ids(ctx, f) == ["1"]
        f2 = parse_filter({"geo_distance_range": {
            "gt": "0km", "lt": "500km", "loc": {"lat": 52.37, "lon": 4.89}}})
        assert _mask_ids(ctx, f2) == ["2"]  # self at exactly 0 excluded by gt
        f3 = parse_filter({"geo_distance_range": {
            "gte": "0km", "lt": "500km", "loc": {"lat": 52.37, "lon": 4.89}}})
        assert _mask_ids(ctx, f3) == ["0", "2"]  # gte includes the origin doc

    def test_geo_polygon_rejects_degenerate(self, ctx):
        with pytest.raises(QueryParsingError):
            parse_filter({"geo_polygon": {"loc": {"points": [
                {"lat": 0, "lon": 0}, {"lat": 0, "lon": 0}]}}})


class TestIndicesTargeting:
    def test_indices_filter_matching_index(self, ctx):
        f = parse_filter({"indices": {
            "indices": ["dsl_*"], "filter": {"term": {"tag": "a"}},
            "no_match_filter": "none"}})
        assert _mask_ids(ctx, f) == ["0", "2"]

    def test_indices_filter_non_matching_defaults_all(self, ctx):
        f = parse_filter({"indices": {
            "index": "other", "filter": {"term": {"tag": "a"}}}})
        assert len(_mask_ids(ctx, f)) == 5  # no_match default = all

    def test_indices_filter_non_matching_none(self, ctx):
        f = parse_filter({"indices": {
            "index": "other", "filter": {"term": {"tag": "a"}},
            "no_match_filter": "none"}})
        assert _mask_ids(ctx, f) == []

    def test_indices_filter_cache_distinguishes_no_match(self, ctx):
        # two filters differing ONLY in no_match_filter must not collide in
        # the per-segment filter cache
        f1 = parse_filter({"indices": {
            "index": "other", "filter": {"term": {"tag": "a"}},
            "no_match_filter": {"term": {"tag": "b"}}}})
        f2 = parse_filter({"indices": {
            "index": "other", "filter": {"term": {"tag": "a"}},
            "no_match_filter": {"term": {"tag": "c"}}}})
        assert _mask_ids(ctx, f1) == ["1", "4"]
        assert _mask_ids(ctx, f2) == ["3"]

    def test_indices_query_targets_index(self, ctx):
        q = parse_query({"indices": {
            "indices": ["dsl_tail"], "query": {"term": {"tag": "c"}},
            "no_match_query": "none"}})
        td = search_shard(ctx, q, 10, use_device=False)
        assert _ids(ctx, td) == ["3"]
        q2 = parse_query({"indices": {
            "indices": ["other"], "query": {"term": {"tag": "c"}},
            "no_match_query": "none"}})
        td2 = search_shard(ctx, q2, 10, use_device=False)
        assert td2.total == 0


class TestTermsLookupUnit:
    def test_rewrite_replaces_lookup(self):
        body = {"query": {"filtered": {"query": {"match_all": {}},
                "filter": {"terms": {"tag": {
                    "index": "users", "type": "u", "id": "1",
                    "path": "prefs.tags"}}}}}}
        got = resolve_terms_lookups(body, lambda i, t, d, r: {
            "found": True, "_source": {"prefs": {"tags": ["a", "c"]}}})
        assert got["query"]["filtered"]["filter"] == {"terms": {"tag": ["a", "c"]}}
        assert body["query"]["filtered"]["filter"]["terms"]["tag"]["id"] == "1"

    def test_missing_doc_resolves_empty(self):
        body = {"filter": {"terms": {"tag": {"index": "x", "id": "9",
                                             "path": "p"}}}}
        got = resolve_terms_lookups(body, lambda i, t, d, r: {"found": False})
        assert got["filter"]["terms"]["tag"] == []

    def test_plain_terms_untouched(self):
        body = {"filter": {"terms": {"tag": ["a", "b"]}}}
        assert resolve_terms_lookups(body, None) is body

    def test_unresolved_lookup_raises_at_parse(self):
        with pytest.raises(QueryParsingError):
            parse_filter({"terms": {"tag": {"index": "x", "id": "1",
                                            "path": "p"}}})


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    registry = LocalTransportRegistry()
    n = Node(name="dsl_node", registry=registry,
             data_path=str(tmp_path_factory.mktemp("dsl_node")))
    n.start([n.local_node.transport_address])
    n.wait_for_master()
    client = n.client()
    client.create_index("users", {"settings": {
        "number_of_shards": 1, "number_of_replicas": 0}})
    client.create_index("tweets", {"settings": {
        "number_of_shards": 2, "number_of_replicas": 0}})
    client.cluster_health(wait_for_status="green")
    client.index("users", "user", {"name": "kim",
                                   "follows": ["ana", "bo"]}, id="1")
    for i, (author, text) in enumerate([
            ("ana", "hello world"), ("bo", "goodbye world"),
            ("cai", "other post"), ("ana", "second post")]):
        client.index("tweets", "tweet", {"author": author, "text": text},
                     id=str(i))
    client.refresh("users")
    client.refresh("tweets")
    yield n, client
    n.close()


class TestTermsLookupEndToEnd:
    def test_lookup_through_get_path(self, node):
        # the canonical reference example: tweets by users kim follows
        _n, client = node
        r = client.search("tweets", {"query": {"filtered": {
            "query": {"match_all": {}},
            "filter": {"terms": {"author": {
                "index": "users", "type": "user", "id": "1",
                "path": "follows"}}}}}})
        ids = sorted(h["_id"] for h in r["hits"]["hits"])
        assert ids == ["0", "1", "3"]

    def test_lookup_missing_doc_matches_nothing(self, node):
        _n, client = node
        r = client.search("tweets", {"query": {"filtered": {
            "query": {"match_all": {}},
            "filter": {"terms": {"author": {
                "index": "users", "type": "user", "id": "404",
                "path": "follows"}}}}}})
        assert r["hits"]["total"] == 0

    def test_indices_filter_end_to_end(self, node):
        # searching tweets: the tag filter applies only on "users"
        _n, client = node
        r = client.search("tweets", {"query": {"filtered": {
            "query": {"match_all": {}},
            "filter": {"indices": {
                "index": "users",
                "filter": {"term": {"author": "nobody"}}}}}}})
        assert r["hits"]["total"] == 4  # no_match default: all


@pytest.fixture(scope="module")
def pc_ctx(tmp_path_factory):
    """Parent/child corpus for the has_child / has_parent FILTER forms."""
    path = tmp_path_factory.mktemp("dsl_pc")
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    svc.put_mapping("blog", {"properties": {
        "title": {"type": "string"}}})
    svc.put_mapping("comment", {"_parent": {"type": "blog"}, "properties": {
        "text": {"type": "string"}}})
    eng = Engine(str(path), svc)
    eng.index("blog", "b1", {"title": "jax on tpu"})
    eng.index("blog", "b2", {"title": "numpy tricks"})
    eng.index("blog", "b3", {"title": "silent post"})
    eng.index("comment", "c1", {"text": "great article"}, parent="b1")
    eng.index("comment", "c2", {"text": "nice read great"}, parent="b2")
    eng.index("comment", "c3", {"text": "meh"}, parent="b2")
    eng.refresh()
    c = ShardContext(eng.acquire_searcher(), svc,
                     SimilarityService(settings, mapper_service=svc))
    yield c
    eng.close()


class TestParentChildFilters:
    def test_has_child_filter(self, pc_ctx):
        f = parse_filter({"has_child": {
            "type": "comment", "query": {"term": {"text": "great"}}}})
        assert sorted(_mask_ids(pc_ctx, f)) == ["b1", "b2"]

    def test_has_child_filter_with_filter_body(self, pc_ctx):
        f = parse_filter({"has_child": {
            "type": "comment", "filter": {"term": {"text": "meh"}}}})
        assert _mask_ids(pc_ctx, f) == ["b2"]

    def test_has_parent_filter(self, pc_ctx):
        f = parse_filter({"has_parent": {
            "parent_type": "blog", "query": {"term": {"title": "jax"}}}})
        assert _mask_ids(pc_ctx, f) == ["c1"]

    def test_has_child_composes_in_bool_filter(self, pc_ctx):
        f = parse_filter({"bool": {
            "must": [{"has_child": {"type": "comment",
                                    "query": {"term": {"text": "great"}}}}],
            "must_not": [{"term": {"title": "numpy"}}]}})
        assert _mask_ids(pc_ctx, f) == ["b1"]

    def test_has_child_filter_sees_new_children(self, tmp_path):
        # the cross-segment join must never serve a stale per-segment cache:
        # a child indexed into a LATER segment changes an EARLIER segment's mask
        settings = Settings.from_flat({})
        svc = MapperService(settings)
        svc.put_mapping("blog", {"properties": {"title": {"type": "string"}}})
        svc.put_mapping("comment", {"_parent": {"type": "blog"},
                                    "properties": {"text": {"type": "string"}}})
        eng = Engine(str(tmp_path), svc)
        eng.index("blog", "p1", {"title": "lonely"})
        eng.refresh()
        c1 = ShardContext(eng.acquire_searcher(), svc,
                          SimilarityService(settings, mapper_service=svc))
        f = parse_filter({"has_child": {
            "type": "comment", "query": {"term": {"text": "late"}}}})
        assert _mask_ids(c1, f) == []  # no children yet (primes any cache)
        eng.index("comment", "c9", {"text": "late arrival"}, parent="p1")
        eng.refresh()
        c2 = ShardContext(eng.acquire_searcher(), svc,
                          SimilarityService(settings, mapper_service=svc))
        assert _mask_ids(c2, f) == ["p1"]  # the new child is visible
        eng.close()
