"""Storage core tests: segments, translog, store, engine lifecycle."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import (
    DocumentAlreadyExistsError,
    VersionConflictError,
)
from elasticsearch_tpu.index import Engine, EXTERNAL, SegmentBuilder, Translog, TranslogOp
from elasticsearch_tpu.index.segment import merge_segments
from elasticsearch_tpu.index.store import Store
from elasticsearch_tpu.index.translog import INDEX, DELETE
from elasticsearch_tpu.mapper import MapperService


def make_engine(tmp_path, name="e"):
    svc = MapperService()
    return Engine(str(tmp_path / name), svc)


class TestSegment:
    def test_build_and_postings(self):
        svc = MapperService()
        m = svc.mapper_for("doc")
        b = SegmentBuilder(gen=1)
        b.add(m.parse({"title": "the quick brown fox"}, "1"))
        b.add(m.parse({"title": "quick quick dog"}, "2"))
        seg = b.freeze()
        docs, freqs = seg.postings("title", "quick")
        assert docs.tolist() == [0, 1]
        assert freqs.tolist() == [1.0, 2.0]
        assert seg.doc_freq("title", "quick") == 2
        assert seg.doc_freq("title", "missing") == 0
        st = seg.field_stats["title"]
        assert st.doc_count == 2 and st.sum_ttf == 7
        # norms encode field lengths via byte315
        from elasticsearch_tpu.common.smallfloat import decode_norm_doclen

        dls = decode_norm_doclen(seg.norms["title"])
        assert 3 <= dls[0] <= 5 and 2 <= dls[1] <= 4

    def test_positions_for_phrase(self):
        svc = MapperService()
        m = svc.mapper_for("doc")
        b = SegmentBuilder(gen=1)
        b.add(m.parse({"t": "alpha beta gamma beta"}, "1"))
        seg = b.freeze()
        pos = seg.term_positions("t", "beta")
        assert [p.tolist() for p in pos] == [[1, 3]]

    def test_doc_values(self):
        svc = MapperService()
        m = svc.mapper_for("doc")
        b = SegmentBuilder(gen=1)
        b.add(m.parse({"price": 10, "tags": "a"}, "1"))
        b.add(m.parse({"price": [3, 7]}, "2"))
        seg = b.freeze()
        assert seg.num_values("price", 0).tolist() == [10.0]
        assert seg.num_values("price", 1).tolist() == [3.0, 7.0]
        assert seg.str_values("tags", 0) == ["a"]

    def test_merge_preserves_postings_and_drops_deleted(self):
        svc = MapperService()
        m = svc.mapper_for("doc")
        b1 = SegmentBuilder(gen=1)
        b1.add(m.parse({"t": "one two"}, "1"))
        b1.add(m.parse({"t": "two three"}, "2"))
        s1 = b1.freeze()
        b2 = SegmentBuilder(gen=2)
        b2.add(m.parse({"t": "three four"}, "3"))
        s2 = b2.freeze()
        s1.delete_doc(0)
        merged = merge_segments([s1, s2], gen=3)
        assert merged.doc_count == 2
        assert merged.doc_freq("t", "three") == 2
        assert merged.doc_freq("t", "one") == 0
        assert set(merged.ids) == {"2", "3"}


class TestTranslog:
    def test_roundtrip_and_replay(self, tmp_path):
        tl = Translog(str(tmp_path / "tl"))
        tl.add(TranslogOp(INDEX, "doc", "1", {"a": 1}, version=1))
        tl.add(TranslogOp(DELETE, "doc", "2", version=3))
        ops = tl.read_ops()
        assert len(ops) == 2
        assert ops[0].source == {"a": 1}
        assert ops[1].op == DELETE and ops[1].version == 3

    def test_torn_tail_is_truncated(self, tmp_path):
        tl = Translog(str(tmp_path / "tl"))
        tl.add(TranslogOp(INDEX, "doc", "1", {"a": 1}))
        tl.add(TranslogOp(INDEX, "doc", "2", {"b": 2}))
        tl.sync()
        path = tl._file(tl.gen)
        tl.close()
        with open(path, "r+b") as f:
            f.truncate(f.seek(0, 2) - 3)  # chop 3 bytes off the last frame
        tl2 = Translog(str(tmp_path / "tl"))
        ops = tl2.read_ops()
        assert len(ops) == 1 and ops[0].id == "1"

    def test_roll_and_prune(self, tmp_path):
        tl = Translog(str(tmp_path / "tl"))
        tl.add(TranslogOp(INDEX, "doc", "1", {}))
        new_gen = tl.roll()
        tl.add(TranslogOp(INDEX, "doc", "2", {}))
        assert len(tl.read_ops(1)) == 2
        tl.prune_before(new_gen)
        assert len(tl.read_ops(1)) == 1


class TestEngine:
    def test_index_get_version_increments(self, tmp_path):
        e = make_engine(tmp_path)
        v1, created = e.index("doc", "1", {"title": "hello"})
        assert (v1, created) == (1, True)
        v2, created = e.index("doc", "1", {"title": "hello again"})
        assert (v2, created) == (2, False)
        g = e.get("doc", "1")  # realtime, pre-refresh
        assert g.found and g.version == 2 and g.source["title"] == "hello again"

    def test_version_conflict(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("doc", "1", {"a": 1})
        with pytest.raises(VersionConflictError):
            e.index("doc", "1", {"a": 2}, version=5)
        e.index("doc", "1", {"a": 2}, version=1)  # correct CAS

    def test_external_versioning(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("doc", "1", {"a": 1}, version=10, version_type=EXTERNAL)
        with pytest.raises(VersionConflictError):
            e.index("doc", "1", {"a": 2}, version=10, version_type=EXTERNAL)
        v, _ = e.index("doc", "1", {"a": 2}, version=42, version_type=EXTERNAL)
        assert v == 42

    def test_create_conflict(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("doc", "1", {"a": 1}, op_type="create")
        with pytest.raises(DocumentAlreadyExistsError):
            e.index("doc", "1", {"a": 2}, op_type="create")
        e.delete("doc", "1")
        e.index("doc", "1", {"a": 3}, op_type="create")  # ok after delete

    def test_delete_and_refresh_tombstones(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("doc", "1", {"a": 1})
        e.index("doc", "2", {"a": 2})
        e.refresh()
        assert e.doc_stats()["count"] == 2
        v, found = e.delete("doc", "1")
        assert found
        assert not e.get("doc", "1").found  # realtime delete visible pre-refresh
        e.refresh()
        assert e.doc_stats() == {"count": 1, "deleted": 1}

    def test_update_tombstones_old_copy(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("doc", "1", {"a": "first"})
        e.refresh()
        e.index("doc", "1", {"a": "second"})
        e.refresh()
        assert e.doc_stats() == {"count": 1, "deleted": 1}
        searcher = e.acquire_searcher()
        assert searcher.doc_freq("a", "first") == 1  # still in postings...
        seg0 = searcher.segments[0]
        assert not seg0.live[0]  # ...but tombstoned

    def test_flush_commit_recover(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("doc", "1", {"title": "persisted doc"})
        e.index("doc", "2", {"title": "another"})
        e.flush()
        e.index("doc", "3", {"title": "only in translog"})
        e.translog.sync()
        e.close()
        # restart from disk: segments from commit + translog replay
        e2 = make_engine(tmp_path)
        replayed = e2.recover_from_store()
        assert replayed == 1
        assert e2.get("doc", "1").found
        assert e2.get("doc", "3").found
        assert e2.doc_stats()["count"] == 3

    def test_recover_applies_tombstones(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("doc", "1", {"a": 1})
        e.index("doc", "2", {"a": 2})
        e.flush()
        e.delete("doc", "1")
        e.flush()
        e.close()
        e2 = make_engine(tmp_path)
        e2.recover_from_store()
        assert not e2.get("doc", "1").found
        assert e2.doc_stats()["count"] == 1

    def test_optimize_merges_segments(self, tmp_path):
        e = make_engine(tmp_path)
        for i in range(5):
            e.index("doc", str(i), {"t": f"word{i} common"})
            e.refresh()
        assert e.segment_count() == 5
        e.delete("doc", "0")
        e.optimize()
        assert e.segment_count() == 1
        assert e.doc_stats() == {"count": 4, "deleted": 0}
        assert e.acquire_searcher().doc_freq("t", "common") == 4

    def test_nested_docs_block_layout(self, tmp_path):
        svc = MapperService()
        svc.put_mapping("doc", {"properties": {
            "comments": {"type": "nested", "properties": {"text": {"type": "string"}}}}})
        e = Engine(str(tmp_path / "n"), svc)
        e.index("doc", "1", {"title": "post", "comments": [{"text": "aa"}, {"text": "bb"}]})
        e.refresh()
        seg = e.acquire_searcher().segments[0]
        assert seg.doc_count == 3  # 2 children + 1 parent
        assert seg.parent_mask.tolist() == [False, False, True]
        assert e.doc_stats()["count"] == 1  # only parents counted
        # delete removes the whole block — but copy-on-write: the OLD searcher's
        # segment keeps its point-in-time live bitmap (Lucene reader semantics)
        e.delete("doc", "1")
        e.refresh()
        assert seg.live.all()  # old point-in-time view unchanged
        new_seg = e.acquire_searcher().segments[0]
        assert not new_seg.live.any()
