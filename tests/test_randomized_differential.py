"""Seeded randomized differential testing: device serving vs host scorer.

The reference tests everything under carrotsearch randomizedtesting — every run
seeded and reproducible (SURVEY §4.1, TESTING.asciidoc:65). This suite applies
that strategy to the framework's core invariant: the DEVICE serving path (sparse
kernel, dense fs kernels, fused aggs) must agree with the HOST scorer on any
query the planner lowers.

Set ESTPU_TEST_SEED to reproduce a failure; the active seed prints on failure.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query
from elasticsearch_tpu.search.aggregations import reduce_aggs
from elasticsearch_tpu.search.execute import search_shard
from elasticsearch_tpu.search.service import execute_query_phase, parse_search_body
from elasticsearch_tpu.search.similarity import SimilarityService

SEED = int(os.environ.get("ESTPU_TEST_SEED", np.random.SeedSequence().entropy % (2**31)))
N_QUERIES = int(os.environ.get("ESTPU_FUZZ_QUERIES", 120))

WORDS = [f"w{i}" for i in range(120)] + ["the", "of", "and"]


def _corpus(rng, similarity):
    tmp = tempfile.mkdtemp()
    settings = Settings.from_flat({"index.similarity.default.type": similarity})
    svc = MapperService(settings)
    eng = Engine(tmp, svc)
    # doc count pinned inside one pow2 bucket (doc_pad 256) so the fuzz loop
    # reuses compiled kernels instead of paying XLA per corpus shape
    n_docs = int(rng.integers(180, 250))
    refresh_at = set(rng.integers(1, n_docs, size=int(rng.integers(0, 3))).tolist())
    for i in range(n_docs):
        d = {"body": " ".join(rng.choice(WORDS, size=int(rng.integers(1, 25)))),
             "price": float(np.round(rng.uniform(0.5, 99), 2)),
             "label": f"L{int(rng.integers(0, 9))}"}
        if rng.random() < 0.7:
            d["pop"] = int(rng.integers(1, 500))
        if rng.random() < 0.3:
            d["tags"] = [int(x) for x in rng.integers(1, 12,
                                                      size=int(rng.integers(1, 4)))]
        eng.index("doc", str(i), d)
        if i in refresh_at:
            eng.refresh()
    for local in rng.integers(0, n_docs, size=int(rng.integers(0, 12))):
        eng.delete("doc", str(int(local)))
    eng.refresh()
    ctx = ShardContext(eng.acquire_searcher(), svc,
                       SimilarityService(settings, mapper_service=svc))
    return eng, ctx


def _rand_term(rng):
    return {"term": {"body": str(rng.choice(WORDS))}}


def _rand_query(rng):
    r = rng.random()
    if r < 0.25:
        q = {"match": {"body": " ".join(rng.choice(WORDS,
                                                   size=int(rng.integers(1, 5))))}}
        if rng.random() < 0.3:
            q["match"]["body"] = {"query": q["match"]["body"], "operator": "and"}
        elif rng.random() < 0.3:
            q["match"]["body"] = {
                "query": q["match"]["body"],
                "minimum_should_match": int(rng.integers(1, 4))}
        return q
    if r < 0.35:
        return _rand_term(rng)
    if r < 0.45:
        # the ES 1.x `filtered` idiom: query + match-gating filter
        inner = _rand_query(rng) if rng.random() < 0.3 else _rand_term(rng)
        f = rng.random()
        if f < 0.4:
            filt = {"range": {"pop": {"gte": int(rng.integers(0, 400))}}}
        elif f < 0.7:
            filt = {"term": {"label": f"L{int(rng.integers(0, 9))}"}}
        else:
            filt = {"bool": {"must": [{"exists": {"field": "pop"}}],
                             "must_not": [{"term": {"label": "L0"}}]}}
        fq: dict = {"query": inner, "filter": filt}
        if rng.random() < 0.2:
            fq["boost"] = float(np.float32(rng.uniform(0.5, 2)))
        return {"filtered": fq}
    if r < 0.7:
        nb = {"should": [_rand_term(rng) for _ in range(int(rng.integers(0, 4)))],
              "must": [_rand_term(rng) for _ in range(int(rng.integers(0, 3)))],
              "must_not": [_rand_term(rng) for _ in range(int(rng.integers(0, 2)))]}
        nb = {k: v for k, v in nb.items() if v}
        if not nb.get("should") and not nb.get("must"):
            nb["should"] = [_rand_term(rng)]
        if nb.get("should") and rng.random() < 0.4:
            nb["minimum_should_match"] = int(rng.integers(1, len(nb["should"]) + 2))
        if rng.random() < 0.3:
            nb["boost"] = float(np.float32(rng.uniform(0.2, 3)))
        return {"bool": nb}
    # function_score over a random sub query
    sub = _rand_query(rng) if rng.random() < 0.5 else _rand_term(rng)
    fs: dict = {"query": sub}
    kind = rng.random()
    if kind < 0.3:
        fs["functions"] = [{_g: {"price": {"origin": float(rng.uniform(10, 60)),
                                           "scale": float(rng.uniform(5, 30))}}}
                           for _g in [str(rng.choice(["gauss", "exp", "linear"]))]]
    elif kind < 0.55:
        fs["field_value_factor"] = {
            "field": "pop", "missing": 1,
            "modifier": str(rng.choice(["none", "log1p", "sqrt", "ln2p"]))}
    elif kind < 0.75:
        fs["functions"] = [
            {"filter": {"range": {"pop": {"gte": int(rng.integers(0, 300))}}},
             "boost_factor": float(np.float32(rng.uniform(0.5, 4)))},
            {"weight": float(np.float32(rng.uniform(0.5, 2)))},
        ]
        fs["score_mode"] = str(rng.choice(["multiply", "sum", "avg", "max",
                                           "min", "first"]))
    else:
        fs["script_score"] = {"script": "_score * log(2 + doc['price'].value)"}
    fs["boost_mode"] = str(rng.choice(["multiply", "replace", "sum", "avg",
                                       "max", "min"]))
    if rng.random() < 0.2:
        fs["max_boost"] = float(np.float32(rng.uniform(1, 5)))
    if rng.random() < 0.15:
        fs["boost"] = float(np.float32(rng.uniform(0.5, 2)))
    return {"function_score": fs}


def _tie_tolerant_equal(dev, host, rel=1e-5, abs_tol=1e-9):
    """Same doc set, per-doc score parity, and identical ordering except among
    near-equal scores (the in-kernel f32 script evaluation vs host
    f64-then-cast; decay-function tails land in sub-denormal territory on one
    path and flush to zero on the other, hence the absolute floor): any
    permutation inside an approx-equal tie group is fine, an inversion across
    a real score gap is not."""
    if sorted(d for _, d in dev.hits) != sorted(d for _, d in host.hits):
        return False
    hs_by = {d: s for s, d in host.hits}
    if not all(s == pytest.approx(hs_by[d], rel=rel, abs=abs_tol)
               for s, d in dev.hits):
        return False
    dev_pos = {d: i for i, (_, d) in enumerate(dev.hits)}
    for i, (sa, a) in enumerate(host.hits):
        for sb, b in host.hits[i + 1:]:
            if sa == pytest.approx(sb, rel=rel, abs=abs_tol):
                continue  # near-tie: order is path-dependent, let it float
            if dev_pos[a] > dev_pos[b]:
                return False
    return True


@pytest.mark.parametrize("similarity", ["BM25", "default"])
def test_randomized_query_parity(similarity):
    rng = np.random.default_rng(SEED)
    eng, ctx = _corpus(rng, similarity)
    try:
        from elasticsearch_tpu.common.errors import ScriptError

        for qi in range(N_QUERIES):
            qd = _rand_query(rng)
            k = int(rng.choice([3, 10, 25]))  # few k shapes → few compiles
            try:
                host = search_shard(ctx, parse_query(qd), k, use_device=False)
            except ScriptError:
                with pytest.raises(ScriptError):
                    search_shard(ctx, parse_query(qd), k, use_device=True)
                continue
            dev = search_shard(ctx, parse_query(qd), k, use_device=True)
            assert dev.total == host.total, \
                f"seed={SEED} query#{qi} {qd}: totals {dev.total} vs {host.total}"
            assert _tie_tolerant_equal(dev, host), \
                f"seed={SEED} query#{qi} {qd}:\n dev {dev.hits[:5]}\n host {host.hits[:5]}"
    finally:
        eng.close()


def test_randomized_sort_parity():
    rng = np.random.default_rng(SEED + 2)
    eng, ctx = _corpus(rng, "BM25")
    try:
        import math

        for qi in range(max(N_QUERIES // 4, 10)):
            spec: dict = {"order": str(rng.choice(["asc", "desc"]))}
            if rng.random() < 0.4:
                spec["missing"] = str(rng.choice(["_last", "_first"])) \
                    if rng.random() < 0.7 else int(rng.integers(0, 600))
            field = str(rng.choice(["pop", "tags"]))
            if field == "tags" and rng.random() < 0.6:
                spec["mode"] = str(rng.choice(["min", "max"]))
            body = {"query": _rand_query(rng), "sort": [{field: spec}],
                    "size": int(rng.integers(1, 20))}
            req = parse_search_body(body)
            dev = execute_query_phase(ctx, req, use_device=True)
            host = execute_query_phase(ctx, req, use_device=False)
            assert dev.total == host.total, f"seed={SEED} sort#{qi} {body}"
            assert [(g, v) for _s, g, v in dev.docs] == \
                [(g, v) for _s, g, v in host.docs], \
                f"seed={SEED} sort#{qi} {body}:\n{dev.docs[:5]}\n{host.docs[:5]}"
            if not (math.isnan(dev.max_score) and math.isnan(host.max_score)):
                assert dev.max_score == pytest.approx(host.max_score, rel=1e-5)
    finally:
        eng.close()


def test_randomized_agg_parity():
    rng = np.random.default_rng(SEED + 1)
    eng, ctx = _corpus(rng, "BM25")
    try:
        for qi in range(max(N_QUERIES // 4, 10)):
            aggs = {}
            for ai in range(int(rng.integers(1, 4))):
                kind = rng.random()
                field = str(rng.choice(["price", "pop", "tags"]))
                if kind < 0.3:
                    aggs[f"a{ai}"] = {str(rng.choice(
                        ["avg", "sum", "min", "max", "stats", "value_count"])):
                        {"field": field}}
                elif kind < 0.5:
                    aggs[f"a{ai}"] = {"terms": {"field": str(rng.choice(
                        ["label", "pop", "tags"])), "size": 50}}
                elif kind < 0.65:
                    aggs[f"a{ai}"] = {"histogram": {
                        "field": field,
                        "interval": float(rng.choice([2, 5, 10, 25]))}}
                elif kind < 0.8:
                    lo = int(rng.integers(0, 200))
                    aggs[f"a{ai}"] = {str(rng.choice(["range", "missing"])): (
                        {"field": field, "ranges": [
                            {"to": lo}, {"from": lo, "to": lo + 150},
                            {"from": lo + 150}]}
                        if rng.random() < 0.7 else {"field": field})}
                    if "ranges" not in list(aggs[f"a{ai}"].values())[0] \
                            and "range" in aggs[f"a{ai}"]:
                        aggs[f"a{ai}"] = {"missing": {"field": field}}
                else:
                    # bucket + metric sub-agg tree
                    sub = {f"s{ai}": {str(rng.choice(
                        ["avg", "sum", "min", "max", "stats"])):
                        {"field": str(rng.choice(["price", "pop", "tags"]))}}}
                    aggs[f"a{ai}"] = {"terms": {"field": str(rng.choice(
                        ["label", "pop"])), "size": 50}, "aggs": sub}
            body = {"query": _rand_query(rng), "size": int(rng.integers(0, 10)),
                    "aggs": aggs}
            req = parse_search_body(body)
            dev = execute_query_phase(ctx, req, use_device=True)
            host = execute_query_phase(ctx, req, use_device=False)
            assert dev.total == host.total, f"seed={SEED} agg#{qi} {body}"
            dr = reduce_aggs(req.aggs, dev.agg_partials)
            hr = reduce_aggs(req.aggs, host.agg_partials)
            _deep_approx(dr, hr, f"seed={SEED} agg#{qi} {body}")
    finally:
        eng.close()


def _deep_approx(a, b, ctx_msg, path=""):
    if isinstance(a, dict) and isinstance(b, dict):
        assert set(a) == set(b), (ctx_msg, path)
        for k in a:
            _deep_approx(a[k], b[k], ctx_msg, f"{path}.{k}")
    elif isinstance(a, list) and isinstance(b, list):
        assert len(a) == len(b), (ctx_msg, path, a, b)
        for i, (x, y) in enumerate(zip(a, b)):
            _deep_approx(x, y, ctx_msg, f"{path}[{i}]")
    elif a is None or b is None:
        assert a is None and b is None, (ctx_msg, path, a, b)
    elif isinstance(a, float) or isinstance(b, float):
        assert a == pytest.approx(b, rel=1e-5, abs=1e-9), (ctx_msg, path, a, b)
    else:
        assert a == b, (ctx_msg, path, a, b)
