"""Stall watchdog + cluster event journal (common/events.py) — ISSUE 13
tentpole (c).

Covers: journal units (bounded ring, per-(type,key) rate limiting, typed
vocabulary, remote ingest dedup), watchdog check units against stub serving
state (batch stall vs the batcher EWMA, queue-wait delta-p99 spikes, breaker
near-trip dwell), the REST surfaces (/_events, /_cat/events, nodes-stats
section, Prometheus counters), cross-node gossip, and the acceptance chaos:
a FaultPolicy-injected device-pull stall is detected within 2 watchdog
periods, producing a typed event naming the shard and batch while healthy
traffic keeps serving.
"""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace

import pytest

from elasticsearch_tpu.common.events import (EVENT_TYPES, EventJournal,
                                             StallWatchdog)
from elasticsearch_tpu.common.metrics import HistogramMetric
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.transport.faults import DEVICE_PULL

from .harness import TestCluster


# ---------------------------------------------------------------------------
# journal units
# ---------------------------------------------------------------------------


class TestEventJournal:
    def _journal(self, **flat):
        return EventJournal(Settings.from_flat(flat), node_name="n1",
                            node_id="n1")

    def test_publish_shape_and_ring_bound(self):
        j = self._journal(**{"node.events.size": 8,
                             "node.events.throttle": "0ms"})
        for i in range(20):
            ev = j.publish("queue_spike", f"m{i}", key=f"k{i}", pool="search")
            assert ev is not None
            assert ev["type"] == "queue_spike" and ev["node"] == "n1"
            assert ev["attrs"] == {"pool": "search"}
        assert len(j.events()) == 8  # ring bound
        assert j.events()[0]["message"] == "m19"  # newest first
        assert j.stats()["emitted"] == 20

    def test_rate_limit_per_type_key(self):
        j = self._journal(**{"node.events.throttle": "10s"})
        assert j.publish("batch_stall", "x", key="b:1") is not None
        assert j.publish("batch_stall", "x again", key="b:1") is None
        assert j.publish("batch_stall", "other batch", key="b:2") is not None
        assert j.publish("queue_spike", "other type", key="b:1") is not None
        assert j.stats()["suppressed"] == 1

    def test_unknown_type_folds_to_watchdog(self):
        j = self._journal()
        ev = j.publish("totally-new", "m")
        assert ev["type"] == "watchdog"
        assert set(j.stats()["by_type"]) == set(EVENT_TYPES)

    def test_ingest_stamps_missing_ts(self):
        """A ts-less gossiped event must not poison every future events()
        sort for the ring's lifetime — arrival time is stamped."""
        j = self._journal()
        assert j.ingest({"seq": 1, "node": "n2", "type": "batch_stall"})
        assert j.ingest({"seq": 2, "node": "n2", "type": "batch_stall",
                         "ts": "bogus"})
        evs = j.events()  # must not raise
        assert all(isinstance(e["ts"], float) and e["ts"] > 0 for e in evs)

    def test_remote_ingest_dedup(self):
        j = self._journal()
        ev = {"seq": 3, "ts": time.time(), "node": "n2", "type": "batch_stall",
              "severity": "warn", "message": "remote", "attrs": {}}
        assert j.ingest(ev) is True
        assert j.ingest(dict(ev)) is False  # same origin seq
        assert j.ingest({**ev, "seq": 2}) is False  # older than watermark
        assert j.ingest({**ev, "seq": 4}) is True
        assert j.ingest({**ev, "node": "n1", "seq": 99}) is False  # our own
        st = j.stats()
        assert st["remote_ingested"] == 2 and st["remote_duplicates"] == 2


# ---------------------------------------------------------------------------
# watchdog check units (stub serving state)
# ---------------------------------------------------------------------------


def _stub_node(**over):
    node = SimpleNamespace(
        node_id="n1",
        settings=Settings.EMPTY,
        events=EventJournal(Settings.from_flat(
            {"node.events.throttle": "0ms"}), node_id="n1"),
        search_batcher=SimpleNamespace(inflight=lambda: None,
                                       _ewma_cost=0.004),
        threadpool=SimpleNamespace(pool_histograms=lambda: {}),
        breakers=SimpleNamespace(stats=lambda: {}),
        cluster_service=SimpleNamespace(
            state=SimpleNamespace(nodes=SimpleNamespace(nodes=[]))),
    )
    for k, v in over.items():
        setattr(node, k, v)
    return node


def _dog(node, **flat):
    return StallWatchdog(node, Settings.from_flat(flat))


class TestWatchdogChecks:
    def test_batch_stall_adaptive_threshold(self):
        node = _stub_node()
        snap = {"batch": 7, "age_s": 0.3, "family": "flat",
                "occupancy": 4, "shard": "idx"}
        node.search_batcher = SimpleNamespace(inflight=lambda: snap,
                                              _ewma_cost=0.01)
        dog = _dog(node, **{"watchdog.batch_stall_min": "100ms",
                            "watchdog.batch_stall_factor": 8.0})
        dog.tick()
        (ev,) = [e for e in node.events.events()
                 if e["type"] == "batch_stall"]
        assert ev["attrs"]["batch"] == 7 and ev["attrs"]["shard"] == "idx"
        assert "idx" in ev["message"] and "[7]" in ev["message"]
        # a batch younger than factor x EWMA stays quiet
        node2 = _stub_node()
        node2.search_batcher = SimpleNamespace(
            inflight=lambda: {**snap, "age_s": 0.05}, _ewma_cost=0.01)
        dog2 = _dog(node2, **{"watchdog.batch_stall_min": "100ms",
                              "watchdog.batch_stall_factor": 8.0})
        dog2.tick()
        assert node2.events.events() == []

    def test_queue_spike_on_delta_p99(self):
        hist = HistogramMetric()
        node = _stub_node(threadpool=SimpleNamespace(
            pool_histograms=lambda: {"search": hist}))
        dog = _dog(node, **{"watchdog.queue_p99_min": "50ms",
                            "watchdog.queue_min_samples": 4})
        dog.tick()  # primes the delta baseline
        for _ in range(10):
            hist.observe(0.001)
        dog.tick()  # healthy tick, learns ~1ms baseline
        assert node.events.events() == []
        for _ in range(10):
            hist.observe(0.8)  # the brown-out
        dog.tick()
        (ev,) = [e for e in node.events.events()
                 if e["type"] == "queue_spike"]
        assert ev["attrs"]["pool"] == "search"
        assert ev["attrs"]["p99_ms"] > 500

    def test_breaker_dwell_needs_consecutive_ticks(self):
        stats = {"request": {"limit": 100, "estimated": 95, "tripped": 0}}
        node = _stub_node(breakers=SimpleNamespace(stats=lambda: stats))
        dog = _dog(node, **{"watchdog.breaker_dwell_ticks": 2})
        dog.tick()
        assert node.events.events() == []  # dwell 1 of 2
        dog.tick()
        (ev,) = [e for e in node.events.events()
                 if e["type"] == "breaker_pressure"]
        assert ev["attrs"]["breaker"] == "request"
        assert ev["attrs"]["dwell_ticks"] == 2
        # dropping below the line resets the dwell
        stats["request"]["estimated"] = 10
        dog.tick()
        stats["request"]["estimated"] = 95
        dog.tick()
        assert len([e for e in node.events.events()
                    if e["type"] == "breaker_pressure"]) == 1

    def test_broken_check_does_not_kill_the_tick(self):
        node = _stub_node(breakers=SimpleNamespace(
            stats=lambda: (_ for _ in ()).throw(RuntimeError("boom"))))
        dog = _dog(node)
        dog.tick()  # must not raise
        assert dog.ticks == 1


# ---------------------------------------------------------------------------
# live: the acceptance chaos + surfaces + gossip
# ---------------------------------------------------------------------------


WATCHDOG_SETTINGS = {
    "watchdog.interval": "100ms",
    "watchdog.batch_stall_min": "200ms",
    "watchdog.batch_stall_factor": 2.0,
    "node.events.throttle": "0ms",
    # a tiny coalescing queue so healthy traffic bypasses to direct launches
    # while the drainer is wedged on the injected stall
    "search.batch.queue_size": 1,
    "search.mesh.enabled": False,
}


def _boot(tmp_path, nodes=1, settings=None):
    cluster = TestCluster(n_nodes=nodes, data_root=tmp_path, seed=3,
                          settings={**WATCHDOG_SETTINGS, **(settings or {})})
    cluster.start()
    c = cluster.client()
    for name in ("stall", "healthy"):
        c.create_index(name, {"settings": {"number_of_shards": 1,
                                           "number_of_replicas": 0}})
        cluster.ensure_green(name)
        for i in range(15):
            c.index(name, "doc", {"body": f"alpha{i % 3}"}, id=str(i))
        c.refresh(name)
    return cluster, c


@pytest.mark.insights
class TestLiveWatchdog:
    def test_device_pull_stall_detected_within_two_periods(self, tmp_path):
        """The acceptance pin: a FaultPolicy-injected device-pull stall is
        detected by the watchdog within 2 watchdog periods of crossing the
        threshold, producing a typed /_events entry naming the shard and
        batch, while healthy traffic keeps serving."""
        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        interval = node.watchdog.interval_s
        threshold = node.watchdog.batch_min_s
        try:
            # warm both indices (compiles + request-cache store for healthy)
            c.search("stall", {"query": {"match": {"body": "alpha1"}}})
            c.search("healthy", {"query": {"match": {"body": "alpha1"}},
                                 "size": 0})
            DEVICE_PULL.arm(2.0, index="stall", times=1)
            out = {}

            def stalled():
                t0 = time.monotonic()
                out["r"] = c.search("stall",
                                    {"query": {"match": {"body": "alpha2"}}})
                out["dt"] = time.monotonic() - t0

            th = threading.Thread(target=stalled)
            t_start = time.monotonic()
            th.start()
            # poll for the typed event; it must land within threshold + 2
            # watchdog periods (+ scheduler slack) of the dispatch
            deadline = threshold + 2 * interval + 0.35
            ev = None
            while time.monotonic() - t_start < 2.0 and ev is None:
                evs = [e for e in node.events.events()
                       if e["type"] == "batch_stall"]
                ev = evs[0] if evs else None
                if ev is None:
                    time.sleep(0.02)
            detected_at = time.monotonic() - t_start
            assert ev is not None, "stall never detected"
            assert detected_at <= deadline, (detected_at, deadline)
            # the event names the shard and the batch
            assert ev["attrs"]["shard"] == "stall"
            assert isinstance(ev["attrs"]["batch"], int)
            assert "stall" in ev["message"]
            assert ev["severity"] == "warn"

            # healthy traffic keeps serving DURING the stall: the cached
            # query answers instantly (zero batcher), and a direct query
            # bypasses the wedged drainer through the full queue
            t0 = time.monotonic()
            r = c.search("healthy", {"query": {"match": {"body": "alpha1"}},
                                     "size": 0})
            assert r["hits"]["total"] > 0
            assert time.monotonic() - t0 < 1.0
            assert time.monotonic() - t_start < 2.0, \
                "healthy check ran after the stall already cleared"

            th.join(10.0)
            assert out["r"]["hits"]["total"] > 0  # the stalled search lands
            assert out["dt"] >= 2.0
        finally:
            DEVICE_PULL.disarm()
            cluster.close()

    def test_events_surfaces(self, tmp_path):
        from elasticsearch_tpu.rest.controller import (RestRequest,
                                                       build_rest_controller)

        cluster, c = _boot(tmp_path)
        node = next(iter(cluster.nodes.values()))
        try:
            node.events.publish("queue_spike", "pool [search] p99 spiked",
                                key="pool:search", pool="search", p99_ms=900)
            rc = build_rest_controller(node)
            r = rc.dispatch(RestRequest(method="GET", path="/_events",
                                        params={}))
            assert r.status == 200 and r.body["total"] >= 1
            types = {e["type"] for e in r.body["events"]}
            assert "queue_spike" in types
            r = rc.dispatch(RestRequest(method="GET", path="/_events",
                                        params={"local": "true",
                                                "size": "1"}))
            assert len(r.body["events"]) == 1
            bad = rc.dispatch(RestRequest(method="GET", path="/_events",
                                          params={"size": "bogus"}))
            assert bad.status == 400
            r = rc.dispatch(RestRequest(method="GET", path="/_cat/events",
                                        params={"v": ""}))
            assert r.status == 200 and "queue_spike" in r.body
            # nodes-stats section + Prometheus counters
            st = c.nodes_stats(metric="events")
            (sections,) = st["nodes"].values()
            assert sections["events"]["journal"]["emitted"] >= 1
            assert sections["events"]["watchdog"]["ticks"] >= 0
            from elasticsearch_tpu.rest.controller import _prometheus_text
            from tools.obs_smoke import _parse_prometheus

            text = _prometheus_text(node)
            _parse_prometheus(text)
            assert 'estpu_events_emitted_total{type="queue_spike"}' in text
            assert "estpu_watchdog_ticks_total" in text
        finally:
            cluster.close()

    def test_gossip_reaches_peer_journals_and_events_dedup(self, tmp_path):
        cluster, c = _boot(tmp_path, nodes=2)
        nodes = list(cluster.nodes.values())
        origin, peer = nodes[0], nodes[1]
        try:
            ev = origin.events.publish("breaker_pressure",
                                       "breaker [request] dwelling",
                                       key="breaker:request",
                                       breaker="request")
            origin.watchdog._gossip(ev)
            for _ in range(100):
                if peer.events.stats()["remote_ingested"] >= 1:
                    break
                time.sleep(0.02)
            remote = [e for e in peer.events.events()
                      if e["type"] == "breaker_pressure"]
            assert remote and remote[0]["node"] == origin.node_id
            # the cluster view dedups the gossiped copy against the origin's
            total = peer.client().cluster_events()
            matching = [e for e in total["events"]
                        if e["type"] == "breaker_pressure"]
            assert len(matching) == 1, matching
        finally:
            cluster.close()


class TestDevicePullFaults:
    def test_arm_times_and_index_matching(self):
        DEVICE_PULL.disarm()
        DEVICE_PULL.arm(0.5, index="only-this", times=2)
        try:
            assert DEVICE_PULL.delay_for("other") == 0.0
            assert DEVICE_PULL.delay_for("only-this") == 0.5
            assert DEVICE_PULL.delay_for("only-this") == 0.5
            # budget exhausted -> auto-disarm
            assert DEVICE_PULL.delay_for("only-this") == 0.0
            assert DEVICE_PULL.active is False
        finally:
            DEVICE_PULL.disarm()
