"""End-to-end search tracing + node telemetry (common/tracing.py, PR 8).

Covers: HistogramMetric units (log-spaced buckets, stripes, percentiles,
Prometheus cumulative view), tracer/span units (sampling, ring bound, wire
context through the binary codec, in-flight tasks), the live-cluster
acceptance path — `_search?trace=true` through the batcher yields a
rest → coordinator → shard → batcher{queue,dispatch,merge} → device-pull
span tree with the batch's device span attributed to every coalesced member
and child durations summing to ≤ each parent — plus `/_nodes/stats/{metric}`
filtering, the Prometheus exposition (parsed with a minimal text-format
parser), the slowlog trace join, the zero-new-syncs sanitizer invariant
(warmed traced loop = 0 recompiles under transfer_guard("disallow")), and a
tpulint-clean scan over every instrumented file."""

import json
import logging
import threading
import time

import pytest

from elasticsearch_tpu.common import tracing
from elasticsearch_tpu.common.metrics import HistogramMetric
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.common.stream import StreamInput, StreamOutput
from elasticsearch_tpu.common.tracing import (
    NOOP_SPAN,
    TraceContext,
    Tracer,
    phase_breakdown,
    span_tree,
)
from elasticsearch_tpu.rest.controller import RestRequest, build_rest_controller

from .harness import TestCluster

WORDS = ["quick", "brown", "fox", "lazy", "dog", "summer", "red", "bear"]


# ---------------------------------------------------------------------------
# HistogramMetric
# ---------------------------------------------------------------------------


class TestHistogramMetric:
    def test_bucketing_and_percentiles(self):
        h = HistogramMetric()
        for _ in range(90):
            h.observe(0.001)  # 1ms
        for _ in range(10):
            h.observe(0.1)  # 100ms
        assert h.count == 100
        assert abs(h.sum - (90 * 0.001 + 10 * 0.1)) < 1e-9
        p50 = h.percentile(0.50)
        p99 = h.percentile(0.99)
        # p50 lands in the ~1ms bucket, p99 in the ~100ms bucket; log-spaced
        # buckets bound the relative error by the bucket ratio (2x)
        assert 0.0004 < p50 < 0.004, p50
        assert 0.04 < p99 < 0.3, p99
        assert p50 <= h.percentile(0.95) <= p99

    def test_empty_and_overflow(self):
        h = HistogramMetric()
        assert h.percentile(0.99) == 0.0
        assert h.stats()["count"] == 0
        h.observe(10_000.0)  # beyond the last bound -> overflow bucket
        buckets, total, _ = h.cumulative()
        assert total == 1
        assert buckets[-1] == (float("inf"), 1)
        assert buckets[-2][1] == 0  # nothing below the last finite bound

    def test_concurrent_observes_lose_nothing(self):
        h = HistogramMetric()

        def worker(seed):
            for i in range(500):
                h.observe(0.0001 * ((seed + i) % 7 + 1))

        threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count == 8 * 500

    def test_cumulative_monotone(self):
        h = HistogramMetric()
        for v in (0.0002, 0.003, 0.04, 0.5, 6.0):
            h.observe(v)
        buckets, total, _ = h.cumulative()
        cums = [c for (_b, c) in buckets]
        assert cums == sorted(cums)
        assert cums[-1] == total == 5

    def test_stats_shape(self):
        h = HistogramMetric()
        h.observe(0.01)
        st = h.stats()
        assert set(st) == {"count", "mean_ms", "p50_ms", "p95_ms", "p99_ms"}
        assert st["count"] == 1 and st["mean_ms"] > 0


# ---------------------------------------------------------------------------
# tracer / span units
# ---------------------------------------------------------------------------


def _tracer(rate="0", ring=None):
    flat = {"search.trace.sample_rate": rate}
    if ring is not None:
        flat["search.trace.ring_size"] = str(ring)
    t = Tracer(Settings.from_flat(flat), node_name="test")
    # the unit tests pin explicit rates — neutralize the CI leg's ESTPU_TRACE
    # override so sampled/unsampled behavior is deterministic here
    t.sample_rate = float(rate)
    return t


class TestTracerUnits:
    def test_unsampled_is_noop(self):
        tr = _tracer("0")
        trace = tr.start_trace("rest")
        assert not trace
        assert trace.root is NOOP_SPAN
        assert trace.span("x") is NOOP_SPAN
        trace.root.end()
        assert tr.traces() == []
        # activating a noop span keeps tracing off for the scope (falsy
        # current span) but MARKS the sampling decision as made: a
        # downstream layer (the coordinator under REST ingress) must see the
        # noop — not None — so it does not roll the sampling dice again
        with tracing.activate(trace.root):
            cur = tracing.current_span()
            assert cur is NOOP_SPAN and not cur
            assert cur.child("coordinator") is NOOP_SPAN
        assert tracing.current_span() is None

    def test_rest_decline_suppresses_coordinator_roll(self, monkeypatch):
        # the double-roll bug: REST ingress loses its sampling roll, the
        # coordinator cannot tell "decided unsampled" from "no decision" and
        # rolls AGAIN — inflating the effective rate (1-(1-r)^2) and rooting
        # the extra traces at "coordinator" with no rest span. The first
        # roll fails (0.99 >= rate), a second roll WOULD succeed (0.0)
        rolls = iter([0.99, 0.0, 0.0])
        monkeypatch.setattr(tracing.random, "random", lambda: next(rolls))
        tr = _tracer("0")
        tr.sample_rate = 0.5
        trace = tr.start_trace("rest")  # roll 1: declined
        assert not trace
        with tracing.activate(trace.root):
            # actions.search's exact pattern: a present (noop) parent means
            # the decision is made — child, never start_trace
            parent = tracing.current_span()
            assert parent is not None
            span = parent.child("coordinator")
            assert span is NOOP_SPAN
        assert tr.stats()["sampled"] == 0
        assert next(rolls) == 0.0  # the second roll was never consumed

    def test_late_span_close_refreshes_ring(self):
        # a timed-out shard attempt's transport span ends only when the late
        # response (or transport error / in-flight backstop) resolves its
        # future — possibly AFTER the root closed. The close must refresh
        # the ring snapshot like a late add_remote does
        tr = _tracer("0")
        trace = tr.start_trace("rest", force=True)
        child = trace.root.child("transport[q]")
        trace.root.end()
        assert {s["name"] for s in tr.traces()[0]["spans"]} == {"rest"}
        child.end()
        assert {s["name"] for s in tr.traces()[0]["spans"]} == \
            {"rest", "transport[q]"}

    def test_late_remote_stitch_refreshes_ring(self):
        # a shard chain the coordinator backstop abandoned resolves AFTER
        # the root span ended: add_remote must refresh the ring snapshot so
        # the stitched spans still reach /_traces (and only grow it)
        tr = _tracer("0", ring=4)
        trace = tr.start_trace("rest", force=True)
        root_id = trace.root.span_id
        trace.root.end()
        assert len(tr.traces()[0]["spans"]) == 1
        trace.add_remote([{"id": 99, "parent": root_id, "name": "shard",
                           "t0": 0.0, "t1": 0.5, "duration_ms": 500.0,
                           "tags": {}}])
        (snap,) = tr.traces()
        assert {s["name"] for s in snap["spans"]} == {"rest", "shard"}
        assert tr.stats()["finished"] == 1  # refreshed in place, not re-added
        # an entry the bounded ring already evicted stays evicted
        for _ in range(4):
            t2 = tr.start_trace("rest", force=True)
            t2.root.end()
        trace.add_remote([{"id": 100, "parent": root_id, "name": "late",
                           "t0": 0.0, "t1": 0.1, "duration_ms": 100.0,
                           "tags": {}}])
        assert all(s["trace_id"] != trace.trace_id for s in tr.traces())

    def test_forced_trace_records_and_rings(self):
        tr = _tracer("0", ring=4)
        ids = []
        for _ in range(7):
            trace = tr.start_trace("rest", force=True)
            with trace.root.child("coordinator"):
                pass
            trace.root.end()
            ids.append(trace.trace_id)
        got = tr.traces()
        assert len(got) == 4  # bounded ring keeps the newest
        assert [t["trace_id"] for t in got] == ids[-1:-5:-1]  # newest first
        names = {s["name"] for s in got[0]["spans"]}
        assert names == {"rest", "coordinator"}

    def test_tasks_shows_in_flight(self):
        tr = _tracer("0")
        trace = tr.start_trace("rest", force=True)
        child = trace.root.child("coordinator")
        tasks = tr.tasks()
        assert len(tasks) == 1
        assert tasks[0]["trace_id"] == trace.trace_id
        assert tasks[0]["current_span"] == "coordinator"
        assert tasks[0]["cancellable"] is False
        assert tasks[0]["running_time_ms"] >= 0
        child.end()
        trace.root.end()
        assert tr.tasks() == []
        assert tr.stats()["in_flight"] == 0

    def test_wire_context_roundtrips_binary_codec(self):
        ctx = TraceContext("abcd1234abcd1234", 1234567890123)
        out = StreamOutput()
        out.write_value({"body": {"q": 1}, "_trace": ctx})
        back = StreamInput(out.bytes()).read_value()
        assert back["_trace"] == ctx
        assert back["body"] == {"q": 1}

    def test_continue_trace_stitches_parent(self):
        tr = _tracer("0")
        root_trace = tr.start_trace("rest", force=True)
        wire = tr.wire_context(root_trace.root)
        shard_trace = tr.continue_trace(wire, "shard")
        assert shard_trace.trace_id == root_trace.trace_id
        assert shard_trace.root.parent_id == root_trace.root.span_id
        shard_trace.root.end()
        root_trace.add_remote(shard_trace.span_dicts())
        root_trace.root.end()
        tree = span_tree(root_trace.span_dicts())
        assert tree["name"] == "rest"
        assert [c["name"] for c in tree["children"]] == ["shard"]
        # continuing nothing is a noop trace
        assert not tr.continue_trace(None, "shard")

    def test_record_explicit_times_and_phase_breakdown(self):
        tr = _tracer("0")
        trace = tr.start_trace("shard", force=True)
        t0 = time.monotonic()
        q = trace.root.record("batcher.queue", t0, t0 + 0.010)
        m = trace.root.record("batcher.merge", t0 + 0.012, t0 + 0.030)
        m.record("device_pull", t0 + 0.012, t0 + 0.020)
        assert q.t1 - q.t0 == pytest.approx(0.010)
        trace.root.end()
        phases = phase_breakdown(trace)
        assert phases["queue_ms"] == pytest.approx(10.0, abs=0.1)
        assert phases["device_ms"] == pytest.approx(8.0, abs=0.1)
        # merge phase is the host-side remainder (merge minus the pull)
        assert phases["merge_ms"] == pytest.approx(10.0, abs=0.1)
        # an unsampled request reads zeros + joins on "-"
        assert phase_breakdown(None) == {"queue_ms": 0.0, "device_ms": 0.0,
                                         "merge_ms": 0.0}


# ---------------------------------------------------------------------------
# live cluster: the ?trace=true contract through the batcher
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tracing")
    with TestCluster(n_nodes=1, data_root=tmp, seed=3, settings={
        # a visible linger window so two concurrent requests coalesce
        "search.batch.linger_ms": "40",
        "search.batch.max_batch": "8",
    }) as cluster:
        node = next(iter(cluster.nodes.values()))
        client = node.client()
        client.create_index("traced", {"settings": {
            "number_of_shards": 1, "number_of_replicas": 0}})
        cluster.ensure_green("traced")
        for i in range(40):
            client.index("traced", "doc",
                         {"body": f"{WORDS[i % 8]} {WORDS[(i + 1) % 8]}"},
                         id=str(i))
        client.refresh("traced")
        rc = build_rest_controller(node)
        # warm occupancy-1 and occupancy-2 executables so traced passes below
        # measure bookkeeping, not XLA compiles
        _concurrent_searches(rc, 2, trace=False)
        yield cluster, node, rc


SEARCH_BODY = {"query": {"match": {"body": "quick brown"}}, "size": 5}


def _concurrent_searches(rc, n, trace=True):
    barrier = threading.Barrier(n)
    out = [None] * n

    def worker(i):
        barrier.wait()
        params = {"trace": "true"} if trace else {}
        out[i] = rc.dispatch(RestRequest(
            method="POST", path="/traced/_search", params=params,
            body=dict(SEARCH_BODY)))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    return out


def _flatten(node, out=None):
    out = [] if out is None else out
    out.append(node)
    for c in node["children"]:
        _flatten(c, out)
    return out


def _find(node, name):
    return [n for n in _flatten(node) if n["name"] == name]


class TestLiveTraceTree:
    def test_trace_true_span_tree_through_batcher(self, live):
        _cluster, node, rc = live
        # retry the race: two requests must land in the SAME linger window for
        # coalesced attribution; each attempt is two fresh traced searches
        coalesced = None
        for _attempt in range(8):
            results = _concurrent_searches(rc, 2)
            assert all(r.status == 200 for r in results), \
                [r.body for r in results]
            trees = [r.body["trace"]["tree"] for r in results]
            dispatches = [
                _find(t, "batcher.dispatch") for t in trees]
            if all(len(d) == 1 for d in dispatches):
                tags = [d[0]["tags"] for d in dispatches]
                if (tags[0].get("occupancy", 0) >= 2
                        and tags[0].get("batch") == tags[1].get("batch")):
                    coalesced = (results, trees, tags)
                    break
        assert coalesced is not None, "requests never coalesced in 8 attempts"
        results, trees, tags = coalesced
        for resp, tree in zip(results, trees):
            # the acceptance chain: rest → coordinator → (transport) → shard →
            # batcher{queue,dispatch,merge} → device_pull
            assert tree["name"] == "rest"
            names = {n["name"] for n in _flatten(tree)}
            assert {"rest", "coordinator", "shard", "batcher.queue",
                    "batcher.dispatch", "batcher.merge",
                    "device_pull"} <= names, names
            (coord,) = _find(tree, "coordinator")
            (shard,) = _find(tree, "shard")
            # the shard span nests (via the transport span) under coordinator
            assert any(n["name"].startswith("transport[")
                       for n in _flatten(coord))
            batcher_names = {c["name"] for c in shard["children"]}
            assert {"batcher.queue", "batcher.dispatch",
                    "batcher.merge"} <= batcher_names
            (merge,) = _find(shard, "batcher.merge")
            assert [c["name"] for c in merge["children"]] == ["device_pull"]
            # every coalesced member carries the shared batch's device span
            (pull,) = _find(tree, "device_pull")
            assert pull["tags"]["batch"] == tags[0]["batch"]
            assert pull["duration_ms"] >= 0
            # child durations sum to ≤ the parent, all the way down
            self._assert_child_sums(tree)
            # the response trace id is findable in the node's /_traces ring
            tid = resp.body["trace"]["trace_id"]
            ring_ids = {t["trace_id"] for t in node.tracer.traces()}
            assert tid in ring_ids

    def _assert_child_sums(self, n):
        child_sum = sum(c["duration_ms"] for c in n["children"])
        assert child_sum <= n["duration_ms"] + 1.0, \
            (n["name"], child_sum, n["duration_ms"])
        for c in n["children"]:
            self._assert_child_sums(c)

    def test_scrolled_search_honors_trace_param(self, live):
        # the scroll branch returns early from the REST handler — it must
        # still root the trace: the initial scan/scroll search is a normal
        # fan-out and ?trace=true promises an inline tree
        _cluster, node, rc = live
        resp = rc.dispatch(RestRequest(
            method="POST", path="/traced/_search",
            params={"scroll": "1m", "trace": "true"},
            body=dict(SEARCH_BODY)))
        assert resp.status == 200
        assert "_scroll_id" in resp.body
        tree = resp.body["trace"]["tree"]
        assert tree["name"] == "rest"
        names = {n["name"] for n in _flatten(tree)}
        assert {"rest", "coordinator", "shard"} <= names, names
        ring_ids = {t["trace_id"] for t in node.tracer.traces()}
        assert resp.body["trace"]["trace_id"] in ring_ids

    def test_untraced_response_has_no_trace_section(self, live):
        _cluster, _node, rc = live
        (resp,) = _concurrent_searches(rc, 1, trace=False)
        assert resp.status == 200
        assert "trace" not in resp.body

    def test_traces_and_tasks_endpoints(self, live):
        _cluster, node, rc = live
        r = rc.dispatch(RestRequest(method="GET", path="/_traces", params={}))
        assert r.status == 200
        assert r.body["total"] == len(r.body["traces"])
        assert r.body["tracing"]["ring_size"] >= r.body["total"]
        for entry in r.body["traces"]:
            assert {"trace_id", "node", "name", "duration_ms",
                    "spans"} <= set(entry)
        t = rc.dispatch(RestRequest(method="GET", path="/_tasks", params={}))
        assert t.status == 200
        (node_entry,) = t.body["nodes"].values()
        assert isinstance(node_entry["tasks"], list)

    def test_slowlog_line_joins_the_trace(self, live):
        _cluster, node, rc = live
        client = node.client()
        client.update_settings("traced", {
            "index.search.slowlog.threshold.query.warn": "0ms"})
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = _Capture()
        logging.getLogger("estpu.action").addHandler(handler)
        try:
            (resp,) = _concurrent_searches(rc, 1)
        finally:
            logging.getLogger("estpu.action").removeHandler(handler)
            client.update_settings("traced", {
                "index.search.slowlog.threshold.query.warn": "-1"})
        assert resp.status == 200
        tid = resp.body["trace"]["trace_id"]
        slow = [m for m in records if "slowlog" in m]
        assert slow, records
        joined = [m for m in slow if f"trace[{tid}]" in m]
        assert joined, slow
        # the per-phase breakdown is on the line (joinable to /_traces)
        assert "queue[" in joined[0] and "device[" in joined[0] \
            and "merge[" in joined[0]


# ---------------------------------------------------------------------------
# /_nodes/stats/{metric} + Prometheus exposition
# ---------------------------------------------------------------------------


def _parse_prometheus(text):
    """Minimal text-format parser: {series_key: value}, {family: type}."""
    types, series = {}, {}
    for line in text.splitlines():
        if not line or line.startswith("# HELP"):
            continue
        if line.startswith("# TYPE"):
            _h, _t, name, typ = line.split()
            types[name] = typ
            continue
        key, val = line.rsplit(" ", 1)
        series[key] = float(val)
    return types, series


class TestStatsSurfaces:
    def test_nodes_stats_metric_filtering(self, live):
        _cluster, node, rc = live
        r = rc.dispatch(RestRequest(
            method="GET", path="/_nodes/stats/thread_pool,breakers", params={}))
        assert r.status == 200
        (sections,) = r.body["nodes"].values()
        assert sorted(sections) == ["breakers", "thread_pool"]
        # every section in the unfiltered response is addressable by name
        full = rc.dispatch(RestRequest(method="GET", path="/_nodes/stats",
                                       params={}))
        (all_sections,) = full.body["nodes"].values()
        for metric in all_sections:
            one = rc.dispatch(RestRequest(
                method="GET", path=f"/_nodes/stats/{metric}", params={}))
            assert one.status == 200, metric
            (s,) = one.body["nodes"].values()
            assert list(s) == [metric]

    def test_unknown_metric_is_400(self, live):
        _cluster, _node, rc = live
        r = rc.dispatch(RestRequest(method="GET", path="/_nodes/stats/bogus",
                                    params={}))
        assert r.status == 400
        assert "bogus" in json.dumps(r.body)

    def test_stats_carry_histogram_percentiles(self, live):
        _cluster, node, _rc = live
        stats = node.client().nodes_stats()["nodes"][node.node_id]
        lat = stats["search"]["latency"]
        assert lat["count"] >= 1
        assert lat["p99_ms"] >= lat["p50_ms"] >= 0
        assert "queue_wait" in stats["thread_pool"]["search"]
        assert "shard_phase" in stats["admission_control"]
        assert "batch" in stats["search"]["batcher"]
        assert stats["tracing"]["ring_size"] >= 1

    def test_prometheus_exposition_parses(self, live):
        _cluster, node, rc = live
        r = rc.dispatch(RestRequest(method="GET", path="/_prometheus/metrics",
                                    params={}))
        assert r.status == 200 and r.content_type.startswith("text/plain")
        types, series = _parse_prometheus(r.body)
        # the required families: breakers, pools, batcher, compile events,
        # search-latency histogram (+ HBM gauge)
        assert types["estpu_breaker_estimated_bytes"] == "gauge"
        assert types["estpu_threadpool_queue_wait_seconds"] == "histogram"
        assert types["estpu_batcher_launches_total"] == "counter"
        assert types["estpu_jax_compile_events_total"] == "counter"
        assert types["estpu_search_latency_seconds"] == "histogram"
        assert types["estpu_hbm_resident_bytes"] == "gauge"
        assert types["estpu_admission_shard_phase_seconds"] == "histogram"
        # adaptive routing + hedging families (PR 10) — per-copy rank gauges
        # carry a copy="node/index/shard" label per observed copy, and the
        # hedge counters are always present; family contiguity for all of
        # them is pinned by the grouping walk below
        assert types["estpu_search_hedges_issued_total"] == "counter"
        assert types["estpu_search_hedges_won_total"] == "counter"
        assert types["estpu_search_hedges_budget_exhausted_total"] == "counter"
        assert types["estpu_search_hedges_budget_tokens"] == "gauge"
        assert types["estpu_routing_probes_total"] == "counter"
        assert types["estpu_routing_quarantined"] == "gauge"
        assert types["estpu_routing_rank_ewma_seconds"] == "gauge"
        assert any(k.startswith('estpu_routing_rank_ewma_seconds{copy="')
                   for k in series), sorted(series)[:5]
        assert series['estpu_breaker_estimated_bytes{breaker="request"}'] == 0
        # histogram contract: +Inf bucket equals _count
        count = series["estpu_search_latency_seconds_count"]
        assert count >= 1
        assert series['estpu_search_latency_seconds_bucket{le="+Inf"}'] == count
        # packed device postings are resident after the searches above
        assert series["estpu_hbm_resident_bytes"] > 0
        launches = series["estpu_batcher_launches_total"]
        assert launches >= 1
        # exposition grouping: every family's samples must be CONTIGUOUS —
        # interleaved families (pool A's gauges, pool B's gauges re-opening
        # the first family) pass the classic scraper but are rejected whole
        # by promtool / OpenMetrics-strict ingesters
        seen, current = set(), None
        for line in r.body.splitlines():
            if not line or line.startswith("#"):
                continue
            name = line.split("{", 1)[0].split(" ", 1)[0]
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[:-len(suffix)]
                if name.endswith(suffix) and f"# TYPE {base} histogram" in r.body:
                    name = base
                    break
            if name != current:
                assert name not in seen, f"family {name} interleaved"
                seen.add(name)
                current = name


# ---------------------------------------------------------------------------
# sanitizer: tracing adds zero device syncs / zero recompiles
# ---------------------------------------------------------------------------


class TestTracedSanitized:
    def test_warmed_traced_loop_zero_recompiles(self, tmp_path):
        """The serving invariant, with tracing fully armed: a warmed traced
        concurrent loop through the batcher performs no implicit transfers
        (hard transfer_guard) and 0 backend compiles — span end-times ride
        the batch's existing pull, so arming tracing adds NO device work."""
        import jax

        from elasticsearch_tpu.common.jaxenv import sanitize
        from elasticsearch_tpu.index import Engine
        from elasticsearch_tpu.mapper import MapperService
        from elasticsearch_tpu.search import ShardContext, parse_query
        from elasticsearch_tpu.search.batcher import DeviceBatcher
        from elasticsearch_tpu.search.execute import lower_flat
        from elasticsearch_tpu.search.similarity import SimilarityService

        settings = Settings.from_flat({})
        svc = MapperService(settings)
        e = Engine(str(tmp_path / "shard0"), svc)
        for i in range(50):
            e.index("doc", str(i),
                    {"body": f"{WORDS[i % 8]} {WORDS[(i + 2) % 8]}"})
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))
        batcher = DeviceBatcher(Settings.from_flat(
            {"search.batch.linger_ms": "25", "search.batch.max_batch": "8"}))
        tracer = _tracer("0")
        texts = ["quick brown", "lazy dog", "red bear", "fox dog"]
        plans = [lower_flat(parse_query({"match": {"body": t}}), ctx)
                 for t in texts]

        def traced_round():
            out = [None] * len(plans)
            errs = [None] * len(plans)

            def worker(i):
                trace = tracer.start_trace("search", force=True)
                try:
                    with tracing.activate(trace.root):
                        out[i] = batcher.execute(plans[i], ctx, 10)
                except Exception as err:  # noqa: BLE001 — assert below
                    errs[i] = err
                finally:
                    trace.root.end()

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(plans))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert all(e2 is None for e2 in errs), errs
            return out

        try:
            warm = traced_round()
            jax.config.update("jax_transfer_guard", "disallow")
            try:
                with sanitize(max_compiles=0, transfers="disallow") as rep:
                    again = traced_round()
            finally:
                jax.config.update("jax_transfer_guard", "allow")
            assert rep.compiles == 0, rep.compile_events
            for w, a in zip(warm, again):
                assert a.hits == w.hits and a.total == w.total
            # every traced request got the batcher spans + the device pull
            for entry in tracer.traces()[:4]:
                names = {s["name"] for s in entry["spans"]}
                assert {"batcher.queue", "batcher.dispatch",
                        "batcher.merge", "device_pull"} <= names, names
        finally:
            batcher.shutdown()

    def test_trace_sync_mode_is_opt_in_and_correct(self, tmp_path,
                                                   monkeypatch):
        """ESTPU_TRACE_SYNC=1 (precise device timing for bench/debug) still
        returns identical results — it only moves the dispatch span's end to
        launch completion."""
        from elasticsearch_tpu.index import Engine
        from elasticsearch_tpu.mapper import MapperService
        from elasticsearch_tpu.search import ShardContext, parse_query
        from elasticsearch_tpu.search.batcher import DeviceBatcher
        from elasticsearch_tpu.search.execute import (execute_flat_batch,
                                                      lower_flat)
        from elasticsearch_tpu.search.similarity import SimilarityService

        assert not tracing.sync_armed()
        monkeypatch.setenv("ESTPU_TRACE_SYNC", "1")
        assert tracing.sync_armed()
        settings = Settings.from_flat({})
        svc = MapperService(settings)
        e = Engine(str(tmp_path / "shard0"), svc)
        for i in range(30):
            e.index("doc", str(i), {"body": f"{WORDS[i % 8]} {WORDS[(i + 1) % 8]}"})
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))
        plan = lower_flat(parse_query({"match": {"body": "quick"}}), ctx)
        expected = execute_flat_batch([plan], ctx, 10)[0]
        batcher = DeviceBatcher(Settings.from_flat({}))
        tracer = _tracer("0")
        trace = tracer.start_trace("search", force=True)
        try:
            with tracing.activate(trace.root):
                got = batcher.execute(plan, ctx, 10)
        finally:
            trace.root.end()
            batcher.shutdown()
        assert got.hits == expected.hits and got.total == expected.total
        names = {s["name"] for s in trace.span_dicts()}
        assert "batcher.dispatch" in names


# ---------------------------------------------------------------------------
# tpulint: the instrumented files stay clean
# ---------------------------------------------------------------------------


def test_observability_files_tpulint_clean():
    """Tracing touches the device hot path (batcher, execute, mesh serving):
    every instrumented file must stay free of findings so the empty baseline
    holds."""
    from tools.tpulint import lint_paths

    wanted = {
        "elasticsearch_tpu/common/tracing.py",
        "elasticsearch_tpu/common/metrics.py",
        "elasticsearch_tpu/common/stream.py",
        "elasticsearch_tpu/search/batcher.py",
        "elasticsearch_tpu/search/execute.py",
        "elasticsearch_tpu/search/service.py",
        "elasticsearch_tpu/transport/service.py",
        "elasticsearch_tpu/actions.py",
        "elasticsearch_tpu/rest/controller.py",
        "elasticsearch_tpu/threadpool.py",
        "elasticsearch_tpu/parallel/mesh_serving.py",
        "elasticsearch_tpu/monitor.py",
    }
    findings = [f for f in lint_paths(None) if f.path in wanted]
    assert findings == [], [f.to_dict() for f in findings]
