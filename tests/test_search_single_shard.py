"""Single-shard search correctness.

The load-bearing test is device-vs-host agreement: every flat-lowerable query must rank
identically through the fused device kernel (ops/scoring.py) and the dense host scorer
(search/execute.py HostScorer), and both must match an independent brute-force
doc-at-a-time scorer written here with Lucene's published formulas.
"""

import math

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.common.smallfloat import NORM_TABLE, decode_norm_doclen
from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard, search_shard_batch
from elasticsearch_tpu.search.execute import count_shard
from elasticsearch_tpu.search.similarity import SimilarityService

DOCS = [
    "the quick brown fox jumps over the lazy dog",
    "quick brown foxes leap over lazy dogs in summer",
    "the red fox and the brown bear",
    "lazy afternoon with a quick snack",
    "dogs and cats living together",
    "the brown dog sleeps all day",
    "fox",
    "a a a a a a a a quick",
    "brown brown brown fox fox quick",
    "nothing relevant here at all",
]


def build_engine(tmp_path, similarity=None, docs=DOCS):
    settings = Settings.from_flat(
        {"index.similarity.default.type": similarity} if similarity else {}
    )
    svc = MapperService(settings)
    e = Engine(str(tmp_path / "shard0"), svc)
    for i, text in enumerate(docs):
        e.index("doc", str(i), {"body": text, "num": i})
        if i % 4 == 3:
            e.refresh()  # force multiple segments
    e.refresh()
    ctx = ShardContext(e.acquire_searcher(), svc,
                       SimilarityService(settings, mapper_service=svc))
    return e, ctx


def brute_force_scores(ctx, field, terms, similarity):
    """Independent doc-at-a-time reference: Lucene practical scoring over all docs."""
    searcher = ctx.searcher
    max_doc = searcher.max_doc
    out = {}
    if similarity == "BM25":
        stats = searcher.field_stats(field)
        avgdl = stats.sum_ttf / max_doc
        for seg, base in zip(searcher.segments, searcher.bases):
            dl = decode_norm_doclen(seg.norms[field])
            for t in terms:
                df = searcher.doc_freq(field, t)
                if df == 0:
                    continue
                idf = math.log(1.0 + (max_doc - df + 0.5) / (df + 0.5))
                docs, freqs = seg.postings(field, t)
                for d, f in zip(docs, freqs):
                    if not (seg.live[d] and seg.parent_mask[d]):
                        continue
                    tfn = f * (1.2 + 1.0) / (f + 1.2 * (1 - 0.75 + 0.75 * dl[d] / avgdl))
                    out[base + int(d)] = out.get(base + int(d), 0.0) + np.float32(idf * tfn)
    else:
        idfs = {}
        for t in terms:
            df = searcher.doc_freq(field, t)
            if df > 0:
                idfs[t] = 1.0 + math.log(max_doc / (df + 1.0))
        ssw = sum(v * v for v in idfs.values())
        qn = 1.0 / math.sqrt(ssw) if ssw > 0 else 1.0
        matched_terms = {}
        for seg, base in zip(searcher.segments, searcher.bases):
            norms = NORM_TABLE[seg.norms[field]]
            for t, idf in idfs.items():
                docs, freqs = seg.postings(field, t)
                for d, f in zip(docs, freqs):
                    if not (seg.live[d] and seg.parent_mask[d]):
                        continue
                    g = base + int(d)
                    out[g] = out.get(g, 0.0) + np.float32(
                        idf * idf * qn * math.sqrt(f) * norms[d])
                    matched_terms[g] = matched_terms.get(g, 0) + 1
        if len(terms) > 1:  # coord
            for g in out:
                out[g] = np.float32(out[g] * matched_terms[g] / len([t for t in terms]))
    return out


def ranked(scores: dict, k=10):
    return sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))[:k]


def assert_hits_equivalent(a, b, rtol=3e-6):
    """Device vs host hit-list equivalence: scores within a few ulps (XLA's f32 division
    is reciprocal-based, ±1-2 ulp vs IEEE numpy/Java — see ops/scoring.py), ordering
    identical except swaps among sub-ulp near-ties."""
    assert len(a) == len(b), (a, b)
    for i, ((sa, da), (sb, db)) in enumerate(zip(a, b)):
        assert sa == pytest.approx(sb, rel=rtol, abs=1e-7), (i, a, b)
        if da != db:
            # permitted only if this is a near-tie neighborhood swap
            others = {d for s, d in b if abs(s - sa) <= rtol * max(abs(sa), 1e-30) + 1e-7}
            assert da in others, (i, a, b)


@pytest.mark.parametrize("similarity", [None, "BM25"])
class TestScoringParity:
    def test_match_single_term(self, tmp_path, similarity):
        e, ctx = build_engine(tmp_path, similarity)
        q = parse_query({"match": {"body": "fox"}})
        device = search_shard(ctx, q, 10, use_device=True)
        host = search_shard(ctx, q, 10, use_device=False)
        assert_hits_equivalent(device.hits, host.hits)
        assert device.total == host.total
        ref = ranked(brute_force_scores(ctx, "body", ["fox"], similarity or "default"))
        assert [d for _, d in host.hits] == [d for d, _ in ref]
        np.testing.assert_allclose([s for s, _ in host.hits], [s for _, s in ref], rtol=1e-6)

    def test_match_multi_term_or(self, tmp_path, similarity):
        e, ctx = build_engine(tmp_path, similarity)
        q = parse_query({"match": {"body": "quick brown fox"}})
        device = search_shard(ctx, q, 10, use_device=True)
        host = search_shard(ctx, q, 10, use_device=False)
        assert_hits_equivalent(device.hits, host.hits)
        ref = ranked(brute_force_scores(ctx, "body", ["quick", "brown", "fox"],
                                        similarity or "default"))
        assert [d for _, d in host.hits] == [d for d, _ in ref]
        np.testing.assert_allclose([s for s, _ in host.hits], [s for _, s in ref], rtol=1e-6)

    def test_match_and_operator(self, tmp_path, similarity):
        e, ctx = build_engine(tmp_path, similarity)
        q = parse_query({"match": {"body": {"query": "quick brown", "operator": "and"}}})
        device = search_shard(ctx, q, 10, use_device=True)
        host = search_shard(ctx, q, 10, use_device=False)
        assert_hits_equivalent(device.hits, host.hits)
        # only docs with BOTH terms
        for _, d in device.hits:
            seg, local = ctx.searcher.resolve(d)
            body = seg.stored[local]["body"]
            assert "quick" in body and "brown" in body

    def test_bool_must_should_must_not(self, tmp_path, similarity):
        e, ctx = build_engine(tmp_path, similarity)
        q = parse_query({"bool": {
            "must": [{"term": {"body": "brown"}}],
            "should": [{"term": {"body": "quick"}}, {"term": {"body": "fox"}}],
            "must_not": [{"term": {"body": "bear"}}],
        }})
        device = search_shard(ctx, q, 10, use_device=True)
        host = search_shard(ctx, q, 10, use_device=False)
        assert_hits_equivalent(device.hits, host.hits)
        assert device.total == host.total
        for _, d in device.hits:
            seg, local = ctx.searcher.resolve(d)
            body = seg.stored[local]["body"]
            assert "brown" in body and "bear" not in body

    def test_minimum_should_match(self, tmp_path, similarity):
        e, ctx = build_engine(tmp_path, similarity)
        q = parse_query({"bool": {
            "should": [{"term": {"body": "quick"}}, {"term": {"body": "brown"}},
                       {"term": {"body": "fox"}}],
            "minimum_should_match": 2,
        }})
        device = search_shard(ctx, q, 10, use_device=True)
        host = search_shard(ctx, q, 10, use_device=False)
        assert_hits_equivalent(device.hits, host.hits)
        for _, d in device.hits:
            seg, local = ctx.searcher.resolve(d)
            body = seg.stored[local]["body"]
            assert sum(t in body.split() or t + "s" in body or t in body
                       for t in ("quick", "brown", "fox")) >= 2

    def test_batch_matches_single(self, tmp_path, similarity):
        e, ctx = build_engine(tmp_path, similarity)
        queries = [
            parse_query({"match": {"body": "fox"}}),
            parse_query({"match": {"body": "lazy dog"}}),
            parse_query({"match": {"body": {"query": "brown fox", "operator": "and"}}}),
            parse_query({"term": {"body": "quick"}}),
        ]
        batch = search_shard_batch(ctx, queries, 10)
        for q, td in zip(queries, batch):
            single = search_shard(ctx, q, 10, use_device=False)
            assert_hits_equivalent(td.hits, single.hits)


class TestQueryTypes:
    def test_term_vs_match_all_count(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        assert search_shard(ctx, parse_query({"match_all": {}}), 20).total == len(DOCS)
        assert count_shard(ctx, parse_query({"match_all": {}})) == len(DOCS)

    def test_phrase(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        td = search_shard(ctx, parse_query({"match_phrase": {"body": "quick brown"}}), 10)
        found = set()
        for _, d in td.hits:
            seg, local = ctx.searcher.resolve(d)
            found.add(seg.stored[local]["body"])
            assert "quick brown" in seg.stored[local]["body"]
        assert len(found) == 2  # docs 0 and 1

    def test_phrase_with_slop(self, tmp_path):
        e, ctx = build_engine(
            tmp_path, docs=["the quick fox brown", "quick brown", "brown quick"])
        td0 = search_shard(ctx, parse_query(
            {"match_phrase": {"body": {"query": "quick brown", "slop": 0}}}), 10)
        td2 = search_shard(ctx, parse_query(
            {"match_phrase": {"body": {"query": "quick brown", "slop": 2}}}), 10)
        assert td0.total == 1
        assert td2.total >= 2

    def test_prefix_wildcard_fuzzy(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        # "fox" in docs 0,2,6,8; "foxes" in doc 1
        assert search_shard(ctx, parse_query({"prefix": {"body": "fo"}}), 10).total == 5
        assert search_shard(ctx, parse_query({"wildcard": {"body": "f*x"}}), 10).total == 4
        assert search_shard(ctx, parse_query({"fuzzy": {"body": "foxs"}}), 10).total == 5
        assert search_shard(ctx, parse_query({"regexp": {"body": "fox(es)?"}}), 10).total == 5

    def test_range_on_numeric(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        td = search_shard(ctx, parse_query({"range": {"num": {"gte": 3, "lt": 6}}}), 10)
        assert td.total == 3
        assert {d for _, d in td.hits} == {
            next(g for g in [b + l for (seg, b) in zip(ctx.searcher.segments, ctx.searcher.bases)
                             for l in range(seg.doc_count) if seg.ids[l] == str(i)])
            for i in (3, 4, 5)
        }

    def test_filtered_query(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        q = parse_query({"filtered": {
            "query": {"match": {"body": "fox"}},
            "filter": {"range": {"num": {"lte": 2}}},
        }})
        td = search_shard(ctx, q, 10)
        assert td.total == 2  # docs 0 and 2 have "fox" and num<=2
        # scores preserved from the inner query (filter doesn't score)
        unfiltered = search_shard(ctx, parse_query({"match": {"body": "fox"}}), 10,
                                  use_device=False)
        scores = {d: s for s, d in unfiltered.hits}
        for s, d in td.hits:
            assert s == pytest.approx(scores[d], rel=1e-6)

    def test_ids_and_terms(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        td = search_shard(ctx, parse_query({"ids": {"values": ["1", "3"]}}), 10)
        assert td.total == 2
        td = search_shard(ctx, parse_query({"terms": {"body": ["bear", "cats"]}}), 10)
        assert td.total == 2

    def test_constant_score_and_bool_filter(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        td = search_shard(ctx, parse_query(
            {"constant_score": {"filter": {"term": {"body": "fox"}}, "boost": 3.0}}), 10)
        assert td.total == 4
        # Lucene semantics: standalone constant_score scores 1.0 — TF-IDF queryNorm
        # (1/sqrt(boost²)) cancels the boost; boost matters only relative to siblings
        assert all(s == pytest.approx(1.0) for s, _ in td.hits)

    def test_dis_max(self, tmp_path):
        # BM25 has no queryNorm, so sub-query scores compose without cross-clause
        # normalization — comparable against standalone term queries
        e, ctx = build_engine(tmp_path, similarity="BM25")
        q = parse_query({"dis_max": {
            "queries": [{"term": {"body": "fox"}}, {"term": {"body": "dog"}}],
            "tie_breaker": 0.5,
        }})
        td = search_shard(ctx, q, 10, use_device=False)
        t_fox = {d: s for s, d in search_shard(ctx, parse_query({"term": {"body": "fox"}}),
                                               10, use_device=False).hits}
        t_dog = {d: s for s, d in search_shard(ctx, parse_query({"term": {"body": "dog"}}),
                                               10, use_device=False).hits}
        for s, d in td.hits:
            f, g = t_fox.get(d, 0.0), t_dog.get(d, 0.0)
            expect = max(f, g) + 0.5 * (f + g - max(f, g))
            assert s == pytest.approx(expect, rel=1e-5)

    def test_query_string(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        td = search_shard(ctx, parse_query(
            {"query_string": {"query": "body:fox AND body:brown"}}), 10)
        assert td.total == 3  # docs 0, 2, 8 contain both terms
        td = search_shard(ctx, parse_query(
            {"query_string": {"query": "fox -bear", "default_field": "body"}}), 10)
        for _, d in td.hits:
            seg, local = ctx.searcher.resolve(d)
            assert "bear" not in seg.stored[local]["body"]

    def test_exists_missing(self, tmp_path):
        svc = MapperService()
        e = Engine(str(tmp_path / "em"), svc)
        e.index("doc", "1", {"a": "x", "b": 1})
        e.index("doc", "2", {"a": "y"})
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc)
        assert search_shard(ctx, parse_query(
            {"constant_score": {"filter": {"exists": {"field": "b"}}}}), 10).total == 1
        assert search_shard(ctx, parse_query(
            {"constant_score": {"filter": {"missing": {"field": "b"}}}}), 10).total == 1

    def test_deleted_docs_excluded(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        e.delete("doc", "6")  # the bare "fox" doc
        e.refresh()
        ctx2 = ShardContext(e.acquire_searcher(), ctx.mapper_service, ctx.similarity_service)
        for use_device in (True, False):
            td = search_shard(ctx2, parse_query({"match": {"body": "fox"}}), 10,
                              use_device=use_device)
            assert td.total == 3
            for _, d in td.hits:
                seg, local = ctx2.searcher.resolve(d)
                assert seg.ids[local] != "6"


class TestFunctionScore:
    def test_field_value_factor(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        q = parse_query({"function_score": {
            "query": {"match": {"body": "fox"}},
            "field_value_factor": {"field": "num", "factor": 2.0},
            "boost_mode": "replace",
        }})
        td = search_shard(ctx, q, 10)
        for s, d in td.hits:
            seg, local = ctx.searcher.resolve(d)
            assert s == pytest.approx(2.0 * seg.num_values("num", local)[0])

    def test_gauss_decay(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        q = parse_query({"function_score": {
            "query": {"match_all": {}},
            "gauss": {"num": {"origin": 0, "scale": 5}},
            "boost_mode": "replace",
        }})
        td = search_shard(ctx, q, 10)
        for s, d in td.hits:
            seg, local = ctx.searcher.resolve(d)
            v = seg.num_values("num", local)[0]
            sigma2 = -(5.0 ** 2) / (2.0 * math.log(0.5))
            assert s == pytest.approx(math.exp(-(v ** 2) / (2 * sigma2)), rel=1e-5)

    def test_script_score(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        q = parse_query({"function_score": {
            "query": {"match": {"body": "fox"}},
            "script_score": {"script": "_score * doc['num'].value + 1"},
            "boost_mode": "replace",
        }})
        td = search_shard(ctx, q, 10)
        base = {d: s for s, d in search_shard(
            ctx, parse_query({"match": {"body": "fox"}}), 10, use_device=False).hits}
        for s, d in td.hits:
            seg, local = ctx.searcher.resolve(d)
            assert s == pytest.approx(base[d] * seg.num_values("num", local)[0] + 1, rel=1e-5)


class TestNested:
    def test_nested_query(self, tmp_path):
        svc = MapperService()
        svc.put_mapping("doc", {"properties": {
            "comments": {"type": "nested", "properties": {
                "text": {"type": "string"}, "stars": {"type": "long"}}}}})
        e = Engine(str(tmp_path / "nested"), svc)
        e.index("doc", "1", {"title": "post one",
                             "comments": [{"text": "great stuff", "stars": 5},
                                          {"text": "terrible", "stars": 1}]})
        e.index("doc", "2", {"title": "post two",
                             "comments": [{"text": "mediocre stuff", "stars": 3}]})
        e.index("doc", "3", {"title": "no comments"})
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc)
        td = search_shard(ctx, parse_query({"nested": {
            "path": "comments",
            "query": {"match": {"comments.text": "stuff"}}}}), 10)
        ids = set()
        for _, d in td.hits:
            seg, local = ctx.searcher.resolve(d)
            ids.add(seg.ids[local])
        assert ids == {"1", "2"}
        # nested filter inside bool
        td = search_shard(ctx, parse_query({"bool": {
            "must": [{"match_all": {}}],
            "filter": [{"nested": {"path": "comments",
                                   "query": {"range": {"comments.stars": {"gte": 4}}}}}],
        }}), 10)
        assert td.total == 1


class TestEdgeCases:
    """Regressions found by end-to-end probing."""

    def test_empty_match_text_returns_no_hits(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        for use_device in (True, False):
            td = search_shard(ctx, parse_query({"match": {"body": ""}}), 5,
                              use_device=use_device)
            assert td.total == 0 and td.hits == []

    def test_msm_exceeding_clause_count_matches_nothing(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        q = parse_query({"bool": {"should": [{"term": {"body": "fox"}}],
                                  "minimum_should_match": 5}})
        for use_device in (True, False):
            assert search_shard(ctx, q, 5, use_device=use_device).total == 0

    def test_must_not_only_bool_matches_non_excluded(self, tmp_path):
        e, ctx = build_engine(tmp_path)
        q = parse_query({"bool": {"must_not": [{"term": {"body": "fox"}}]}})
        for use_device in (True, False):
            td = search_shard(ctx, q, 20, use_device=use_device)
            assert td.total == len(DOCS) - 4  # docs 0,2,6,8 contain "fox"

    def test_nested_filter_only_syntax(self, tmp_path):
        svc = MapperService()
        svc.put_mapping("doc", {"properties": {
            "c": {"type": "nested", "properties": {"x": {"type": "string"}}}}})
        e = Engine(str(tmp_path / "nf"), svc)
        e.index("doc", "1", {"c": [{"x": "present"}]})
        e.index("doc", "2", {"c": [{"y": "other"}]})
        e.refresh()
        ctx = ShardContext(e.acquire_searcher(), svc)
        td = search_shard(ctx, parse_query(
            {"nested": {"path": "c", "filter": {"exists": {"field": "c.x"}}}}), 10)
        assert td.total == 1

    def test_delete_by_query_survives_restart(self, tmp_path):
        svc = MapperService()
        e = Engine(str(tmp_path / "dbq"), svc)
        e.index("doc", "1", {"t": "remove me"})
        e.index("doc", "2", {"t": "keep me"})
        e.refresh()
        e.delete_by_uids(["doc#1"], query={"match": {"t": "remove"}})
        e.refresh()
        assert e.doc_stats()["count"] == 1
        e.translog.sync()
        e.close()
        e2 = Engine(str(tmp_path / "dbq"), svc)
        e2.recover_from_store()
        assert e2.doc_stats()["count"] == 1  # deleted doc must NOT resurrect
        assert not e2.get("doc", "1").found

    def test_optimize_then_crash_recovers(self, tmp_path):
        svc = MapperService()
        e = Engine(str(tmp_path / "oc"), svc)
        for i in range(4):
            e.index("doc", str(i), {"t": f"word{i}"})
            e.refresh()
        e.flush()
        e.optimize()  # must write a new commit before deleting old segment files
        e.close()     # simulate crash-without-flush after optimize
        e2 = Engine(str(tmp_path / "oc"), svc)
        e2.recover_from_store()
        assert e2.doc_stats()["count"] == 4
