"""Overload protection: hierarchical breakers, bounded queues with 429
backpressure, deadline-aware admission control — unit + live-cluster chaos.

The acceptance shape (ISSUE 4): with a deliberately small parent budget, a
concurrent burst of wide-agg searches yields CircuitBreakingError surfaced as
HTTP 429 with Retry-After, zero crashes, all breakers back to 0 estimated
bytes afterwards, and a subsequent plain search answers 200 with correct
hits; threadpool saturation likewise yields 429 (not deadlock) with rejected
counters visible in /_nodes/stats.
"""

import contextlib
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from elasticsearch_tpu.common.breaker import (
    CircuitBreakerService,
    MemoryCircuitBreaker,
    reserve,
)
from elasticsearch_tpu.common.deadline import NO_DEADLINE, Deadline
from elasticsearch_tpu.common.errors import (
    CircuitBreakingError,
    RejectedExecutionError,
)
from elasticsearch_tpu.common.retry import is_transient
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.search.service import SearchAdmissionController
from elasticsearch_tpu.threadpool import ThreadPool

from .harness import TestCluster


# ---------------------------------------------------------------------------
# breaker hierarchy (unit)
# ---------------------------------------------------------------------------


class TestPackEstimateMatchesLayout:
    """The fielddata breaker's segment-pack estimate must track the QUANTIZED
    layout (ops/device_index.pack_shape_math) — the old 8 B × 2 all-f32 math
    overstated every u8 segment by ~40%, inflating breaker pressure."""

    def _packed_actual_bytes(self, seg, packed):
        import numpy as np

        return (
            packed.host_docs.nbytes + packed.host_freqs.nbytes  # retained host
            + np.asarray(packed.blk_docs).nbytes  # device planes
            + np.asarray(packed.blk_tf).nbytes
            + np.asarray(packed.blk_nb).nbytes
            + np.asarray(packed.blk_tf).nbytes  # quantize staging (host)
            + np.asarray(packed.blk_nb).nbytes
            + 2 * packed.doc_pad  # live mask, host + device
            + sum(np.asarray(a).nbytes for a in packed.norm_bytes.values())
        )

    def test_estimate_matches_actual_packed_bytes(self, tmp_path):
        import numpy as np

        from elasticsearch_tpu.common.settings import Settings as S
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.mapper.core import MapperService
        from elasticsearch_tpu.ops.device_index import (
            bytes_per_posting, pack_estimate_bytes, pack_segment,
            packed_resident_bytes)

        rng = np.random.default_rng(23)
        svc = MapperService(S.from_flat({}))
        eng = Engine(str(tmp_path / "est"), svc)
        words = [f"w{i}" for i in range(60)]
        for i in range(200):
            eng.index("doc", str(i), {"b": " ".join(rng.choice(words, size=12))})
        eng.refresh()
        seg = eng.acquire_searcher().segments[0]
        from elasticsearch_tpu.ops.device_index import PACK_TRANSIENT_SLOT_BYTES

        est = pack_estimate_bytes(seg)
        packed = pack_segment(seg)
        # text-only segment: estimate == retained/uploaded planes plus the
        # documented per-slot transient allowance, exactly (shared shape
        # math); any drift between estimate and pack is a regression
        NBpad = np.asarray(packed.blk_docs).shape[0]
        assert est == (self._packed_actual_bytes(seg, packed)
                       + NBpad * 128 * PACK_TRANSIENT_SLOT_BYTES)
        # device-resident postings are the quantized 6 B/posting (u8 ladder,
        # no dense plane until a fallback faults it in)
        assert packed.blk_freqs is None
        assert packed_resident_bytes(packed) == NBpad * 128 * bytes_per_posting(
            packed.tf_layout)
        assert bytes_per_posting(packed.tf_layout) <= 6
        eng.close()

    def test_estimate_never_under_reserves_with_dv_columns(self, tmp_path):
        import numpy as np

        from elasticsearch_tpu.common.settings import Settings as S
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.mapper.core import MapperService
        from elasticsearch_tpu.ops.device_index import (
            pack_estimate_bytes, pack_segment)

        svc = MapperService(S.from_flat({}))
        eng = Engine(str(tmp_path / "estdv"), svc)
        rng = np.random.default_rng(29)
        for i in range(120):
            eng.index("doc", str(i), {"b": f"w{int(rng.integers(20))} text",
                                      "n": int(i), "price": float(i) * 1.5})
        eng.refresh()
        seg = eng.acquire_searcher().segments[0]
        est = pack_estimate_bytes(seg)
        packed = pack_segment(seg)
        actual = self._packed_actual_bytes(seg, packed) + sum(
            np.asarray(c).nbytes for c in packed.dv_single.values())
        # dv columns are estimated at the f64 upper bound (multi-valued
        # columns never upload) — estimate must bound actual from above,
        # within the padded-column + pack-transient slack
        from elasticsearch_tpu.ops.device_index import PACK_TRANSIENT_SLOT_BYTES

        NBpad = np.asarray(packed.blk_docs).shape[0]
        assert actual <= est
        assert est - actual <= (8 * packed.doc_pad * len(seg.dv_num)
                                + NBpad * 128 * PACK_TRANSIENT_SLOT_BYTES)
        eng.close()


class TestBreakerHierarchy:
    def test_child_trips_under_own_limit(self):
        svc = CircuitBreakerService(total_budget_bytes=1000)
        br = svc.breaker("request")  # limit 600, overhead 1.0
        br.add_estimate_and_maybe_break(500, "a")
        with pytest.raises(CircuitBreakingError):
            br.add_estimate_and_maybe_break(200, "b")
        assert br.used == 500 and br.trip_count == 1
        br.release(500)
        assert br.used == 0 and svc.parent.used == 0

    def test_parent_trips_across_children(self):
        # parent 700; request 600; fielddata 800×1.03 — each child fits its own
        # limit but together they blow the shared budget
        svc = CircuitBreakerService(total_budget_bytes=1000)
        svc.breaker("fielddata").add_estimate_and_maybe_break(500, "cols")
        with pytest.raises(CircuitBreakingError) as ei:
            svc.breaker("request").add_estimate_and_maybe_break(300, "merge")
        assert "parent" in str(ei.value)
        # the failed charge left NOTHING accounted anywhere
        assert svc.breaker("request").used == 0
        assert svc.parent.used == 500
        assert svc.parent.trip_count == 1
        svc.breaker("fielddata").release(500)
        assert svc.parent.used == 0

    def test_trip_names_the_tripped_breaker(self):
        # serving paths degrade ONLY on fielddata trips; request/parent trips
        # must shed — the error carries which breaker fired
        svc = CircuitBreakerService(total_budget_bytes=1000)
        with pytest.raises(CircuitBreakingError) as ei:
            svc.breaker("request").add_estimate_and_maybe_break(700, "x")
        assert ei.value.breaker == "request"
        svc.breaker("fielddata").add_estimate_and_maybe_break(500, "y")
        with pytest.raises(CircuitBreakingError) as ei:
            svc.breaker("request").add_estimate_and_maybe_break(300, "z")
        assert ei.value.breaker == "parent"
        svc.breaker("fielddata").release(500)

    def test_release_clamps_at_zero_and_counts_leak(self):
        svc = CircuitBreakerService(total_budget_bytes=1000)
        br = svc.breaker("request")
        br.add_estimate_and_maybe_break(100, "x")
        br.release(60)
        br.release(60)  # over-release: clamps, never goes negative
        assert br.used == 0
        assert br.leak_detected == 1
        assert svc.parent.used == 0
        # headroom was NOT inflated by the bad release: a full-limit charge
        # still fits exactly once
        br.add_estimate_and_maybe_break(600, "y")
        with pytest.raises(CircuitBreakingError):
            br.add_estimate_and_maybe_break(1, "z")
        br.release(600)

    def test_concurrent_adds_never_blow_past_limit(self):
        br = MemoryCircuitBreaker(100, 1.0, "t")
        successes = []

        def worker():
            for _ in range(50):
                try:
                    br.add_estimate_and_maybe_break(1, "w")
                    successes.append(1)
                except CircuitBreakingError:
                    pass

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # the read-modify-write is atomic: exactly `limit` units were admitted
        assert len(successes) == 100
        assert br.used == 100

    def test_reserve_scope_always_releases(self):
        svc = CircuitBreakerService(total_budget_bytes=1000)
        br = svc.breaker("request")
        with reserve(br, 200, "scope"):
            assert br.used == 200
        assert br.used == 0
        with pytest.raises(RuntimeError):
            with reserve(br, 200, "scope"):
                raise RuntimeError("boom")
        assert br.used == 0 and svc.parent.used == 0
        # None breaker and zero bytes are no-ops
        with reserve(None, 100):
            pass
        with reserve(br, 0):
            assert br.used == 0

    def test_settings_driven_limits(self):
        settings = Settings.from_flat({
            "indices.breaker.total_budget": "1kb",
            "indices.breaker.request.limit": "50%",
        })
        svc = CircuitBreakerService(settings)
        assert svc.total_budget == 1024
        assert svc.breaker("request").limit == 512
        assert svc.parent.limit == int(1024 * 0.7)
        assert svc.breaker("in_flight_requests").limit == 1024

    def test_stats_shape(self):
        svc = CircuitBreakerService(total_budget_bytes=1000)
        stats = svc.stats()
        for name in ("request", "fielddata", "in_flight_requests", "parent"):
            for key in ("limit", "estimated", "tripped", "leak_detected"):
                assert key in stats[name], (name, key)


# ---------------------------------------------------------------------------
# bounded thread pools (unit)
# ---------------------------------------------------------------------------


class TestBoundedThreadPool:
    def test_queue_full_rejects_with_429(self):
        tp = ThreadPool(Settings.from_flat({
            "threadpool.search.size": 1, "threadpool.search.queue_size": 1}))
        try:
            gate = threading.Event()
            tp.submit("search", gate.wait)
            deadline = time.monotonic() + 5.0
            while tp.stats()["search"]["active"] != 1:
                assert time.monotonic() < deadline, tp.stats()["search"]
                time.sleep(0.005)
            tp.submit("search", gate.wait)  # fills the 1-slot queue
            with pytest.raises(RejectedExecutionError) as ei:
                tp.submit("search", gate.wait)
            assert ei.value.status == 429
            st = tp.stats()["search"]
            assert st["rejected"] == 1 and st["queue"] == 1 and st["active"] == 1
            gate.set()
            deadline = time.monotonic() + 5.0
            while tp.stats()["search"]["completed"] != 2:
                assert time.monotonic() < deadline, tp.stats()["search"]
                time.sleep(0.005)
        finally:
            tp.shutdown()

    def test_rejection_is_transient_for_retry_policy(self):
        assert is_transient(RejectedExecutionError("queue full"))

    def test_shutdown_cancels_timers_and_scheduler(self):
        tp = ThreadPool()
        fired = []
        timer = tp.schedule(5.0, "generic", lambda: fired.append("timer"))
        task_ticks = []
        tp.schedule_with_fixed_delay(0.03, lambda: task_ticks.append(1))
        time.sleep(0.1)
        tp.shutdown()
        # cancelled, not left to fire into a dead node (finished is set by
        # cancel(); the timer THREAD may take a beat to exit — join it)
        assert timer.finished.is_set()
        timer.join(timeout=2.0)
        assert not timer.is_alive()
        assert not tp._scheduler_thread.is_alive()
        ticks_at_shutdown = len(task_ticks)
        time.sleep(0.12)
        assert len(task_ticks) == ticks_at_shutdown
        assert fired == []
        with pytest.raises(RejectedExecutionError):
            tp.submit("search", lambda: None)

    def test_schedule_after_shutdown_never_fires(self):
        tp = ThreadPool()
        tp.shutdown()
        fired = []
        t = tp.schedule(0.01, "generic", lambda: fired.append(1))
        time.sleep(0.05)
        assert fired == [] and not t.is_alive()


# ---------------------------------------------------------------------------
# admission control (unit)
# ---------------------------------------------------------------------------


class TestAdmissionControl:
    def test_rejects_unservable_budget(self):
        ctrl = SearchAdmissionController(min_samples=3)
        for _ in range(3):
            ctrl.observe(0.5)
        with pytest.raises(RejectedExecutionError) as ei:
            ctrl.admit(Deadline.after(0.001))
        assert ei.value.status == 429
        assert ei.value.retry_after_s == pytest.approx(0.5)
        assert ctrl.stats()["rejected"] == 1

    def test_admits_generous_and_unbounded_budgets(self):
        ctrl = SearchAdmissionController(min_samples=3)
        for _ in range(3):
            ctrl.observe(0.5)
        ctrl.admit(Deadline.after(10.0))
        ctrl.admit(NO_DEADLINE)
        assert ctrl.stats()["rejected"] == 0

    def test_slow_outlier_decays_instead_of_poisoning(self):
        # one wedged 5s failover chain must not 429 servable 500ms requests
        # for hundreds of observations: the admit() signal is an EWMA, and a
        # handful of healthy samples wash the outlier out
        ctrl = SearchAdmissionController(min_samples=3)
        for _ in range(3):
            ctrl.observe(0.01)
        ctrl.observe(5.0)
        with pytest.raises(RejectedExecutionError):
            ctrl.admit(Deadline.after(0.5))  # right after the spike: shed
        for _ in range(10):
            ctrl.observe(0.01)
        ctrl.admit(Deadline.after(0.5))  # recovered — no rejection
        assert ctrl.stats()["ewma_shard_phase_ms"] < 500

    def test_cold_node_never_rejects(self):
        ctrl = SearchAdmissionController(min_samples=10)
        for _ in range(9):
            ctrl.observe(5.0)  # even huge latencies: below min_samples
        ctrl.admit(Deadline.after(0.001))
        assert ctrl.stats()["rejected"] == 0


# ---------------------------------------------------------------------------
# live-cluster chaos (REST surface over real sockets)
# ---------------------------------------------------------------------------


def _call(base, method, path, body=None, raw_body=None, timeout=60):
    data = None
    headers = {}
    if raw_body is not None:
        data = raw_body.encode()
        headers["Content-Type"] = "application/x-ndjson"
    elif body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(base + path, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status, payload = resp.status, resp.read().decode()
            resp_headers = dict(resp.headers)
    except urllib.error.HTTPError as e:
        status, payload = e.code, e.read().decode()
        resp_headers = dict(e.headers)
    try:
        parsed = json.loads(payload) if payload else None
    except ValueError:
        parsed = payload
    return status, parsed, resp_headers


@contextlib.contextmanager
def _http_cluster(tmp_path, settings=None, n_docs=0, shards=1):
    with TestCluster(n_nodes=1, data_root=tmp_path, seed=11,
                     settings=settings or {}) as cluster:
        node = next(iter(cluster.nodes.values()))
        server = node.start_http(port=0)
        base = f"http://127.0.0.1:{server.port}"
        status, body, _h = _call(base, "PUT", "/overload", {"settings": {
            "number_of_shards": shards, "number_of_replicas": 0}})
        assert status == 200 and body["acknowledged"], body
        cluster.ensure_green("overload")
        # bulk in chunks small enough to clear even a shrunken in-flight budget
        for lo in range(0, n_docs, 200):
            lines = []
            for i in range(lo, min(lo + 200, n_docs)):
                lines.append(json.dumps(
                    {"index": {"_index": "overload", "_type": "doc",
                               "_id": str(i)}}))
                lines.append(json.dumps({"tag": f"t{i % 7}", "n": i}))
            status, body, _h = _call(base, "POST", "/_bulk",
                                     raw_body="\n".join(lines) + "\n")
            assert status == 200 and not body.get("errors"), body
        if n_docs:
            status, _b, _h = _call(base, "POST", "/overload/_refresh")
            assert status == 200
        yield cluster, node, base


WIDE_AGG_SEARCH = {
    # explain pins the HOST mask path (deterministic request-breaker charge of
    # max_doc × (5 + 16·n_aggs) bytes) — the "expensive aggregation" face
    "query": {"match_all": {}},
    "aggs": {"tags": {"terms": {"field": "tag"}}},
    "explain": True,
    "size": 3,
}


class TestOverloadChaos:
    def test_breaker_burst_yields_429_then_full_recovery(self, tmp_path):
        # 48kb parent budget: one 2000-doc wide-agg query phase estimates
        # ~42kb against a 28.8kb request limit — every burst search must shed
        with _http_cluster(tmp_path,
                           settings={"indices.breaker.total_budget": "48kb"},
                           n_docs=2000) as (cluster, node, base):
            results = []
            results_lock = threading.Lock()

            def hammer():
                st, body, headers = _call(base, "POST", "/overload/_search",
                                          WIDE_AGG_SEARCH)
                with results_lock:
                    results.append((st, body, headers))

            threads = [threading.Thread(target=hammer) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            statuses = [st for st, _b, _h in results]
            assert len(statuses) == 6
            # ≥1 breaker trip surfaced as 429 — and NOTHING crashed (no 5xx)
            assert 429 in statuses, statuses
            assert all(st < 500 for st in statuses), statuses
            for st, body, headers in results:
                if st == 429:
                    assert "Retry-After" in headers, headers
                    assert int(headers["Retry-After"]) >= 1
                    assert body["error"]["type"] in (
                        "CircuitBreakingException", "RejectedExecutionException"
                    ), body
            # graceful degradation: only the offending requests aborted, every
            # reservation was released — breakers drain to 0 estimated bytes
            deadline = time.monotonic() + 5.0
            while True:
                st, stats, _h = _call(base, "GET", "/_nodes/stats")
                assert st == 200
                node_stats = stats["nodes"][node.node_id]
                estimates = {name: b["estimated"]
                             for name, b in node_stats["breakers"].items()}
                if all(v == 0 for v in estimates.values()):
                    break
                assert time.monotonic() < deadline, estimates
                time.sleep(0.05)
            tripped = sum(b["tripped"]
                          for b in node_stats["breakers"].values())
            assert tripped >= 1, node_stats["breakers"]
            # the node keeps serving: a plain search answers green
            st, body, _h = _call(base, "POST", "/overload/_search",
                                 {"query": {"match_all": {}}, "size": 5})
            assert st == 200, body
            assert body["hits"]["total"] == 2000
            assert len(body["hits"]["hits"]) == 5

    def test_threadpool_saturation_yields_429_not_deadlock(self, tmp_path):
        with _http_cluster(tmp_path,
                           settings={"threadpool.search.size": 1,
                                     "threadpool.search.queue_size": 1},
                           n_docs=20) as (cluster, node, base):
            gate = threading.Event()
            # occupy the single search worker AND the single queue slot
            node.threadpool.submit("search", gate.wait)
            deadline = time.monotonic() + 5.0
            while node.threadpool.stats()["search"]["active"] != 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            node.threadpool.submit("search", gate.wait)
            try:
                st, body, headers = _call(
                    base, "POST", "/overload/_search",
                    {"query": {"match_all": {}}}, timeout=30)
                assert st == 429, body
                assert "Retry-After" in headers
                assert body["error"]["type"] == "RejectedExecutionException", body
            finally:
                gate.set()
            st, stats, _h = _call(base, "GET", "/_nodes/stats")
            pool = stats["nodes"][node.node_id]["thread_pool"]["search"]
            assert pool["rejected"] >= 1, pool
            # queue drained → the same search now answers
            deadline = time.monotonic() + 5.0
            while node.threadpool.stats()["search"]["queue"] > 0:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            st, body, _h = _call(base, "POST", "/overload/_search",
                                 {"query": {"match_all": {}}})
            assert st == 200 and body["hits"]["total"] == 20

    def test_admission_control_rejects_unservable_timeout(self, tmp_path):
        with _http_cluster(tmp_path, n_docs=20) as (cluster, node, base):
            # seed the coordinator's latency signal: shard phases "take" 500ms
            for _ in range(node.actions.admission.min_samples):
                node.actions.admission.observe(0.5)
            st, body, headers = _call(
                base, "POST", "/overload/_search?timeout=1ms",
                {"query": {"match_all": {}}})
            assert st == 429, body
            assert body["error"]["type"] == "RejectedExecutionException"
            assert headers.get("Retry-After") == "1"
            assert node.actions.admission.stats()["rejected"] >= 1
            # a generous budget sails through the same gate
            st, body, _h = _call(base, "POST", "/overload/_search?timeout=30s",
                                 {"query": {"match_all": {}}})
            assert st == 200 and body["hits"]["total"] == 20


# ---------------------------------------------------------------------------
# REST stats surface (satellite: breaker + queue stats over /_nodes/stats)
# ---------------------------------------------------------------------------


class TestRestOverloadStats:
    def test_nodes_stats_exposes_breakers_and_queues(self, tmp_path):
        with _http_cluster(tmp_path, n_docs=5) as (cluster, node, base):
            # one flat (device-lowerable) search so the batcher counters move
            st, body, _h = _call(base, "POST", "/overload/_search",
                                 {"query": {"term": {"tag": "t0"}}})
            assert st == 200, body
            st, stats, _h = _call(base, "GET", "/_nodes/stats")
            assert st == 200
            node_stats = stats["nodes"][node.node_id]
            breakers = node_stats["breakers"]
            for name in ("parent", "request", "fielddata",
                         "in_flight_requests"):
                for key in ("limit", "estimated", "tripped"):
                    assert key in breakers[name], (name, key)
                assert breakers[name]["limit"] > 0
                assert breakers[name]["estimated"] == 0
            pools = node_stats["thread_pool"]
            for name in ("search", "index", "bulk", "get"):
                for key in ("queue", "rejected", "threads", "active",
                            "queue_size", "completed"):
                    assert key in pools[name], (name, key)
            # the searches this fixture ran left latency observations behind
            assert "admission_control" in node_stats
            assert set(node_stats["admission_control"]) == {
                "observed", "mean_shard_phase_ms", "ewma_shard_phase_ms",
                "rejected", "shard_phase"}
            # the histogram twin of the EWMA (PR 8): tail percentiles ride along
            assert {"p50_ms", "p95_ms", "p99_ms"} <= set(
                node_stats["admission_control"]["shard_phase"])
            # cross-request micro-batching counters (search/batcher.py)
            batcher = node_stats["search"]["batcher"]
            for key in ("launches", "coalesced", "occupancy_mean",
                        "linger_flushes", "deadline_flushes"):
                assert key in batcher, key
            # this fixture's searches rode the batcher: the coordinator's
            # flat query phases coalesce through it even at occupancy 1
            assert batcher["launches"] >= 1
            assert batcher["coalesced"] >= batcher["launches"]
            # the drainer occupies its named pool (visible liveness signal)
            assert "search_batcher" in node_stats["thread_pool"]
